"""Test-support subsystems shipped with the package (chaos injection)."""

from filodb_tpu.testing.chaos import (ChaosError, ChaosInjector,  # noqa: F401
                                      fire, install, installed, uninstall)
