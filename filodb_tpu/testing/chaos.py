"""Chaos-injection harness: drop / delay / error RPCs at named fault
points inside the cluster plane.

The production code calls ``chaos.fire("point", key=value, ...)`` at its
fault points; with no injector installed this is a single attribute read
and return (safe to leave in hot-ish control paths). Tests install an
injector EXPLICITLY — there is deliberately no env-var switch, so a
production deployment can never trip faults by inherited environment
(the reference gets the same effect from Akka's TestKit-only failure
injectors living in src/test).

Fault points wired in this build:

  * ``grpc.call``     — grpcsvc/client.py before every stub dial
                        (ctx: node, addr, method)
  * ``http.peer``     — parallel/cluster.py before every peer HTTP fetch
                        (ctx: node, url)
  * ``ingest.batch``  — ingest/driver.py before a stream batch is
                        applied (ctx: shard, offset)
  * ``ingest.flush``  — ingest/driver.py before a group flush
                        (ctx: shard, group)
  * ``handoff.adopt`` — parallel/membership.py before the adopt
                        request of a planned handoff (ctx: shard, node)
  * ``handoff.await`` — parallel/membership.py on each poll while the
                        draining node waits for the successor to
                        advertise ACTIVE (ctx: shard)
  * ``handoff.transfer`` — parallel/membership.py before each peer
                        ownership-transfer push (ctx: shard, node)
  * ``qos.admit``     — http/server.py before the query-gate admission
                        decision on every query endpoint hit
                        (ctx: tenant, endpoint)
  * ``qos.shed``      — http/server.py when an over-budget tenant
                        enters the brownout degrade ladder, before any
                        rung runs (ctx: tenant, query)

Usage:

    inj = ChaosInjector()
    inj.fail("grpc.call", times=2, match=lambda c: c["node"] == "node1")
    inj.delay("http.peer", 0.5)
    with inj:                      # or chaos.install(inj) / uninstall()
        ... run the scenario ...
    assert inj.fired("grpc.call") == 2
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


class ChaosError(ConnectionError):
    """Default injected fault. Subclasses ConnectionError (an OSError)
    so the HTTP peer path maps it to TransportError exactly like a real
    refused/reset connection."""


@dataclass
class _Rule:
    kind: str                              # "error" | "delay" | "drop"
    match: Optional[Callable[[Dict], bool]] = None
    times: Optional[int] = None            # None = every matching fire
    exc: Optional[Callable[[], BaseException]] = None
    delay_s: float = 0.0
    hits: int = 0
    field_lock: threading.Lock = field(default_factory=threading.Lock)

    def applies(self, ctx: Dict) -> bool:
        if self.match is not None and not self.match(ctx):
            return False
        with self.field_lock:
            if self.times is not None and self.hits >= self.times:
                return False
            self.hits += 1
            return True


class ChaosInjector:
    """Holds fault rules per point and a log of every fire."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: Dict[str, List[_Rule]] = {}
        self._fired: Dict[str, int] = {}
        self.log: List[Dict] = []

    # -- rule builders -----------------------------------------------------
    def fail(self, point: str,
             exc: Optional[Callable[[], BaseException]] = None,
             times: Optional[int] = None,
             match: Optional[Callable[[Dict], bool]] = None
             ) -> "ChaosInjector":
        """Raise at ``point`` (default: ChaosError, a ConnectionError)."""
        self._add(point, _Rule("error", match, times,
                               exc or (lambda: ChaosError(
                                   f"chaos: injected fault at {point}"))))
        return self

    def drop(self, point: str, times: Optional[int] = None,
             match: Optional[Callable[[Dict], bool]] = None
             ) -> "ChaosInjector":
        """Black-hole the call: a long stall then transport error — the
        'packets dropped, TCP timeout' shape (distinct from fail()'s
        instant connection-refused)."""
        self._add(point, _Rule("drop", match, times))
        return self

    def delay(self, point: str, delay_s: float,
              times: Optional[int] = None,
              match: Optional[Callable[[Dict], bool]] = None
              ) -> "ChaosInjector":
        self._add(point, _Rule("delay", match, times, delay_s=delay_s))
        return self

    def _add(self, point: str, rule: _Rule) -> None:
        with self._lock:
            self._rules.setdefault(point, []).append(rule)

    # -- introspection -----------------------------------------------------
    def fired(self, point: str) -> int:
        """How many times ``point`` was REACHED (whether or not a rule
        triggered) — lets tests assert 'no further dials' after a
        breaker opens."""
        with self._lock:
            return self._fired.get(point, 0)

    # -- the hot hook ------------------------------------------------------
    def on_fire(self, point: str, ctx: Dict) -> None:
        with self._lock:
            self._fired[point] = self._fired.get(point, 0) + 1
            self.log.append({"point": point, **ctx})
            rules = list(self._rules.get(point, ()))
        for rule in rules:
            if not rule.applies(ctx):
                continue
            if rule.kind == "delay":
                time.sleep(rule.delay_s)
            elif rule.kind == "drop":
                # bounded stall standing in for a TCP timeout: long
                # enough that an un-deadlined caller visibly hangs,
                # short enough for test suites
                time.sleep(rule.delay_s or 2.0)
                raise ChaosError(f"chaos: dropped call at {point}")
            else:
                raise rule.exc()

    def __enter__(self) -> "ChaosInjector":
        install(self)
        return self

    def __exit__(self, *exc) -> None:
        uninstall()


_installed: Optional[ChaosInjector] = None


def install(injector: ChaosInjector) -> ChaosInjector:
    global _installed
    _installed = injector
    return injector


def uninstall() -> None:
    global _installed
    _installed = None


def installed() -> Optional[ChaosInjector]:
    return _installed


def fire(point: str, **ctx) -> None:
    """Production-side hook: no-op unless an injector is installed."""
    inj = _installed
    if inj is not None:
        inj.on_fire(point, ctx)
