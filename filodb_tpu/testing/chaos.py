"""Chaos-injection harness: drop / delay / error RPCs at named fault
points inside the cluster plane.

The production code calls ``chaos.fire("point", key=value, ...)`` at its
fault points; with no injector installed this is a single attribute read
and return (safe to leave in hot-ish control paths). Tests install an
injector EXPLICITLY — there is deliberately no env-var switch, so a
production deployment can never trip faults by inherited environment
(the reference gets the same effect from Akka's TestKit-only failure
injectors living in src/test).

Fault points wired in this build:

  * ``grpc.call``     — grpcsvc/client.py before every stub dial
                        (ctx: node, addr, method)
  * ``http.peer``     — parallel/cluster.py before every peer HTTP fetch
                        (ctx: node, url)
  * ``ingest.batch``  — ingest/driver.py before a stream batch is
                        applied (ctx: shard, offset)
  * ``ingest.flush``  — ingest/driver.py before a group flush
                        (ctx: shard, group)
  * ``handoff.adopt`` — parallel/membership.py before the adopt
                        request of a planned handoff (ctx: shard, node)
  * ``handoff.await`` — parallel/membership.py on each poll while the
                        draining node waits for the successor to
                        advertise ACTIVE (ctx: shard)
  * ``handoff.transfer`` — parallel/membership.py before each peer
                        ownership-transfer push (ctx: shard, node)
  * ``qos.admit``     — http/server.py before the query-gate admission
                        decision on every query endpoint hit
                        (ctx: tenant, endpoint)
  * ``qos.shed``      — http/server.py when an over-budget tenant
                        enters the brownout degrade ladder, before any
                        rung runs (ctx: tenant, query)

Disk-fault points (the file-I/O fault layer): durable-tier writers
route record bytes through :func:`write` and readers filter loaded
bytes through :func:`filter_read`, so tests can fire ENOSPC/EIO
(``fail`` with an errno-carrying OSError — see :func:`enospc` /
:func:`eio`), short/torn writes (``torn_write``: a prefix lands on
disk, then the write errors), and read-side bit flips (``bit_flip``)
at named points:

  * ``wal.append``    — ingest/stream.py, each framed record write
                        (ctx: path, nbytes)
  * ``wal.fsync``     — ingest/stream.py group-commit fsync
                        (ctx: path)
  * ``wal.read``      — ingest/stream.py, every byte range a reader
                        loads (ctx: path, offset)
  * ``chunklog.write`` / ``chunklog.read``
                      — store/columnstore.py chunk-log records
                        (ctx: dataset, shard[, offset])
  * ``partkeys.write`` / ``partkeys.read``
                      — store/columnstore.py partkey-log records
                        (ctx: dataset, shard)
  * ``checkpoint.write`` / ``checkpoint.read``
                      — store/columnstore.py checkpoint documents
                        (ctx: dataset, shard)

``bit_flip`` also applies on write points — that is how tests write
genuinely corrupt files through the real writers.

Usage:

    inj = ChaosInjector()
    inj.fail("grpc.call", times=2, match=lambda c: c["node"] == "node1")
    inj.delay("http.peer", 0.5)
    inj.fail("wal.append", exc=chaos.enospc, times=3)
    inj.bit_flip("wal.read", times=1)
    with inj:                      # or chaos.install(inj) / uninstall()
        ... run the scenario ...
    assert inj.fired("grpc.call") == 2
"""

from __future__ import annotations

import errno as _errno
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple


class ChaosError(ConnectionError):
    """Default injected fault. Subclasses ConnectionError (an OSError)
    so the HTTP peer path maps it to TransportError exactly like a real
    refused/reset connection."""


def enospc() -> OSError:
    """A faithful out-of-space error (errno set, like the kernel's)."""
    return OSError(_errno.ENOSPC, "chaos: no space left on device")


def eio() -> OSError:
    """A faithful I/O error (the failing-disk shape)."""
    return OSError(_errno.EIO, "chaos: input/output error")


@dataclass
class _Rule:
    kind: str          # "error" | "delay" | "drop" | "torn" | "bitflip"
    match: Optional[Callable[[Dict], bool]] = None
    times: Optional[int] = None            # None = every matching fire
    exc: Optional[Callable[[], BaseException]] = None
    delay_s: float = 0.0
    keep: float = 0.5         # torn: fraction (<1.0) or bytes to keep
    flip_offset: Optional[int] = None      # bitflip: None = middle byte
    flip_mask: int = 0x01
    hits: int = 0
    field_lock: threading.Lock = field(default_factory=threading.Lock)

    def applies(self, ctx: Dict) -> bool:
        if self.match is not None and not self.match(ctx):
            return False
        with self.field_lock:
            if self.times is not None and self.hits >= self.times:
                return False
            self.hits += 1
            return True


class ChaosInjector:
    """Holds fault rules per point and a log of every fire."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: Dict[str, List[_Rule]] = {}
        self._fired: Dict[str, int] = {}
        self.log: List[Dict] = []

    # -- rule builders -----------------------------------------------------
    def fail(self, point: str,
             exc: Optional[Callable[[], BaseException]] = None,
             times: Optional[int] = None,
             match: Optional[Callable[[Dict], bool]] = None
             ) -> "ChaosInjector":
        """Raise at ``point`` (default: ChaosError, a ConnectionError)."""
        self._add(point, _Rule("error", match, times,
                               exc or (lambda: ChaosError(
                                   f"chaos: injected fault at {point}"))))
        return self

    def drop(self, point: str, times: Optional[int] = None,
             match: Optional[Callable[[Dict], bool]] = None
             ) -> "ChaosInjector":
        """Black-hole the call: a long stall then transport error — the
        'packets dropped, TCP timeout' shape (distinct from fail()'s
        instant connection-refused)."""
        self._add(point, _Rule("drop", match, times))
        return self

    def delay(self, point: str, delay_s: float,
              times: Optional[int] = None,
              match: Optional[Callable[[Dict], bool]] = None
              ) -> "ChaosInjector":
        self._add(point, _Rule("delay", match, times, delay_s=delay_s))
        return self

    def torn_write(self, point: str, keep: float = 0.5,
                   times: Optional[int] = 1,
                   match: Optional[Callable[[Dict], bool]] = None
                   ) -> "ChaosInjector":
        """Short/torn write at a disk point: a prefix of the buffer
        (``keep`` < 1.0 = fraction, >= 1 = bytes) reaches the file,
        then the write raises EIO — the crash-mid-write shape that
        leaves a torn record on disk."""
        self._add(point, _Rule("torn", match, times, keep=keep))
        return self

    def bit_flip(self, point: str, offset: Optional[int] = None,
                 mask: int = 0x01, times: Optional[int] = 1,
                 match: Optional[Callable[[Dict], bool]] = None
                 ) -> "ChaosInjector":
        """Flip bits in the buffer passing a disk point (read side:
        bit rot / a bad sector read; write side: corrupt bytes landing
        on disk). ``offset`` indexes the buffer (negative = from the
        end, None = middle byte); ``mask`` is XORed into that byte."""
        self._add(point, _Rule("bitflip", match, times,
                               flip_offset=offset, flip_mask=mask))
        return self

    def _add(self, point: str, rule: _Rule) -> None:
        with self._lock:
            self._rules.setdefault(point, []).append(rule)

    # -- introspection -----------------------------------------------------
    def fired(self, point: str) -> int:
        """How many times ``point`` was REACHED (whether or not a rule
        triggered) — lets tests assert 'no further dials' after a
        breaker opens."""
        with self._lock:
            return self._fired.get(point, 0)

    # -- disk-point data hooks ---------------------------------------------
    def on_write(self, point: str, data: bytes, ctx: Dict
                 ) -> Tuple[bytes, Optional[BaseException]]:
        """Transform an outbound buffer at a disk write point. Returns
        ``(bytes_to_write, exc_to_raise_after)``: torn writes land a
        prefix THEN error (the crash-mid-write shape), errors land
        nothing, bit flips land corrupt bytes and succeed."""
        with self._lock:
            self._fired[point] = self._fired.get(point, 0) + 1
            self.log.append({"point": point, "nbytes": len(data), **ctx})
            rules = list(self._rules.get(point, ()))
        exc: Optional[BaseException] = None
        for rule in rules:
            if not rule.applies(ctx):
                continue
            if rule.kind == "delay":
                time.sleep(rule.delay_s)
            elif rule.kind == "error":
                return b"", rule.exc()
            elif rule.kind == "torn":
                keep = (int(len(data) * rule.keep) if rule.keep < 1.0
                        else int(rule.keep))
                keep = max(0, min(len(data), keep))
                return data[:keep], eio()
            elif rule.kind == "bitflip":
                data = _flip(data, rule)
            elif rule.kind == "drop":
                time.sleep(rule.delay_s or 2.0)
                return b"", eio()
        return data, exc

    def on_read(self, point: str, data: bytes, ctx: Dict) -> bytes:
        """Transform an inbound buffer at a disk read point (errors
        raise, bit flips corrupt what the reader sees)."""
        with self._lock:
            self._fired[point] = self._fired.get(point, 0) + 1
            self.log.append({"point": point, "nbytes": len(data), **ctx})
            rules = list(self._rules.get(point, ()))
        for rule in rules:
            if not rule.applies(ctx):
                continue
            if rule.kind == "delay":
                time.sleep(rule.delay_s)
            elif rule.kind == "error":
                raise rule.exc()
            elif rule.kind == "bitflip":
                data = _flip(data, rule)
        return data

    # -- the hot hook ------------------------------------------------------
    def on_fire(self, point: str, ctx: Dict) -> None:
        with self._lock:
            self._fired[point] = self._fired.get(point, 0) + 1
            self.log.append({"point": point, **ctx})
            rules = list(self._rules.get(point, ()))
        for rule in rules:
            if not rule.applies(ctx):
                continue
            if rule.kind == "delay":
                time.sleep(rule.delay_s)
            elif rule.kind == "drop":
                # bounded stall standing in for a TCP timeout: long
                # enough that an un-deadlined caller visibly hangs,
                # short enough for test suites
                time.sleep(rule.delay_s or 2.0)
                raise ChaosError(f"chaos: dropped call at {point}")
            else:
                raise rule.exc()

    def __enter__(self) -> "ChaosInjector":
        install(self)
        return self

    def __exit__(self, *exc) -> None:
        uninstall()


_installed: Optional[ChaosInjector] = None


def install(injector: ChaosInjector) -> ChaosInjector:
    global _installed
    _installed = injector
    return injector


def uninstall() -> None:
    global _installed
    _installed = None


def installed() -> Optional[ChaosInjector]:
    return _installed


def _flip(data: bytes, rule: _Rule) -> bytes:
    if not data:
        return data
    off = rule.flip_offset if rule.flip_offset is not None else len(data) // 2
    if off < 0:
        off += len(data)
    if not 0 <= off < len(data):
        return data
    buf = bytearray(data)
    buf[off] ^= (rule.flip_mask & 0xFF) or 0x01
    return bytes(buf)


def fire(point: str, **ctx) -> None:
    """Production-side hook: no-op unless an injector is installed."""
    inj = _installed
    if inj is not None:
        inj.on_fire(point, ctx)


def write(point: str, fobj, data: bytes, **ctx) -> int:
    """Disk-point write hook: route record bytes to ``fobj.write``
    through the installed injector (no injector: a plain write). Torn
    rules land a prefix then raise; error rules raise before any byte
    lands; bitflip rules land corrupt bytes — through the real
    writer's own code path."""
    inj = _installed
    if inj is None:
        return fobj.write(data)
    out, exc = inj.on_write(point, data, ctx)
    n = fobj.write(out) if out else 0
    if exc is not None:
        raise exc
    return n


def filter_read(point: str, data: bytes, **ctx) -> bytes:
    """Disk-point read hook: pass loaded bytes through the installed
    injector (no injector: identity)."""
    inj = _installed
    if inj is None:
        return data
    return inj.on_read(point, data, ctx)
