"""ULP-certification rail (graftlint v4): every numeric annotation in
the tree is dynamically certified, engine-as-assertion style.

:mod:`filodb_tpu.lint.rules_numerics` makes ``@precision`` /
``@order_insensitive`` annotations mandatory at every hybrid site; this
module makes them HONEST. For each registered claim a harness evaluates
the annotated site on seeded inputs shaped by its static bound:

  * **precision claims** run the production path against an f64
    reference (the exact-f64 twin evaluator, the pure-Python refeval
    window loop, or a straight f64 formula) and measure the worst
    error in output-dtype ulps. ``rel_ulps=0`` claims are certified
    BITWISE.
  * **order claims** run the site at 1, 2, 4, and 8 virtual devices
    and measure the worst relative deviation across device counts.
    ``tolerance=0.0`` claims are certified bitwise at every count —
    the dynamic half of the mesh-on/off byte-identity cross-check.

A claim whose measurement exceeds its declared tolerance, or that has
no registered harness at all, is an error-severity ``ulp-certification``
finding in the tier-1 gate: an annotation the rail cannot certify is a
lie, and lies about precision do not ship. Results are memoized per
process (the claims are fixed at import time), so repeated ``run_lint``
calls — the fixture tests — pay the compile cost once.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from filodb_tpu.lint import Finding, register_rule
from filodb_tpu.lint import numerics as nmod

register_rule("ulp-certification", "numerics",
              "a @precision/@order_insensitive annotation failed "
              "dynamic certification (or has no harness) — the "
              "declared tolerance is a lie")

DEVICE_COUNTS = (1, 2, 4, 8)

# claim name -> (kind, harness); precision harnesses return
# (prod, ref, floor), order harnesses are called per device count
HARNESSES: Dict[str, Tuple[str, Callable]] = {}


def precision_harness(name: str) -> Callable:
    def deco(fn):
        HARNESSES[name] = ("precision", fn)
        return fn
    return deco


def order_harness(name: str) -> Callable:
    def deco(fn):
        HARNESSES[name] = ("order", fn)
        return fn
    return deco


@dataclass
class CertResult:
    name: str
    kind: str                   # precision | order
    ok: bool
    measured: float             # worst ulps / rel deviation observed
    claimed: float
    detail: str = ""
    device_counts: Tuple[int, ...] = ()


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------


def _measure_precision(claim: nmod.PrecisionClaim, prod, ref,
                       floor=0.0) -> CertResult:
    import numpy as np
    prod = np.asarray(prod)
    ref = np.asarray(ref)
    if prod.shape != ref.shape:
        return CertResult(claim.name, "precision", False, math.inf,
                          claim.rel_ulps,
                          f"shape mismatch {prod.shape} vs {ref.shape}")
    if np.issubdtype(prod.dtype, np.floating):
        nan_p, nan_r = np.isnan(prod), np.isnan(ref)
        if not np.array_equal(nan_p, nan_r):
            return CertResult(claim.name, "precision", False, math.inf,
                              claim.rel_ulps, "NaN structure differs "
                              "between production and reference")
        m = ~nan_p
        if claim.rel_ulps == 0:
            same = np.array_equal(prod[m], ref[m].astype(prod.dtype))
            return CertResult(
                claim.name, "precision", same, 0.0 if same else math.inf,
                0.0, "bitwise" if same else "exact claim but values "
                "differ from the reference")
        pf = prod.astype(np.float64)[m]
        rf = np.asarray(ref, np.float64)[m]
        err = np.maximum(np.abs(pf - rf) - np.asarray(floor), 0.0)
        # one ulp of the reference in the PRODUCTION dtype
        sp = np.spacing(np.abs(rf).astype(prod.dtype)).astype(np.float64)
        sp = np.maximum(sp, float(np.finfo(prod.dtype).tiny))
        ulps = float(np.max(err / sp)) if err.size else 0.0
        return CertResult(
            claim.name, "precision", ulps <= claim.rel_ulps, ulps,
            claim.rel_ulps,
            f"max {ulps:.3g} ulps over {int(m.sum())} values")
    same = np.array_equal(prod, ref)
    return CertResult(claim.name, "precision", same,
                      0.0 if same else math.inf, claim.rel_ulps,
                      "bitwise" if same else "integer outputs differ")


def _rel_dev(a, b) -> float:
    import numpy as np
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    nan_a, nan_b = np.isnan(a), np.isnan(b)
    if not np.array_equal(nan_a, nan_b):
        return math.inf
    m = ~nan_a
    if not m.any():
        return 0.0
    diff = np.abs(a[m] - b[m])
    scale = np.maximum(np.maximum(np.abs(a[m]), np.abs(b[m])), 1e-300)
    return float(np.max(diff / scale))


def _measure_order(claim: nmod.OrderClaim, harness,
                   counts: Sequence[int]) -> CertResult:
    import numpy as np
    results = {}
    for n in counts:
        out = harness(n)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        results[n] = [np.asarray(o) for o in out]
    base = results[counts[0]]
    worst = 0.0
    for n in counts[1:]:
        for a, b in zip(base, results[n]):
            if claim.tolerance == 0.0:
                pa, pb = np.asarray(a), np.asarray(b)
                eq = np.array_equal(pa, pb) or (
                    np.issubdtype(pa.dtype, np.floating)
                    and np.array_equal(np.isnan(pa), np.isnan(pb))
                    and np.array_equal(pa[~np.isnan(pa)],
                                       pb[~np.isnan(pb)]))
                if not eq:
                    return CertResult(
                        claim.name, "order", False, math.inf, 0.0,
                        f"byte-identity claim but {counts[0]} vs {n} "
                        f"devices differ", tuple(counts))
            else:
                worst = max(worst, _rel_dev(a, b))
    ok = worst <= claim.tolerance
    return CertResult(claim.name, "order", ok, worst, claim.tolerance,
                      f"max rel deviation {worst:.3g} across device "
                      f"counts {tuple(counts)}", tuple(counts))


# ---------------------------------------------------------------------------
# certify
# ---------------------------------------------------------------------------

_MEMO: Optional[List[CertResult]] = None


def ensure_virtual_devices() -> None:
    """Ask XLA for 8 virtual CPU devices if the backend is not up yet
    (harmless once initialized; tier-1's conftest does the same)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def certify_all(force: bool = False) -> List[CertResult]:
    """Certify every registered claim. Memoized per process."""
    global _MEMO
    if _MEMO is not None and not force:
        return _MEMO
    ensure_virtual_devices()
    nmod.import_annotated_modules()
    import jax
    avail = len(jax.devices())
    counts = [d for d in DEVICE_COUNTS if d <= avail]
    out: List[CertResult] = []
    for name, claim in sorted(nmod.PRECISION.items()):
        entry = HARNESSES.get(name)
        if entry is None or entry[0] != "precision":
            out.append(CertResult(
                name, "precision", False, math.inf, claim.rel_ulps,
                "no certification harness registered — an annotation "
                "the rail cannot evaluate cannot ship"))
            continue
        try:
            prod, ref, floor = entry[1]()
            out.append(_measure_precision(claim, prod, ref, floor))
        except Exception as e:  # noqa: BLE001 — a gate must not crash
            out.append(CertResult(name, "precision", False, math.inf,
                                  claim.rel_ulps, f"harness crashed: "
                                  f"{type(e).__name__}: {e}"))
    for name, claim in sorted(nmod.ORDER.items()):
        entry = HARNESSES.get(name)
        if entry is None or entry[0] != "order":
            out.append(CertResult(
                name, "order", False, math.inf, claim.tolerance,
                "no certification harness registered"))
            continue
        if len(counts) < 2:
            out.append(CertResult(
                name, "order", False, math.inf, claim.tolerance,
                f"only {avail} device(s) available — an order claim "
                f"needs at least two device counts to certify"))
            continue
        try:
            out.append(_measure_order(claim, entry[1], counts))
        except Exception as e:  # noqa: BLE001
            out.append(CertResult(name, "order", False, math.inf,
                                  claim.tolerance, f"harness crashed: "
                                  f"{type(e).__name__}: {e}"))
    _MEMO = out
    return out


def _claim_anchor(claim, mods) -> Tuple[Optional[str], int]:
    relpath = claim.module.replace(".", "/") + ".py"
    for mod in mods or ():
        if mod.relpath == relpath:
            needle = claim.name
            for i, line in enumerate(mod.lines, start=1):
                if needle in line:
                    return relpath, i
            return relpath, 1
    return relpath, 1


def check_certifications(mods=None
                         ) -> List[Tuple[Optional[str], Finding]]:
    """Lint-facing entry: one finding per failed certification."""
    out: List[Tuple[Optional[str], Finding]] = []
    for res in certify_all():
        if res.ok:
            continue
        claim = nmod.PRECISION.get(res.name) or nmod.ORDER.get(res.name)
        relpath, line = _claim_anchor(claim, mods)
        out.append((relpath, Finding(
            rule="ulp-certification", path=relpath or "?", line=line,
            message=(f"annotation {res.name!r} ({res.kind}) failed "
                     f"certification: measured {res.measured:.3g} vs "
                     f"claimed {res.claimed:.3g} — {res.detail}"),
            context=f"ulpcert:{res.name}")))
    return out


# ---------------------------------------------------------------------------
# in-tree harnesses
# ---------------------------------------------------------------------------
#
# Each harness builds SEEDED inputs shaped by the claim's static bound
# (dense tiles, monotone counters, windows with >= 2 samples, branch
# conditions away from knife edges) so certification is deterministic.

_SEED = 0x0DD5


def _counter_world(jitter: bool = True):
    """Shared synthetic world: [N, S] transposed dense counter tiles
    with large-magnitude values (the catastrophic-cancellation regime
    the f64 value channel exists for)."""
    import numpy as np
    rng = np.random.default_rng(_SEED)
    N, S = 128, 8
    dt = 10_000
    base = 1_700_000_000_000
    jit_ms = rng.integers(-2000, 2001, (N, S)) if jitter \
        else np.zeros((N, S), dtype=np.int64)
    ts = base + np.arange(N, dtype=np.int64)[:, None] * dt + jit_ms
    # counters starting at ~1e12 with ~O(10) increments: deltas are
    # exact in f64, catastrophically cancelled in a pure-f32 channel
    v = (1e12 + rng.uniform(0, 1e3, S)[None, :]
         + np.cumsum(rng.uniform(1.0, 20.0, (N, S)), axis=0))
    grid = dict(num_slots=N, base=base, dt=dt,
                w0s=base + 20 * dt + 1_500, w0e=base + 26 * dt + 1_500,
                step=2 * dt, nsteps=16)
    return ts, v, grid


def _ref_windows(ts, v, grid, func="rate"):
    """Pure-Python per-window reference (promql/refeval semantics) →
    [T, S] f64."""
    import numpy as np

    from filodb_tpu.promql.refeval import eval_range_fn
    T, S = grid["nsteps"], ts.shape[1]
    out = np.full((T, S), np.nan)
    for s in range(S):
        ts_l = [int(x) for x in ts[:, s]]
        v_l = [float(x) for x in v[:, s]]
        for t in range(T):
            we = grid["w0e"] + t * grid["step"]
            ws = grid["w0s"] + t * grid["step"]
            out[t, s] = eval_range_fn(func, ts_l, v_l, ws, we)
    return out


@precision_harness("counter-exact-slot-index")
def _h_counter_exact():
    import numpy as np

    from filodb_tpu.query.tilestore import _eval_counter_t
    ts, v, g = _counter_world()
    import jax.numpy as jnp
    arrs = {"ts": jnp.asarray(ts, jnp.float64), "ff_v": jnp.asarray(v)}
    prod = np.asarray(_eval_counter_t(
        "rate", g["nsteps"], arrs, g["num_slots"], g["base"], g["dt"],
        g["w0s"], g["w0e"], g["step"]))
    return prod, _ref_windows(ts, v, g), 0.0


@precision_harness("counter-fast-hybrid")
def _h_counter_fast():
    import numpy as np

    from filodb_tpu.query.tilestore import (_eval_counter_fast,
                                            _eval_counter_t)
    ts, v, g = _counter_world()
    import jax.numpy as jnp
    tsr = (ts - g["base"]).astype(np.int32)
    prod = np.asarray(_eval_counter_fast(
        "rate", g["nsteps"], {"tsr": jnp.asarray(tsr),
                              "ff_v": jnp.asarray(v)},
        g["num_slots"], np.int64(g["base"]), g["dt"],
        np.int64(g["w0s"]), np.int64(g["w0e"]), np.int64(g["step"])))
    ref = np.asarray(_eval_counter_t(
        "rate", g["nsteps"], {"ts": jnp.asarray(ts, jnp.float64),
                              "ff_v": jnp.asarray(v)},
        g["num_slots"], g["base"], g["dt"], g["w0s"], g["w0e"],
        g["step"]))
    return prod, ref, 0.0


@precision_harness("counter-slide-hybrid")
def _h_counter_slide():
    import numpy as np

    from filodb_tpu.query.tilestore import (_eval_counter_slide,
                                            _eval_counter_t)
    ts, v, g = _counter_world(jitter=False)     # regular grid: st = 2
    import jax.numpy as jnp
    st = g["step"] // g["dt"]
    N, S = ts.shape

    def perm(a, dtype):
        G = -(-N // st) + g["nsteps"] + 4
        pad = G * st - N
        ap = np.concatenate([a, np.zeros((pad, S), a.dtype)], axis=0)
        return jnp.asarray(
            ap.reshape(G, st, S).swapaxes(0, 1).astype(dtype))

    tsr = (ts - g["base"]).astype(np.int32)
    arrs = {"tsr_p": perm(tsr, np.int32), "ff_v_p": perm(v, np.float64)}
    prod = np.asarray(_eval_counter_slide(
        "rate", g["nsteps"], st, arrs, g["num_slots"],
        np.int64(g["base"]), g["dt"], np.int64(g["w0s"]),
        np.int64(g["w0e"]), np.int64(g["step"])))
    ref = np.asarray(_eval_counter_t(
        "rate", g["nsteps"], {"ts": jnp.asarray(ts, jnp.float64),
                              "ff_v": jnp.asarray(v)},
        g["num_slots"], g["base"], g["dt"], g["w0s"], g["w0e"],
        g["step"]))
    return prod, ref, 0.0


@precision_harness("counter-epilogue-f32")
def _h_epilogue():
    """_f32_epilogue vs the f64 reference formula. Inputs keep the
    extrapolation branches away from knife edges (dstart/dend well
    under threshold, dzero far above) so production and reference take
    the SAME branch and only rounding differs."""
    import numpy as np

    import jax.numpy as jnp

    from filodb_tpu.query.tilestore import _f32_epilogue
    rng = np.random.default_rng(_SEED + 1)
    T, S = 48, 8
    counts = rng.integers(5, 50, (T, S)).astype(np.int32)
    wstart = (np.arange(T, dtype=np.int64)[:, None] * 60_000)
    wdur = 300_000
    wend = wstart + wdur
    t1 = (wstart + rng.integers(100, 400, (T, S))).astype(np.int64)
    t2 = (wend - rng.integers(100, 400, (T, S))).astype(np.int64)
    v1 = 1e6 + rng.uniform(0, 1e3, (T, S))
    v2 = v1 + rng.uniform(5.0, 500.0, (T, S))
    prod = np.asarray(_f32_epilogue(
        "rate", jnp.asarray(counts), jnp.asarray(t1, jnp.int32),
        jnp.asarray(v1), jnp.asarray(t2, jnp.int32), jnp.asarray(v2),
        jnp.asarray(wstart, jnp.int32), jnp.asarray(wend, jnp.int32),
        jnp.float32(wdur / 1000.0)))
    # f64 reference, same formula
    delta = v2 - v1
    sampled = (t2 - t1) / 1000.0
    dstart = (t1 - wstart) / 1000.0
    dend = (wend - t2) / 1000.0
    avg_dur = sampled / (counts - 1.0)
    dzero = np.where((delta > 0) & (v1 >= 0),
                     sampled * (v1 / np.where(delta == 0, np.nan,
                                              delta)), np.inf)
    dstart = np.minimum(dstart, dzero)
    thresh = avg_dur * 1.1
    extrap = sampled \
        + np.where(dstart < thresh, dstart, avg_dur * 0.5) \
        + np.where(dend < thresh, dend, avg_dur * 0.5)
    ref = delta * (extrap / sampled) / (wdur / 1000.0)
    ref = np.where(counts >= 2, ref, np.nan)
    return prod, ref, 0.0


@precision_harness("fixed-point-split")
def _h_fixed_split():
    """The 61-bit hi/lo split + the kernel's f32 recombine
    (dh*2^(31-s) + dl*2^-s) vs the direct f64 boundary delta, with the
    declared span*2^-59 quantization floor."""
    import numpy as np

    from filodb_tpu.query.tilestore import AlignedTiles
    rng = np.random.default_rng(_SEED + 2)
    N, S = 64, 8
    dt = 10_000
    base = 0
    ts = (np.arange(N, dtype=np.int64)[:, None] * dt
          + np.zeros((1, S), np.int64)).T * 1.0      # [S, N] exact grid
    # mixed magnitudes: huge counters, small gauges, negatives
    scales = np.array([1e12, 1e6, 1.0, 1e-3, 5e8, 42.0, 1e10, 7.0])
    vals = (scales[:, None]
            * (1.0 + np.cumsum(rng.uniform(0, 1e-4, (S, N)), axis=1)))
    vals[2] = rng.uniform(-50, 50, N)                # sign-mixed gauge
    valid = np.ones((S, N), dtype=bool)
    tiles = AlignedTiles([{"i": str(i)} for i in range(S)], base, dt,
                         valid, ts, vals)
    fx = tiles._fixed_channels("v")
    assert fx is not None
    hi, lo, _mid, s = (np.asarray(x) for x in fx)    # [N, S], [S]
    c1 = np.ldexp(np.float32(1.0), 31 - s).astype(np.float32)
    c2 = np.ldexp(np.float32(1.0), -s).astype(np.float32)
    i, j = 10, 50                                    # boundary pair
    dh = (hi[j] - hi[i]).astype(np.float32)
    dl = (lo[j] - lo[i]).astype(np.float32)
    prod = dh * c1 + dl * c2                         # [S] f32
    ref = (vals[:, j] - vals[:, i])                  # [S] f64
    span = vals.max(axis=1) - vals.min(axis=1)
    floor = span * 2.0 ** -59
    return prod, ref, floor


@precision_harness("groupsum-recombine-f32")
def _h_groupsum_recombine():
    """The group-sum kernel's recombine (pallas_kernels._groupsum_kernel
    lines around `delta = dh * c1 + dl * c2`): exact int32 hi/lo deltas
    over FULL-SPAN boundary pairs (dl wide enough to round in f32),
    recombined in f32, vs the direct f64 delta."""
    import numpy as np

    from filodb_tpu.query.tilestore import AlignedTiles
    rng = np.random.default_rng(_SEED + 6)
    N, S = 64, 8
    dt = 10_000
    ts = (np.arange(N, dtype=np.int64)[None, :] * dt
          + np.zeros((S, 1), np.int64)) * 1.0
    scales = np.array([1e12, 1e6, 1.0, 1e-3, 5e8, 42.0, 1e10, 7.0])
    vals = (scales[:, None]
            * (1.0 + np.cumsum(rng.uniform(0, 0.2, (S, N)), axis=1)))
    valid = np.ones((S, N), dtype=bool)
    tiles = AlignedTiles([{"i": str(i)} for i in range(S)], 0, dt,
                         valid, ts, vals)
    fx = tiles._fixed_channels("v")
    assert fx is not None
    hi, lo, _mid, s = (np.asarray(x) for x in fx)
    c1 = np.ldexp(np.float32(1.0), 31 - s).astype(np.float32)
    c2 = np.ldexp(np.float32(1.0), -s).astype(np.float32)
    i, j = 0, N - 1                 # widest boundary pair in the tile
    dh = (hi[j] - hi[i]).astype(np.float32)
    dl = (lo[j] - lo[i]).astype(np.float32)
    prod = dh * c1 + dl * c2
    ref = vals[:, j] - vals[:, i]
    span = vals.max(axis=1) - vals.min(axis=1)
    return prod, ref, span * 2.0 ** -59


@precision_harness("extrapolated-rate-f64")
def _h_extrapolated_rate():
    """tpu._extrapolated_rate (the shared f64 formula) vs the
    pure-Python reference loop (promql/refeval._extrapolated) on the
    same boundary tuples."""
    import numpy as np

    import jax.numpy as jnp

    from filodb_tpu.promql.refeval import _extrapolated
    from filodb_tpu.query.tpu import _extrapolated_rate
    rng = np.random.default_rng(_SEED + 7)
    T, S = 32, 8
    wstart = np.arange(T, dtype=np.int64)[:, None] * 60_000
    wend = wstart + 300_000
    counts = rng.integers(2, 40, (T, S))
    t1 = wstart + rng.integers(50, 2_000, (T, S))
    t2 = wend - rng.integers(50, 2_000, (T, S))
    v1 = 1e9 + rng.uniform(0, 1e3, (T, S))
    v2 = v1 + rng.uniform(0.0, 800.0, (T, S))
    prod = np.asarray(_extrapolated_rate(
        jnp.asarray(wstart, jnp.float64), jnp.asarray(wend, jnp.float64),
        jnp.asarray(counts), jnp.asarray(t1, jnp.float64),
        jnp.asarray(v1), jnp.asarray(t2, jnp.float64), jnp.asarray(v2),
        True, True))
    ref = np.full((T, S), np.nan)
    for t in range(T):
        for si in range(S):
            n = int(counts[t, si])
            sts = [int(t1[t, si])] + [int(t1[t, si])] * max(n - 2, 0) \
                + [int(t2[t, si])]
            svs = [float(v1[t, si])] * max(n - 1, 1) \
                + [float(v2[t, si])]
            ref[t, si] = _extrapolated(
                int(wstart[t, 0]), int(wend[t, 0]), sts[:n], svs[:n],
                is_counter=True, is_rate=True) if n >= 2 else np.nan
    return prod, ref, 0.0


@precision_harness("append-carry-exact")
def _h_append_carry():
    """Donated append vs from-scratch rebuild, reset-free block:
    bitwise (the annotation's exact claim)."""
    import numpy as np

    import jax.numpy as jnp

    from filodb_tpu.parallel.shardstore import _append_step
    rng = np.random.default_rng(_SEED + 3)
    C, S, n, K = 64, 8, 40, 12
    v_full = np.cumsum(rng.uniform(0.5, 10.0, (n + K, S)), axis=0) + 1e9
    tsr = np.zeros((C, S), np.int32)
    v = np.zeros((C, S))
    cv = np.zeros((C, S))
    v[:n] = v_full[:n]
    cv[:n] = v_full[:n]            # no resets: corrected == raw
    new_tsr = np.arange(K, dtype=np.int32)[:, None] + np.zeros(
        (1, S), np.int32)
    out_tsr, out_v, out_cv = _append_step(
        jnp.asarray(tsr), jnp.asarray(v), jnp.asarray(cv),
        jnp.asarray(new_tsr), jnp.asarray(v_full[n:]), n)
    prod = np.asarray(out_cv)[n:n + K]
    ref = v_full[n:]               # rebuild: no resets -> cv == v
    return prod, ref, 0.0


def _shard_mesh(ndev: int):
    import numpy as np

    import jax
    from jax.sharding import Mesh
    devs = np.asarray(jax.devices()[:ndev]).reshape(ndev, 1)
    return Mesh(devs, ("shard", "time"))


@order_harness("grouped-reduce-psum")
def _h_grouped_reduce(ndev: int):
    import numpy as np

    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from filodb_tpu.parallel.mesh import _grouped_reduce, _shard_map
    rng = np.random.default_rng(_SEED + 4)
    S, T, G = 16, 12, 4
    local = rng.normal(0, 1e3, (S, T))
    local[rng.random((S, T)) < 0.1] = np.nan         # stale entries
    gids = rng.integers(0, G, S).astype(np.int32)
    gids[-2:] = -1                                    # padding rows
    mesh = _shard_mesh(ndev)
    outs = []
    for agg in ("sum", "avg"):
        def body(loc, g):
            return _grouped_reduce(loc, g, G, agg)
        f = _shard_map(
            body, mesh=mesh, in_specs=(P("shard", None), P("shard")),
            out_specs=P(), check_vma=False)
        outs.append(np.asarray(f(jnp.asarray(local),
                                 jnp.asarray(gids))))
    return tuple(outs)


@order_harness("grouped-pair-psum")
def _h_grouped_pair(ndev: int):
    import numpy as np

    import jax.numpy as jnp

    from filodb_tpu.parallel.shardstore import _build_grouped_pair_eval
    ts, v, g = _counter_world()
    S = ts.shape[1]
    rng = np.random.default_rng(_SEED + 5)
    gids = rng.integers(0, 3, S).astype(np.int32)
    tsr = (ts - g["base"]).astype(np.int32)
    run = _build_grouped_pair_eval(_shard_mesh(ndev), "rate",
                                   g["nsteps"], 3)
    sums, cnts = run(jnp.asarray(tsr), jnp.asarray(v),
                     jnp.asarray(gids), np.int64(g["num_slots"]),
                     np.int64(g["base"]), np.int64(g["dt"]),
                     np.int64(g["w0s"]), np.int64(g["w0e"]),
                     np.int64(g["step"]))
    return np.asarray(sums), np.asarray(cnts)
