"""Device-dataflow layer over the project call graph (graftlint v3).

The PR 7 engine (``callgraph.py``) answers "who calls whom, holding
which locks". This layer adds the *value*-level facts the SPMD and
cache families need, still as pure AST work:

  * **Entry points** — every ``jax.jit`` / ``pjit`` / ``shard_map`` /
    ``pallas_call`` wrapping site in the project (decorator form,
    ``functools.partial`` form, and direct-call form), with its parsed
    mesh axes, ``in_specs``/``out_specs`` PartitionSpecs, static
    argument names, and ``donate_argnums``/``donate_argnames``.
  * **Per-site closure** — the functions reachable from each entry
    point's body over call/callback edges: the code that actually runs
    under that trace, across modules.
  * **Static-ness propagation** — which parameters of closure functions
    are trace-static (bound from ``static_argnames``, constants, or
    other static names, including through lexical nesting): Python
    control flow on a static value is uniform across devices; control
    flow on anything else is where collectives go to deadlock.
  * **Donation bindings** — which local/module/attribute names hold a
    donating jitted callable, and the argument expressions at each of
    its call sites (the donation-safety rule's input).
  * **Listener bridges** — classes that collect callbacks
    (``subscribe``/``add_*_listener`` registrars appending a function
    parameter to instance state) and later dispatch them (iterating the
    same container and calling the elements). The AST cannot resolve
    ``for cb in self._subscribers: cb(ev)``; the bridge pairs each
    dispatcher with the callbacks registered at project call sites of
    the matching registrar, giving ``reaches()`` the edge an event
    needs to travel from a mutation publisher through a subscription to
    a cache's invalidation hook.

Everything is derived from the shared :class:`~filodb_tpu.lint.
callgraph.CallGraph`; nothing is imported or executed.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import (Dict, FrozenSet, List, Optional, Sequence, Set,
                    Tuple)

from filodb_tpu.lint import ModuleSource
from filodb_tpu.lint import callgraph as cgmod

# collective primitives that synchronize across a named mesh axis: every
from filodb_tpu.lint.astwalk import walk_nodes
# participant must execute the same sequence or the program deadlocks
# (multi-host) or silently computes over a partial group
COLLECTIVE_LEAVES = frozenset({
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "pbroadcast", "pdot",
})

# host-identity reads: Python control flow on these is *guaranteed* to
# diverge across processes in a multi-controller deployment
_HOST_DIVERGENT_LEAVES = frozenset({
    "process_index", "host_id", "gethostname", "getpid", "urandom",
    "random", "randint", "choice",
})

_STRUCTURED_CONTROL = frozenset({"cond", "switch", "while_loop"})

_SPMD_WRAPPERS = ("jit", "pjit", "shard_map", "pallas_call", "pmap")


def _dotted(node) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _leaf(node) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _wrapper_kind(fn_expr) -> Optional[str]:
    """'jit' / 'shard_map' / 'pallas_call' when the expression names a
    tracing wrapper, else None."""
    d = _dotted(fn_expr) or ""
    leaf = d.rsplit(".", 1)[-1]
    if "shard_map" in leaf:
        return "shard_map"
    if leaf in ("jit", "pjit"):
        return "jit"
    if leaf == "pallas_call":
        return "pallas_call"
    if leaf == "pmap":
        return "shard_map"      # same balance semantics: mapped axis
    return None


# -- PartitionSpec parsing ----------------------------------------------------

@dataclass
class SpecInfo:
    """One parsed ``P(...)`` / ``None`` spec literal."""
    axes: Tuple[str, ...] = ()      # axis names mentioned
    arity: Optional[int] = None     # positional entries declared
    known: bool = False
    line: int = 0
    bad_entries: Tuple[str, ...] = ()   # non-str/int/None constants
    # POSITIONAL axis indices (jax positional-PartitionSpec semantics:
    # n = n-th mesh axis name, a single -1 = every axis not otherwise
    # mentioned) — resolved against the site's mesh axis ORDER
    pos_entries: Tuple[int, ...] = ()


def resolve_positional(spec: "SpecInfo",
                       order: Optional[Tuple[str, ...]]
                       ) -> Tuple[Tuple[str, ...], List[str]]:
    """(resolved axis names, problems) of a spec's positional entries
    against an ordered mesh-axis tuple. With no order known, nothing
    resolves and nothing is flagged; the -1-repeated and
    out-of-range error cases mirror the runtime resolver
    (parallel/mesh.resolve_spec)."""
    problems: List[str] = []
    if sum(1 for i in spec.pos_entries if i == -1) > 1:
        problems.append("-1 appears more than once in one PartitionSpec")
    if order is None:
        return (), problems
    names: List[str] = []
    mentioned = set(spec.axes)
    for i in spec.pos_entries:
        if i != -1:
            if not -len(order) <= i < len(order):
                problems.append(
                    f"positional index {i} out of range for mesh axes "
                    f"{order}")
            else:
                mentioned.add(order[i])
    for i in spec.pos_entries:
        if i == -1:
            names.extend(n for n in order if n not in mentioned)
        elif -len(order) <= i < len(order):
            names.append(order[i])
    return tuple(names), problems


def parse_spec(expr) -> SpecInfo:
    line = getattr(expr, "lineno", 0)
    if isinstance(expr, ast.Constant) and expr.value is None:
        return SpecInfo(axes=(), arity=0, known=True, line=line)
    if isinstance(expr, ast.Call):
        leaf = _leaf(expr.func)
        if leaf in ("P", "PartitionSpec"):
            axes: List[str] = []
            bad: List[str] = []
            pos: List[int] = []

            def harvest(el) -> None:
                if isinstance(el, ast.UnaryOp) \
                        and isinstance(el.op, ast.USub) \
                        and isinstance(el.operand, ast.Constant) \
                        and isinstance(el.operand.value, int) \
                        and not isinstance(el.operand.value, bool):
                    pos.append(-el.operand.value)   # e.g. the -1 form
                    return
                if not isinstance(el, ast.Constant):
                    return      # Name/expr entries: unknown, still a P
                v = el.value
                if isinstance(v, str):
                    axes.append(v)
                elif isinstance(v, bool):
                    bad.append(repr(v))
                elif isinstance(v, int):
                    pos.append(v)
                elif v is not None:
                    bad.append(repr(v))

            for a in expr.args:
                if isinstance(a, (ast.Tuple, ast.List)):
                    for el in a.elts:
                        harvest(el)
                else:
                    harvest(a)
            return SpecInfo(axes=tuple(axes), arity=len(expr.args),
                            known=True, line=line,
                            bad_entries=tuple(bad),
                            pos_entries=tuple(pos))
    return SpecInfo(line=line)


def parse_specs_arg(expr) -> Tuple[Optional[List[SpecInfo]], List[SpecInfo]]:
    """Parse an ``in_specs``/``out_specs`` kwarg. Returns
    ``(spec_list, all_specs)`` — ``spec_list`` is positional (one entry
    per argument) when the literal is a tuple/list, else None;
    ``all_specs`` is every P literal found (axis harvesting)."""
    if expr is None:
        return None, []
    if isinstance(expr, (ast.Tuple, ast.List)):
        specs = [parse_spec(e) for e in expr.elts]
        return specs, specs
    s = parse_spec(expr)
    return None, [s]


# -- mesh axis resolution -----------------------------------------------------

def _mesh_axes_of_call(call: ast.Call) -> Optional[Tuple[str, ...]]:
    """Axis names of a ``Mesh(devs, ("a", "b"))`` construction."""
    if _leaf(call.func) != "Mesh":
        return None
    cand = None
    if len(call.args) >= 2:
        cand = call.args[1]
    for kw in call.keywords:
        if kw.arg == "axis_names":
            cand = kw.value
    if isinstance(cand, (ast.Tuple, ast.List)):
        axes = [e.value for e in cand.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
        if axes:
            return tuple(axes)
    if isinstance(cand, ast.Constant) and isinstance(cand.value, str):
        return (cand.value,)
    return None


class MeshIndex:
    """Mesh constructions per module: variable bindings, mesh-returning
    functions, and the module/project axis universes."""

    def __init__(self, mods: Sequence[ModuleSource]):
        # module -> var name -> axes
        self.vars: Dict[str, Dict[str, Tuple[str, ...]]] = {}
        # module -> function name -> axes (functions returning Mesh(...))
        self.makers: Dict[str, Dict[str, Tuple[str, ...]]] = {}
        self.module_axes: Dict[str, Set[str]] = {}
        self.project_axes: Set[str] = set()
        # module -> distinct ORDERED axis tuples of its Mesh literals:
        # when a module declares exactly one order, positional
        # PartitionSpec indices resolve against it
        self.module_orders: Dict[str, Set[Tuple[str, ...]]] = {}
        self.project_orders: Set[Tuple[str, ...]] = set()
        for mod in mods:
            dotted = cgmod.module_dotted(mod.relpath)
            mvars: Dict[str, Tuple[str, ...]] = {}
            makers: Dict[str, Tuple[str, ...]] = {}
            axes_here: Set[str] = set()
            orders_here: Set[Tuple[str, ...]] = set()
            for node in walk_nodes(mod.tree):
                if isinstance(node, ast.Call):
                    axes = _mesh_axes_of_call(node)
                    if axes:
                        axes_here.update(axes)
                        orders_here.add(axes)
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    axes = _mesh_axes_of_call(node.value)
                    if axes:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                mvars[t.id] = axes
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    for sub in walk_nodes(node):
                        if isinstance(sub, ast.Return) and \
                                isinstance(sub.value, ast.Call):
                            axes = _mesh_axes_of_call(sub.value)
                            if axes:
                                makers[node.name] = axes
            self.vars[dotted] = mvars
            self.makers[dotted] = makers
            self.module_axes[dotted] = axes_here
            self.project_axes |= axes_here
            self.module_orders[dotted] = orders_here
            self.project_orders |= orders_here

    def axis_order(self, module: str) -> Optional[Tuple[str, ...]]:
        """The unambiguous ordered axis tuple positional PartitionSpec
        indices resolve against: the module's single declared order,
        falling back to the project's single order, else None."""
        orders = self.module_orders.get(module) or set()
        if len(orders) == 1:
            return next(iter(orders))
        if not orders and len(self.project_orders) == 1:
            return next(iter(self.project_orders))
        return None

    def resolve(self, module: str, expr,
                local_assigns: Dict[str, ast.AST]) -> Optional[Tuple[str, ...]]:
        """Axes of a ``mesh=`` expression, best effort."""
        if isinstance(expr, ast.Call):
            axes = _mesh_axes_of_call(expr)
            if axes:
                return axes
            leaf = _leaf(expr.func)
            if leaf and leaf in self.makers.get(module, {}):
                return self.makers[module][leaf]
            for mk in self.makers.values():
                if leaf in mk:
                    return mk[leaf]
        if isinstance(expr, ast.Name):
            src = local_assigns.get(expr.id)
            if src is not None and src is not expr:
                return self.resolve(module, src, {})
            axes = self.vars.get(module, {}).get(expr.id)
            if axes:
                return axes
        return None


# -- SPMD entry points --------------------------------------------------------

@dataclass
class SpmdSite:
    """One jit/shard_map/pallas_call wrapping site."""
    kind: str                       # jit | shard_map | pallas_call
    module: str
    relpath: str
    line: int
    body_keys: Tuple[str, ...]      # FuncInfo keys of the wrapped body
    body_param_count: Optional[int] = None
    static_names: FrozenSet[str] = frozenset()
    donate_nums: Tuple[int, ...] = ()
    donate_names: Tuple[str, ...] = ()
    mesh_axes: Optional[Tuple[str, ...]] = None
    in_specs: Optional[List[SpecInfo]] = None       # positional list
    out_specs: Optional[List[SpecInfo]] = None
    all_specs: List[SpecInfo] = field(default_factory=list)
    out_specs_is_tuple: bool = False
    binding: Optional[str] = None   # name the wrapped callable binds to


def _static_names_from_kwargs(keywords) -> Set[str]:
    out: Set[str] = set()
    for kw in keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                out |= {e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
    return out


def _donate_from_kwargs(keywords) -> Tuple[Tuple[int, ...], Tuple[str, ...]]:
    nums: List[int] = []
    names: List[str] = []
    for kw in keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums.append(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums += [e.value for e in v.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, int)]
        elif kw.arg == "donate_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names.append(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                names += [e.value for e in v.elts
                          if isinstance(e, ast.Constant)
                          and isinstance(e.value, str)]
    return tuple(nums), tuple(names)


class DeviceDataflow:
    """SPMD entry points + per-function trace environments + donation
    bindings + listener bridges, over one CallGraph."""

    def __init__(self, mods: Sequence[ModuleSource], cg: cgmod.CallGraph):
        self.mods = list(mods)
        self.cg = cg
        self.mesh = MeshIndex(mods)
        self.sites: List[SpmdSite] = []
        # func key -> merged axis env over every site reaching it
        self.axes_env: Dict[str, Set[str]] = {}
        # func key -> True when reachable from at least one collective-
        # bearing (shard_map/pmap) context
        self.spmd_reachable: Set[str] = set()
        # func key -> True when reachable from any trace entry at all
        self.traced: Set[str] = set()
        # func key -> param name -> "static" | "dynamic" (absent=unknown)
        self.param_status: Dict[str, Dict[str, str]] = {}
        # (module, "name") or (module, "Cls.attr") -> donating SpmdSite
        self.donation_bindings: Dict[Tuple[str, str], SpmdSite] = {}
        self._funcinfo_by_node: Dict[int, cgmod.FuncInfo] = {
            id(fi.node): fi for fi in cg.funcs.values()}
        self._lambda_by_line: Dict[Tuple[str, int], str] = {}
        for key, fi in cg.funcs.items():
            if fi.name == "<lambda>":
                self._lambda_by_line.setdefault(
                    (fi.module, fi.lineno), key)
        # func key -> directly nested (lexical) function keys
        self._lexical_children: Dict[str, List[str]] = {}
        for key, fi in cg.funcs.items():
            if ".<locals>." in fi.qualname:
                pq = fi.qualname.rsplit(".<locals>.", 1)[0]
                self._lexical_children.setdefault(
                    f"{fi.module}:{pq}", []).append(key)
        self._discover_sites()
        self._compute_closures()
        self._propagate_static()
        self._build_bridges()

    # -- site discovery -----------------------------------------------------

    def _body_keys_for(self, mod_dotted: str, expr,
                       enclosing: Optional[cgmod.FuncInfo]) -> Tuple[str, ...]:
        """Resolve the wrapped-callable expression to FuncInfo keys."""
        if isinstance(expr, ast.Lambda):
            k = self._lambda_by_line.get((mod_dotted, expr.lineno))
            return (k,) if k else ()
        if isinstance(expr, ast.Call):
            # functools.partial(f, ...) — unwrap
            d = _dotted(expr.func) or ""
            if d.rsplit(".", 1)[-1] == "partial" and expr.args:
                return self._body_keys_for(mod_dotted, expr.args[0],
                                           enclosing)
            return ()
        name = _leaf(expr)
        if name is None:
            return ()
        keys = [k for k, fi in self.cg.funcs.items()
                if fi.module == mod_dotted and fi.name == name]
        if len(keys) > 1 and enclosing is not None:
            near = [k for k in keys
                    if self.cg.funcs[k].qualname.startswith(
                        enclosing.qualname)]
            if near:
                return tuple(near)
        return tuple(keys)

    def _discover_sites(self) -> None:
        for mod in self.mods:
            dotted = cgmod.module_dotted(mod.relpath)
            # local Name -> assigned value expr, for mesh resolution
            assigns: Dict[str, ast.AST] = {}
            for node in walk_nodes(mod.tree):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            assigns.setdefault(t.id, node.value)
            for node in walk_nodes(mod.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self._sites_from_decorators(mod, dotted, node, assigns)
                elif isinstance(node, ast.Call):
                    self._site_from_call(mod, dotted, node, assigns)

    def _sites_from_decorators(self, mod, dotted, node, assigns) -> None:
        fi = self._funcinfo_by_node.get(id(node))
        if fi is None:
            return
        for d in node.decorator_list:
            call = d if isinstance(d, ast.Call) else None
            target = call.func if call else d
            kind = _wrapper_kind(target)
            keywords = list(call.keywords) if call else []
            if kind is None and call is not None:
                # functools.partial(jax.jit, ...) decorator form
                dname = _dotted(call.func) or ""
                if dname.rsplit(".", 1)[-1] == "partial" and call.args:
                    kind = _wrapper_kind(call.args[0])
            if kind is None:
                continue
            self._add_site(mod, dotted, kind, getattr(d, "lineno",
                                                      node.lineno),
                           (fi.key,), keywords, assigns,
                           binding=node.name,
                           param_count=len(node.args.args)
                           + len(node.args.posonlyargs))

    def _site_from_call(self, mod, dotted, node: ast.Call, assigns) -> None:
        kind = _wrapper_kind(node.func)
        if kind is None or not node.args:
            return
        enclosing = self._enclosing_func(mod, node)
        body = self._body_keys_for(dotted, node.args[0], enclosing)
        binding = None
        param_count = None
        if body:
            bfi = self.cg.funcs.get(body[0])
            if bfi is not None and not isinstance(bfi.node, ast.Lambda):
                param_count = len(bfi.node.args.args) \
                    + len(bfi.node.args.posonlyargs)
            elif bfi is not None:
                param_count = len(bfi.node.args.args)
        self._add_site(mod, dotted, kind, node.lineno, body,
                       list(node.keywords), assigns, binding=binding,
                       param_count=param_count)

    def _enclosing_func(self, mod, node) -> Optional[cgmod.FuncInfo]:
        """The innermost FunctionDef lexically containing ``node`` (by
        line span, best effort)."""
        best = None
        line = getattr(node, "lineno", 0)
        for fi in self.cg.funcs.values():
            if fi.relpath != mod.relpath:
                continue
            end = getattr(fi.node, "end_lineno", fi.lineno)
            if fi.lineno <= line <= end:
                if best is None or fi.lineno > best.lineno:
                    best = fi
        return best

    def _add_site(self, mod, dotted, kind, line, body_keys, keywords,
                  assigns, binding=None, param_count=None) -> None:
        in_specs_expr = out_specs_expr = mesh_expr = None
        for kw in keywords:
            if kw.arg == "in_specs":
                in_specs_expr = kw.value
            elif kw.arg == "out_specs":
                out_specs_expr = kw.value
            elif kw.arg == "mesh":
                mesh_expr = kw.value
        in_list, in_all = parse_specs_arg(in_specs_expr)
        out_list, out_all = parse_specs_arg(out_specs_expr)
        nums, names = _donate_from_kwargs(keywords)
        site = SpmdSite(
            kind=kind, module=dotted, relpath=mod.relpath, line=line,
            body_keys=tuple(k for k in body_keys if k),
            body_param_count=param_count,
            static_names=frozenset(_static_names_from_kwargs(keywords)),
            donate_nums=nums, donate_names=names,
            mesh_axes=(self.mesh.resolve(dotted, mesh_expr, assigns)
                       if mesh_expr is not None else None),
            in_specs=in_list, out_specs=out_list,
            all_specs=in_all + out_all,
            out_specs_is_tuple=isinstance(out_specs_expr,
                                          (ast.Tuple, ast.List)),
            binding=binding)
        self.sites.append(site)

    # -- closures + axis env -------------------------------------------------

    def site_order(self, site: SpmdSite) -> Optional[Tuple[str, ...]]:
        """Ordered mesh axes positional spec indices resolve against at
        this site."""
        return site.mesh_axes or self.mesh.axis_order(site.module)

    def site_axes(self, site: SpmdSite) -> Set[str]:
        axes: Set[str] = set(site.mesh_axes or ())
        order = self.site_order(site)
        for s in site.all_specs:
            axes |= set(s.axes)
            if s.pos_entries:
                names, _ = resolve_positional(s, order)
                axes |= set(names)
        if not axes:
            axes |= self.mesh.module_axes.get(site.module, set())
        if not axes:
            axes |= self.mesh.project_axes
        return axes

    def closure_of(self, keys: Sequence[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = [k for k in keys if k in self.cg.funcs]
        while stack:
            k = stack.pop()
            if k in seen:
                continue
            seen.add(k)
            fi = self.cg.funcs[k]
            for s in fi.sites:
                if s.kind in ("call", "callback"):
                    for c in s.callees:
                        if c not in seen and c in self.cg.funcs:
                            stack.append(c)
            # lexically nested functions run under the same trace
            for k2 in self._lexical_children.get(k, ()):
                if k2 not in seen:
                    stack.append(k2)
        return seen

    def _compute_closures(self) -> None:
        self._site_closures: Dict[int, Set[str]] = {}
        for i, site in enumerate(self.sites):
            clo = self.closure_of(site.body_keys)
            self._site_closures[i] = clo
            axes = self.site_axes(site)
            for k in clo:
                self.traced.add(k)
                env = self.axes_env.setdefault(k, set())
                if site.kind in ("shard_map",):
                    self.spmd_reachable.add(k)
                    env |= axes
                elif axes:
                    env |= axes

    # -- static-ness --------------------------------------------------------

    def _params_of(self, fi: cgmod.FuncInfo) -> List[str]:
        node = fi.node
        if isinstance(node, ast.Lambda):
            a = node.args
        else:
            a = node.args
        out = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
        return out

    def _lexical_static(self, fi: cgmod.FuncInfo,
                        status: Dict[str, Dict[str, str]]) -> Set[str]:
        """Static names visible from lexical ancestors."""
        out: Set[str] = set()
        qual = fi.qualname
        while ".<locals>." in qual:
            qual = qual.rsplit(".<locals>.", 1)[0]
            pk = f"{fi.module}:{qual}"
            pfi = self.cg.funcs.get(pk)
            if pfi is None:
                continue
            st = status.get(pk, {})
            for p in self._params_of(pfi):
                if st.get(p) == "static":
                    out.add(p)
        return out

    def _propagate_static(self) -> None:
        status: Dict[str, Dict[str, str]] = {}
        # seeds: entry bodies get static_argnames; everything else unknown
        for site in self.sites:
            for bk in site.body_keys:
                fi = self.cg.funcs.get(bk)
                if fi is None:
                    continue
                st = status.setdefault(bk, {})
                for p in self._params_of(fi):
                    if p in site.static_names:
                        if st.get(p) != "dynamic":
                            st[p] = "static"
                    else:
                        st[p] = "dynamic"
        traced = self.traced
        # one AST pass per traced function, cached: the fixpoint rounds
        # below only re-evaluate the recorded (callees, args) tuples
        call_args: Dict[str, List[Tuple[Tuple[str, ...], List,
                                        List]]] = {}
        for k in traced:
            fi = self.cg.funcs.get(k)
            if fi is None:
                continue
            entries = []
            for node in walk_nodes(fi.node):
                if isinstance(node, ast.Call):
                    callee_keys = self._callees_at(fi, node.lineno)
                    if callee_keys:
                        entries.append((callee_keys, list(node.args),
                                        list(node.keywords)))
            call_args[k] = entries
        for _round in range(6):
            changed = False
            for k in traced:
                fi = self.cg.funcs.get(k)
                if fi is None:
                    continue
                st = status.setdefault(k, {})
                eff_static = {p for p, v in st.items() if v == "static"} \
                    | self._lexical_static(fi, status)
                for callee_keys, args, keywords in call_args.get(k, ()):
                    for ck in callee_keys:
                        cfi = self.cg.funcs.get(ck)
                        if cfi is None or ck not in traced:
                            continue
                        params = self._params_of(cfi)
                        drop_self = 1 if (cfi.cls and params
                                          and params[0] == "self") else 0
                        cst = status.setdefault(ck, {})
                        for i, a in enumerate(args):
                            pi = i + drop_self
                            if pi >= len(params):
                                break
                            p = params[pi]
                            s = self._arg_static(a, eff_static)
                            prev = cst.get(p)
                            new = self._meet(prev, s)
                            if new != prev:
                                cst[p] = new
                                changed = True
                        for kw in keywords:
                            if kw.arg and kw.arg in params:
                                s = self._arg_static(kw.value, eff_static)
                                prev = cst.get(kw.arg)
                                new = self._meet(prev, s)
                                if new != prev:
                                    cst[kw.arg] = new
                                    changed = True
            if not changed:
                break
        self.param_status = status

    @staticmethod
    def _meet(prev: Optional[str], new: str) -> str:
        if prev == "dynamic" or new == "dynamic":
            return "dynamic"
        if prev == "static" or new == "static":
            return "static"
        return new

    def _arg_static(self, expr, eff_static: Set[str]) -> str:
        if isinstance(expr, ast.Constant):
            return "static"
        if isinstance(expr, ast.Name):
            if expr.id in eff_static:
                return "static"
            # module-level constants / imports are trace-static
            # (they cannot vary per device within one build)
            return "dynamic"
        return "dynamic"

    def _callees_at(self, fi: cgmod.FuncInfo, line: int) -> Tuple[str, ...]:
        out: List[str] = []
        for s in fi.sites:
            if s.line == line and s.kind == "call":
                out.extend(s.callees)
        return tuple(out)

    # -- queries used by the SPMD rules -------------------------------------

    def dynamic_names(self, key: str) -> Set[str]:
        """Names inside ``key`` whose value can differ across devices /
        hosts: non-static params plus locals derived from them."""
        fi = self.cg.funcs.get(key)
        if fi is None:
            return set()
        st = self.param_status.get(key, {})
        dyn = {p for p in self._params_of(fi)
               if st.get(p, "unknown") == "dynamic" and p != "self"}
        # one derivation pass: locals assigned from dynamic reads
        for _ in range(2):
            grew = False
            for node in walk_nodes(fi.node):
                if isinstance(node, ast.Assign):
                    reads = {n.id for n in ast.walk(node.value)
                             if isinstance(n, ast.Name)}
                    if reads & dyn:
                        for t in node.targets:
                            if isinstance(t, ast.Name) \
                                    and t.id not in dyn:
                                dyn.add(t.id)
                                grew = True
            if not grew:
                break
        return dyn

    # -- listener bridges ---------------------------------------------------

    def _build_bridges(self) -> None:
        cg = self.cg
        # (class name, attr) -> registrar FuncInfo keys
        registrars: Dict[Tuple[str, str], List[str]] = {}
        # (class name, attr) -> dispatcher FuncInfo keys
        dispatchers: Dict[Tuple[str, str], List[str]] = {}
        for ci in cg._classes_by_mod.values():
            for mname, mfi in ci.methods.items():
                node = mfi.node
                params = {a.arg for a in node.args.args} - {"self"}
                for sub in walk_nodes(node):
                    # registrar: self.<attr>.append(<param>)
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr in ("append", "add") \
                            and isinstance(sub.func.value, ast.Attribute) \
                            and isinstance(sub.func.value.value, ast.Name) \
                            and sub.func.value.value.id == "self" \
                            and len(sub.args) == 1 \
                            and isinstance(sub.args[0], ast.Name) \
                            and sub.args[0].id in params:
                        registrars.setdefault(
                            (ci.name, sub.func.value.attr), []).append(
                                mfi.key)
                    # dispatcher: for cb in [list(]self.<attr>[)]: cb(...)
                    if isinstance(sub, ast.For) \
                            and isinstance(sub.target, ast.Name):
                        attr = self._self_attr_in_iter(sub.iter)
                        if attr is None:
                            continue
                        tgt = sub.target.id
                        for inner in ast.walk(sub):
                            if isinstance(inner, ast.Call) \
                                    and isinstance(inner.func, ast.Name) \
                                    and inner.func.id == tgt:
                                dispatchers.setdefault(
                                    (ci.name, attr), []).append(mfi.key)
                                break
        # registrar method name -> [(class, attr)] for unresolved calls
        by_name: Dict[str, List[Tuple[str, str]]] = {}
        reg_keys: Dict[str, Tuple[str, str]] = {}
        for (cls, attr), keys in registrars.items():
            for k in keys:
                reg_keys[k] = (cls, attr)
                by_name.setdefault(cg.funcs[k].name, []).append(
                    (cls, attr))
        # registered callbacks per (class, attr)
        callbacks: Dict[Tuple[str, str], Set[str]] = {}
        for fi in cg.funcs.values():
            call_sites = [s for s in fi.sites if s.kind == "call"]
            cb_sites = [s for s in fi.sites if s.kind == "callback"]
            for s in call_sites:
                target: Optional[Tuple[str, str]] = None
                for c in s.callees:
                    if c in reg_keys:
                        target = reg_keys[c]
                        break
                if target is None:
                    # unresolved receiver: accept a UNIQUE registrar name
                    name = s.label.rsplit(".", 1)[-1]
                    owners = by_name.get(name, [])
                    if len(set(owners)) == 1 and not s.callees:
                        target = owners[0]
                if target is None:
                    continue
                for s2 in cb_sites:
                    if s2.line == s.line:
                        callbacks.setdefault(target, set()).update(
                            s2.callees)
        # bridge edges: dispatcher -> registered callbacks
        self.bridge_edges: Dict[str, Set[str]] = {}
        for key, disp_keys in dispatchers.items():
            cbs = callbacks.get(key)
            if not cbs:
                continue
            for dk in disp_keys:
                self.bridge_edges.setdefault(dk, set()).update(cbs)

    def reaches(self, start: str, target: str,
                max_depth: int = 64) -> Optional[List[str]]:
        """A call-graph path (list of func keys) from ``start`` to
        ``target`` over call/callback/thread + bridge edges, or None."""
        if start == target:
            return [start]
        prev: Dict[str, str] = {}
        seen = {start}
        frontier = [start]
        depth = 0
        while frontier and depth < max_depth:
            depth += 1
            nxt: List[str] = []
            for k in frontier:
                fi = self.cg.funcs.get(k)
                succ: Set[str] = set(self.bridge_edges.get(k, ()))
                if fi is not None:
                    for s in fi.sites:
                        succ.update(s.callees)
                for c in succ:
                    if c in seen:
                        continue
                    seen.add(c)
                    prev[c] = k
                    if c == target:
                        path = [c]
                        while path[-1] != start:
                            path.append(prev[path[-1]])
                        return list(reversed(path))
                    nxt.append(c)
            frontier = nxt
        return None

    @staticmethod
    def _self_attr_in_iter(it) -> Optional[str]:
        """`self.<attr>` mentioned by a for-iter expression (directly,
        or through list(...)/tuple(...)/.values())."""
        cand = it
        if isinstance(cand, ast.Call):
            if isinstance(cand.func, ast.Name) \
                    and cand.func.id in ("list", "tuple", "sorted") \
                    and cand.args:
                cand = cand.args[0]
            elif isinstance(cand.func, ast.Attribute) \
                    and cand.func.attr == "values":
                cand = cand.func.value
        if isinstance(cand, ast.Attribute) \
                and isinstance(cand.value, ast.Name) \
                and cand.value.id == "self":
            return cand.attr
        return None


def build(mods: Sequence[ModuleSource],
          cg: Optional[cgmod.CallGraph] = None) -> DeviceDataflow:
    if cg is None:
        cg = cgmod.build(mods)
    return DeviceDataflow(mods, cg)
