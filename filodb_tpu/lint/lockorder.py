"""Canonical lock-acquisition order for the threaded subsystems.

The interprocedural engine derives the *observed* acquisition-order
graph (``CallGraph.order_pairs``); cycles in it are potential deadlocks
(``lock-order-cycle``) regardless of this table. The table adds a
*declared* order for the known hot locks: acquiring a lock that sits
EARLIER in the list while holding a later one is a
``lock-order-policy`` finding even before a second thread closes the
cycle — the policy keeps the order consistent so cycles cannot form as
the call graph grows.

The order is coordinator-out-to-leaf (coarse, long-lived coordination
locks first; fine, short-hold data locks last). A lock not listed here
is unconstrained relative to the table (cycle detection still covers
it). Lock names are the engine's canonical form: ``Cls.attr`` for
instance locks (one order node per class — the standard abstraction),
``pkg.mod:name`` for module globals.

When real code needs a new nesting, EXTEND the table (and think about
which side every existing pair lands on) rather than pragma-ing the
finding: the table is the documentation of record for "which lock may
I take while holding which".
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

# outermost (acquired first) .. innermost (acquired last, leaf)
CANONICAL_LOCK_ORDER: Tuple[str, ...] = (
    # host-level coordination: the worker supervisor's fleet state
    # (its methods never call into a worker's in-process locks — the
    # supervisor talks to workers over HTTP/bus only — but keep it
    # outermost so that invariant is policy, not accident)
    "Supervisor._lock",
    # node-level coordination: membership/handoff + crash reassignment
    "MembershipManager._lock",
    "FiloServer._reassign_lock",
    # serving-path subsystem locks
    "MicroBatcher._lock",
    "BreakerRegistry._lock",
    "PlanCache._lock",
    "ResultCache._lock",
    # memstore / device-store data locks
    "TpuBackend._exec_lock",
    "TpuBackend._tile_lock",
    "TimeSeriesShard._odp_lock",
    "TimeSeriesPartition._cache_lock",
    # tenant QoS (query/qos.py): the admission controller's gate
    # counters sit above the budget map, which sits above individual
    # bucket leaves (TenantBudgets.bucket() creates under the map lock;
    # snapshot() reads bucket counters while iterating the map)
    "AdmissionController._lock",
    "TenantBudgets._lock",
    "TokenBucket._lock",
    # leaves: short-hold counters, per-object state, channel caches
    "ShardMapper._lock",
    "CircuitBreaker._lock",
    "BatchStats._lock",
    "SplitResult._lock",
    # rules engine (filodb_tpu/rules): scheduler/election/alert state.
    # Evaluations and write-backs run strictly OUTSIDE it; while held
    # it only touches registry family leaves (below), so it sits above
    # the observability leaves and below every serving-path lock.
    "RulesEngine._lock",
    "WebhookNotifier._lock",
    # observability leaves: the self-monitor's tick counters, the
    # device profiler's executable table (compiles run OUTSIDE it),
    # and the metric registry's family maps (collect_into snapshots
    # under the lock, samples outside)
    "SelfMonitor._lock",
    "DeviceProfiler._lock",
    # the wall-clock sampling profiler's folded-stack tables and the
    # trace exporter's bounded queue: both export through registry
    # family leaves (below) and never call back up the stack
    "SamplingProfiler._lock",
    "TraceExporter._lock",
    "MetricsRegistry._lock",
    "CounterFamily._lock",
    "GaugeFamily._lock",
    "GrpcQueryServer._rpc_lock",
    "LogIngestionStream._lock",
    "MemoryIngestionStream._lock",
    "filodb_tpu.grpcsvc.client:_channels_lock",
    # control-plane bus (standalone/bus.py): registry locks release
    # before any socket send; per-connection send locks are pure leaves
    "SupervisorBus._lock",
    "BusClient._lock",
    "BusClient._send_lock",
)

_INDEX: Dict[str, int] = {name: i
                          for i, name in enumerate(CANONICAL_LOCK_ORDER)}


def policy_violation(held: str, acquired: str) -> Optional[str]:
    """Non-None (the message core) when acquiring ``acquired`` while
    holding ``held`` contradicts the canonical order. Pairs with a lock
    outside the table are unconstrained."""
    hi, ai = _INDEX.get(held), _INDEX.get(acquired)
    if hi is None or ai is None or ai > hi:
        return None
    return (f"acquires {acquired} (order #{ai}) while holding {held} "
            f"(order #{hi}) — canonical order is outermost-first; see "
            f"filodb_tpu/lint/lockorder.py")
