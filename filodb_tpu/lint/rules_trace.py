"""Trace-safety rules.

A module-local reachability analysis finds every function that can run
under a JAX trace — ``jax.jit`` / ``shard_map`` decorated or wrapped
functions, functions handed to ``jit(...)`` / ``shard_map(...)`` /
``pallas_call(...)`` (directly or through ``functools.partial``),
Pallas kernel bodies (any function with a ``*_ref`` parameter — the
Ref-passing convention all kernels here follow), and everything those
functions mention or lexically contain.

Inside traced functions the rules flag:

  * ``trace-side-effect`` — Python work that silently burns into the
    trace as a constant or runs once per (re)trace instead of per call:
    ``time.time()``-family reads, ``print``, stdlib/numpy ``random``,
    ``open``/``input``/``os.urandom``.
  * ``trace-tracer-leak`` — host escapes that crash or silently
    constant-fold under trace: ``.item()``, ``bool()/int()/float()`` on
    a non-static parameter (static ``static_argnames`` / partial-bound
    parameters are exempt), a bare tracer parameter interpolated into
    an f-string.
  * ``trace-mutate-capture`` — mutating a captured Python container
    (append/update/subscript-assign/``global``) on a name that is not
    local to the function or any lexically enclosing function: the
    mutation escapes the trace and happens once, at trace time, not per
    call. Closure-local accumulation (DMA lists, Ref stores captured
    from the enclosing kernel) is the normal Pallas/JAX idiom and is
    allowed.
  * ``trace-f64-constant`` — 64-bit dtypes (``float64``/``int64``)
    mentioned inside a Pallas kernel body; Mosaic cannot legalize
    64-bit vectors, which is why the wrappers trace under
    ``_enable_x64(False)``.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from filodb_tpu.lint import Finding, ModuleSource, register_rule

register_rule("trace-side-effect", "trace",
              "Python side effect inside a jit/shard_map/pallas-traced "
              "function")
from filodb_tpu.lint.astwalk import walk_nodes
register_rule("trace-tracer-leak", "trace",
              "tracer escapes to host: .item(), bool()/int()/float() "
              "coercion, or tracer in f-string")
register_rule("trace-mutate-capture", "trace",
              "mutation of a captured Python container inside a traced "
              "function")
register_rule("trace-f64-constant", "trace",
              "64-bit dtype inside a Pallas kernel body (Mosaic cannot "
              "legalize f64/i64 vectors)")

_TIME_FNS = {"time", "monotonic", "perf_counter", "sleep", "process_time",
             "time_ns", "monotonic_ns", "perf_counter_ns", "clock"}
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "popitem",
             "clear", "update", "setdefault", "add", "discard", "sort",
             "reverse", "write"}
_JIT_MARKERS = ("jit", "shard_map", "pmap")


def _dotted(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


@dataclass(eq=False)            # identity hash: nodes index sets/dicts
class FnInfo:
    node: ast.AST                     # FunctionDef | AsyncFunctionDef
    qualname: str
    params: List[str]
    static_params: Set[str] = field(default_factory=set)
    traced: bool = False
    pallas_body: bool = False
    parent: Optional["FnInfo"] = None
    locals_cache: Optional[Set[str]] = None


class _Index(ast.NodeVisitor):
    """Collect imports, function defs (with lexical parents), and
    trace roots."""

    def __init__(self) -> None:
        self.fns: List[FnInfo] = []
        self.by_node: Dict[ast.AST, FnInfo] = {}
        self.by_name: Dict[str, List[FnInfo]] = {}
        self.time_aliases: Set[str] = set()
        self.random_aliases: Set[str] = set()
        self.numpy_aliases: Set[str] = set()
        self.os_aliases: Set[str] = set()
        # local name -> (module, original) for from-imports
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.module_aliases: Set[str] = set()
        self._stack: List[FnInfo] = []

    # imports ---------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            local = a.asname or a.name.split(".")[0]
            root = a.name.split(".")[0]
            self.module_aliases.add(local)
            if root == "time":
                self.time_aliases.add(local)
            elif root == "random":
                self.random_aliases.add(local)
            elif root == "os":
                self.os_aliases.add(local)
            elif root == "numpy" or a.name in ("jax.numpy",):
                self.numpy_aliases.add(local)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        for a in node.names:
            local = a.asname or a.name
            self.from_imports[local] = (mod, a.name)
            if mod in ("jax", "jax.experimental") \
                    and a.name in ("numpy",):
                self.numpy_aliases.add(local)
            if mod.split(".")[0] in ("jax", "numpy", "functools", "os",
                                     "time", "random", "typing"):
                self.module_aliases.add(local)
        self.generic_visit(node)

    # functions -------------------------------------------------------
    def _params_of(self, node) -> List[str]:
        a = node.args
        names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    def _static_from_deco(self, deco: ast.expr) -> Set[str]:
        out: Set[str] = set()
        if isinstance(deco, ast.Call):
            for kw in deco.keywords:
                if kw.arg in ("static_argnames", "static_argnums") \
                        and isinstance(kw.value, (ast.Tuple, ast.List)):
                    for el in kw.value.elts:
                        if isinstance(el, ast.Constant) \
                                and isinstance(el.value, str):
                            out.add(el.value)
                elif kw.arg == "static_argnames" \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    out.add(kw.value.value)
        return out

    def _visit_fn(self, node) -> None:
        qual = ".".join([f.node.name for f in self._stack] + [node.name])
        info = FnInfo(node=node, qualname=qual,
                      params=self._params_of(node),
                      parent=self._stack[-1] if self._stack else None)
        for d in node.decorator_list:
            try:
                text = ast.unparse(d)
            except Exception:       # noqa: BLE001
                text = ""
            if any(m in text for m in _JIT_MARKERS):
                info.traced = True
                info.static_params |= self._static_from_deco(d)
        if any(p.endswith("_ref") for p in info.params):
            info.traced = True
            info.pallas_body = True
        self.fns.append(info)
        self.by_node[node] = info
        self.by_name.setdefault(node.name, []).append(info)
        self._stack.append(info)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # call-site roots: jit(f) / shard_map(f) / pallas_call(f) ----------
    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in ("jit", "pallas_call") or "shard_map" in leaf:
            static = self._static_from_deco(node)
            if node.args:
                self._mark_root(node.args[0], static,
                                pallas=(leaf == "pallas_call"))
        self.generic_visit(node)

    def _mark_root(self, arg: ast.expr, static: Set[str],
                   pallas: bool) -> None:
        target: Optional[str] = None
        bound = 0
        if isinstance(arg, ast.Name):
            target = arg.id
        elif isinstance(arg, ast.Call):
            fname = _dotted(arg.func) or ""
            if fname.rsplit(".", 1)[-1] == "partial" and arg.args:
                inner = arg.args[0]
                if isinstance(inner, ast.Name):
                    target = inner.id
                    bound = len(arg.args) - 1
        if target is None:
            return
        for info in self.by_name.get(target, ()):  # module-wide by name
            info.traced = True
            if pallas:
                info.pallas_body = True
            info.static_params |= set(info.params[:bound]) | static


def _reachable(index: _Index) -> Set[FnInfo]:
    """Fixpoint: roots + lexical children + name mentions."""
    reach: Set[FnInfo] = {f for f in index.fns if f.traced}
    changed = True
    while changed:
        changed = False
        for f in index.fns:
            if f in reach:
                continue
            # lexical containment: a def inside a traced function runs
            # under that trace (fori_loop bodies, pl.when branches)
            if f.parent is not None and f.parent in reach:
                # propagate pallas-body-ness to nested helpers
                f.pallas_body = f.pallas_body or f.parent.pallas_body
                reach.add(f)
                changed = True
        # mentions: a reachable function naming another function pulls
        # it in (helpers called, callbacks passed)
        for f in list(reach):
            for node in walk_nodes(f.node):
                if isinstance(node, ast.Name) \
                        and node.id in index.by_name:
                    for g in index.by_name[node.id]:
                        if g is not f and g not in reach:
                            g.pallas_body = g.pallas_body or f.pallas_body
                            reach.add(g)
                            changed = True
    return reach


def _locals_with_ancestors(info: FnInfo) -> Set[str]:
    """Locals of the function plus every lexical ancestor — the set of
    names whose mutation stays inside the trace closure."""
    out: Set[str] = set()
    cur: Optional[FnInfo] = info
    while cur is not None:
        out |= _locals_of(cur)
        cur = cur.parent
    return out


def _locals_of(info: FnInfo) -> Set[str]:
    if info.locals_cache is not None:
        return info.locals_cache
    out: Set[str] = set(info.params)

    def add_target(t: ast.expr) -> None:
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                add_target(el)
        elif isinstance(t, ast.Starred):
            add_target(t.value)

    for node in walk_nodes(info.node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                add_target(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            add_target(node.target)
        elif isinstance(node, ast.For):
            add_target(node.target)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            add_target(node.optional_vars)
        elif isinstance(node, ast.comprehension):
            add_target(node.target)
        elif isinstance(node, ast.NamedExpr):
            add_target(node.target)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                out.add(a.asname or a.name.split(".")[0])
    info.locals_cache = out
    return out


def _own_nodes(info: FnInfo, index: _Index) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested defs (they
    are checked as their own functions)."""
    stack = list(ast.iter_child_nodes(info.node))
    while stack:
        node = stack.pop()
        yield node
        if node in index.by_node:
            continue
        stack.extend(ast.iter_child_nodes(node))


def check_module(mod: ModuleSource) -> Iterable[Finding]:
    index = _Index()
    index.visit(mod.tree)
    reach = _reachable(index)
    findings: List[Finding] = []

    def emit(rule: str, node: ast.AST, info: FnInfo, msg: str) -> None:
        findings.append(Finding(
            rule=rule, path=mod.relpath,
            line=getattr(node, "lineno", 1), message=msg,
            context=f"{info.qualname}:{msg}"))

    for info in sorted(reach, key=lambda f: f.node.lineno):
        local = _locals_with_ancestors(info)
        tracers = set(info.params) - info.static_params
        # f-strings inside `raise` build a static error message at trace
        # time — the standard (and harmless) pattern; exempt them
        raise_fmt = {
            id(n) for r in walk_nodes(info.node) if isinstance(r, ast.Raise)
            for n in ast.walk(r) if isinstance(n, ast.FormattedValue)}
        for node in _own_nodes(info, index):
            if isinstance(node, ast.Call):
                self_check_call(node, info, index, local, tracers, emit)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    base = t
                    while isinstance(base, (ast.Subscript,
                                            ast.Attribute)):
                        base = base.value
                    if isinstance(t, ast.Subscript) \
                            and isinstance(base, ast.Name) \
                            and base.id not in local \
                            and base.id not in index.module_aliases:
                        emit("trace-mutate-capture", node, info,
                             f"subscript assignment mutates captured "
                             f"{base.id!r} at trace time")
            elif isinstance(node, ast.Global) and node.names:
                emit("trace-mutate-capture", node, info,
                     f"global mutation of {', '.join(node.names)} "
                     f"inside a traced function")
            elif isinstance(node, ast.FormattedValue):
                v = node.value
                if id(node) not in raise_fmt \
                        and isinstance(v, ast.Name) and v.id in tracers:
                    emit("trace-tracer-leak", node, info,
                         f"tracer parameter {v.id!r} interpolated into "
                         f"an f-string (formats the tracer object, not "
                         f"a value)")
            if info.pallas_body:
                if isinstance(node, ast.Attribute) \
                        and node.attr in ("float64", "int64") \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id in index.numpy_aliases:
                    emit("trace-f64-constant", node, info,
                         f"{node.value.id}.{node.attr} inside a Pallas "
                         f"kernel body")
                elif isinstance(node, ast.Constant) \
                        and node.value in ("float64", "int64"):
                    emit("trace-f64-constant", node, info,
                         f"dtype string {node.value!r} inside a Pallas "
                         f"kernel body")
    return findings


def self_check_call(node: ast.Call, info: FnInfo, index: _Index,
                    local: Set[str], tracers: Set[str], emit) -> None:
    dotted = _dotted(node.func)
    if dotted is None:
        # method call f().g() etc: still check mutator-on-captured-name
        return _check_mutator(node, info, index, local, emit)
    parts = dotted.split(".")
    base, leaf = parts[0], parts[-1]
    # side effects
    if dotted in ("print", "input", "open"):
        emit("trace-side-effect", node, info,
             f"{dotted}() inside a traced function")
        return
    if base in index.time_aliases and len(parts) == 2 \
            and leaf in _TIME_FNS:
        emit("trace-side-effect", node, info,
             f"{dotted}() reads the host clock at trace time")
        return
    if base in index.random_aliases and len(parts) >= 2:
        emit("trace-side-effect", node, info,
             f"stdlib random ({dotted}) inside a traced function — "
             f"use jax.random with an explicit key")
        return
    if base in index.numpy_aliases and len(parts) >= 3 \
            and parts[1] == "random":
        emit("trace-side-effect", node, info,
             f"numpy RNG ({dotted}) burns one draw into the trace — "
             f"use jax.random with an explicit key")
        return
    if base in index.os_aliases and leaf == "urandom":
        emit("trace-side-effect", node, info,
             f"{dotted}() inside a traced function")
        return
    fi = index.from_imports.get(dotted)
    if fi is not None:
        srcmod, orig = fi
        if srcmod == "time" and orig in _TIME_FNS:
            emit("trace-side-effect", node, info,
                 f"{orig}() (from time) reads the host clock at trace "
                 f"time")
            return
        if srcmod == "random":
            emit("trace-side-effect", node, info,
                 f"{orig}() (from random) inside a traced function")
            return
    # tracer leaks
    if dotted in ("bool", "int", "float") and len(node.args) == 1 \
            and isinstance(node.args[0], ast.Name) \
            and node.args[0].id in tracers:
        emit("trace-tracer-leak", node, info,
             f"{dotted}() coerces tracer parameter "
             f"{node.args[0].id!r} to a host value")
        return
    if isinstance(node.func, ast.Attribute) and leaf == "item" \
            and not node.args:
        emit("trace-tracer-leak", node, info,
             ".item() pulls a device value to host under trace")
        return
    _check_mutator(node, info, index, local, emit)


def _check_mutator(node: ast.Call, info: FnInfo, index: _Index,
                   local: Set[str], emit) -> None:
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr in _MUTATORS):
        return
    base = f.value
    if isinstance(base, ast.Name) and base.id not in local \
            and base.id not in index.module_aliases:
        emit("trace-mutate-capture", node, info,
             f"{base.id}.{f.attr}() mutates a captured container at "
             f"trace time")
