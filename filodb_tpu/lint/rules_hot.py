"""Hot-path rule: ``host-transfer-in-hot-loop``.

Flags device→host transfer calls inside functions marked as part of
the per-query serving fast path (decorated with
:func:`filodb_tpu.lint.hotpath.hot_path`, or named in a module-level
``__hot_path__`` tuple), including their lexically nested helpers.

Why: an ``np.asarray`` / ``.item()`` / ``.block_until_ready()`` /
``jax.device_get`` on a device array blocks the calling thread until
the device catches up AND holds the Python-side position in the async
dispatch pipeline — one stray sync in a per-query path turns
overlapped host/device execution back into lock-step round trips (the
exact regression the serving fast path removed). The checker cannot
prove an array is device-resident statically, so the rule is scoped to
explicitly-marked hot functions and every transfer-shaped call inside
them must either go away or carry a
``# graftlint: disable=host-transfer-in-hot-loop (reason)`` pragma
naming the deliberate sync point (e.g. the single amortized per-batch
conversion in ``SplitResult.get``).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from filodb_tpu.lint import Finding, ModuleSource, register_rule

register_rule(
    "host-transfer-in-hot-loop", "trace",
    "device->host transfer (np.asarray/.item()/block_until_ready/"
    "device_get) inside a @hot_path per-query function")

# call leaves that pull device data to host (or block on the device)
from filodb_tpu.lint.astwalk import walk_nodes
_TRANSFER_LEAVES = {"asarray", "array", "ascontiguousarray", "item",
                    "block_until_ready", "device_get", "tolist"}
# numpy-module transfer calls need a numpy alias base; these method
# names flag on ANY receiver (device arrays are the plausible receiver
# in hot-path code; pragma the exceptions)
_METHOD_LEAVES = {"item", "block_until_ready", "tolist"}


def _dotted(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _module_hot_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__hot_path__" \
                        and isinstance(node.value, (ast.Tuple, ast.List)):
                    for el in node.value.elts:
                        if isinstance(el, ast.Constant) \
                                and isinstance(el.value, str):
                            out.add(el.value)
    return out


def _is_hot(node, hot_names: Set[str]) -> bool:
    if node.name in hot_names:
        return True
    for d in node.decorator_list:
        name = _dotted(d if not isinstance(d, ast.Call) else d.func)
        if name and name.rsplit(".", 1)[-1] == "hot_path":
            return True
    return False


def _numpy_aliases(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in walk_nodes(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[0] == "numpy" \
                        or a.name == "jax.numpy":
                    out.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "") in ("jax",) :
                for a in node.names:
                    if a.name == "numpy":
                        out.add(a.asname or a.name)
    return out


def check_module(mod: ModuleSource) -> Iterable[Finding]:
    hot_names = _module_hot_names(mod.tree)
    np_aliases = _numpy_aliases(mod.tree) | {"np", "jnp"}
    findings: List[Finding] = []

    hot_fns = []
    for node in walk_nodes(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _is_hot(node, hot_names):
            hot_fns.append(node)

    def emit(call: ast.Call, fn, what: str) -> None:
        findings.append(Finding(
            rule="host-transfer-in-hot-loop", path=mod.relpath,
            line=call.lineno,
            message=f"{what} inside hot-path function {fn.name!r} "
                    f"syncs device->host on the per-query path",
            context=f"{fn.name}:{what}:{call.lineno}"))

    for fn in hot_fns:
        # nested defs run in the hot path too: walk the whole subtree
        for node in walk_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted is not None:
                parts = dotted.split(".")
                if len(parts) >= 2 and parts[0] in np_aliases \
                        and parts[-1] in _TRANSFER_LEAVES:
                    emit(node, fn, f"{dotted}()")
                    continue
                if len(parts) >= 2 and parts[0] == "jax" \
                        and parts[-1] == "device_get":
                    emit(node, fn, f"{dotted}()")
                    continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _METHOD_LEAVES \
                    and not node.args:
                # method form: x.item() / x.block_until_ready() /
                # x.tolist() — receiver type unknown, flag in hot scope
                emit(node, fn, f".{f.attr}()")
    return findings
