"""Device-memory residency & capacity dataflow rules (graftlint v5).

Built on the v3 :mod:`filodb_tpu.lint.callgraph` /
:mod:`filodb_tpu.lint.dataflow` engine: a residency analysis tracks
device-allocation sites (``jnp.zeros``/``jnp.full``/``jnp.asarray``/
``jax.device_put``/…) through local bindings into LONG-LIVED stores —
object attributes, module-level caches, ``@cache_registry`` inventory
dicts — and holds every escape to the ``@capacity`` bytes budgets of
:mod:`filodb_tpu.lint.capacity` (certified dynamically by
:mod:`filodb_tpu.lint.memcert`). Four error families:

  * ``hbm-residency-budget`` — a device allocation escapes into a
    long-lived store from a host-side (untraced) function that carries
    no ``@capacity(bytes_per_sample=..., reason=...)`` claim on
    itself, a lexical ancestor, or its class. Unaccounted residency is
    exactly how "tens of millions of series per chip" dies quietly:
    HBM fills with buffers nobody priced.
  * ``device-buffer-leak`` — lifetime analysis over the registered
    cache inventory: a ``@cache_registry`` store that accumulates
    device arrays by subscript must have an eviction operation
    (``pop``/``del``/``clear``/FIFO cap/weakref finalizer) on that
    attribute, and when the registry declares ``invalidated_by``
    hooks, an eviction site reachable from a hook through the call
    graph. Also: one tainted buffer stored into two different stores
    in one function (double-retention — the ledger double-counts and
    neither store owns eviction).
  * ``oversized-transfer`` — inside ``@hot_path`` functions: a
    device→host pull of a whole resident channel (``np.asarray`` /
    ``jax.device_get`` of a bare store attribute — slice on device
    first), or a host→device transfer of a buffer whose allocation is
    pow2-capacity-padded (``_next_pow2``/``_pad_pow2`` in the shape)
    when the unpadded slice would do; ``@capacity`` on the site
    declares the padding priced and exempts it.
  * ``vmem-frontier-budget`` — unify the ``_gs_pipeline``
    tile/DMA-buffer frontier arithmetic with the kernel contracts:
    a ``vmem_budget`` parameter must stay under the physical
    per-core VMEM (:data:`filodb_tpu.lint.contracts.VMEM_BYTES`), the
    chooser must actually TEST against its declared budget, and —
    when the kernel module is in the lint set — an independent
    re-derivation of the footprint sweeps the chooser's whole
    (step-tile, pipeline-depth) grid: every configuration the chooser
    returns must fit both the declared budget and the kernel
    contract's, and the chooser must not reject a workload whose
    minimal configuration fits (a premature host fallback is a silent
    10x).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from filodb_tpu.lint import Finding, ModuleSource, register_rule
from filodb_tpu.lint import callgraph as cgmod
from filodb_tpu.lint import dataflow as dfmod
from filodb_tpu.lint import contracts as contracts_mod
from filodb_tpu.lint.rules_cache import _collect_registries
from filodb_tpu.lint.rules_spmd import _own_nodes

register_rule("hbm-residency-budget", "capacity",
              "a device allocation escapes into a long-lived store "
              "(object attr / module cache / registry dict) without a "
              "@capacity(bytes_per_sample=..., reason=...) claim")
from filodb_tpu.lint.astwalk import walk_nodes
register_rule("device-buffer-leak", "capacity",
              "device arrays retained in a registered store with no "
              "eviction path reachable from its invalidation events, "
              "or one buffer double-retained by two stores")
register_rule("oversized-transfer", "capacity",
              "hot-path host<->device transfer of a whole resident "
              "channel or of a capacity-padded buffer where a slice "
              "suffices")
register_rule("vmem-frontier-budget", "capacity",
              "kernel frontier arithmetic disagrees with the declared "
              "VMEM budget: budget above physical VMEM, a chooser "
              "that never tests its budget, or a frontier point whose "
              "re-derived footprint does not fit")

# host-side constructors whose result is a device buffer under JAX
# (jnp.* array factories; jax.device_put). np.* allocations are host
# memory and do NOT count — residency is HBM.
_ALLOC_LEAVES = {"zeros", "ones", "full", "empty", "zeros_like",
                 "ones_like", "full_like", "asarray", "array",
                 "arange", "linspace", "where", "concatenate", "stack"}
_JNP_BASES = {"jnp", "jax.numpy"}

# device->host pull calls (the oversized-transfer whole-channel check)
_PULL_LEAVES = {"asarray", "array", "device_get"}


def _call_base(e: ast.Call) -> Optional[str]:
    """Dotted base of a call's function ('jnp' for jnp.zeros(...))."""
    d = dfmod._dotted(e.func)
    if d is None or "." not in d:
        return None
    return d.rsplit(".", 1)[0]


def _is_device_alloc(e) -> bool:
    """``e`` is a call that manufactures a device buffer."""
    if not isinstance(e, ast.Call):
        return False
    leaf = dfmod._leaf(e.func)
    base = _call_base(e)
    if leaf == "device_put":
        return base in ("jax", None)
    return leaf in _ALLOC_LEAVES and base in _JNP_BASES


def _contains_device_alloc(e) -> bool:
    return any(_is_device_alloc(n) for n in ast.walk(e)
               if isinstance(n, ast.Call))


def _is_self_attr(e) -> Optional[str]:
    """'attr' when ``e`` is ``self.attr``, else None."""
    if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
            and e.value.id == "self":
        return e.attr
    return None


# -- @capacity annotation discovery ------------------------------------------


class _CapacityAnnotations:
    """Function keys and class names carrying ``@capacity``."""

    def __init__(self, cg: cgmod.CallGraph):
        self.funcs: Set[str] = set()
        self.classes: Set[Tuple[str, str]] = set()   # (module, cls)
        for key, fi in cg.funcs.items():
            node = fi.node
            if isinstance(node, ast.Lambda):
                continue
            for d in node.decorator_list:
                target = d.func if isinstance(d, ast.Call) else d
                if dfmod._leaf(target) == "capacity":
                    self.funcs.add(key)
        for (module, cls), ci in cg._classes_by_mod.items():
            for d in ci.node.decorator_list:
                target = d.func if isinstance(d, ast.Call) else d
                if dfmod._leaf(target) == "capacity":
                    self.classes.add((module, cls))

    def covers(self, cg: cgmod.CallGraph, key: str) -> bool:
        fi = cg.funcs.get(key)
        if fi is None:
            return False
        qual = fi.qualname
        keys = [key]
        while ".<locals>." in qual:
            qual = qual.rsplit(".<locals>.", 1)[0]
            keys.append(f"{fi.module}:{qual}")
        if any(k in self.funcs for k in keys):
            return True
        return fi.cls is not None and (fi.module, fi.cls) in self.classes


# -- per-function residency analysis -----------------------------------------


class _Escapes:
    """Device-alloc taint + store escapes inside one function body."""

    def __init__(self, fn_node):
        self.tainted: Set[str] = set()       # locals bound to allocs
        # local container names that received tainted subscript stores
        self.tainted_containers: Set[str] = set()
        # (store label, line, tainted local or None) per escape
        self.stores: List[Tuple[str, int, Optional[str], ast.AST]] = []
        nodes = list(_own_nodes(fn_node))
        # two taint-propagation passes (no store recording), then one
        # recording pass — stores must not duplicate across passes
        self._record = False
        for _ in range(2):
            for node in nodes:
                self._visit(node)
        self._record = True
        for node in nodes:
            self._visit(node)

    def _value_taint(self, value) -> Optional[str]:
        """The tainted local a stored value carries, '<alloc>' for a
        direct allocation, None for clean values. Dict/list/tuple
        literals of tainted names are containers of device buffers."""
        if isinstance(value, ast.Name):
            if value.id in self.tainted \
                    or value.id in self.tainted_containers:
                return value.id
            return None
        if _is_device_alloc(value):
            return "<alloc>"
        if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
            for e in value.elts:
                t = self._value_taint(e)
                if t is not None:
                    return t
            return None
        if isinstance(value, ast.Dict):
            for e in value.values:
                t = self._value_taint(e)
                if t is not None:
                    return t
        return None

    def _visit(self, node) -> None:
        if not isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
            return
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        value = node.value
        if value is None:
            return
        taint = self._value_taint(value)
        for t in targets:
            # local binding: x = jnp.zeros(...)
            if isinstance(t, ast.Name):
                if taint is not None:
                    self.tainted.add(t.id)
                continue
            # tuple unpack of allocs taints every name
            if isinstance(t, ast.Tuple) and taint is not None:
                for e in t.elts:
                    if isinstance(e, ast.Name):
                        self.tainted.add(e.id)
                continue
            # self.attr = X
            attr = _is_self_attr(t)
            if attr is not None and taint is not None:
                if self._record:
                    self.stores.append((f"self.{attr}", node.lineno,
                                        taint, t))
                continue
            if isinstance(t, ast.Subscript):
                attr = _is_self_attr(t.value)
                if attr is not None and taint is not None:
                    # self.attr[k] = X — dict-store growth
                    if self._record:
                        self.stores.append((f"self.{attr}[]",
                                            node.lineno, taint, t))
                elif isinstance(t.value, ast.Name) and taint is not None:
                    # local[k] = alloc: container becomes tainted; it
                    # escapes if the container itself is stored
                    self.tainted_containers.add(t.value.id)


# -- vmem frontier re-derivation ---------------------------------------------


def _ref_frontier_footprint(pk, st: int, dspan: int, hi: int, lo: int,
                            nsteps: int, G: int, tt: int,
                            nbuf: int) -> int:
    """Independent re-derivation of the groupsum on-chip footprint for
    one frontier point — the contract side of the chooser arithmetic
    (constants read off the kernel module so a retune moves both)."""
    lead = 1 if st == 1 else 0
    mlen = tt + pk._GS_AL + (-(-(dspan + lead) // pk._GS_AL)) * pk._GS_AL
    nstreams = 1 + (1 if hi != pk.GS_CUR and st != 1 else 0) \
        + (1 if lo != pk.GS_CUR and st != 1 else 0)
    t_pad = -(-nsteps // tt) * tt
    accum = 2 * t_pad * G * 4
    fixed = pk._GS_SS * G * 4 + 8 * pk._GS_SS * 4
    scratch = nbuf * nstreams * mlen * 3 * pk._GS_SS * 4
    return accum + scratch + fixed


def _sweep_frontier(pk, budget: int) -> List[Tuple[str, Tuple]]:
    """Sweep the chooser's whole admissible grid; return violations as
    (kind, point) — 'overflow' when a returned configuration's
    re-derived footprint exceeds ``budget``, 'premature-fallback' when
    the chooser returns None although the minimal configuration
    (narrow tile, double buffer) fits."""
    bad: List[Tuple[str, Tuple]] = []
    modes = (pk.GS_BOTH, pk.GS_CUR, pk.GS_ALT)
    for st in (1, 2, 3, 6):
        for dspan in (0, 1, 6, 12, 24, pk._GS_DSPAN_MAX):
            for hi in modes:
                for lo in modes:
                    for nsteps in (64, 512, 2880, 8192):
                        for G in (16, 512):
                            pt = (st, dspan, hi, lo, nsteps, G)
                            got = pk._gs_pipeline(st, dspan, hi, lo,
                                                  nsteps, G,
                                                  vmem_budget=budget)
                            if got is not None:
                                tt, nbuf = got
                                fp = _ref_frontier_footprint(
                                    pk, st, dspan, hi, lo, nsteps, G,
                                    tt, nbuf)
                                if fp > budget:
                                    bad.append(("overflow",
                                                pt + (tt, nbuf, fp)))
                            else:
                                fp = _ref_frontier_footprint(
                                    pk, st, dspan, hi, lo, nsteps, G,
                                    pk._GS_TT, 2)
                                if fp <= budget:
                                    bad.append(("premature-fallback",
                                                pt + (fp,)))
    return bad


def _check_vmem_frontier(mods: Sequence[ModuleSource],
                         cg: cgmod.CallGraph
                         ) -> List[Tuple[Optional[str], Finding]]:
    out: List[Tuple[Optional[str], Finding]] = []
    for key, fi in sorted(cg.funcs.items()):
        node = fi.node
        if isinstance(node, ast.Lambda):
            continue
        args = node.args
        names = [a.arg for a in args.args] \
            + [a.arg for a in args.kwonlyargs]
        if "vmem_budget" not in names:
            continue
        # (1) declared default must fit physical VMEM
        defaults = list(zip(reversed(args.args), reversed(args.defaults)))
        declared: Optional[int] = None
        for a, d in defaults:
            if a.arg == "vmem_budget":
                declared = _int_const(d)
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if a.arg == "vmem_budget" and d is not None:
                declared = _int_const(d)
        if declared is not None and declared > contracts_mod.VMEM_BYTES:
            out.append((fi.relpath, Finding(
                rule="vmem-frontier-budget", path=fi.relpath,
                line=fi.lineno,
                message=(f"{fi.qualname}: vmem_budget default "
                         f"{declared} exceeds physical per-core VMEM "
                         f"({contracts_mod.VMEM_BYTES}) — a chooser "
                         f"can admit footprints the chip cannot hold"),
                context=f"{fi.qualname}:budget-over-vmem")))
        # (2) a chooser (a function that WALKS a frontier — it loops)
        # must TEST against its budget somewhere; declaration helpers
        # that merely forward the kwarg are not choosers
        is_chooser = any(isinstance(n, (ast.For, ast.While))
                         for n in ast.walk(node))
        uses_budget = any(
            isinstance(n, ast.Compare) and any(
                isinstance(side, ast.Name) and side.id == "vmem_budget"
                for side in [n.left] + list(n.comparators))
            for n in ast.walk(node))
        if is_chooser and not uses_budget:
            out.append((fi.relpath, Finding(
                rule="vmem-frontier-budget", path=fi.relpath,
                line=fi.lineno,
                message=(f"{fi.qualname}: takes a vmem_budget but "
                         f"never compares a footprint against it — "
                         f"the frontier walk is unbudgeted"),
                context=f"{fi.qualname}:budget-unused")))
    # (3) symbolic sweep of the in-tree groupsum frontier against the
    # kernel contract, when the kernel module is being linted
    krel = "filodb_tpu/query/pallas_kernels.py"
    if any(m.relpath == krel for m in mods):
        import importlib
        pk = importlib.import_module("filodb_tpu.query.pallas_kernels")
        contract = contracts_mod.CONTRACTS.get(
            ("filodb_tpu.query.pallas_kernels", "counter_groupsum"))
        budget = min(
            contract.vmem_budget if contract and contract.vmem_budget
            else contracts_mod.VMEM_BYTES, contracts_mod.VMEM_BYTES)
        line = 1
        for m in mods:
            if m.relpath == krel:
                for i, ln in enumerate(m.lines, start=1):
                    if "def _gs_pipeline" in ln:
                        line = i
                        break
        for kind, pt in _sweep_frontier(pk, budget)[:8]:
            if kind == "overflow":
                st, dspan, hi, lo, nsteps, G, tt, nbuf, fp = pt
                msg = (f"_gs_pipeline admits (tt={tt}, nbuf={nbuf}) at "
                       f"(st={st}, dspan={dspan}, hi={hi}, lo={lo}, "
                       f"nsteps={nsteps}, G={G}) but the re-derived "
                       f"footprint {fp} exceeds the contract budget "
                       f"{budget}")
            else:
                st, dspan, hi, lo, nsteps, G, fp = pt
                msg = (f"_gs_pipeline falls back to host at (st={st}, "
                       f"dspan={dspan}, hi={hi}, lo={lo}, "
                       f"nsteps={nsteps}, G={G}) although the minimal "
                       f"configuration fits ({fp} <= {budget})")
            out.append((krel, Finding(
                rule="vmem-frontier-budget", path=krel, line=line,
                message=msg, context=f"gs-frontier:{kind}:{pt[:6]}")))
    return out


def _int_const(e) -> Optional[int]:
    from filodb_tpu.lint.rules_numerics import _int_const as f
    return f(e)


# -- hot-path transfer scope -------------------------------------------------


def _hot_keys(cg: cgmod.CallGraph, mods: Sequence[ModuleSource]
              ) -> Set[str]:
    hot: Set[str] = set()
    for key, fi in cg.funcs.items():
        node = fi.node
        if isinstance(node, ast.Lambda):
            continue
        for d in node.decorator_list:
            target = d.func if isinstance(d, ast.Call) else d
            if dfmod._leaf(target) == "hot_path":
                hot.add(key)
    for mod in mods:
        dotted = cgmod.module_dotted(mod.relpath)
        for node in mod.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__hot_path__":
                        from filodb_tpu.lint.rules_cache import _const
                        v = _const(node.value)
                        if isinstance(v, tuple):
                            for name in v:
                                hot.add(f"{dotted}:{name}")
    return hot


def _pow2_padded_locals(fn_node) -> Set[str]:
    """Locals whose allocation shape runs through a pow2 capacity pad
    (``_next_pow2``/``_pad_pow2``), plus the pad-width names feeding
    them."""
    padded: Set[str] = set()
    pad_names: Set[str] = set()
    for _ in range(2):
        for node in _own_nodes(fn_node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not isinstance(t, ast.Name):
                continue
            uses_pad = False
            for n in ast.walk(node.value):
                if isinstance(n, ast.Call) and dfmod._leaf(n.func) in \
                        ("_next_pow2", "_pad_pow2", "next_pow2"):
                    uses_pad = True
                if isinstance(n, ast.Name) and n.id in pad_names:
                    uses_pad = True
            if uses_pad:
                pad_names.add(t.id)
                if isinstance(node.value, ast.Call) and \
                        dfmod._leaf(node.value.func) in (
                            "zeros", "full", "empty", "ones"):
                    padded.add(t.id)
    return padded


def _check_transfers(cg: cgmod.CallGraph, mods: Sequence[ModuleSource],
                     ann: _CapacityAnnotations
                     ) -> List[Tuple[Optional[str], Finding]]:
    out: List[Tuple[Optional[str], Finding]] = []
    for key in sorted(_hot_keys(cg, mods)):
        fi = cg.funcs.get(key)
        if fi is None or ann.covers(cg, key):
            continue
        padded = _pow2_padded_locals(fi.node)
        for call in _own_nodes(fi.node):
            if isinstance(call, ast.Call):
                leaf = dfmod._leaf(call.func)
                base = _call_base(call)
                # (i) whole-resident-channel pull to host
                if leaf in _PULL_LEAVES and base in ("np", "numpy",
                                                     "jax") \
                        and call.args:
                    attr = _is_self_attr(call.args[0])
                    if attr is not None:
                        out.append((fi.relpath, Finding(
                            rule="oversized-transfer", path=fi.relpath,
                            line=call.lineno,
                            message=(
                                f"{fi.qualname}: pulls the whole "
                                f"resident channel self.{attr} to the "
                                f"host on the hot path — slice on "
                                f"device and transfer the window"),
                            context=f"{fi.qualname}:pull:{attr}")))
                # (ii) capacity-padded buffer shipped to device
                if leaf == "device_put" or (leaf == "asarray"
                                            and base in _JNP_BASES):
                    for a in call.args[:1]:
                        if isinstance(a, ast.Name) and a.id in padded:
                            out.append((fi.relpath, Finding(
                                rule="oversized-transfer",
                                path=fi.relpath, line=call.lineno,
                                message=(
                                    f"{fi.qualname}: transfers the "
                                    f"pow2-capacity-padded buffer "
                                    f"{a.id!r} to the device on the "
                                    f"hot path — pad on device or "
                                    f"ship the exact slice "
                                    f"(@capacity declares the "
                                    f"padding priced if deliberate)"),
                                context=(f"{fi.qualname}:padded:"
                                         f"{a.id}"))))
    return out


# -- leak analysis -----------------------------------------------------------

_EVICT_CALL_LEAVES = {"pop", "popitem", "clear"}


def _evicts_attr(fn_node, attr: str) -> bool:
    """The function body evicts from ``self.<attr>`` (pop/del/clear/
    reassign-to-empty) or wires a weakref finalizer."""
    for node in walk_nodes(fn_node):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) \
                    and f.attr in _EVICT_CALL_LEAVES:
                tgt = f.value
                if _is_self_attr(tgt) == attr:
                    return True
            leaf = dfmod._leaf(f)
            if leaf in ("ref", "finalize") \
                    and (_call_base(node) or "").endswith("weakref"):
                return True
        if isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) \
                        and _is_self_attr(t.value) == attr:
                    return True
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if _is_self_attr(t) == attr and isinstance(
                        node.value, (ast.Dict, ast.List)) \
                        and not getattr(node.value, "keys",
                                        getattr(node.value, "elts", ())):
                    return True
    return False


def _check_leaks(cg: cgmod.CallGraph, df: dfmod.DeviceDataflow,
                 mods: Sequence[ModuleSource],
                 escapes_by_key: Dict[str, _Escapes]
                 ) -> List[Tuple[Optional[str], Finding]]:
    out: List[Tuple[Optional[str], Finding]] = []
    regs, _ = _collect_registries(cg, mods)
    regs_by_cls: Dict[str, list] = {}
    for reg in regs:
        if reg.owner_cls:
            regs_by_cls.setdefault(reg.owner_cls, []).append(reg)

    # (a) registered stores accumulating device arrays need eviction
    for (module, cls), ci in sorted(cg._classes_by_mod.items()):
        if cls not in regs_by_cls:
            continue
        grown: Dict[str, Tuple[str, int]] = {}   # attr -> (key, line)
        for mname, mfi in ci.methods.items():
            esc = escapes_by_key.get(mfi.key)
            if esc is None:
                continue
            for label, line, _taint, _t in esc.stores:
                if label.endswith("[]"):
                    grown.setdefault(label[5:-2], (mfi.key, line))
        for attr, (store_key, line) in sorted(grown.items()):
            evictors = [m for m in ci.methods.values()
                        if m.name != "__init__"
                        and _evicts_attr(m.node, attr)]
            # a finalizer/FIFO-cap in the storing method itself counts
            store_fi = cg.funcs.get(store_key)
            if store_fi is not None \
                    and _evicts_attr(store_fi.node, attr):
                evictors.append(store_fi)
            if not evictors:
                out.append((ci.relpath, Finding(
                    rule="device-buffer-leak", path=ci.relpath,
                    line=line,
                    message=(
                        f"{cls}.{attr} accumulates device arrays with "
                        f"no eviction operation anywhere in the class "
                        f"(no pop/del/clear/weakref finalizer) — the "
                        f"store can only grow"),
                    context=f"{cls}.{attr}:no-eviction")))
                continue
            # invalidation-event reachability: when the registry
            # declares hooks, some eviction site must be reachable
            # from one of them
            hooks: List[str] = []
            for reg in regs_by_cls[cls]:
                for hook in reg.invalidated_by.values():
                    hk = cg.resolve_method(cls, hook)
                    if hk:
                        hooks.append(hk)
            if hooks:
                reachable = False
                for hk in hooks:
                    for ev in evictors:
                        if hk == ev.key \
                                or df.reaches(hk, ev.key) is not None:
                            reachable = True
                if not reachable:
                    out.append((ci.relpath, Finding(
                        rule="device-buffer-leak", path=ci.relpath,
                        line=line,
                        message=(
                            f"{cls}.{attr} holds device arrays but no "
                            f"eviction site is reachable from the "
                            f"registry's invalidation hooks — the "
                            f"declared events never free the bytes"),
                        context=f"{cls}.{attr}:unreachable-eviction")))

    # (b) double-retention of one buffer by two stores
    for key, esc in sorted(escapes_by_key.items()):
        fi = cg.funcs.get(key)
        if fi is None:
            continue
        by_name: Dict[str, List[Tuple[str, int]]] = {}
        for label, line, taint, _t in esc.stores:
            if taint and taint != "<alloc>":
                by_name.setdefault(taint, []).append((label, line))
        for name, sites in sorted(by_name.items()):
            stores = sorted({lab for lab, _ in sites})
            if len(stores) > 1:
                line = min(ln for _, ln in sites)
                out.append((fi.relpath, Finding(
                    rule="device-buffer-leak", path=fi.relpath,
                    line=line,
                    message=(
                        f"{fi.qualname}: buffer {name!r} is retained "
                        f"by {len(stores)} stores "
                        f"({', '.join(stores)}) — double-counted "
                        f"residency with no single eviction owner"),
                    context=f"{fi.qualname}:double:{name}")))
    return out


# -- entry -------------------------------------------------------------------


def check_project(mods: Sequence[ModuleSource],
                  cg: Optional[cgmod.CallGraph] = None,
                  df: Optional[dfmod.DeviceDataflow] = None
                  ) -> List[Tuple[Optional[str], Finding]]:
    if df is None:
        df = dfmod.build(mods, cg)
    cg = df.cg
    ann = _CapacityAnnotations(cg)
    out: List[Tuple[Optional[str], Finding]] = []

    # traced functions don't retain — jit outputs escape through the
    # dispatch, and Pallas bodies are on-chip; residency is a HOST
    # code property
    traced: Set[str] = set(df.traced)
    for site in df.sites:
        if site.kind == "pallas_call":
            traced |= df.closure_of(site.body_keys)

    escapes_by_key: Dict[str, _Escapes] = {}
    for key, fi in sorted(cg.funcs.items()):
        if key in traced or isinstance(fi.node, ast.Lambda):
            continue
        esc = _Escapes(fi.node)
        if esc.stores:
            escapes_by_key[key] = esc

    # (1) hbm-residency-budget
    for key, esc in sorted(escapes_by_key.items()):
        fi = cg.funcs[key]
        if ann.covers(cg, key):
            continue
        for label, line, _taint, _t in esc.stores:
            out.append((fi.relpath, Finding(
                rule="hbm-residency-budget", path=fi.relpath, line=line,
                message=(
                    f"{fi.qualname}: a device allocation escapes into "
                    f"the long-lived store {label} with no "
                    f"@capacity(bytes_per_sample=..., reason=...) "
                    f"claim on the function or its class — "
                    f"unaccounted HBM residency"),
                context=f"{fi.qualname}:resident:{label}")))

    # module-level resident globals: NAME = jnp.zeros(...) at top level
    for mod in mods:
        dotted = cgmod.module_dotted(mod.relpath)
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) \
                    and _contains_device_alloc(node.value):
                names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
                if not names:
                    continue
                out.append((mod.relpath, Finding(
                    rule="hbm-residency-budget", path=mod.relpath,
                    line=node.lineno,
                    message=(
                        f"module-level device allocation bound to "
                        f"{', '.join(names)} lives for the process "
                        f"lifetime with no @capacity claim — "
                        f"unaccounted HBM residency"),
                    context=f"{dotted}:{names[0]}:module-resident")))

    # (2) device-buffer-leak
    out.extend(_check_leaks(cg, df, mods, escapes_by_key))
    # (3) oversized-transfer
    out.extend(_check_transfers(cg, mods, ann))
    # (4) vmem-frontier-budget
    out.extend(_check_vmem_frontier(mods, cg))
    return out
