"""Numeric-precision & determinism annotations (graftlint v4).

The engine's correctness story rests on precision invariants that lived
only in docstrings: the f32-hybrid counter fast path carries exact
int32 hi/lo splits with an f32 recombine, timestamps ride int32
milliseconds under a dispatcher span guard, and the mesh serving path
psums f64 partial aggregates whose grouping depends on the device
count. These annotations make every such hybrid site DECLARE its
budget, and two rails hold the declaration to account:

  * statically — :mod:`filodb_tpu.lint.rules_numerics` runs a
    dtype-and-precision dataflow over every jit/shard_map/pallas entry
    point and errors on any 64→32 narrowing, f32 accumulation, or
    float collective that is not annotated here;
  * dynamically — :mod:`filodb_tpu.lint.ulpcert` evaluates every
    annotated site on seeded inputs in f64-reference vs production
    dtype (order claims at 1/2/4/8 virtual devices) and CERTIFIES the
    claimed tolerance. An annotation the rail cannot certify fails
    tier-1: a lie in a ``@precision`` is a build break, not a comment.

Annotations:

  * :func:`precision` — the site narrows a value with f64/int64
    provenance into an f32/int32 op on purpose, with a stated budget:

      - ``bits`` — the significand/width budget the narrow
        representation must cover (31 for the int31 relative-timestamp
        span guard, 24 for an f32 epilogue, 61 for the fixed-point
        hi/lo split);
      - ``rel_ulps`` — claimed max error of the site's output vs the
        f64 reference, in output-dtype ulps (0 = exact, certified
        bitwise);
      - ``accum_terms`` — static bound on the number of terms any
        reduction at the site accumulates (the accumulation-bound
        family checks ``accum_terms <= 2**mantissa`` for the
        accumulator dtype: 2**24 for an f32 sum);
      - ``compensated`` — the site uses an f64 accumulator or a
        compensated sum, exempting it from the mantissa bound;
      - ``reason`` — required prose: WHY the narrowing is safe (which
        dispatcher guard, which exactness argument).

  * :func:`order_insensitive` — the site's reduction grouping depends
    on mesh shape / device count (psum, segment-sum, one-hot matmul
    over float) and claims its result moves less than ``tolerance``
    (max relative deviation) across groupings. ``tolerance=0.0`` is a
    byte-identity claim, certified bitwise at every device count — the
    static cross-check for the mesh-on/off parity pins.

All decorators are runtime-neutral: they attach ``__precision__`` /
``__order_insensitive__`` and register the claim for the rails.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

# f32 significand: 24 bits (1 implicit); one ulp of a normalized value
# is at most 2**-23 of the value
F32_MANTISSA_BITS = 24
F32_REL_ULP = 2.0 ** -23
F64_MANTISSA_BITS = 53

MANTISSA_BITS = {"float32": F32_MANTISSA_BITS,
                 "float64": F64_MANTISSA_BITS,
                 "bfloat16": 8, "float16": 11}


@dataclass(frozen=True)
class PrecisionClaim:
    """One ``@precision`` declaration."""
    name: str
    bits: int
    reason: str
    rel_ulps: float = 0.0           # 0 = exact (certified bitwise)
    accum_terms: Optional[int] = None
    compensated: bool = False
    module: str = ""
    qualname: str = ""

    def rel_bound(self, cross_program: bool = False) -> float:
        """Relative error bound implied by the claim for an f32-output
        site. ``cross_program=True`` doubles it: two independently
        lowered programs (mesh-on vs mesh-off) each within
        ``rel_ulps`` of the correctly-rounded reference differ by at
        most twice the claim."""
        k = 2.0 if cross_program else 1.0
        return k * max(self.rel_ulps, 1.0) * F32_REL_ULP


@dataclass(frozen=True)
class OrderClaim:
    """One ``@order_insensitive`` declaration."""
    name: str
    tolerance: float                # max rel deviation across groupings
    reason: str
    module: str = ""
    qualname: str = ""


# claim name -> claim (names are globally unique — the ulpcert harness
# registry and the test helpers key on them)
PRECISION: Dict[str, PrecisionClaim] = {}
ORDER: Dict[str, OrderClaim] = {}


def _register(table: Dict, claim, fn) -> None:
    prev = table.get(claim.name)
    if prev is not None and prev.qualname != claim.qualname:
        raise ValueError(
            f"numerics claim {claim.name!r} declared twice "
            f"({prev.qualname} and {claim.qualname})")
    table[claim.name] = claim


def precision(name: Optional[str] = None, *, bits: int, reason: str,
              rel_ulps: float = 0.0,
              accum_terms: Optional[int] = None,
              compensated: bool = False) -> Callable:
    """Declare a deliberate precision-narrowing site (see module
    docstring). ``reason`` must be non-empty prose."""
    if not reason or not reason.strip():
        raise ValueError("@precision requires a non-empty reason")

    def deco(fn):
        claim = PrecisionClaim(
            name=name or getattr(fn, "__qualname__",
                                 getattr(fn, "__name__", "?")),
            bits=int(bits), reason=reason, rel_ulps=float(rel_ulps),
            accum_terms=accum_terms, compensated=bool(compensated),
            module=getattr(fn, "__module__", "") or "",
            qualname=getattr(fn, "__qualname__",
                             getattr(fn, "__name__", "?")))
        _register(PRECISION, claim, fn)
        try:
            fn.__precision__ = claim
        except (AttributeError, TypeError):   # functools.partial etc.
            pass
        return fn
    return deco


def order_insensitive(name: Optional[str] = None, *, tolerance: float,
                      reason: str) -> Callable:
    """Declare a mesh-shape-dependent float reduction with its claimed
    cross-grouping tolerance (0.0 = byte-identity, certified bitwise
    at 1/2/4/8 virtual devices)."""
    if not reason or not reason.strip():
        raise ValueError("@order_insensitive requires a non-empty reason")

    def deco(fn):
        claim = OrderClaim(
            name=name or getattr(fn, "__qualname__",
                                 getattr(fn, "__name__", "?")),
            tolerance=float(tolerance), reason=reason,
            module=getattr(fn, "__module__", "") or "",
            qualname=getattr(fn, "__qualname__",
                             getattr(fn, "__name__", "?")))
        _register(ORDER, claim, fn)
        try:
            fn.__order_insensitive__ = claim
        except (AttributeError, TypeError):
            pass
        return fn
    return deco


def precision_claim(name: str) -> PrecisionClaim:
    """Look up a registered ``@precision`` claim by name (importing the
    engine modules that declare in-tree claims first)."""
    if name not in PRECISION:
        import_annotated_modules()
    return PRECISION[name]


def order_claim(name: str) -> OrderClaim:
    if name not in ORDER:
        import_annotated_modules()
    return ORDER[name]


# the modules carrying in-tree annotations; ulpcert + the claim lookup
# helpers import these so the registry is populated without executing
# anything device-side
ANNOTATED_MODULES: Tuple[str, ...] = (
    "filodb_tpu.query.tilestore",
    "filodb_tpu.query.pallas_kernels",
    "filodb_tpu.query.tpu",
    "filodb_tpu.parallel.mesh",
    "filodb_tpu.parallel.shardstore",
)


def import_annotated_modules() -> None:
    import importlib
    for m in ANNOTATED_MODULES:
        importlib.import_module(m)


def claim_inventory() -> Dict[str, object]:
    """All registered claims (README table / debugging)."""
    import_annotated_modules()
    return {"precision": dict(PRECISION), "order": dict(ORDER)}
