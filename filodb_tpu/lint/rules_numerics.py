"""Numeric-precision & determinism dataflow rules (graftlint v4).

Built on :mod:`filodb_tpu.lint.dataflow` (entry points, per-site
closures) with a local dtype-class inference: every assignment inside a
traced function is classified into {f64, f32, i64, i32, bool, neutral,
unknown} from explicit dtypes (``astype``, ``dtype=`` kwargs,
``jnp.float32(...)`` constructors, dtype aliases like ``f32 =
jnp.float32``) and propagation through arithmetic (the widest operand
wins; anything touching an unknown stays unknown — the rules only fire
on PROVABLE facts, never on inference gaps). Four error families:

  * ``precision-narrowing`` — a value with provable f64/int64
    provenance flows into an f32/int32 cast inside a traced function
    that carries no ``@precision(bits=..., reason=...)`` annotation
    (on itself or a lexical ancestor). The int31 relative-timestamp
    span-guard idiom is the canonical annotated instance: the
    narrowing is SAFE, but only because a dispatcher guard proves the
    span fits — the annotation names that proof.
  * ``accumulation-bound`` — an f32-accumulated reduction (sum /
    cumsum / dot / matmul / psum) whose term count is not statically
    bounded under the f32 mantissa (2**24): the enclosing function
    must carry ``@precision`` with ``accum_terms=N`` (checked
    ``N <= 2**24``) or ``compensated=True`` (f64 accumulate /
    compensated sum), or accumulate in f64 via ``dtype=``. A declared
    bound exceeding the accumulator mantissa is itself an error.
  * ``reduction-order-determinism`` — a float (or unprovable-dtype)
    ``psum``/``pmean``/``psum_scatter``/``segment_sum`` inside a
    shard_map-traced closure: the reduction grouping depends on mesh
    shape and device count, so the site must be
    ``@order_insensitive(tolerance=...)`` (certified across 1/2/4/8
    virtual devices by the ulpcert rail; ``tolerance=0.0`` claims
    byte-identity and is certified bitwise — the static cross-check
    for the mesh-on/off parity pins) or provably integer/exact
    (integer operand, or pmin/pmax which are order-free).
  * ``mixed-dtype-comparison`` — inside a Pallas kernel body, a
    comparison whose operands mix f32 and f64, or whose operand is a
    float cast of a provably-integer value: the comparison's branch
    can flip across backends (XLA:TPU rounds int→f32 differently past
    2**24 than the f64 host path), which is exactly the class of bug
    no single-backend test catches.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from filodb_tpu.lint import Finding, ModuleSource, register_rule
from filodb_tpu.lint import callgraph as cgmod
from filodb_tpu.lint import dataflow as dfmod
from filodb_tpu.lint.rules_spmd import _own_nodes

register_rule("precision-narrowing", "numerics",
              "f64/int64 value flows into an f32/int32 op without a "
              "@precision(bits=..., reason=...) annotation")
register_rule("accumulation-bound", "numerics",
              "f32 accumulation without a static term bound under the "
              "mantissa (2**24) or a compensated/f64-accumulate marker")
register_rule("reduction-order-determinism", "numerics",
              "mesh-shape-dependent float reduction (psum/segment-sum/"
              "one-hot matmul) without @order_insensitive(tolerance=...)"
              " and not provably integer/exact")
register_rule("mixed-dtype-comparison", "numerics",
              "f32/f64-mixed or int-cast-to-float comparison inside a "
              "Pallas body — branch behavior can differ across backends")

# dtype classes
F64, F32, F16, I64, I32, BOOL, NEUTRAL = \
    "f64", "f32", "f16", "i64", "i32", "bool", "neutral"

_DTYPE_LEAVES = {
    "float64": F64, "double": F64,
    "float32": F32,
    "float16": F16, "bfloat16": F16,
    "int64": I64, "uint64": I64,
    "int32": I32, "uint32": I32, "int8": I32, "uint8": I32,
    "int16": I32, "uint16": I32,
    "bool_": BOOL,
}

_FLOATS = {F64, F32, F16}
_INTS = {I64, I32}
_WIDE = {F64, I64}
_NARROW_FLOAT = {F32, F16}

_MANTISSA = {F32: 24, F16: 11, F64: 53}

# reductions whose accumulator the accumulation-bound family budgets
_ACCUM_LEAVES = {"sum", "nansum", "cumsum", "dot", "matmul", "einsum",
                 "psum", "pmean"}
# order-dependent collectives / segment reductions (pmin/pmax/segment_
# min/max are order-free and exempt)
_ORDER_COLLECTIVES = {"psum", "pmean", "psum_scatter", "pdot"}
_ORDER_SEGMENTS = {"segment_sum", "segment_prod"}


def _dtype_class_of_expr(expr, aliases: Dict[str, str]) -> Optional[str]:
    """Dtype class named by a dtype-position expression
    (``jnp.float32`` / a local alias / a 'float32' string)."""
    leaf = dfmod._leaf(expr)
    if leaf is not None:
        if leaf in _DTYPE_LEAVES:
            return _DTYPE_LEAVES[leaf]
        if leaf in aliases:
            return aliases[leaf]
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return _DTYPE_LEAVES.get(expr.value)
    return None


def _widest(classes: Sequence[Optional[str]]) -> Optional[str]:
    """Widest dtype class of operands; None (unknown) dominates so the
    rules never fire on an inference gap."""
    real = [c for c in classes if c != NEUTRAL]
    if any(c is None for c in real):
        return None
    if not real:
        return NEUTRAL
    floats = [c for c in real if c in _FLOATS]
    if floats:
        for c in (F64, F32, F16):
            if c in floats:
                return c
    ints = [c for c in real if c in _INTS]
    if ints:
        return I64 if I64 in ints else I32
    return real[0]


class _DtypeEnv:
    """Per-function dtype-class environment: two passes over the
    assignments in source order reach a fixpoint for the straight-line
    channel math these kernels are made of."""

    def __init__(self, fn_node, aliases: Dict[str, str]):
        self.aliases = dict(aliases)
        self.env: Dict[str, Optional[str]] = {}
        # names holding a float cast of a provably-integer value (the
        # mixed-dtype-comparison family's taint)
        self.float_from_int: set = set()
        # local dtype aliases (f32 = jnp.float32)
        for node in _own_nodes(fn_node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                cls = _dtype_class_of_expr(node.value, self.aliases)
                if cls is not None and dfmod._leaf(node.value) \
                        in _DTYPE_LEAVES:
                    self.aliases[node.targets[0].id] = cls
        for _ in range(2):
            for node in _own_nodes(fn_node):
                if isinstance(node, ast.Assign):
                    cls = self.classify(node.value)
                    tainted = self.is_int_float_cast(node.value)
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.env[t.id] = cls
                            if tainted:
                                self.float_from_int.add(t.id)
                            else:
                                self.float_from_int.discard(t.id)
                        elif isinstance(t, (ast.Tuple, ast.List)):
                            for el in t.elts:
                                if isinstance(el, ast.Name):
                                    self.env[el.id] = None
                elif isinstance(node, ast.AugAssign) \
                        and isinstance(node.target, ast.Name):
                    cls = _widest([self.env.get(node.target.id),
                                   self.classify(node.value)])
                    self.env[node.target.id] = cls

    # -- classification ------------------------------------------------
    def classify(self, e) -> Optional[str]:
        if isinstance(e, ast.Constant):
            v = e.value
            if isinstance(v, bool):
                return BOOL
            if isinstance(v, int):
                return NEUTRAL
            if isinstance(v, float):
                return NEUTRAL
            return None
        if isinstance(e, ast.Name):
            if e.id in self.env:
                return self.env[e.id]
            return None
        if isinstance(e, ast.UnaryOp):
            return self.classify(e.operand)
        if isinstance(e, ast.Compare):
            return BOOL
        if isinstance(e, ast.BoolOp):
            return BOOL
        if isinstance(e, ast.BinOp):
            return _widest([self.classify(e.left),
                            self.classify(e.right)])
        if isinstance(e, ast.IfExp):
            return _widest([self.classify(e.body),
                            self.classify(e.orelse)])
        if isinstance(e, ast.Subscript):
            return self.classify(e.value)
        if isinstance(e, ast.Attribute):
            if e.attr == "T":
                return self.classify(e.value)
            return None
        if isinstance(e, ast.Call):
            return self._classify_call(e)
        return None

    def _classify_call(self, e: ast.Call) -> Optional[str]:
        leaf = dfmod._leaf(e.func)
        for kw in e.keywords:
            if kw.arg == "dtype":
                cls = _dtype_class_of_expr(kw.value, self.aliases)
                if cls is not None:
                    return cls
        if leaf == "astype" and isinstance(e.func, ast.Attribute):
            if e.args:
                return _dtype_class_of_expr(e.args[0], self.aliases)
            return None
        if leaf in _DTYPE_LEAVES:
            return _DTYPE_LEAVES[leaf]
        if leaf in self.aliases:
            return self.aliases[leaf]
        if leaf == "broadcasted_iota" and e.args:
            return _dtype_class_of_expr(e.args[0], self.aliases)
        if leaf == "axis_index":
            return I32
        if leaf in ("where",):
            return _widest([self.classify(a) for a in e.args[1:3]])
        if leaf in ("floor", "ceil", "rint", "abs", "clip", "minimum",
                    "maximum", "take", "reshape", "transpose", "mod",
                    "floor_divide", "concatenate", "stack", "pad",
                    "cumsum", "sum", "nansum", "dot", "matmul",
                    "dynamic_slice", "dynamic_slice_in_dim",
                    "dynamic_update_slice_in_dim", "squeeze",
                    "broadcast_to", "swapaxes", "ldexp"):
            args = e.args[:1] if leaf in ("take", "clip", "pad") \
                else e.args
            return _widest([self.classify(a) for a in args]
                           or [None])
        if leaf in ("isnan", "isfinite", "isinf", "logical_and",
                    "logical_or", "logical_not"):
            return BOOL
        if leaf == "arange":
            # without an explicit dtype the result depends on x64 mode
            return None
        return None

    def is_int_float_cast(self, e) -> bool:
        """``e`` is (or names) a float cast of a provably-int value."""
        if isinstance(e, ast.Name):
            return e.id in self.float_from_int
        if isinstance(e, ast.Call):
            cast = self.cast_site(e)
            return cast is not None and cast[0] in _FLOATS \
                and cast[1] in _INTS
        return False

    # -- cast-site detection -------------------------------------------
    def cast_site(self, e: ast.Call
                  ) -> Optional[Tuple[str, Optional[str], ast.AST]]:
        """(target class, operand class, operand expr) when ``e`` is a
        dtype cast — ``x.astype(D)`` or ``D(x)`` — else None."""
        leaf = dfmod._leaf(e.func)
        if leaf == "astype" and isinstance(e.func, ast.Attribute) \
                and e.args:
            tgt = _dtype_class_of_expr(e.args[0], self.aliases)
            if tgt is None:
                return None
            return tgt, self.classify(e.func.value), e.func.value
        if leaf in _DTYPE_LEAVES and len(e.args) == 1 \
                and not e.keywords:
            # constructor form jnp.int32(x); require a jnp/np/jax base
            # or a known alias so unrelated calls don't classify
            if isinstance(e.func, ast.Attribute) or leaf in self.aliases:
                return (_DTYPE_LEAVES[leaf], self.classify(e.args[0]),
                        e.args[0])
        return None


# -- annotation discovery ----------------------------------------------------


def _int_const(e) -> Optional[int]:
    """Tiny constant folder for annotation kwargs (2**24, 1 << 20)."""
    if isinstance(e, ast.Constant) and isinstance(e.value, int) \
            and not isinstance(e.value, bool):
        return e.value
    if isinstance(e, ast.BinOp):
        l, r = _int_const(e.left), _int_const(e.right)
        if l is None or r is None:
            return None
        try:
            if isinstance(e.op, ast.Pow):
                return l ** r
            if isinstance(e.op, ast.LShift):
                return l << r
            if isinstance(e.op, ast.Mult):
                return l * r
            if isinstance(e.op, ast.Add):
                return l + r
            if isinstance(e.op, ast.Sub):
                return l - r
        except (OverflowError, ValueError):
            return None
    if isinstance(e, ast.UnaryOp) and isinstance(e.op, ast.USub):
        v = _int_const(e.operand)
        return -v if v is not None else None
    return None


class _Annotations:
    """@precision / @order_insensitive decorators per function key,
    with parsed static kwargs."""

    def __init__(self, cg: cgmod.CallGraph):
        self.precision: Dict[str, Dict[str, object]] = {}
        self.order: Set[str] = set()
        for key, fi in cg.funcs.items():
            node = fi.node
            if isinstance(node, ast.Lambda):
                continue
            for d in node.decorator_list:
                call = d if isinstance(d, ast.Call) else None
                target = call.func if call else d
                leaf = dfmod._leaf(target)
                if leaf == "precision":
                    info: Dict[str, object] = {}
                    if call:
                        for kw in call.keywords:
                            if kw.arg == "accum_terms":
                                info["accum_terms"] = _int_const(kw.value)
                            elif kw.arg == "compensated":
                                info["compensated"] = (
                                    isinstance(kw.value, ast.Constant)
                                    and kw.value.value is True)
                            elif kw.arg == "bits":
                                info["bits"] = _int_const(kw.value)
                    self.precision[key] = info
                elif leaf == "order_insensitive":
                    self.order.add(key)

    def _ancestors(self, cg: cgmod.CallGraph, key: str) -> List[str]:
        out = [key]
        fi = cg.funcs.get(key)
        if fi is None:
            return out
        qual = fi.qualname
        while ".<locals>." in qual:
            qual = qual.rsplit(".<locals>.", 1)[0]
            out.append(f"{fi.module}:{qual}")
        return out

    def precision_for(self, cg, key: str) -> Optional[Dict[str, object]]:
        for k in self._ancestors(cg, key):
            if k in self.precision:
                return self.precision[k]
        return None

    def order_for(self, cg, key: str) -> bool:
        return any(k in self.order for k in self._ancestors(cg, key))


# -- the families ------------------------------------------------------------


def _module_aliases(mod: ModuleSource) -> Dict[str, str]:
    """Module-level dtype aliases (``f32 = jnp.float32``)."""
    out: Dict[str, str] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            leaf = dfmod._leaf(node.value)
            if leaf in _DTYPE_LEAVES:
                out[node.targets[0].id] = _DTYPE_LEAVES[leaf]
    return out


def check_project(mods: Sequence[ModuleSource],
                  cg: Optional[cgmod.CallGraph] = None,
                  df: Optional[dfmod.DeviceDataflow] = None
                  ) -> List[Tuple[Optional[str], Finding]]:
    if df is None:
        df = dfmod.build(mods, cg)
    cg = df.cg
    ann = _Annotations(cg)
    bymod = {m.relpath: m for m in mods}
    out: List[Tuple[Optional[str], Finding]] = []

    pallas_keys: Set[str] = set()
    for site in df.sites:
        if site.kind == "pallas_call":
            pallas_keys |= df.closure_of(site.body_keys)
    # rules_trace's heuristic: a *_ref parameter marks a Pallas kernel
    # body even before its pallas_call site exists
    for key, fi in cg.funcs.items():
        node = fi.node
        if not isinstance(node, ast.Lambda) and any(
                a.arg.endswith("_ref") for a in node.args.args):
            pallas_keys.add(key)

    for key in sorted(df.traced | pallas_keys):
        fi = cg.funcs.get(key)
        if fi is None:
            continue
        mod = bymod.get(fi.relpath)
        if mod is None:
            continue
        env = _DtypeEnv(fi.node, _module_aliases(mod))
        p_ann = ann.precision_for(cg, key)
        o_ann = ann.order_for(cg, key)

        for node in _own_nodes(fi.node):
            if not isinstance(node, (ast.Call, ast.BinOp, ast.Compare)):
                continue
            # (1) precision-narrowing
            if isinstance(node, ast.Call):
                cast = env.cast_site(node)
                if cast is not None:
                    tgt, src, _operand = cast
                    if tgt in (F32, F16, I32) and src in _WIDE \
                            and p_ann is None:
                        out.append((fi.relpath, Finding(
                            rule="precision-narrowing", path=fi.relpath,
                            line=node.lineno,
                            message=(
                                f"{fi.qualname}: a {src} value is cast "
                                f"to {tgt} in a traced function with no "
                                f"@precision(bits=..., reason=...) "
                                f"budget — if a guard makes this safe "
                                f"(span guard, exact split), annotate "
                                f"the site with it"),
                            context=f"{fi.qualname}:narrow:{src}->{tgt}")))
            # (2) accumulation-bound
            acc = _accum_site(node, env)
            if acc is not None:
                acc_cls, label = acc
                if acc_cls in _NARROW_FLOAT:
                    terms = (p_ann or {}).get("accum_terms")
                    comp = bool((p_ann or {}).get("compensated"))
                    limit = 2 ** _MANTISSA[acc_cls]
                    if p_ann is None or (terms is None and not comp):
                        out.append((fi.relpath, Finding(
                            rule="accumulation-bound", path=fi.relpath,
                            line=node.lineno,
                            message=(
                                f"{fi.qualname}: {label} accumulates in "
                                f"{acc_cls} with no static term bound — "
                                f"declare @precision(accum_terms=N) "
                                f"(N <= 2**{_MANTISSA[acc_cls]}) or "
                                f"compensated=True, or accumulate in "
                                f"f64 via dtype="),
                            context=f"{fi.qualname}:accum:{label}")))
                    elif terms is not None and terms > limit:
                        out.append((fi.relpath, Finding(
                            rule="accumulation-bound", path=fi.relpath,
                            line=node.lineno,
                            message=(
                                f"{fi.qualname}: declared accum_terms="
                                f"{terms} exceeds the {acc_cls} "
                                f"mantissa bound 2**{_MANTISSA[acc_cls]}"
                                f" — the sum loses integer exactness "
                                f"before the bound is reached"),
                            context=f"{fi.qualname}:accum-over:{label}")))
            # (3) reduction-order-determinism
            if isinstance(node, ast.Call) and key in df.spmd_reachable:
                leaf = dfmod._leaf(node.func)
                if leaf in _ORDER_COLLECTIVES or leaf in _ORDER_SEGMENTS:
                    opnd = env.classify(node.args[0]) if node.args \
                        else None
                    # integer/bool operands are exact under any
                    # grouping; NEUTRAL is a python literal (device
                    # counting via psum(1) — exact small constants)
                    if opnd not in (_INTS | {BOOL, NEUTRAL}) \
                            and not o_ann:
                        out.append((fi.relpath, Finding(
                            rule="reduction-order-determinism",
                            path=fi.relpath, line=node.lineno,
                            message=(
                                f"{fi.qualname}: {leaf}() over a "
                                f"{opnd or 'non-provable'} dtype inside "
                                f"a shard_map closure — the reduction "
                                f"grouping depends on mesh shape; "
                                f"declare @order_insensitive("
                                f"tolerance=...) (certified at 1/2/4/8 "
                                f"devices) or make the operand "
                                f"integer/exact"),
                            context=f"{fi.qualname}:order:{leaf}")))
            # (4) mixed-dtype-comparison (Pallas bodies only)
            if isinstance(node, ast.Compare) and key in pallas_keys:
                sides = [node.left] + list(node.comparators)
                classes = [env.classify(s) for s in sides]
                if F32 in classes and F64 in classes:
                    out.append((fi.relpath, Finding(
                        rule="mixed-dtype-comparison", path=fi.relpath,
                        line=node.lineno,
                        message=(f"{fi.qualname}: comparison mixes f32 "
                                 f"and f64 operands inside a Pallas "
                                 f"body — the implicit promotion "
                                 f"differs across backends"),
                        context=f"{fi.qualname}:cmp:f32f64")))
                else:
                    for s in sides:
                        if env.is_int_float_cast(s):
                            out.append((fi.relpath, Finding(
                                rule="mixed-dtype-comparison",
                                path=fi.relpath, line=node.lineno,
                                message=(
                                    f"{fi.qualname}: an integer value "
                                    f"is cast to float to feed a "
                                    f"comparison inside a Pallas body "
                                    f"— past 2**24 the rounding flips "
                                    f"branches between backends; "
                                    f"compare in integer space"),
                                context=(f"{fi.qualname}:cmp:"
                                         f"intcast"))))
                            break
    return out


def _accum_site(node, env: _DtypeEnv
                ) -> Optional[Tuple[Optional[str], str]]:
    """(accumulator dtype class, label) when ``node`` is a reduction
    that accumulates; None otherwise. A ``dtype=`` kwarg on the
    reduction is the accumulator (the f64-accumulate escape)."""
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
        return _widest([env.classify(node.left),
                        env.classify(node.right)]), "matmul(@)"
    if not isinstance(node, ast.Call):
        return None
    leaf = dfmod._leaf(node.func)
    if leaf not in _ACCUM_LEAVES:
        return None
    # require a plausible numeric base (jnp/np/lax) or bare name import
    if isinstance(node.func, ast.Attribute):
        d = dfmod._dotted(node.func) or ""
        base = d.split(".", 1)[0]
        if base not in ("jnp", "np", "jax", "lax", "numpy"):
            return None
    for kw in node.keywords:
        if kw.arg == "dtype":
            cls = _dtype_class_of_expr(kw.value, env.aliases)
            if cls is not None:
                return cls, f"{leaf}()"
    if leaf in ("dot", "matmul", "einsum"):
        cls = _widest([env.classify(a) for a in node.args
                       if not isinstance(a, ast.Constant)] or [None])
        return cls, f"{leaf}()"
    if not node.args:
        return None
    return env.classify(node.args[0]), f"{leaf}()"
