"""Lock-discipline annotations.

:func:`guarded_by` is a class decorator declaring that certain instance
fields may only be touched while a named lock attribute of the same
object is held:

    @guarded_by("_cache_lock", "_decode_cache", "_merge_cache")
    class TimeSeriesPartition: ...

Decorators stack for fields guarded by different locks. The decorator
is runtime-neutral (it only records ``cls.__guarded_by__``); the AST
checker in ``filodb_tpu.lint.rules_lock`` enforces, statically:

  * every read/write of a guarded ``self.<field>`` happens inside a
    ``with self.<lock>:`` block (``__init__`` and methods whose name
    ends in ``_locked`` — the caller-holds-the-lock convention — are
    exempt);
  * accesses through another object (``part._decode_cache``) require
    ``with part.<lock>:``; foreign-object checks apply to
    underscore-prefixed fields only (public counters may be read racily
    by design — suppress with a pragma where that is intentional);
  * no blocking call (sleep / socket / dial / fan-out) is made while
    any declared lock is held.

Module-level shared state uses a plain dict assignment the checker
reads the same way::

    __guarded_by__ = {"_channels": "_channels_lock"}
"""

from __future__ import annotations


def guarded_by(lock: str, *fields: str):
    """Declare ``fields`` guarded by instance attribute ``lock``."""
    def deco(cls):
        decls = dict(getattr(cls, "__guarded_by__", {}) or {})
        for f in fields:
            decls[f] = lock
        cls.__guarded_by__ = decls
        return cls
    return deco


def single_writer(reason: str):
    """Declare that instances of this class are mutated by at most ONE
    thread at a time *by design* — the per-shard single-writer
    invariant (a shard's index/partitions/stats are touched only by the
    thread that currently owns the shard: its ingestion driver, or the
    bootstrap that runs strictly before the driver starts; ownership
    transfer is a happens-before edge the membership protocol pins).

    graftlint's ``thread-unguarded-shared-state`` inference reasons per
    (class, attribute) and cannot see that two roots mutate *disjoint
    instances*; this declaration is the documented escape hatch — and,
    like a pragma, it REQUIRES a reason string. Runtime-neutral: only
    records ``cls.__single_writer__``."""
    def deco(cls):
        cls.__single_writer__ = reason
        return cls
    return deco
