"""Memoized ``ast.walk`` for the lint engine's hot sweeps.

The gate run walks every module tree a dozen-plus times (one per rule
family) and every function subtree several more (dataflow fixpoints,
donation checks, the body walker). The trees are immutable for the
duration of a run, so the flattened node list is computed once per
root and shared — generator/deque overhead was the single largest
line item in the 30s pre-commit budget.

Cache entries hold a strong reference to the root node, so ``id``
reuse cannot alias a stale entry; ``run_lint`` clears the cache at the
top of each run to bound memory across repeated runs in one process.
Only cache roots that are re-walked (module trees, function defs) —
one-shot walks of small sub-expressions should keep calling
``ast.walk`` directly rather than paying a cache slot.
"""

from __future__ import annotations

import ast
from typing import Dict, Tuple

_CACHE: Dict[int, Tuple[ast.AST, Tuple[ast.AST, ...]]] = {}


def walk_nodes(root: ast.AST) -> Tuple[ast.AST, ...]:
    """``tuple(ast.walk(root))``, computed once per root per run."""
    ent = _CACHE.get(id(root))
    if ent is None or ent[0] is not root:
        ent = (root, tuple(ast.walk(root)))
        _CACHE[id(root)] = ent
    return ent[1]


def clear() -> None:
    _CACHE.clear()
