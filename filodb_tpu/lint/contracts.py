"""Kernel contract declarations.

A :func:`kernel_contract` decorator sits on every device-kernel entry
point in the package — the two real ``pallas_call`` wrappers, the jitted
XLA kernels, the shard_map collectives, and the host-side dispatchers
that gate them — and states, in one checkable place, what the docstrings
used to promise:

  * block shapes, dtypes, and memory spaces (Pallas kinds), plus the
    worst-case configuration the dispatcher will admit;
  * the VMEM budget the footprint of those blocks must fit;
  * the trailing-dim tiling the TPU requires ((sublane, 128), sublane
    8/16/32 by itemsize);
  * grid/index-map in-bounds behavior;
  * whether inputs ride int31 relative timestamps, and which dispatcher
    predicate proves the span fits;
  * an ``example()`` of abstract inputs so ``jax.eval_shape`` can check
    the wrapper's output shapes/dtypes without a TPU (or a fully custom
    ``check()`` for kernels that need an axis/mesh context).

This module is imported by the hot kernel modules, so it stays
dependency-free and does nothing at runtime beyond attaching the
declaration and registering it; all verification lives in
``filodb_tpu.lint.rules_kernel`` and runs only under the linter.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Sequence, Tuple

VMEM_BYTES = 16 << 20          # per-core VMEM (v4/v5 class parts)

# minimum sublane count by dtype itemsize: trailing dims must tile to
# (sublane, 128)
SUBLANE_BY_ITEMSIZE = {1: 32, 2: 16, 4: 8}

VMEM, SMEM, HBM, ANY, SEM = "vmem", "smem", "hbm", "any", "semaphore"


@dataclass(frozen=True)
class Block:
    """One declared array block (input, output, or scratch).

    ``shape`` is the worst-case BLOCK shape resident on chip at once
    (double buffering spelled out in the shape, e.g. leading 2).
    ``array_shape`` + ``index_map`` (block-index convention, as in
    ``pl.BlockSpec``) opt the block into the grid-bounds check.
    ``tiled=False`` exempts a VMEM block from the (sublane, 128) check —
    scalars/params and 1-D vectors."""
    name: str
    shape: Tuple[int, ...]
    dtype: str = "float32"
    space: str = VMEM
    tiled: bool = True
    array_shape: Optional[Tuple[int, ...]] = None
    index_map: Optional[Callable] = None

    def itemsize(self) -> int:
        import numpy as np
        return int(np.dtype(self.dtype).itemsize)

    def nbytes(self) -> int:
        n = self.itemsize()
        for d in self.shape:
            n *= int(d)
        return n


@dataclass
class KernelContract:
    """The checked declaration attached to a kernel entry point."""
    name: str
    kind: str                          # pallas | jit | shard_map | dispatch
    fn: Callable = None
    module: str = ""
    qualname: str = ""
    grid: Optional[Tuple[int, ...]] = None
    blocks: Tuple[Block, ...] = ()
    scratch: Tuple[Block, ...] = ()
    outputs: Tuple[Block, ...] = ()
    vmem_budget: Optional[int] = None
    # inputs are int32 offsets relative to a base: the dispatcher
    # predicate named here must prove the whole span fits rel_time_bits
    rel_time_bits: Optional[int] = None
    span_guard: Optional[str] = None
    # example() -> (args, kwargs) of ShapeDtypeStructs/static values for
    # jax.eval_shape; expect(out) -> error string or None
    example: Optional[Callable[[], Tuple[tuple, dict]]] = None
    expect: Optional[Callable[[object], Optional[str]]] = None
    # fully custom abstract check (mesh/axis contexts): -> error or None
    check: Optional[Callable[[], Optional[str]]] = None
    notes: str = ""

    def all_vmem_blocks(self) -> Tuple[Block, ...]:
        return tuple(b for b in (*self.blocks, *self.scratch,
                                 *self.outputs) if b.space == VMEM)

    def vmem_footprint(self) -> int:
        return sum(b.nbytes() for b in self.all_vmem_blocks())


# (module, name) -> contract; keyed so re-execution of a module (tests,
# importlib.reload) replaces rather than duplicates
CONTRACTS: Dict[Tuple[str, str], KernelContract] = {}


def kernel_contract(name: str, *, kind: str,
                    grid: Optional[Tuple[int, ...]] = None,
                    blocks: Sequence[Block] = (),
                    scratch: Sequence[Block] = (),
                    outputs: Sequence[Block] = (),
                    vmem_budget: Optional[int] = None,
                    rel_time_bits: Optional[int] = None,
                    span_guard: Optional[str] = None,
                    example: Optional[Callable] = None,
                    expect: Optional[Callable] = None,
                    check: Optional[Callable] = None,
                    notes: str = ""):
    """Attach and register a :class:`KernelContract`.

    Stack OUTSIDE ``jax.jit`` (closest to the reader) so the registered
    callable is the jitted entry point the rest of the code calls."""
    def deco(fn):
        c = KernelContract(
            name=name, kind=kind, fn=fn,
            module=getattr(fn, "__module__", "") or "",
            qualname=getattr(fn, "__qualname__",
                             getattr(fn, "__name__", name)),
            grid=tuple(grid) if grid is not None else None,
            blocks=tuple(blocks), scratch=tuple(scratch),
            outputs=tuple(outputs), vmem_budget=vmem_budget,
            rel_time_bits=rel_time_bits, span_guard=span_guard,
            example=example, expect=expect, check=check, notes=notes)
        CONTRACTS[(c.module, name)] = c
        try:
            fn.__kernel_contract__ = c
        except (AttributeError, TypeError):   # e.g. functools.partial
            pass
        return fn
    return deco


def contracts_for_module(module: str):
    return [c for (m, _), c in sorted(CONTRACTS.items()) if m == module]
