"""Whole-program call graph + interprocedural lock analysis.

The PR 2 lock rules see one function body at a time; both bugs they
caught since (the ``_merge_cache`` race, the abort-vs-driver-start
registration gate) lived across *call chains* and *lock pairs*. This
module is the engine under graftlint's concurrency families
(``rules_concurrency``): it builds a project call graph over the parsed
:class:`~filodb_tpu.lint.ModuleSource` set and computes, statically:

  * **definitions** — every function, method, nested closure, and
    lambda, keyed by a module-qualified name (``pkg.mod:Cls.meth``);
  * **edges** — call sites resolved by lexical scope, import tables,
    ``self.``-method dispatch, constructor-typed locals/attributes
    (``self._q = queue.Queue()`` makes ``self._q.get()`` a Queue.get),
    and a unique-method fallback (an attribute call resolves to a class
    method only when exactly one class in the project defines it).
    Edges are kinded: ``call`` (same thread, held locks flow through),
    ``thread`` (``threading.Thread(target=...)`` / executor
    ``.submit(fn)`` — a NEW thread root, empty held set), ``callback``
    (a function reference passed as an argument — may run later on
    another thread: reachability flows, held locks do not);
  * **lock behavior** — per function: canonical locks acquired (and
    what was already held), calls and blocking primitives with the
    lexically-held set at each site, compound mutations of shared
    attributes/globals;
  * **propagation** — ``may_held`` (union over callers: which locks can
    be held on entry — feeds the acquisition-order graph and the
    deep blocking rule), ``must_held`` (intersection over reachable
    callers: which locks are *always* held on entry — feeds the
    unguarded-shared-state guard check), per-thread-root forward
    reachability, and a transitive ``blocks`` summary (the nearest
    blocking primitive reachable from each function, with one example
    call chain for the report).

Canonical lock names: ``Cls.attr`` for instance locks (all instances
of a class share one order node — the standard lock-order abstraction),
``pkg.mod:name`` for module globals. A ``with`` on an attribute whose
owner cannot be typed canonicalizes to ``?.attr``: it still counts as
"a lock is held" for the blocking rule but is excluded from the order
graph (an unknown owner would alias unrelated locks into false cycles).

Everything here is pure AST work — nothing is imported or executed.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from filodb_tpu.lint import ModuleSource

# builtin constructor types we track for blocking-primitive typing
from filodb_tpu.lint.astwalk import walk_nodes
_BUILTIN_TYPES = {
    ("threading", "Lock"): "threading.Lock",
    ("threading", "RLock"): "threading.RLock",
    ("threading", "Condition"): "threading.Condition",
    ("threading", "Event"): "threading.Event",
    ("threading", "Semaphore"): "threading.Semaphore",
    ("threading", "BoundedSemaphore"): "threading.Semaphore",
    ("threading", "Thread"): "threading.Thread",
    ("queue", "Queue"): "queue.Queue",
    ("queue", "SimpleQueue"): "queue.Queue",
}

_LOCK_TYPES = {"threading.Lock", "threading.RLock", "threading.Semaphore"}

# method names that mutate their receiver in place (compound — not the
# GIL-atomic single-rebind publish idiom)
_MUTATOR_METHODS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "add", "discard", "update", "setdefault", "appendleft", "popleft",
}

# blocking primitives by call-leaf name (unconditional)
_BLOCKING_LEAVES = {
    "sleep": "time.sleep",
    "urlopen": "urllib.urlopen",
    "create_connection": "socket dial",
    "getaddrinfo": "DNS resolve",
    "fsync": "os.fsync",
    "result": "Future.result",
    "block_until_ready": "device sync",
    "device_get": "device sync",
    "check_output": "subprocess",
    "check_call": "subprocess",
    "run_until_complete": "event loop",
}
_BLOCKING_BASES = {"requests": "HTTP fetch", "subprocess": "subprocess",
                   "socket": "socket op"}

# project functions that ARE blocking primitives even though their body
# hides the wait behind an abstraction the leaf table can't see
# (qualified by "Cls.name" or bare function name)
BLOCKING_QUALNAMES = {
    "SplitResult.get": "device sync (per-batch device->host copy)",
}

_SPAWN_LEAVES = {"submit", "run_in_executor", "start_new_thread",
                 "call_soon_threadsafe", "apply_async"}


def module_dotted(relpath: str) -> str:
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    return p.replace("/", ".")


@dataclass
class CallSite:
    """One resolved call inside a function body."""
    line: int
    held: FrozenSet[str]            # canonical locks lexically held
    callees: Tuple[str, ...]        # FuncInfo keys (may be empty)
    kind: str                       # call | thread | callback
    blocking: Optional[str] = None  # blocking-primitive label, if any
    label: str = ""                 # source-ish name for messages


@dataclass
class Acquisition:
    lock: str                       # canonical name
    line: int
    held: FrozenSet[str]            # locks lexically held at acquisition


@dataclass
class Mutation:
    """A compound mutation of shared state (attr or module global)."""
    target: str                     # "Cls.attr" or "pkg.mod:name"
    line: int
    held: FrozenSet[str]
    detail: str                     # e.g. "drivers.pop(...)"


@dataclass
class FuncInfo:
    key: str                        # "pkg.mod:Qual.Name" — unique id
    relpath: str
    module: str
    cls: Optional[str]
    name: str
    qualname: str                   # Cls.meth / outer.<locals>.inner
    node: ast.AST
    lineno: int
    thread_root: Optional[str] = None   # @thread_root name, if marked
    sites: List[CallSite] = field(default_factory=list)
    acquisitions: List[Acquisition] = field(default_factory=list)
    mutations: List[Mutation] = field(default_factory=list)


@dataclass
class ClassInfo:
    name: str
    relpath: str
    module: str
    node: ast.ClassDef
    methods: Dict[str, FuncInfo] = field(default_factory=dict)
    attr_types: Dict[str, str] = field(default_factory=dict)
    guarded: Dict[str, str] = field(default_factory=dict)  # field -> lock
    single_writer: Optional[str] = None     # @single_writer reason


def _decorator_names(node) -> List[str]:
    out = []
    for d in getattr(node, "decorator_list", ()):
        t = d.func if isinstance(d, ast.Call) else d
        if isinstance(t, ast.Attribute):
            out.append(t.attr)
        elif isinstance(t, ast.Name):
            out.append(t.id)
    return out


def _thread_root_name(node) -> Optional[str]:
    """The @thread_root marker (bare or called with a name)."""
    for d in getattr(node, "decorator_list", ()):
        t = d.func if isinstance(d, ast.Call) else d
        leaf = t.attr if isinstance(t, ast.Attribute) else \
            t.id if isinstance(t, ast.Name) else None
        if leaf == "thread_root":
            if isinstance(d, ast.Call):
                for a in d.args:
                    if isinstance(a, ast.Constant) \
                            and isinstance(a.value, str):
                        return a.value
            return getattr(node, "name", "<root>")
    return None


class CallGraph:
    """The project-wide graph plus the propagation results."""

    def __init__(self, mods: Sequence[ModuleSource]):
        self.mods = list(mods)
        self.funcs: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}        # by class name
        self._classes_by_mod: Dict[Tuple[str, str], ClassInfo] = {}
        # method name -> [class names defining it] (unique-name fallback)
        self._method_owners: Dict[str, List[str]] = {}
        # module dotted -> {local name -> ("mod", dotted) | ("func", key)
        #                   | ("class", class name)}
        self._scopes: Dict[str, Dict[str, Tuple[str, str]]] = {}
        # module dotted -> {global name -> type}
        self._global_types: Dict[str, Dict[str, str]] = {}
        # module dotted -> set of module-level mutable-global names
        self._module_globals: Dict[str, Set[str]] = {}
        self._module_guarded: Dict[str, Dict[str, str]] = {}
        self._index()
        self._analyze_bodies()
        # propagation products (computed lazily via compute())
        self.may_held: Dict[str, FrozenSet[str]] = {}
        self.must_held: Dict[str, FrozenSet[str]] = {}
        # func key -> (caller key, line, lock) provenance for may_held
        self.held_via: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.blocks: Dict[str, Tuple[str, Tuple[Tuple[str, int], ...]]] = {}
        self.roots: Dict[str, str] = {}     # func key -> root kind/name
        self.reachable_from: Dict[str, Set[str]] = {}
        self.compute()

    # -- pass 1: definitions, imports, types -------------------------------

    def _index(self) -> None:
        for mod in self.mods:
            dotted = module_dotted(mod.relpath)
            scope: Dict[str, Tuple[str, str]] = {}
            self._scopes[dotted] = scope
            self._global_types.setdefault(dotted, {})
            self._module_globals.setdefault(dotted, set())
            for node in mod.tree.body:
                if isinstance(node, (ast.Import, ast.ImportFrom)):
                    self._index_import(node, scope)
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._index_class(mod, dotted, node, scope)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    self._index_func(mod, dotted, node, None, node.name,
                                     scope)
                elif isinstance(node, ast.Assign):
                    self._index_module_assign(mod, dotted, node)
        # attribute typing runs after EVERY class is indexed, so
        # annotations/constructors referencing later-defined classes
        # still resolve
        for ci in self._classes_by_mod.values():
            self._type_class_attrs(ci)

    def _index_import(self, node, scope) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                scope[name] = ("mod", alias.name if alias.asname
                               else alias.name.split(".")[0])
        else:
            if node.module is None or node.level:
                return
            for alias in node.names:
                name = alias.asname or alias.name
                scope[name] = ("import_from", f"{node.module}:{alias.name}")

    def _index_module_assign(self, mod, dotted, node: ast.Assign) -> None:
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            if t.id == "__guarded_by__" and isinstance(node.value, ast.Dict):
                table = {}
                for k, v in zip(node.value.keys, node.value.values):
                    if isinstance(k, ast.Constant) \
                            and isinstance(v, ast.Constant):
                        table[str(k.value)] = str(v.value)
                self._module_guarded.setdefault(dotted, {}).update(table)
                continue
            ty = self._expr_type_static(node.value, dotted)
            if ty:
                self._global_types[dotted][t.id] = ty
            if isinstance(node.value, (ast.Dict, ast.List, ast.Set,
                                       ast.DictComp, ast.ListComp,
                                       ast.Call)):
                self._module_globals[dotted].add(t.id)

    def _index_class(self, mod, dotted, node: ast.ClassDef, scope) -> None:
        ci = ClassInfo(name=node.name, relpath=mod.relpath, module=dotted,
                       node=node)
        # @guarded_by / @single_writer declarations (rules_lock
        # semantics shared with filodb_tpu.lint.locks)
        for d in node.decorator_list:
            if isinstance(d, ast.Call):
                t = d.func
                leaf = t.attr if isinstance(t, ast.Attribute) else \
                    t.id if isinstance(t, ast.Name) else None
                vals = [a.value for a in d.args
                        if isinstance(a, ast.Constant)
                        and isinstance(a.value, str)]
                if leaf == "guarded_by" and len(vals) >= 2:
                    for f in vals[1:]:
                        ci.guarded[f] = vals[0]
                elif leaf == "single_writer" and vals:
                    ci.single_writer = vals[0]
        self._classes_by_mod[(dotted, node.name)] = ci
        # first definition wins for the by-name map; ambiguity recorded
        self.classes.setdefault(node.name, ci)
        scope.setdefault(node.name, ("class", node.name))
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = self._index_func(mod, dotted, item, node.name,
                                      f"{node.name}.{item.name}", scope)
                ci.methods[item.name] = fi
                self._method_owners.setdefault(item.name, []).append(
                    node.name)
    def _type_class_attrs(self, ci: ClassInfo) -> None:
        """Attribute types from every method's `self.x = T(...)` and
        `self.x = param` where the parameter annotation names a class."""
        for item in ci.node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                params = {a.arg: self._annotation_type(a.annotation)
                          for a in item.args.args if a.annotation}
                for sub in walk_nodes(item):
                    if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                        t = sub.targets[0]
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            ty = self._expr_type_static(sub.value,
                                                        ci.module)
                            if ty is None and isinstance(sub.value,
                                                         ast.Name):
                                ty = params.get(sub.value.id)
                            if ty:
                                ci.attr_types.setdefault(t.attr, ty)

    def _annotation_type(self, ann) -> Optional[str]:
        """A parameter annotation that names a project class (bare or
        string-quoted), else None."""
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.strip().strip("'\"")
        elif isinstance(ann, ast.Name):
            name = ann.id
        elif isinstance(ann, ast.Attribute):
            name = ann.attr
        else:
            return None
        return name if name in self.classes else None

    def _index_func(self, mod, dotted, node, cls: Optional[str],
                    qualname: str, scope) -> FuncInfo:
        key = f"{dotted}:{qualname}"
        fi = FuncInfo(key=key, relpath=mod.relpath, module=dotted,
                      cls=cls, name=getattr(node, "name", "<lambda>"),
                      qualname=qualname, node=node, lineno=node.lineno,
                      thread_root=_thread_root_name(node))
        self.funcs[key] = fi
        if cls is None:
            scope.setdefault(getattr(node, "name", qualname),
                             ("func", key))
        # nested defs (closures) — indexed so thread targets resolve
        for item in ast.iter_child_nodes(node):
            self._index_nested(mod, dotted, item, cls, qualname)
        return fi

    def _index_nested(self, mod, dotted, node, cls, parent_qual) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._index_func(
                mod, dotted, node, cls,
                f"{parent_qual}.<locals>.{node.name}", self._scopes[dotted])
            return
        for item in ast.iter_child_nodes(node):
            self._index_nested(mod, dotted, item, cls, parent_qual)

    def _expr_type_static(self, expr, dotted) -> Optional[str]:
        """Type of a constructor-ish expression, or None."""
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                ty = self._expr_type_static(v, dotted)
                if ty:
                    return ty
            return None
        if isinstance(expr, ast.IfExp):
            return (self._expr_type_static(expr.body, dotted)
                    or self._expr_type_static(expr.orelse, dotted))
        if not isinstance(expr, ast.Call):
            return None
        f = expr.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            ty = _BUILTIN_TYPES.get((f.value.id, f.attr))
            if ty:
                return ty
            return f.attr if f.attr in self.classes else None
        if isinstance(f, ast.Name):
            if f.id in self.classes:
                return f.id
            ent = self._scopes.get(dotted, {}).get(f.id)
            if ent and ent[0] == "import_from":
                leaf = ent[1].split(":")[1]
                ty = _BUILTIN_TYPES.get(tuple(ent[1].split(":")))
                if ty:
                    return ty
                if leaf in self.classes:
                    return leaf
        return None

    # -- pass 2: per-function lexical analysis ------------------------------

    def _analyze_bodies(self) -> None:
        for fi in list(self.funcs.values()):
            _BodyWalker(self, fi).run()

    # -- resolution helpers -------------------------------------------------

    def class_of(self, name: str) -> Optional[ClassInfo]:
        return self.classes.get(name)

    def resolve_method(self, cls_name: str, meth: str) -> Optional[str]:
        ci = self.classes.get(cls_name)
        if ci and meth in ci.methods:
            return ci.methods[meth].key
        # one level of bases by name
        if ci:
            for b in ci.node.bases:
                bn = b.id if isinstance(b, ast.Name) else \
                    b.attr if isinstance(b, ast.Attribute) else None
                if bn and bn != cls_name:
                    bi = self.classes.get(bn)
                    if bi and meth in bi.methods:
                        return bi.methods[meth].key
        return None

    def unique_method(self, meth: str) -> Optional[str]:
        """Last-resort resolution for an attribute call on an untyped
        receiver: the method name must be defined by exactly ONE class
        in the project AND be multi-word/private (``flush_all``,
        ``_adopt_shard``) — generic verbs (``flush``, ``get``,
        ``read``) alias stdlib/file objects into false edges."""
        if "_" not in meth:
            return None
        owners = self._method_owners.get(meth, [])
        if len(owners) == 1:
            return self.resolve_method(owners[0], meth)
        return None

    # -- propagation --------------------------------------------------------

    def compute(self) -> None:
        self._compute_roots()
        self._propagate_may_held()
        self._compute_blocks()
        self._propagate_must_held()
        self._compute_reachability()

    def _compute_roots(self) -> None:
        for fi in self.funcs.values():
            if fi.thread_root is not None:
                self.roots[fi.key] = fi.thread_root
        for fi in self.funcs.values():
            for s in fi.sites:
                if s.kind == "thread":
                    for c in s.callees:
                        self.roots.setdefault(
                            c, self.funcs[c].qualname)
        # module-level __thread_roots__ declarations
        for mod in self.mods:
            dotted = module_dotted(mod.relpath)
            for node in mod.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) \
                                and t.id == "__thread_roots__" \
                                and isinstance(node.value,
                                               (ast.Tuple, ast.List)):
                            for e in node.value.elts:
                                if isinstance(e, ast.Constant):
                                    k = f"{dotted}:{e.value}"
                                    if k in self.funcs:
                                        self.roots.setdefault(
                                            k, str(e.value))

    def _propagate_may_held(self) -> None:
        """may_held(g) = union over call edges f->g of
        (may_held(f) | lexical held at the site). Thread/callback edges
        reset to empty (a new thread holds nothing of its spawner)."""
        may: Dict[str, Set[str]] = {k: set() for k in self.funcs}
        work = list(self.funcs.keys())
        while work:
            fkey = work.pop()
            fi = self.funcs[fkey]
            base = may[fkey]
            for s in fi.sites:
                if s.kind != "call":
                    continue
                incoming = base | set(s.held)
                if not incoming:
                    continue
                for c in s.callees:
                    if c not in may:
                        continue
                    new = incoming - may[c]
                    if new:
                        may[c] |= new
                        for lk in new:
                            self.held_via.setdefault(
                                (c, lk), (fkey, s.line))
                        work.append(c)
        self.may_held = {k: frozenset(v) for k, v in may.items()}

    def _compute_blocks(self) -> None:
        """blocks(f): a blocking-primitive label reachable from f via
        same-thread call edges, with one example chain
        ((func key, line), ...) ending at the primitive site."""
        blocks: Dict[str, Tuple[str, Tuple[Tuple[str, int], ...]]] = {}
        for fi in self.funcs.values():
            for s in fi.sites:
                if s.blocking and fi.key not in blocks:
                    blocks[fi.key] = (s.blocking, ((fi.key, s.line),))
        changed = True
        while changed:
            changed = False
            for fi in self.funcs.values():
                if fi.key in blocks:
                    continue
                for s in fi.sites:
                    if s.kind != "call":
                        continue
                    for c in s.callees:
                        if c in blocks and c != fi.key:
                            label, chain = blocks[c]
                            if len(chain) < 8:
                                blocks[fi.key] = (
                                    label, ((fi.key, s.line),) + chain)
                                changed = True
                                break
                    if fi.key in blocks:
                        break
        self.blocks = blocks

    def _propagate_must_held(self) -> None:
        """must_held(g) = intersection over root-reachable call edges
        f->g of (must_held(f) | lexical held). Roots and unreached
        functions get the empty set."""
        # collect callers per function, restricted to same-thread edges
        callers: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        for fi in self.funcs.values():
            for s in fi.sites:
                if s.kind != "call":
                    continue
                for c in s.callees:
                    callers.setdefault(c, []).append((fi.key, s.held))
        TOP = None      # lattice top: "all locks"
        must: Dict[str, Optional[FrozenSet[str]]] = \
            {k: TOP for k in self.funcs}
        for r in self.roots:
            must[r] = frozenset()
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for g in self.funcs:
                acc: Optional[FrozenSet[str]] = None
                any_caller = False
                for fkey, held in callers.get(g, ()):  # noqa: B020
                    fm = must.get(fkey)
                    if fm is TOP:
                        continue        # caller itself unreached yet
                    any_caller = True
                    inc = frozenset(fm | held)
                    acc = inc if acc is None else (acc & inc)
                if g in self.roots:
                    acc = frozenset() if acc is None else frozenset()
                    any_caller = True
                if any_caller and acc is not None and must[g] != acc:
                    if must[g] is TOP or acc != must[g]:
                        must[g] = acc
                        changed = True
        self.must_held = {k: (v if v is not None else frozenset())
                          for k, v in must.items()}

    def _compute_reachability(self) -> None:
        """Forward closure per thread root over call+callback edges
        (thread edges start their own root)."""
        succ: Dict[str, Set[str]] = {}
        for fi in self.funcs.values():
            out = succ.setdefault(fi.key, set())
            for s in fi.sites:
                if s.kind in ("call", "callback"):
                    out.update(s.callees)
        for r in self.roots:
            seen = {r}
            stack = [r]
            while stack:
                f = stack.pop()
                for n in succ.get(f, ()):
                    if n not in seen:
                        seen.add(n)
                        stack.append(n)
            self.reachable_from[r] = seen

    # -- queries used by the rules -----------------------------------------

    def guarded_decl(self, target: str) -> Optional[str]:
        """The declared @guarded_by lock for "Cls.attr" / "mod:name"
        targets, if any."""
        if ":" in target:
            dotted, name = target.split(":", 1)
            return self._module_guarded.get(dotted, {}).get(name)
        cls, _, attr = target.partition(".")
        ci = self.classes.get(cls)
        return ci.guarded.get(attr) if ci else None

    def single_writer_decl(self, target: str) -> Optional[str]:
        """The @single_writer reason of the target's owning class, if
        declared (instances owned by one thread at a time by design —
        ownership transfer is a happens-before edge)."""
        if ":" in target:
            return None
        ci = self.classes.get(target.partition(".")[0])
        return ci.single_writer if ci else None

    def order_pairs(self) -> Dict[Tuple[str, str],
                                  Tuple[str, int, Tuple[str, ...]]]:
        """All observed acquisition-order pairs (A then B, A still
        held): {(A, B): (func key, line of B's acquisition, provenance
        chain of how A came to be held)}. Unknown-owner locks (``?.``)
        and self-pairs are excluded — see the module docstring."""
        pairs: Dict[Tuple[str, str],
                    Tuple[str, int, Tuple[str, ...]]] = {}
        for fi in self.funcs.values():
            inherited = self.may_held.get(fi.key, frozenset())
            for acq in fi.acquisitions:
                if acq.lock.startswith("?."):
                    continue
                for h in acq.held | inherited:
                    if h.startswith("?.") or h == acq.lock:
                        continue
                    k = (h, acq.lock)
                    if k not in pairs:
                        chain: Tuple[str, ...] = ()
                        if h not in acq.held:
                            via = self.held_via.get((fi.key, h))
                            if via:
                                chain = (f"{self.funcs[via[0]].qualname} "
                                         f"({via[0].split(':')[0]}:"
                                         f"{via[1]})",)
                        pairs[k] = (fi.key, acq.line, chain)
        return pairs


class _BodyWalker:
    """Lexical walk of one function body: with-lock scopes, call sites,
    blocking primitives, compound mutations. Nested defs are separate
    FuncInfos (they may run later, on another thread) — only their
    *spawn/callback* relationship is recorded here."""

    def __init__(self, cg: CallGraph, fi: FuncInfo):
        self.cg = cg
        self.fi = fi
        self.scope = cg._scopes.get(fi.module, {})
        self.locals: Dict[str, str] = {}        # var -> type name
        ci = cg._classes_by_mod.get((fi.module, fi.cls)) if fi.cls \
            else None
        self.cls_info = ci

    def run(self) -> None:
        node = self.fi.node
        body = node.body if not isinstance(node, ast.Lambda) \
            else [ast.Expr(node.body)]
        # parameter defaults etc. are not walked — call behavior only
        for child in body:
            self._walk(child, frozenset())

    # -- type inference -----------------------------------------------------

    def _expr_type(self, expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if expr.id == "self" and self.fi.cls:
                return self.fi.cls
            ty = self.locals.get(expr.id)
            if ty:
                return ty
            g = self.cg._global_types.get(self.fi.module, {})
            if expr.id in g:
                return g[expr.id]
            ent = self.scope.get(expr.id)
            if ent and ent[0] == "class":
                return None     # a class object, not an instance
            return None
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            base_ty = self._expr_type(expr.value)
            if base_ty:
                ci = self.cg.classes.get(base_ty)
                if ci:
                    return ci.attr_types.get(expr.attr)
        return self.cg._expr_type_static(expr, self.fi.module)

    # -- canonical lock naming ----------------------------------------------

    def _lock_name(self, e) -> Optional[str]:
        """Canonical name for a with-context expression that looks like
        a lock (non-Call Attribute/Name), else None. Semaphores are
        excluded: an admission gate is *designed* to be held across
        blocking work — it bounds concurrency, it is not a mutex."""
        if isinstance(e, ast.Attribute):
            base = e.value
            if isinstance(base, ast.Name):
                ty = self._expr_type(base)
                if ty:
                    ci = self.cg.classes.get(ty)
                    if ci and ci.attr_types.get(e.attr) \
                            == "threading.Semaphore":
                        return None
                    return f"{ty}.{e.attr}"
                return f"?.{e.attr}"
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name):
                ty = self._expr_type(base)
                if ty:
                    return f"{ty}.{e.attr}"
                return f"?.{e.attr}"
            return f"?.{e.attr}"
        if isinstance(e, ast.Name):
            if e.id in self.cg._global_types.get(self.fi.module, {}) \
                    or e.id in self.cg._module_globals.get(
                        self.fi.module, set()):
                return f"{self.fi.module}:{e.id}"
            ty = self.locals.get(e.id)
            if ty in _LOCK_TYPES:
                return f"?.{e.id}"
            return None
        return None

    # -- the walk -----------------------------------------------------------

    def _walk(self, node, held: FrozenSet[str]) -> None:
        if isinstance(node, ast.With):
            inner = set(held)
            for item in node.items:
                self._visit_expr(item.context_expr, held)
                lk = self._lock_name(item.context_expr)
                if lk is not None:
                    self.fi.acquisitions.append(
                        Acquisition(lock=lk, line=node.lineno,
                                    held=frozenset(inner)))
                    inner.add(lk)
            for child in node.body:
                self._walk(child, frozenset(inner))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return      # separate FuncInfo; not this thread's flow
        if isinstance(node, ast.Assign):
            self._visit_assign(node, held)
            for t in node.targets:
                self._visit_expr(t, held, store=True)
            self._visit_expr(node.value, held)
            return
        if isinstance(node, ast.AugAssign):
            self._visit_aug(node, held)
            self._visit_expr(node.value, held)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._visit_del(t, held)
            return
        self._visit_expr_or_children(node, held)

    def _visit_expr_or_children(self, node, held) -> None:
        if isinstance(node, ast.expr):
            self._visit_expr(node, held)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child, held)
            else:
                self._walk(child, held)

    def _visit_expr(self, node, held, store: bool = False) -> None:
        if isinstance(node, ast.Call):
            self._visit_call(node, held)
            return
        if isinstance(node, ast.Lambda):
            return      # body belongs to the lambda FuncInfo
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child, held)

    # -- mutations ----------------------------------------------------------

    def _shared_target(self, e) -> Optional[str]:
        """Canonical shared-state id for an attribute/global expression:
        "Cls.attr" when the owner types to a project class, "mod:name"
        for module globals."""
        if isinstance(e, ast.Attribute):
            ty = self._expr_type(e.value) if isinstance(
                e.value, (ast.Name, ast.Attribute)) else None
            if ty and ty in self.cg.classes:
                return f"{ty}.{e.attr}"
            return None
        if isinstance(e, ast.Name):
            if e.id in self.cg._module_globals.get(self.fi.module, set()):
                return f"{self.fi.module}:{e.id}"
        return None

    def _note_mutation(self, target: Optional[str], node, held,
                       detail: str) -> None:
        if target is None:
            return
        if self.fi.name == "__init__" or self.fi.name.endswith("_locked"):
            return      # construction / caller-holds-the-lock convention
        self.fi.mutations.append(Mutation(
            target=target, line=getattr(node, "lineno", self.fi.lineno),
            held=held, detail=detail))

    def _visit_assign(self, node: ast.Assign, held) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self._note_mutation(self._shared_target(t.value), node,
                                    held, "subscript store")
            elif isinstance(t, ast.Attribute):
                tgt = self._shared_target(t)
                # plain rebind is the GIL-atomic publish idiom — only a
                # read-modify-write of the SAME field is compound
                if tgt and self._reads_target(node.value, t):
                    self._note_mutation(tgt, node, held,
                                        "read-modify-write rebind")
            elif isinstance(t, ast.Name):
                ty = self._expr_type(node.value)
                if ty:
                    self.locals[t.id] = ty
                if t.id in self.cg._module_globals.get(
                        self.fi.module, set()) \
                        and self._declares_global(t.id):
                    if self._reads_name(node.value, t.id):
                        self._note_mutation(
                            f"{self.fi.module}:{t.id}", node, held,
                            "read-modify-write rebind")

    def _visit_aug(self, node: ast.AugAssign, held) -> None:
        t = node.target
        if isinstance(t, ast.Attribute):
            self._note_mutation(self._shared_target(t), node, held,
                                "augmented assign")
        elif isinstance(t, ast.Subscript):
            self._note_mutation(self._shared_target(t.value), node, held,
                                "augmented subscript")
        elif isinstance(t, ast.Name) and self._declares_global(t.id):
            self._note_mutation(f"{self.fi.module}:{t.id}", node, held,
                                "augmented assign")

    def _visit_del(self, t, held) -> None:
        if isinstance(t, ast.Subscript):
            self._note_mutation(self._shared_target(t.value), t, held,
                                "del item")

    def _declares_global(self, name: str) -> bool:
        for sub in walk_nodes(self.fi.node):
            if isinstance(sub, ast.Global) and name in sub.names:
                return True
        return False

    def _reads_target(self, expr, attr: ast.Attribute) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Attribute) and sub.attr == attr.attr:
                return True
        return False

    def _reads_name(self, expr, name: str) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id == name:
                return True
        return False

    # -- calls --------------------------------------------------------------

    def _func_ref(self, e) -> Optional[str]:
        """Resolve an expression used as a function VALUE (not called):
        thread targets, submit args, callbacks."""
        if isinstance(e, ast.Lambda):
            key = f"{self.fi.module}:{self.fi.qualname}" \
                  f".<locals>.<lambda@{e.lineno}>"
            if key not in self.cg.funcs:
                fi = FuncInfo(key=key, relpath=self.fi.relpath,
                              module=self.fi.module, cls=self.fi.cls,
                              name="<lambda>",
                              qualname=f"{self.fi.qualname}.<lambda>",
                              node=e, lineno=e.lineno)
                self.cg.funcs[key] = fi
                _BodyWalker(self.cg, fi).run()
            return key
        if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name):
            ty = self._expr_type(e.value)
            if ty:
                return self.cg.resolve_method(ty, e.attr)
            return None
        if isinstance(e, ast.Attribute) \
                and isinstance(e.value, ast.Attribute) \
                and isinstance(e.value.value, ast.Name):
            # bound-method reference one attribute deeper:
            # `self.result_cache.invalidate` as a callback argument
            ty = self._expr_type(e.value)
            if ty:
                return self.cg.resolve_method(ty, e.attr)
            return None
        if isinstance(e, ast.Name):
            return self._resolve_name_callee(e.id)
        return None

    def _resolve_name_callee(self, name: str) -> Optional[str]:
        # nested def of this function?
        key = f"{self.fi.module}:{self.fi.qualname}.<locals>.{name}"
        if key in self.cg.funcs:
            return key
        # sibling nested def (shared parent scope)
        parent = self.fi.qualname.rsplit(".<locals>.", 1)[0]
        key = f"{self.fi.module}:{parent}.<locals>.{name}"
        if key in self.cg.funcs:
            return key
        # module-level function / import
        ent = self.scope.get(name)
        if ent:
            if ent[0] == "func":
                return ent[1]
            if ent[0] == "class":
                ci = self.cg.classes.get(ent[1])
                if ci and "__init__" in ci.methods:
                    return ci.methods["__init__"].key
                return None
            if ent[0] == "import_from":
                m, leaf = ent[1].split(":", 1)
                k = f"{m}:{leaf}"
                if k in self.cg.funcs:
                    return k
                ci = self.cg._classes_by_mod.get((m, leaf))
                if ci and "__init__" in ci.methods:
                    return ci.methods["__init__"].key
        return None

    def _visit_call(self, node: ast.Call, held) -> None:
        f = node.func
        leaf = f.attr if isinstance(f, ast.Attribute) else \
            f.id if isinstance(f, ast.Name) else None
        callees: List[str] = []
        kind = "call"
        label = leaf or "<call>"
        blocking = None

        base_ty = None
        if isinstance(f, ast.Attribute):
            base_ty = self._expr_type(f.value) \
                if isinstance(f.value, (ast.Name, ast.Attribute)) else None

        # Thread(target=...) spawn
        ctor_ty = self._expr_type(node)
        if ctor_ty == "threading.Thread" or \
                (leaf == "Thread" and ctor_ty is None):
            tgt = None
            for kw in node.keywords:
                if kw.arg == "target":
                    tgt = self._func_ref(kw.value)
            if tgt:
                self.fi.sites.append(CallSite(
                    line=node.lineno, held=held, callees=(tgt,),
                    kind="thread", label="Thread(target=...)"))
            self._visit_args(node, held)
            return

        # executor-style spawn: .submit(fn) etc.
        if leaf in _SPAWN_LEAVES and isinstance(f, ast.Attribute):
            refs = [r for r in (self._func_ref(a) for a in node.args)
                    if r]
            if refs:
                self.fi.sites.append(CallSite(
                    line=node.lineno, held=held, callees=tuple(refs),
                    kind="thread", label=f".{leaf}(fn)"))
            self._visit_args(node, held, skip_refs=True)
            return

        # resolve the callee
        if isinstance(f, ast.Name):
            c = self._resolve_name_callee(f.id)
            if c:
                callees.append(c)
        elif isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name):
                ent = self.scope.get(base.id)
                if ent and ent[0] == "mod":
                    # alias.func() on an imported project module
                    for m in self.cg._scopes:
                        if m == ent[1] or m.endswith("." + ent[1]):
                            k = f"{m}:{f.attr}"
                            if k in self.cg.funcs:
                                callees.append(k)
                                break
            if not callees and base_ty:
                c = self.cg.resolve_method(base_ty, f.attr)
                if c:
                    callees.append(c)
                    label = f"{base_ty}.{f.attr}"
            if not callees and leaf:
                c = self.cg.unique_method(leaf)
                if c:
                    callees.append(c)

        # blocking primitive?
        blocking = self._blocking_label(node, f, leaf, base_ty, callees)

        self.fi.sites.append(CallSite(
            line=node.lineno, held=held, callees=tuple(callees),
            kind=kind, blocking=blocking, label=label))

        # function references passed as arguments -> callback edges
        cb = []
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            r = self._func_ref(a)
            if r and r not in callees:
                cb.append(r)
        if cb:
            self.fi.sites.append(CallSite(
                line=node.lineno, held=held, callees=tuple(cb),
                kind="callback", label=f"{label}(callback)"))
        self._visit_args(node, held)
        # chained receivers: `threading.Thread(...).start()` — the
        # inner constructor (and its spawn edge) lives in func.value
        if isinstance(f, ast.Attribute) and not isinstance(
                f.value, ast.Name):
            self._visit_expr(f.value, held)

        # receiver mutation: self.attr.append(...) etc. — but NOT when
        # the name resolved to a project method (`mapper.update(...)`
        # is ShardMapper.update, a call edge, not dict.update)
        if leaf in _MUTATOR_METHODS and isinstance(f, ast.Attribute) \
                and not callees:
            self._note_mutation(self._shared_target(f.value), node, held,
                                f"{leaf}(...)")

    def _visit_args(self, node: ast.Call, held,
                    skip_refs: bool = False) -> None:
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            if skip_refs and self._func_ref(a):
                continue
            self._visit_expr(a, held)

    def _blocking_label(self, node, f, leaf, base_ty,
                        callees) -> Optional[str]:
        if leaf is None:
            return None
        # typed primitives first (most precise)
        if base_ty == "queue.Queue" and leaf == "get":
            for kw in node.keywords:
                if kw.arg in ("timeout", "block"):
                    return None     # bounded / non-blocking get
            return "Queue.get (unbounded)"
        if base_ty in ("threading.Event", "threading.Condition") \
                and leaf == "wait":
            for kw in node.keywords:
                if kw.arg == "timeout":
                    return "Event.wait"
            if node.args:
                return "Event.wait"
            return "Event.wait (unbounded)"
        if base_ty == "threading.Thread" and leaf == "join":
            return "Thread.join"
        # project-declared blocking qualnames
        for c in callees:
            q = self.cg.funcs[c].qualname if c in self.cg.funcs else ""
            if q in BLOCKING_QUALNAMES or leaf in BLOCKING_QUALNAMES:
                return BLOCKING_QUALNAMES.get(
                    q, BLOCKING_QUALNAMES.get(leaf))
        if leaf in _BLOCKING_LEAVES:
            return _BLOCKING_LEAVES[leaf]
        base_name = None
        b = f.value if isinstance(f, ast.Attribute) else None
        while isinstance(b, ast.Attribute):
            b = b.value
        if isinstance(b, ast.Name):
            base_name = b.id
        if base_name in _BLOCKING_BASES:
            return _BLOCKING_BASES[base_name]
        return None


def build(mods: Iterable[ModuleSource]) -> CallGraph:
    """Build + propagate the project call graph."""
    return CallGraph(list(mods))
