"""Cache-invalidation completeness (graftlint v3).

Every cache-soundness bug this repo has shipped (PR 5's dispatch-scope
key component, PR 6's watermark-coverage hole) was a world-mutation
event some cache failed to account for — found by a human, after the
fact. This family mechanizes the review using the declarations in
:mod:`filodb_tpu.lint.caches` and the call-graph/bridge machinery in
:mod:`filodb_tpu.lint.dataflow`:

  * ``cache-invalidation-completeness`` —
      - a **push** event (``invalidated_by``): every ``@publishes(ev)``
        function in the project must REACH the cache's hook method
        through the call graph, where listener/subscriber indirection
        (``mapper.subscribe(cb)`` ... ``for cb in self._subscribers:
        cb(ev)``) is crossed via inferred registration bridges. Delete
        the line that wires the results cache to topology events and
        this rule fires at the topology publisher.
      - a **pull** event (``validated_by``): each named lookup hook
        must reach an ``@event_source(ev)`` function — the check that
        compares the cached extent against the live epoch/watermark
        cannot silently rot out of the lookup path.
      - inventory hygiene: a declared event with neither a publisher
        nor a source, a hook name that resolves to no method, and a
        ``@publishes``/``@event_source`` marker naming an event no
        registry declares are each findings.
  * ``cache-unregistered`` — a class that is visibly a cache (name
    ends in ``Cache``, or ``__init__`` creates a dict attribute whose
    name says cache) with no ``@cache_registry`` declaration: an
    unregistered cache is one nobody has answered "what invalidates
    this?" for.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from filodb_tpu.lint import Finding, ModuleSource, register_rule
from filodb_tpu.lint import callgraph as cgmod
from filodb_tpu.lint import dataflow as dfmod

register_rule("cache-invalidation-completeness", "cache",
              "a key-affecting event's publisher does not reach a "
              "registered cache's invalidation hook (or a lookup hook "
              "lost its event source)")
from filodb_tpu.lint.astwalk import walk_nodes
register_rule("cache-unregistered", "cache",
              "a cache class carries no @cache_registry declaration "
              "(nobody has declared what invalidates it)")


@dataclass
class _Registry:
    name: str
    owner_cls: Optional[str]        # class name (None: module-level)
    module: str
    relpath: str
    line: int
    invalidated_by: Dict[str, str] = field(default_factory=dict)
    validated_by: Dict[str, Tuple[str, ...]] = field(
        default_factory=dict)
    keyed: Tuple[str, ...] = ()


def _const(expr):
    """Python value of a constant-literal expression (str/tuple/dict),
    or None when it is not one."""
    if isinstance(expr, ast.Constant):
        return expr.value
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = []
        for e in expr.elts:
            v = _const(e)
            if v is None:
                return None
            out.append(v)
        return tuple(out)
    if isinstance(expr, ast.Dict):
        out = {}
        for k, v in zip(expr.keys, expr.values):
            kk, vv = _const(k), _const(v)
            if kk is None or vv is None:
                return None
            out[kk] = vv
        return out
    return None


def _norm_hooks(v) -> Tuple[str, ...]:
    if isinstance(v, str):
        return (v,)
    if isinstance(v, (list, tuple)):
        return tuple(x for x in v if isinstance(x, str))
    return ()


def _collect_registries(cg: cgmod.CallGraph,
                        mods: Sequence[ModuleSource]
                        ) -> Tuple[List[_Registry], Set[str]]:
    """All @cache_registry / __cache_registry__ declarations, plus the
    set of class names that carry at least one."""
    regs: List[_Registry] = []
    registered_classes: Set[str] = set()
    for ci in cg._classes_by_mod.values():
        for d in ci.node.decorator_list:
            if not isinstance(d, ast.Call):
                continue
            if dfmod._leaf(d.func) != "cache_registry":
                continue
            registered_classes.add(ci.name)
            name = _const(d.args[0]) if d.args else None
            reg = _Registry(name=str(name or ci.name),
                            owner_cls=ci.name, module=ci.module,
                            relpath=ci.relpath, line=d.lineno)
            for kw in d.keywords:
                v = _const(kw.value)
                if kw.arg == "invalidated_by" and isinstance(v, dict):
                    reg.invalidated_by = {str(k): str(h)
                                          for k, h in v.items()}
                elif kw.arg == "validated_by" and isinstance(v, dict):
                    reg.validated_by = {str(k): _norm_hooks(h)
                                        for k, h in v.items()}
                elif kw.arg == "keyed" and isinstance(v, tuple):
                    reg.keyed = v
            regs.append(reg)
    for mod in mods:
        dotted = cgmod.module_dotted(mod.relpath)
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name) \
                        and t.id == "__cache_registry__":
                    table = _const(node.value)
                    if not isinstance(table, dict):
                        continue
                    for name, entry in table.items():
                        if not isinstance(entry, dict):
                            continue
                        reg = _Registry(
                            name=str(name), owner_cls=None,
                            module=dotted, relpath=mod.relpath,
                            line=node.lineno,
                            invalidated_by={
                                str(k): str(v) for k, v in
                                (entry.get("invalidated_by")
                                 or {}).items()},
                            validated_by={
                                str(k): _norm_hooks(v) for k, v in
                                (entry.get("validated_by")
                                 or {}).items()},
                            keyed=tuple(entry.get("keyed") or ()))
                        regs.append(reg)
    return regs, registered_classes


def _collect_marked(cg: cgmod.CallGraph, marker: str
                    ) -> Dict[str, List[str]]:
    """event -> [func keys] for @publishes / @event_source markers."""
    out: Dict[str, List[str]] = {}
    for key, fi in cg.funcs.items():
        for d in getattr(fi.node, "decorator_list", ()):
            if not isinstance(d, ast.Call):
                continue
            if dfmod._leaf(d.func) != marker:
                continue
            for a in d.args:
                if isinstance(a, ast.Constant) \
                        and isinstance(a.value, str):
                    out.setdefault(a.value, []).append(key)
    return out


def _resolve_hook(cg: cgmod.CallGraph, reg: _Registry,
                  hook: str) -> Optional[str]:
    if reg.owner_cls is not None:
        return cg.resolve_method(reg.owner_cls, hook)
    k = f"{reg.module}:{hook}"
    return k if k in cg.funcs else None


def _fmt_path(cg: cgmod.CallGraph, path: Sequence[str]) -> str:
    names = [cg.funcs[k].qualname for k in path if k in cg.funcs]
    return " -> ".join(names[:6]) + (" ..." if len(names) > 6 else "")


def check_project(mods: Sequence[ModuleSource],
                  cg: Optional[cgmod.CallGraph] = None,
                  df: Optional[dfmod.DeviceDataflow] = None
                  ) -> List[Tuple[Optional[str], Finding]]:
    if df is None:
        df = dfmod.build(mods, cg)
    cg = df.cg
    out: List[Tuple[Optional[str], Finding]] = []
    regs, registered = _collect_registries(cg, mods)
    publishers = _collect_marked(cg, "publishes")
    sources = _collect_marked(cg, "event_source")
    declared_events: Set[str] = set()
    for reg in regs:
        declared_events |= set(reg.invalidated_by)
        declared_events |= set(reg.validated_by)

    def emit(relpath, line, msg, ctx) -> None:
        out.append((relpath, Finding(
            rule="cache-invalidation-completeness", path=relpath,
            line=line, message=msg, context=ctx)))

    for reg in regs:
        # push events: every publisher must reach the hook
        for ev, hook in sorted(reg.invalidated_by.items()):
            hk = _resolve_hook(cg, reg, hook)
            if hk is None:
                emit(reg.relpath, reg.line,
                     f"cache {reg.name!r}: invalidation hook {hook!r} "
                     f"for event {ev!r} resolves to no method",
                     f"registry:{reg.name}:{ev}:missing-hook")
                continue
            pubs = publishers.get(ev, [])
            if not pubs and ev not in sources:
                emit(reg.relpath, reg.line,
                     f"cache {reg.name!r}: event {ev!r} has no "
                     f"@publishes publisher anywhere in the project — "
                     f"either the event inventory or the publisher "
                     f"marker is missing",
                     f"registry:{reg.name}:{ev}:unpublished")
            for pk in pubs:
                if df.reaches(pk, hk) is None:
                    pfi = cg.funcs[pk]
                    emit(pfi.relpath, pfi.lineno,
                         f"{pfi.qualname} publishes {ev!r} but does "
                         f"not reach cache {reg.name!r}'s invalidation "
                         f"hook {reg.owner_cls or reg.module}.{hook} "
                         f"through any call/subscription path — the "
                         f"cache serves stale entries across this "
                         f"event",
                         f"publish:{ev}:{reg.name}:{pfi.qualname}")
        # pull events: each lookup hook must consult an event source
        for ev, hooks in sorted(reg.validated_by.items()):
            srcs = sources.get(ev, [])
            if not srcs:
                emit(reg.relpath, reg.line,
                     f"cache {reg.name!r}: pull event {ev!r} has no "
                     f"@event_source function in the project",
                     f"registry:{reg.name}:{ev}:no-source")
                continue
            for hook in hooks:
                hk = _resolve_hook(cg, reg, hook)
                if hk is None:
                    emit(reg.relpath, reg.line,
                         f"cache {reg.name!r}: lookup hook {hook!r} "
                         f"for pull event {ev!r} resolves to no "
                         f"method",
                         f"registry:{reg.name}:{ev}:missing-hook:"
                         f"{hook}")
                    continue
                if all(df.reaches(hk, sk) is None for sk in srcs):
                    hfi = cg.funcs[hk]
                    emit(hfi.relpath, hfi.lineno,
                         f"{hfi.qualname} is declared to validate "
                         f"cache {reg.name!r} against {ev!r} but never "
                         f"reads its @event_source — lookups no "
                         f"longer check this event",
                         f"pull:{ev}:{reg.name}:{hook}")
    # stale markers: events nothing declares
    for ev, keys in sorted(publishers.items()):
        if ev in declared_events:
            continue
        for pk in keys:
            pfi = cg.funcs[pk]
            emit(pfi.relpath, pfi.lineno,
                 f"{pfi.qualname} publishes {ev!r} but no "
                 f"@cache_registry declares that event — stale marker "
                 f"or missing registry entry",
                 f"orphan-publish:{ev}:{pfi.qualname}")
    for ev, keys in sorted(sources.items()):
        if ev in declared_events:
            continue
        for sk in keys:
            sfi = cg.funcs[sk]
            emit(sfi.relpath, sfi.lineno,
                 f"{sfi.qualname} is an @event_source for {ev!r} but "
                 f"no @cache_registry declares that event",
                 f"orphan-source:{ev}:{sfi.qualname}")
    # unregistered caches
    for ci in cg._classes_by_mod.values():
        if ci.name in registered:
            continue
        looks_like = ci.name.endswith("Cache")
        attr = None
        init = ci.methods.get("__init__")
        if init is not None and not looks_like:
            for node in walk_nodes(init.node):
                tgt = None
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1:
                    tgt, val = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None:
                    tgt, val = node.target, node.value
                else:
                    continue
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                name = tgt.attr
                if "cache" not in name.lower() \
                        or name.endswith("_lock"):
                    continue
                if isinstance(val, ast.Dict) or (
                        isinstance(val, ast.Call)
                        and dfmod._leaf(val.func) in ("dict",
                                                      "OrderedDict")):
                    attr = name
                    break
        if looks_like or attr is not None:
            why = f"dict attribute {attr!r}" if attr else "its name"
            out.append((ci.relpath, Finding(
                rule="cache-unregistered", path=ci.relpath,
                line=ci.node.lineno,
                message=(f"class {ci.name} looks like a cache "
                         f"({why}) but carries no @cache_registry "
                         f"declaration — declare its key-affecting "
                         f"events (filodb_tpu/lint/caches.py)"),
                context=f"unregistered:{ci.name}")))
    return out
