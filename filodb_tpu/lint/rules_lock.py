"""Lock-discipline rules.

Driven by :func:`filodb_tpu.lint.locks.guarded_by` class decorators
(and module-level ``__guarded_by__`` dicts for module-global state):

  * ``lock-guarded-access`` — a guarded field is read or written
    outside a ``with <owner>.<lock>:`` block. ``self.<field>`` is
    checked inside the declaring class (``__init__`` and ``*_locked``
    methods exempt — construction happens-before publication, and the
    ``_locked`` suffix is the caller-holds-the-lock convention);
    ``other.<field>`` is checked package-wide for underscore-prefixed
    guarded fields (public counters may be read racily on purpose —
    pragma those reads).
  * ``lock-blocking-call`` — a blocking call (sleep, socket dial,
    urlopen/requests, subprocess, future ``.result()``) made while any
    declared lock is held: the classic way one slow peer stalls every
    thread behind the lock.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from filodb_tpu.lint import Finding, ModuleSource, register_rule

register_rule("lock-guarded-access", "lock",
              "guarded field accessed outside its declared lock")
from filodb_tpu.lint.astwalk import walk_nodes
register_rule("lock-blocking-call", "lock",
              "blocking call made while holding a lock")

_BLOCKING_LEAVES = {"sleep", "urlopen", "create_connection", "getaddrinfo",
                    "result", "system", "check_output", "check_call",
                    "run_until_complete"}
_BLOCKING_BASES = {"requests", "subprocess"}

Held = FrozenSet[Tuple[str, str]]       # (owner name or "", lock attr)


@dataclass
class LockDecls:
    """Package-wide declaration tables."""
    # (relpath, class name) -> {field: lock}
    by_class: Dict[Tuple[str, str], Dict[str, str]] = field(
        default_factory=dict)
    # underscore field -> possible locks (foreign-object checks)
    foreign: Dict[str, Set[str]] = field(default_factory=dict)
    # relpath -> {global name: lock name}
    by_module: Dict[str, Dict[str, str]] = field(default_factory=dict)


def _guarded_by_decl(d: ast.expr) -> Optional[Tuple[str, List[str]]]:
    if not isinstance(d, ast.Call):
        return None
    target = d.func
    name = target.attr if isinstance(target, ast.Attribute) else \
        target.id if isinstance(target, ast.Name) else None
    if name != "guarded_by" or not d.args:
        return None
    vals = [a.value for a in d.args
            if isinstance(a, ast.Constant) and isinstance(a.value, str)]
    if len(vals) != len(d.args) or len(vals) < 2:
        return None
    return vals[0], vals[1:]


def collect_declarations(mods: Iterable[ModuleSource]) -> LockDecls:
    decls = LockDecls()
    for mod in mods:
        for node in walk_nodes(mod.tree):
            if isinstance(node, ast.ClassDef):
                fields: Dict[str, str] = {}
                for d in node.decorator_list:
                    got = _guarded_by_decl(d)
                    if got is None:
                        continue
                    lock, names = got
                    for f in names:
                        fields[f] = lock
                if fields:
                    decls.by_class[(mod.relpath, node.name)] = fields
                    for f, lock in fields.items():
                        if f.startswith("_"):
                            decls.foreign.setdefault(f, set()).add(lock)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) \
                            and t.id == "__guarded_by__" \
                            and isinstance(node.value, ast.Dict):
                        table: Dict[str, str] = {}
                        for k, v in zip(node.value.keys,
                                        node.value.values):
                            if isinstance(k, ast.Constant) \
                                    and isinstance(v, ast.Constant):
                                table[str(k.value)] = str(v.value)
                        if table:
                            decls.by_module.setdefault(
                                mod.relpath, {}).update(table)
    return decls


def _with_locks(node: ast.With) -> Set[Tuple[str, str]]:
    out: Set[Tuple[str, str]] = set()
    for item in node.items:
        e = item.context_expr
        if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name):
            out.add((e.value.id, e.attr))
        elif isinstance(e, ast.Name):
            out.add(("", e.id))
    return out


def _exempt(fn_name: str) -> bool:
    return fn_name == "__init__" or fn_name.endswith("_locked")


class _MethodChecker:
    """Walk one function body tracking held locks lexically."""

    def __init__(self, mod: ModuleSource, qualname: str,
                 self_fields: Dict[str, str],
                 foreign: Dict[str, Set[str]],
                 globals_: Dict[str, str],
                 findings: List[Finding]) -> None:
        self.mod = mod
        self.qualname = qualname
        self.self_fields = self_fields
        self.foreign = foreign
        self.globals_ = globals_
        self.findings = findings

    def emit(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(Finding(
            rule=rule, path=self.mod.relpath,
            line=getattr(node, "lineno", 1), message=msg,
            context=f"{self.qualname}:{msg}"))

    def walk(self, node: ast.AST, held: Held) -> None:
        if isinstance(node, ast.With):
            inner = frozenset(held | _with_locks(node))
            for item in node.items:
                self.walk(item.context_expr, held)
            for child in node.body:
                self.walk(child, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs (callbacks) don't inherit the lexical lock:
            # they may run later, off-thread
            for child in ast.iter_child_nodes(node):
                self.walk(child, frozenset())
            return
        self.check(node, held)
        for child in ast.iter_child_nodes(node):
            self.walk(child, held)

    def check(self, node: ast.AST, held: Held) -> None:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name):
            owner, attr = node.value.id, node.attr
            if owner == "self" and attr in self.self_fields:
                lock = self.self_fields[attr]
                if ("self", lock) not in held:
                    self.emit("lock-guarded-access", node,
                              f"self.{attr} accessed without "
                              f"`with self.{lock}:`")
            elif owner != "self" and attr in self.foreign \
                    and attr.startswith("_"):
                locks = self.foreign[attr]
                if not any((owner, lk) in held for lk in locks):
                    want = "/".join(sorted(locks))
                    self.emit("lock-guarded-access", node,
                              f"{owner}.{attr} accessed without "
                              f"`with {owner}.{want}:`")
        elif isinstance(node, ast.Name) and node.id in self.globals_:
            lock = self.globals_[node.id]
            if ("", lock) not in held:
                self.emit("lock-guarded-access", node,
                          f"module global {node.id} accessed without "
                          f"`with {lock}:`")
        if held and isinstance(node, ast.Call):
            self.check_blocking(node, held)

    def check_blocking(self, node: ast.Call, held: Held) -> None:
        f = node.func
        leaf = f.attr if isinstance(f, ast.Attribute) else \
            f.id if isinstance(f, ast.Name) else None
        if leaf is None:
            return
        base = None
        if isinstance(f, ast.Attribute):
            b = f.value
            while isinstance(b, ast.Attribute):
                b = b.value
            if isinstance(b, ast.Name):
                base = b.id
        blocking = (leaf in _BLOCKING_LEAVES
                    or (base in _BLOCKING_BASES)
                    or (base == "socket"))
        if blocking:
            locks = ", ".join(
                f"{o + '.' if o else ''}{lk}" for o, lk in sorted(held))
            name = leaf if base is None else f"{base}...{leaf}"
            self.emit("lock-blocking-call", node,
                      f"blocking call {name}() while holding {locks}")


def check_module(mod: ModuleSource, decls: LockDecls
                 ) -> Iterable[Finding]:
    findings: List[Finding] = []
    globals_ = decls.by_module.get(mod.relpath, {})
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef):
            fields = decls.by_class.get((mod.relpath, node.name), {})
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if _exempt(item.name):
                    continue
                chk = _MethodChecker(
                    mod, f"{node.name}.{item.name}", fields,
                    decls.foreign, globals_, findings)
                for child in item.body:
                    chk.walk(child, frozenset())
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _exempt(node.name):
                continue
            chk = _MethodChecker(mod, node.name, {}, decls.foreign,
                                 globals_, findings)
            for child in node.body:
                chk.walk(child, frozenset())
    return findings
