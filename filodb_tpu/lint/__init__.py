"""graftlint: static analysis for the invariants this repo's hot path
lives by.

The Pallas/JAX hot loop is hand-budgeted — VMEM footprints, (8, 128)
trailing-dim tiling, the int31 relative-timestamp span guard, exact
f64->3xf32 splits — and the threaded layers (memstore, ingest streams,
gRPC service, resilience) grow locks organically. Those invariants
historically lived in docstrings and in the builder's head; graftlint
makes them *checked*, on every PR, on CPU-only CI, before anything
touches a TPU.

Rule families (see the rule modules for the catalog):

  * ``rules_kernel`` — kernel contracts: every ``pallas_call`` site
    carries a :func:`filodb_tpu.lint.contracts.kernel_contract`
    declaration (block shapes, dtypes, scratch, budget); the checker
    recomputes the VMEM footprint, verifies trailing-dim tiling,
    grid/index-map bounds, the int31 span guard, and abstract-evals the
    wrapper via ``jax.eval_shape`` — no TPU needed.
  * ``rules_trace`` — trace safety: AST pass over functions reachable
    under ``jax.jit`` / ``shard_map`` / ``pallas_call`` flagging Python
    side effects, tracer leaks, captured-container mutation, and 64-bit
    dtypes inside Pallas kernel bodies.
  * ``rules_lock`` — lock discipline:
    :func:`filodb_tpu.lint.locks.guarded_by` annotations on shared
    fields, checked for access outside a ``with <lock>:`` scope and for
    blocking calls made while a lock is held.
  * ``rules_concurrency`` — whole-program analysis over the project
    call graph (``callgraph.py``): lock-order cycles + the canonical
    order policy (``lockorder.py``), blocking primitives reachable
    through call chains while a lock is held, and inference of shared
    state mutated from >=2 thread roots (``threads.thread_root``) with
    no common guard and no ``@guarded_by``.
  * ``rules_spmd`` (v3) — SPMD/device dataflow over the entry-point
    layer in ``dataflow.py``: collectives under divergent control flow
    or with axis names absent from the enclosing mesh/spec
    (``spmd-collective-balance``), use-after-donate / double-donate /
    donate-of-live-state (``donation-safety``, advisory
    ``donation-missing``), and PartitionSpec arity + axis-name
    consistency (``partition-spec-consistency``).
  * ``rules_promql`` (promlint) — the PromQL surface
    (``filodb_tpu/promql/semant.py``): every shipped rule file
    (``examples/*.yaml``) loads through the rules loader with semantic
    analysis (type/schema checking, label dataflow, normalized
    duplicate detection), and a seeded differential micro-soak runs
    generated well-typed queries engine-vs-reference
    (``promql-differential-mismatch``); ``--changed-only`` skips the
    soak (the full rail runs in tier-1).
  * ``rules_numerics`` (v4) — numeric-precision & determinism dataflow
    (``numerics.py`` annotations): provable f64/int64 values narrowing
    into f32/int32 without a ``@precision(bits=..., reason=...)``
    budget (``precision-narrowing``), f32 accumulations without a
    static term bound under the mantissa (``accumulation-bound``),
    mesh-shape-dependent float reductions without
    ``@order_insensitive(tolerance=...)``
    (``reduction-order-determinism``), and f32/f64-mixed or
    int-cast-to-float comparisons inside Pallas bodies
    (``mixed-dtype-comparison``). The inversion: ``ulpcert.py``
    evaluates every annotation on seeded inputs, f64-reference vs
    production dtype (order claims at 1/2/4/8 virtual devices), and
    CERTIFIES the claimed tolerance — an uncertifiable annotation is
    an error (``ulp-certification``).
  * ``rules_cache`` (v3) — the cache inventory (``caches.py``):
    every ``@publishes`` mutation publisher must reach every
    registered cache's invalidation hook (through inferred
    listener-registration bridges), every pull-validated lookup hook
    must still read its ``@event_source``
    (``cache-invalidation-completeness``); cache-looking classes
    without a registry are ``cache-unregistered``.

Mechanics:

  * run it: ``python -m filodb_tpu.lint`` (add ``--json`` for
    machine-readable findings, ``--changed-only`` for a git-diff-scoped
    pre-commit run — the interprocedural rules still analyze the whole
    graph but only findings anchored in changed files are reported);
    tier-1 runs it via ``tests/test_lint_clean.py``.
  * suppress one finding: ``# graftlint: disable=<rule> (reason)`` on
    the offending line or the line above it. A reason string is
    required — bare disables are themselves a finding.
  * grandfather findings: ``filodb_tpu/lint/baseline.json`` holds keys
    of known findings; the run fails only on NEW findings. The shipped
    baseline is empty — keep it that way.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

ERROR = "error"
WARNING = "warning"

_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*disable=([\w\-,]+)\s*(?:\(([^)]*)\))?")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule: str
    path: str               # repo-relative, forward slashes
    line: int
    message: str
    severity: str = ERROR
    context: str = ""       # enclosing qualname (stable across line drift)

    def key(self) -> str:
        """Stable identity for baseline matching: deliberately excludes
        the line number so unrelated edits don't churn the baseline."""
        return f"{self.path}::{self.rule}::{self.context or self.message}"

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] "
                f"{self.message}")


@dataclass(frozen=True)
class Rule:
    """One registered rule: AST rules get a per-module hook, runtime
    rules (kernel contracts) run once over the imported registry."""
    id: str
    family: str             # kernel | trace | lock | meta
    severity: str
    doc: str


_RULES: Dict[str, Rule] = {}


def register_rule(id: str, family: str, doc: str,
                  severity: str = ERROR) -> Rule:
    rule = Rule(id=id, family=family, severity=severity, doc=doc)
    _RULES[id] = rule
    return rule


def rules() -> Dict[str, Rule]:
    """The rule catalog (id -> Rule), importing all rule modules."""
    _load_rule_modules()
    return dict(_RULES)


register_rule(
    "pragma-no-reason", "meta",
    "a `# graftlint: disable=` pragma must carry a (reason) string")
register_rule(
    "pragma-unknown-rule", "meta",
    "a pragma disables a rule id that does not exist")


@dataclass
class ModuleSource:
    """Parsed view of one file handed to AST rules."""
    path: str               # absolute
    relpath: str            # repo/package-relative, forward slashes
    source: str
    tree: ast.Module
    lines: List[str]
    # line -> (set of disabled rule ids, reason or None)
    pragmas: Dict[int, Tuple[frozenset, Optional[str]]]


def _parse_pragmas(lines: Sequence[str]
                   ) -> Dict[int, Tuple[frozenset, Optional[str]]]:
    out: Dict[int, Tuple[frozenset, Optional[str]]] = {}
    for i, text in enumerate(lines, start=1):
        m = _PRAGMA_RE.search(text)
        if m:
            ids = frozenset(x.strip() for x in m.group(1).split(",")
                            if x.strip())
            out[i] = (ids, m.group(2))
    return out


def load_module(path: str, root: Optional[str] = None
                ) -> Optional[ModuleSource]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError):
        return None
    rel = os.path.relpath(path, root) if root else path
    rel = rel.replace(os.sep, "/")
    lines = source.splitlines()
    return ModuleSource(path=path, relpath=rel, source=source, tree=tree,
                        lines=lines, pragmas=_parse_pragmas(lines))


def _suppressed(mod: ModuleSource, f: Finding) -> bool:
    """A finding is suppressed by a pragma on its line or the line
    directly above it naming its rule (or `all`)."""
    for ln in (f.line, f.line - 1):
        entry = mod.pragmas.get(ln)
        if entry and (f.rule in entry[0] or "all" in entry[0]):
            return True
    return False


def _pragma_findings(mod: ModuleSource) -> List[Finding]:
    out = []
    known = set(_RULES)
    for ln, (ids, reason) in mod.pragmas.items():
        if not reason or not reason.strip():
            out.append(Finding(
                rule="pragma-no-reason", path=mod.relpath, line=ln,
                message="disable pragma without a (reason) string",
                context=f"pragma:{','.join(sorted(ids))}"))
        for rid in ids:
            if rid != "all" and rid not in known:
                out.append(Finding(
                    rule="pragma-unknown-rule", path=mod.relpath, line=ln,
                    message=f"pragma disables unknown rule {rid!r}",
                    context=f"pragma:{rid}"))
    return out


# -- baseline ---------------------------------------------------------------

def baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: Optional[str] = None) -> frozenset:
    path = path or baseline_path()
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return frozenset()
    return frozenset(data.get("findings", []))


# -- runner -----------------------------------------------------------------

@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)   # new (fail)
    baselined: List[Finding] = field(default_factory=list)  # grandfathered
    suppressed: int = 0
    files: int = 0

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    def to_json(self) -> Dict:
        return {"files": self.files,
                "findings": [f.to_json() for f in self.findings],
                "baselined": [f.to_json() for f in self.baselined],
                "suppressed": self.suppressed,
                "exit_code": 1 if self.errors else 0}


def package_root() -> str:
    """Directory containing the ``filodb_tpu`` package (the repo root
    when run from a checkout)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(os.path.abspath(p))
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__",)]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.abspath(
                            os.path.join(dirpath, fn)))
    return out


_rule_modules_loaded = False


def _load_rule_modules() -> None:
    global _rule_modules_loaded
    if _rule_modules_loaded:
        return
    _rule_modules_loaded = True
    from filodb_tpu.lint import (memcert,  # noqa: F401
                                 rules_cache, rules_capacity,
                                 rules_concurrency, rules_hot,
                                 rules_kernel, rules_lock,
                                 rules_numerics, rules_promql,
                                 rules_span, rules_spmd, rules_trace,
                                 ulpcert)


def run_lint(paths: Optional[Sequence[str]] = None, *,
             baseline: Optional[frozenset] = None,
             check_contracts: bool = True,
             report_only: Optional[frozenset] = None) -> LintResult:
    """Lint ``paths`` (default: the ``filodb_tpu`` package).

    AST rules run per file; the concurrency families run once over the
    whole module set (the call graph is a project artifact); when
    ``check_contracts`` is set, files that belong to an importable
    package are imported and every registered
    :class:`~filodb_tpu.lint.contracts.KernelContract` they declare is
    verified (VMEM budget, tiling, grid bounds, span guard,
    ``jax.eval_shape``).

    ``report_only`` (a set of repo-relative paths) keeps the analysis
    whole-program but drops findings anchored outside those files —
    the ``--changed-only`` pre-commit mode."""
    # the ulp-certification rail needs 1/2/4/8 virtual devices; the
    # flag must land before ANY rule initializes the jax backend (the
    # promql soak and the kernel contracts both do). No-op when a
    # backend is already up (tests force 8 devices in conftest).
    from filodb_tpu.lint.ulpcert import ensure_virtual_devices
    ensure_virtual_devices()
    from filodb_tpu.lint import astwalk
    astwalk.clear()     # fresh memoized-walk cache per run
    _load_rule_modules()
    from filodb_tpu.lint import (rules_cache, rules_capacity,
                                 rules_concurrency, rules_hot,
                                 rules_kernel, rules_lock,
                                 rules_numerics, rules_promql,
                                 rules_span, rules_spmd, rules_trace)
    from filodb_tpu.lint import callgraph as _cgmod
    from filodb_tpu.lint import dataflow as _dfmod
    root = package_root()
    if paths is None:
        paths = [os.path.join(root, "filodb_tpu")]
    if baseline is None:
        baseline = load_baseline()
    files = iter_py_files(paths)
    result = LintResult(files=len(files))
    mods: List[ModuleSource] = []
    for path in files:
        mod = load_module(path, root=root)
        if mod is None:
            continue
        mods.append(mod)
    # two passes: lock declarations are collected package-wide first so
    # cross-class (foreign-object) guarded accesses resolve
    lock_decls = rules_lock.collect_declarations(mods)
    raw: List[Tuple[ModuleSource, Finding]] = []
    for mod in mods:
        for f in _pragma_findings(mod):
            raw.append((mod, f))
        for f in rules_kernel.check_module(mod):
            raw.append((mod, f))
        for f in rules_trace.check_module(mod):
            raw.append((mod, f))
        for f in rules_hot.check_module(mod):
            raw.append((mod, f))
        for f in rules_span.check_module(mod):
            raw.append((mod, f))
        for f in rules_lock.check_module(mod, lock_decls):
            raw.append((mod, f))
    bymod_path = {m.relpath: m for m in mods}
    # one call graph + one dataflow layer shared by every
    # interprocedural family (concurrency, SPMD, cache completeness)
    cg = _cgmod.build(mods)
    df = _dfmod.DeviceDataflow(mods, cg)
    for relpath, f in rules_concurrency.check_project(mods, cg=cg):
        raw.append((bymod_path.get(relpath), f))
    for relpath, f in rules_spmd.check_project(mods, cg=cg, df=df):
        raw.append((bymod_path.get(relpath), f))
    for relpath, f in rules_cache.check_project(mods, cg=cg, df=df):
        raw.append((bymod_path.get(relpath), f))
    for relpath, f in rules_numerics.check_project(mods, cg=cg, df=df):
        raw.append((bymod_path.get(relpath), f))
    for relpath, f in rules_capacity.check_project(mods, cg=cg, df=df):
        raw.append((bymod_path.get(relpath), f))
    # promql family: shipped rule-file sweep + (full runs only) the
    # seeded differential micro-soak. --changed-only skips the soak —
    # the fast pre-commit path; tier-1 runs the full rail.
    for relpath, f in rules_promql.check_project(
            mods, root, skip_soak=report_only is not None):
        raw.append((bymod_path.get(relpath), f))
    if check_contracts:
        bymod = {m.relpath: m for m in mods}
        for relpath, f in rules_kernel.check_contracts(mods, root):
            mod = bymod.get(relpath)
            raw.append((mod, f) if mod is not None else (None, f))
        # the ulp-certification rail (numerics annotations evaluated
        # f64-reference vs production, order claims at 1/2/4/8 virtual
        # devices) rides the same runtime-verification gate as the
        # kernel contracts; skipped under --changed-only (pre-commit
        # fast path — tier-1 runs the full rail). Results are memoized
        # per process, so fixture-scoped run_lint calls stay fast.
        if report_only is None:
            from filodb_tpu.lint import ulpcert
            for relpath, f in ulpcert.check_certifications(mods):
                mod = bymod.get(relpath)
                raw.append((mod, f) if mod is not None else (None, f))
            # the capacity-certification rail (v5): every @capacity
            # residency claim is built at seeded sizes and its real
            # device bytes measured; sharded claims at 1/2/4/8 virtual
            # devices. Memoized like ulpcert.
            from filodb_tpu.lint import memcert
            for relpath, f in memcert.check_certifications(mods):
                mod = bymod.get(relpath)
                raw.append((mod, f) if mod is not None else (None, f))
    for mod, f in raw:
        if mod is not None and _suppressed(mod, f):
            result.suppressed += 1
        elif report_only is not None and f.path not in report_only:
            continue
        elif f.key() in baseline:
            result.baselined.append(f)
        else:
            result.findings.append(f)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result.baselined.sort(key=lambda f: (f.path, f.line, f.rule))
    return result
