"""graftlint promql family: the promlint semantic analyzer wired into
the repo's static-analysis gate.

Two checks run inside every ``python -m filodb_tpu.lint`` invocation
(and therefore inside the tier-1 ``tests/test_lint_clean.py`` gate):

* **Rule-file sweep** — every shipped rule file (``examples/*.yaml`` /
  ``.yml`` / ``.json``) loads through the rules loader with promlint
  semantic analysis (:mod:`filodb_tpu.promql.semant`): type errors,
  schema misuse (``rate()`` on a declared gauge), label-dataflow
  breaks, and normalized duplicate detection. Findings keep their
  ``promql-*`` rule ids, so ``--json`` / ``--github`` emit them under
  the promql family prefix and CI annotates the YAML line.

* **Differential micro-soak** — a tiny seeded arm of the full
  correctness rail (tests/test_promql_differential.py): generated
  well-typed queries, engine-vs-reference, any mismatch is a
  ``promql-differential-mismatch`` finding. Skipped under
  ``--changed-only`` (the fast pre-commit path; the full soak runs in
  tier-1).
"""

from __future__ import annotations

import glob
import math
import os
import re
from typing import List, Optional, Tuple

from filodb_tpu.lint import ERROR, WARNING, Finding, register_rule
from filodb_tpu.promql.semant import RULES as _SEMANT_RULES

for _rid, (_sev, _doc) in sorted(_SEMANT_RULES.items()):
    register_rule(_rid, "promql", _doc, severity=_sev)
register_rule(
    "promql-rule-file", "promql",
    "a shipped rule file fails loader/structural validation")
register_rule(
    "promql-rule-file-warning", "promql",
    "non-fatal promlint finding in a shipped rule file",
    severity=WARNING)
register_rule(
    "promql-differential-mismatch", "promql",
    "a generated well-typed query evaluates differently on the engine "
    "and the pure-Python reference evaluator")

_RULE_ID_RE = re.compile(r"\[(promql-[\w\-]+)\]")

SOAK_SEED = 0x50AC
SOAK_QUERIES = 12


def _line_of(text: str, needle: str) -> int:
    """1-based line of the first occurrence of ``needle`` (trimmed) in
    ``text``; 1 when not found."""
    needle = needle.strip()
    if needle:
        for i, line in enumerate(text.splitlines(), start=1):
            if needle in line:
                return i
    return 1


def _rule_file_findings(path: str, root: str
                        ) -> List[Tuple[Optional[str], Finding]]:
    from filodb_tpu.rules.loader import check_rules_file_full
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError:
        text = ""
    out: List[Tuple[Optional[str], Finding]] = []
    errors, warnings = check_rules_file_full(path)
    for msg, fallback_rule, severity in (
            [(e, "promql-rule-file", ERROR) for e in errors]
            + [(w, "promql-rule-file-warning", WARNING) for w in warnings]):
        m = _RULE_ID_RE.search(msg)
        rule = m.group(1) if m and m.group(1) in _SEMANT_RULES \
            else fallback_rule
        sev = _SEMANT_RULES[rule][0] if rule in _SEMANT_RULES \
            else severity
        # promlint renders carry the expr on their second line — use it
        # to anchor the finding at the expression's line in the YAML
        lines = msg.splitlines()
        anchor = lines[1] if len(lines) > 1 else msg
        head = lines[0]
        out.append((rel, Finding(
            rule=rule, path=rel, line=_line_of(text, anchor),
            message=head, severity=sev, context=f"rulefile:{rel}")))
    return out


def _soak_findings(root: str) -> List[Tuple[Optional[str], Finding]]:
    """Seeded engine-vs-reference micro-soak over synthetic in-memory
    data; each mismatch is one finding. Deterministic (fixed seed) so
    the gate cannot flake."""
    import numpy as np

    from filodb_tpu.core.memstore import TimeSeriesShard
    from filodb_tpu.core.record import RecordBuilder
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
    from filodb_tpu.promql.gen import QueryGen
    from filodb_tpu.promql.parser import (TimeStepParams,
                                          parse_query_range)
    from filodb_tpu.promql.refeval import (RefEvalError, RefSeries,
                                           ref_eval)
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.model import GridResult, ScalarResult

    t0 = 1_600_000_000
    start, step, end = t0 + 600, 60, t0 + 1200
    shard = TimeSeriesShard(DatasetRef("timeseries"), DEFAULT_SCHEMAS, 0)
    b = RecordBuilder(DEFAULT_SCHEMAS)
    ref: List = []
    import random as _random
    rng = _random.Random(3)
    for metric, schema in (("http_requests_total", "prom-counter"),
                           ("errors_total", "prom-counter"),
                           ("cpu_usage", "gauge"),
                           ("queue_depth", "gauge")):
        for inst in ("i0", "i1"):
            labels = {"_metric_": metric, "_ws_": "demo",
                      "_ns_": "App-0", "job": "api", "instance": inst}
            v = 0.0
            ts, vals = [], []
            for k in range(140):
                t = t0 + k * 10
                if rng.random() < 0.04:
                    continue
                v = v + rng.random() * 3 if schema == "prom-counter" \
                    else 20 * math.sin(k / 11.0) + rng.random()
                b.add_sample(schema, labels, t * 1000, v)
                ts.append(t * 1000)
                vals.append(v)
            ref.append(RefSeries(dict(labels), ts, vals))
    # classic-bucket histogram world for the generator's
    # histogram_quantile shapes (v4 widening): complete cumulative
    # bucket sets per (job, instance), monotone across le
    les = ("0.1", "0.5", "1", "2.5", "+Inf")
    for job in ("api", "web"):
        for inst in ("i0", "i1"):
            cum = [0.0] * len(les)
            per_le = {le: ([], []) for le in les}
            for k in range(140):
                t = t0 + k * 10
                if rng.random() < 0.04:
                    continue
                run = 0.0
                for bi, le in enumerate(les):
                    run += rng.random() * 2
                    cum[bi] += run
                    per_le[le][0].append(t * 1000)
                    per_le[le][1].append(cum[bi])
            for le in les:
                labels = {
                    "_metric_": "http_request_duration_seconds_bucket",
                    "_ws_": "demo", "_ns_": "App-0", "job": job,
                    "instance": inst, "le": le}
                hts, hvals = per_le[le]
                for t, v in zip(hts, hvals):
                    b.add_sample("prom-counter", labels, t, v)
                ref.append(RefSeries(dict(labels), list(hts),
                                     list(hvals)))
    for c in b.containers():
        shard.ingest(c)
    shard.flush_all()

    def canon(res):
        if isinstance(res, ScalarResult):
            return {(): list(res.values)}
        assert isinstance(res, GridResult)
        return {tuple(sorted(k.items())): list(res.values[i])
                for i, k in enumerate(res.keys)}

    def close(a, b):
        if math.isnan(a) and math.isnan(b):
            return True
        if math.isinf(a) or math.isinf(b):
            return a == b
        return abs(a - b) <= 1e-6 + 1e-6 * max(abs(a), abs(b))

    out: List[Tuple[Optional[str], Finding]] = []
    g = QueryGen(seed=SOAK_SEED)
    rel = "filodb_tpu/lint/rules_promql.py"
    for i in range(SOAK_QUERIES):
        q = g.query()
        try:
            plan = parse_query_range(q, TimeStepParams(start, step, end))
            eng = canon(QueryEngine([shard]).execute(plan))
            rf = ref_eval(q, ref, start, step, end)
        except RefEvalError:
            continue            # generator widened past refeval scope
        except Exception as e:  # noqa: BLE001 — a gate must not crash
            out.append((rel, Finding(
                rule="promql-differential-mismatch", path=rel, line=1,
                message=f"soak[{i}] {q!r} crashed: {e}",
                context=f"soak:{SOAK_SEED}:{i}")))
            continue
        bad = None
        if set(eng) != set(rf):
            bad = "series keysets differ"
        else:
            for k in eng:
                if not all(close(a, b) for a, b in zip(eng[k], rf[k])):
                    bad = f"values differ at {k}"
                    break
        if bad:
            out.append((rel, Finding(
                rule="promql-differential-mismatch", path=rel, line=1,
                message=f"soak[{i}] {q!r}: engine vs reference: {bad}",
                context=f"soak:{SOAK_SEED}:{i}")))
    return out


def check_project(mods, root: str, skip_soak: bool = False
                  ) -> List[Tuple[Optional[str], Finding]]:
    out: List[Tuple[Optional[str], Finding]] = []
    ex_dir = os.path.join(root, "examples")
    for pat in ("*.yaml", "*.yml", "*.json"):
        for path in sorted(glob.glob(os.path.join(ex_dir, pat))):
            out.extend(_rule_file_findings(path, root))
    if not skip_soak:
        out.extend(_soak_findings(root))
    return out
