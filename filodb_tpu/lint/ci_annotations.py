"""GitHub-workflow annotations & SARIF reports from graftlint findings.

Turns a :class:`~filodb_tpu.lint.LintResult` (or its ``--json``
serialization) into GitHub's workflow-command lines::

    ::error file=filodb_tpu/query/tpu.py,line=512,title=graftlint trace-side-effect::print() inside a traced function

printed on stdout so a CI step like

.. code-block:: yaml

    - run: python -m filodb_tpu.lint --github

surfaces findings as inline PR annotations. New findings annotate as
``error``; baselined (grandfathered) findings annotate as ``warning``
so they stay visible without failing the run. Messages are sanitized
per the workflow-command escaping rules (%, CR, LF in the message;
additionally ``,`` and ``:`` in properties).
"""

from __future__ import annotations

from typing import Dict, List


def _esc_msg(s: str) -> str:
    return (str(s).replace("%", "%25").replace("\r", "%0D")
            .replace("\n", "%0A"))


def _esc_prop(s: str) -> str:
    return (_esc_msg(s).replace(":", "%3A").replace(",", "%2C"))


def _line(level: str, f: Dict) -> str:
    return (f"::{level} file={_esc_prop(f.get('path', ''))},"
            f"line={int(f.get('line', 1))},"
            f"title={_esc_prop('graftlint ' + f.get('rule', ''))}"
            f"::{_esc_msg(f.get('message', ''))}")


def github_annotations(result_json: Dict) -> List[str]:
    """Workflow-command lines for one lint run (``LintResult.to_json()``
    shape): errors for new findings, warnings for baselined ones."""
    out: List[str] = []
    for f in result_json.get("findings", []):
        level = "error" if f.get("severity", "error") == "error" \
            else "warning"
        out.append(_line(level, f))
    for f in result_json.get("baselined", []):
        out.append(_line("warning", f))
    return out


def _sarif_result(f: Dict, level: str) -> Dict:
    return {
        "ruleId": f.get("rule", ""),
        "level": level,
        "message": {"text": f.get("message", "")},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.get("path", ""),
                                     "uriBaseId": "%SRCROOT%"},
                "region": {"startLine": max(int(f.get("line", 1)), 1)},
            },
        }],
        "partialFingerprints": {
            "graftlint/key": f"{f.get('path', '')}::{f.get('rule', '')}"
                             f"::{f.get('context', '')}",
        },
    }


def sarif_report(result_json: Dict) -> Dict:
    """SARIF 2.1.0 log for one lint run (``LintResult.to_json()``
    shape) so findings land in code-scanning UIs. The tool driver
    carries the FULL rule catalog — every graftlint family (kernel,
    trace, lock, concurrency, spmd, cache, promql, numerics, span,
    hot-path, meta) — so the UI can group and filter by rule; new
    findings report at their registered severity, baselined
    (grandfathered) findings report as ``note`` so they stay visible
    without failing a gate."""
    from filodb_tpu.lint import rules
    catalog = rules()
    driver_rules = [
        {
            "id": rid,
            "shortDescription": {"text": rule.doc},
            "properties": {"family": rule.family},
            "defaultConfiguration": {
                "level": "error" if rule.severity == "error"
                else "warning"},
        }
        for rid, rule in sorted(catalog.items())
    ]
    results: List[Dict] = []
    for f in result_json.get("findings", []):
        level = "error" if f.get("severity", "error") == "error" \
            else "warning"
        results.append(_sarif_result(f, level))
    for f in result_json.get("baselined", []):
        results.append(_sarif_result(f, "note"))
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri": "https://example.invalid/graftlint",
                "rules": driver_rules,
            }},
            "results": results,
        }],
    }
