"""Thread-entry-point annotations and the runtime thread inventory.

:func:`thread_root` marks a function as the entry point of a thread —
the target of a ``threading.Thread``, an executor loop, or a periodic
daemon. graftlint's interprocedural engine (``lint/callgraph.py``)
discovers ``Thread(target=...)`` / ``.submit(fn)`` spawn sites on its
own; the explicit marker exists for three reasons:

  * entry points the AST cannot see (stdlib ``ThreadingHTTPServer``
    spawning per-connection handler threads, callbacks invoked by a
    foreign framework);
  * the **unguarded-shared-state** rule's root set: state compound-
    mutated from two or more roots with no common lock and no
    ``@guarded_by`` declaration is a finding;
  * the runtime inventory behind ``GET /debug/threads``: every marked
    root is listed with its module, qualname, and the ``@guarded_by``
    summary of its class, joined against ``threading.enumerate()``.

Usage (bare or named)::

    @thread_root                     # name defaults to the qualname
    def _run(self): ...

    @thread_root("failure-detector")
    def _run(self): ...

Modules that cannot import the decorator declare
``__thread_roots__ = ("fn_name", ...)`` instead (same AST semantics,
no runtime inventory entry).

The decorator is runtime-neutral: it records the function in
``THREAD_ROOTS`` and returns it unchanged.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Union

# qualname -> {"name": display name, "module": module, "qualname": ...}
THREAD_ROOTS: Dict[str, Dict[str, str]] = {}


def thread_root(arg: Union[Callable, str, None] = None):
    """Mark a function as a thread entry point (see module docstring).

    Works bare (``@thread_root``) or with a display name
    (``@thread_root("failure-detector")``)."""
    def _register(fn: Callable, name: Optional[str]) -> Callable:
        qual = getattr(fn, "__qualname__", getattr(fn, "__name__",
                                                   str(fn)))
        THREAD_ROOTS[qual] = {
            "name": name or qual,
            "module": getattr(fn, "__module__", "?"),
            "qualname": qual,
        }
        fn.__thread_root__ = name or qual
        return fn

    if callable(arg):
        return _register(arg, None)
    return lambda fn: _register(fn, arg)


def _guard_summary(module: str, qualname: str) -> Dict[str, str]:
    """The ``@guarded_by`` table of the root's class, resolved from the
    live module (best effort — {} when the class has no declarations or
    the module isn't imported)."""
    import sys
    mod = sys.modules.get(module)
    if mod is None or "." not in qualname:
        return {}
    cls_name = qualname.split(".")[0]
    cls = getattr(mod, cls_name, None)
    table = getattr(cls, "__guarded_by__", None)
    return dict(table) if isinstance(table, dict) else {}


def thread_inventory() -> List[Dict[str, object]]:
    """The ``/debug/threads`` payload: every registered root with its
    guard summary, plus which live threads currently run (matched by
    thread name against the root's display name / function name)."""
    live = {t.name: {"ident": t.ident, "daemon": t.daemon,
                     "alive": t.is_alive()}
            for t in threading.enumerate()}
    out: List[Dict[str, object]] = []
    for qual, info in sorted(THREAD_ROOTS.items()):
        fn_leaf = qual.rsplit(".", 1)[-1]
        matches = [dict(name=n, **v) for n, v in live.items()
                   if info["name"] in n or fn_leaf in n
                   or n.startswith(info["name"].split("-")[0])]
        out.append({
            "name": info["name"],
            "root": f"{info['module']}.{qual}",
            "guards": _guard_summary(info["module"], qual),
            "live_threads": matches,
        })
    return out
