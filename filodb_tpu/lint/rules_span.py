"""Span-discipline rule: ``span-discipline``.

Two invariants the tracing layer (filodb_tpu.obs.trace) lives by:

  1. **Spans are opened via the context manager.** A bare
     ``start_span(...)`` call (or a span/event opened as a discarded
     expression statement) has no guaranteed close: an exception
     between open and close leaks an unfinished span and corrupts the
     thread-local parent chain. ``with span("x"): ...`` (optionally
     ``as sp``) is the only sanctioned shape.
  2. **No string formatting for span/trace attributes inside
     ``@hot_path`` code unless behind the sampling guard.** ``span()``
     is ~zero-cost when no trace is active — but its ARGUMENTS are
     evaluated unconditionally. An f-string / ``%`` / ``.format()``
     built per call re-introduces per-query allocation + formatting on
     the untraced fast path, exactly the cost the no-op design removed.
     Hoist the formatting behind ``if trace_active():`` (or
     ``...sampled``) or pass raw values and let the span store them.

Suppress a deliberate case with
``# graftlint: disable=span-discipline (reason)``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from filodb_tpu.lint import Finding, ModuleSource, register_rule
from filodb_tpu.lint.rules_hot import _is_hot, _module_hot_names

register_rule(
    "span-discipline", "trace",
    "bare start_span without a context manager, or string formatting "
    "for span attributes inside @hot_path code outside the sampling "
    "guard")

# call leaves that open/annotate spans (the obs.trace API surface)
from filodb_tpu.lint.astwalk import walk_nodes
_SPAN_OPENERS = {"span", "event", "start_span"}
_SPAN_ANNOTATORS = {"tag"}
# names in an `if` test that count as the sampling guard
_GUARD_MARKERS = ("sampled", "trace_active", "is_traced", "active")


def _dotted(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _is_span_call(call: ast.Call, leaves: Set[str]) -> Optional[str]:
    """Dotted callee name when ``call`` targets the span API (final
    component in ``leaves``, and for bare/ambiguous receivers the path
    must smell like the trace module), else None."""
    dotted = _dotted(call.func)
    if dotted is None:
        return None
    parts = dotted.split(".")
    leaf = parts[-1]
    if leaf not in leaves:
        return None
    if len(parts) == 1:
        return dotted       # bare `span(...)` / `start_span(...)`
    base = ".".join(parts[:-1]).lower()
    if "trace" in base or "tracer" in base or leaf == "start_span" \
            or leaf == "tag":
        return dotted
    return None


def _has_string_formatting(node: ast.expr) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.JoinedStr):
            return True     # f-string
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Mod):
            # "..." % args (left side a literal or plausible string)
            if isinstance(sub.left, ast.Constant) \
                    and isinstance(sub.left.value, str):
                return True
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Attribute) and f.attr == "format":
                return True
            if isinstance(f, ast.Name) and f.id in ("str", "repr"):
                return True
    return False


def _guarded(test: ast.expr) -> bool:
    """True when an `if` test reads like the sampling guard."""
    for sub in ast.walk(test):
        name = None
        if isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
        if name and any(m in name.lower() for m in _GUARD_MARKERS):
            return True
    return False


def check_module(mod: ModuleSource) -> Iterable[Finding]:
    findings: List[Finding] = []
    hot_names = _module_hot_names(mod.tree)

    # -- invariant 1: context-manager discipline, whole module ----------
    with_ctx_calls: Set[int] = set()
    for node in walk_nodes(mod.tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    with_ctx_calls.add(id(expr))
    for node in walk_nodes(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _is_span_call(node, {"start_span"})
        if dotted is not None and id(node) not in with_ctx_calls:
            findings.append(Finding(
                rule="span-discipline", path=mod.relpath,
                line=node.lineno,
                message=f"bare {dotted}() — spans must be opened via "
                        f"the context manager (`with span(...):`); an "
                        f"exception between open and close leaks the "
                        f"span",
                context=f"bare-open:{dotted}:{node.lineno}"))
    # a span/event opened as a DISCARDED expression statement is the
    # same leak (event() is exempt: it is a point annotation that
    # records immediately and returns nothing to close)
    for node in walk_nodes(mod.tree):
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            dotted = _is_span_call(node.value, {"span"})
            if dotted is not None:
                findings.append(Finding(
                    rule="span-discipline", path=mod.relpath,
                    line=node.lineno,
                    message=f"{dotted}() opened and discarded — use "
                            f"`with {dotted}(...):` so the span closes",
                    context=f"discarded:{dotted}:{node.lineno}"))

    # -- invariant 2: no per-call formatting in @hot_path span args -----
    hot_fns = [n for n in walk_nodes(mod.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
               and _is_hot(n, hot_names)]

    def visit(node: ast.AST, guarded: bool, fn) -> None:
        if isinstance(node, ast.If):
            body_guarded = guarded or _guarded(node.test)
            for child in node.body:
                visit(child, body_guarded, fn)
            for child in node.orelse:
                visit(child, guarded, fn)
            return
        if isinstance(node, ast.Call):
            dotted = _is_span_call(
                node, _SPAN_OPENERS | _SPAN_ANNOTATORS)
            if dotted is not None and not guarded:
                args = list(node.args) + [kw.value for kw in
                                          node.keywords]
                if any(_has_string_formatting(a) for a in args):
                    findings.append(Finding(
                        rule="span-discipline", path=mod.relpath,
                        line=node.lineno,
                        message=f"string formatting in {dotted}() "
                                f"arguments inside hot-path function "
                                f"{fn.name!r}: span args evaluate even "
                                f"when tracing is off — guard with "
                                f"`if trace_active():` or pass raw "
                                f"values",
                        context=f"hot-format:{fn.name}:{node.lineno}"))
        for child in ast.iter_child_nodes(node):
            visit(child, guarded, fn)

    for fn in hot_fns:
        for stmt in fn.body:
            visit(stmt, False, fn)
    return findings
