"""SPMD & device-dataflow rules (graftlint v3).

Built on :mod:`filodb_tpu.lint.dataflow` (entry points, per-site
closures, static-ness propagation). Multi-chip bugs are the worst class
this repo will grow: an unbalanced collective hangs every host in the
mesh with no stack trace, a donated-buffer read corrupts silently, and
neither is catchable by a single-chip CPU test. Three error families
plus one advisory:

  * ``spmd-collective-balance`` — a collective (``psum`` /
    ``all_gather`` / ``ppermute`` ...) inside a ``shard_map``-traced
    closure sits under Python-level control flow that can diverge
    across processes (a test reading ``process_index()`` / host
    identity / RNG, or a value the static-ness propagation cannot prove
    trace-static), or under a ``lax.cond``/``switch``/``while_loop``
    branch (device-varying predicates execute different collective
    sequences per device), or names a mesh axis that does not exist in
    the enclosing mesh/spec environment. Any of these is a multi-host
    deadlock or a silent partial-group reduction.
  * ``donation-safety`` — a buffer donated via ``donate_argnums`` /
    ``donate_argnames`` is read after the donating call, donated twice
    along one path, or aliased by live shared state (an attribute /
    container the donation invalidates behind the owner's back). The
    refresh idiom ``self.buf = step(self.buf)`` — rebinding the same
    state from the result in the same statement — is exempt.
  * ``partition-spec-consistency`` — ``in_specs`` arity must match the
    wrapped body's positional parameters, ``out_specs`` arity must
    match the body's returned tuple, PartitionSpec entries must be
    axis-name strings (or None), and every named axis must exist in the
    constructing mesh (falling back to the module's, then the
    project's, mesh-axis universe — so ``P("shards")`` against a
    ``("shard", "time")`` mesh is caught at lint time, not as a
    run-time KeyError on an 8-device pod).
  * ``donation-missing`` (advisory, warning severity) — a jit-wrapped
    callable invoked in a rebind loop (``x = step(x, ...)`` inside
    ``for``/``while``) without donation: the tile-store refresh shape
    ROADMAP 2 wants zero-copy. Advisory because donation is an API
    contract change, not a local fix.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from filodb_tpu.lint import Finding, ModuleSource, register_rule
from filodb_tpu.lint import callgraph as cgmod
from filodb_tpu.lint import dataflow as dfmod

register_rule("spmd-collective-balance", "spmd",
              "collective under divergent control flow, lax.cond "
              "branch, or with an axis name absent from the mesh/spec "
              "environment")
from filodb_tpu.lint.astwalk import walk_nodes
register_rule("donation-safety", "spmd",
              "donated buffer read after the call, donated twice, or "
              "aliased by live shared state")
register_rule("partition-spec-consistency", "spmd",
              "PartitionSpec arity/axis-name inconsistent with the "
              "wrapped body or constructing mesh")
register_rule("donation-missing", "spmd",
              "jit callable re-binding its own argument in a loop "
              "without donate_argnums (zero-copy refresh candidate)",
              severity="warning")


def _collective_axes(node: ast.Call) -> Tuple[str, ...]:
    """Axis names named by a collective call (positional string args +
    axis_name/axis kwarg, strings or tuples of strings)."""
    out: List[str] = []

    def harvest(e) -> None:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.append(e.value)
        elif isinstance(e, (ast.Tuple, ast.List)):
            for el in e.elts:
                harvest(el)

    for a in node.args[1:]:
        harvest(a)
    for kw in node.keywords:
        if kw.arg in ("axis_name", "axis"):
            harvest(kw.value)
    return tuple(out)


def _is_collective(node: ast.Call) -> bool:
    leaf = dfmod._leaf(node.func)
    if leaf not in dfmod.COLLECTIVE_LEAVES:
        return False
    # require a lax/jax base or a bare name (from-import) — keeps
    # unrelated methods that happen to share a name out
    if isinstance(node.func, ast.Attribute):
        d = dfmod._dotted(node.func) or ""
        return "lax" in d or d.startswith("jax")
    return True


def _own_nodes(fn_node) -> List[ast.AST]:
    """Body nodes excluding nested function/lambda bodies (those are
    their own closure members)."""
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _test_divergence(test, dyn: Set[str]) -> Optional[str]:
    """Why a control-flow test may diverge across hosts/devices, or
    None when it is provably uniform-enough."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Call):
            leaf = dfmod._leaf(sub.func)
            if leaf in dfmod._HOST_DIVERGENT_LEAVES:
                return f"reads host-divergent {leaf}()"
        if isinstance(sub, ast.Name) and sub.id in dyn:
            return (f"branches on {sub.id!r}, which is not "
                    f"trace-static")
    return None


class _DivergenceWalker:
    """Find collective calls and the divergent control context they sit
    under, within one function's own body."""

    def __init__(self, dyn: Set[str]):
        self.dyn = dyn
        self.hits: List[Tuple[ast.Call, str]] = []      # (call, why)
        self.clean: List[ast.Call] = []

    def walk(self, fn_node) -> None:
        body = fn_node.body if not isinstance(fn_node, ast.Lambda) \
            else [ast.Expr(fn_node.body)]
        for stmt in body:
            self._walk(stmt, None)

    def _walk(self, node, why: Optional[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        here = why
        if isinstance(node, (ast.If, ast.While)):
            d = _test_divergence(node.test, self.dyn)
            if d is not None:
                here = here or f"under a divergent if/while ({d})"
        elif isinstance(node, ast.For):
            d = _test_divergence(node.iter, self.dyn)
            if d is not None:
                here = here or f"under a loop whose bounds diverge ({d})"
        if isinstance(node, ast.Call) and _is_collective(node):
            if here is not None:
                self.hits.append((node, here))
            else:
                self.clean.append(node)
        for child in ast.iter_child_nodes(node):
            self._walk(child, here)


def _check_collectives(df: dfmod.DeviceDataflow
                       ) -> List[Tuple[str, Finding]]:
    out: List[Tuple[str, Finding]] = []
    cg = df.cg
    for key in sorted(df.spmd_reachable):
        fi = cg.funcs.get(key)
        if fi is None:
            continue
        env = df.axes_env.get(key, set())
        dyn = df.dynamic_names(key)
        w = _DivergenceWalker(dyn)
        w.walk(fi.node)
        for call, why in w.hits:
            f = Finding(
                rule="spmd-collective-balance", path=fi.relpath,
                line=call.lineno,
                message=(f"{fi.qualname}: collective "
                         f"{dfmod._leaf(call.func)}() {why} inside a "
                         f"shard_map-traced body — hosts/devices that "
                         f"skip it deadlock the mesh"),
                context=f"{fi.qualname}:divergent:"
                        f"{dfmod._leaf(call.func)}")
            out.append((fi.relpath, f))
        for call in w.clean + [c for c, _ in w.hits]:
            axes = _collective_axes(call)
            missing = [a for a in axes if env and a not in env]
            if missing:
                f = Finding(
                    rule="spmd-collective-balance", path=fi.relpath,
                    line=call.lineno,
                    message=(f"{fi.qualname}: collective "
                             f"{dfmod._leaf(call.func)}() names axis "
                             f"{missing[0]!r} which is absent from the "
                             f"enclosing mesh/spec environment "
                             f"({', '.join(sorted(env)) or 'empty'})"),
                    context=f"{fi.qualname}:axis:{missing[0]}")
                out.append((fi.relpath, f))
        # lax.cond / switch / while_loop branches containing collectives
        for node in _own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            leaf = dfmod._leaf(node.func)
            if leaf not in dfmod._STRUCTURED_CONTROL:
                continue
            d = dfmod._dotted(node.func) or ""
            if "lax" not in d and not d.startswith("jax"):
                continue
            for ref in self_branch_refs(df, fi, node):
                if _closure_has_collective(df, ref):
                    rfi = cg.funcs[ref]
                    f = Finding(
                        rule="spmd-collective-balance", path=fi.relpath,
                        line=node.lineno,
                        message=(f"{fi.qualname}: lax.{leaf} branch "
                                 f"{rfi.qualname} contains a "
                                 f"collective — a device-varying "
                                 f"predicate executes different "
                                 f"collective sequences per device"),
                        context=f"{fi.qualname}:branch:{rfi.qualname}")
                    out.append((fi.relpath, f))
                    break
    return out


def self_branch_refs(df: dfmod.DeviceDataflow, fi: cgmod.FuncInfo,
                     node: ast.Call) -> List[str]:
    """FuncInfo keys of branch/body functions handed to a lax control
    primitive."""
    out: List[str] = []
    for a in node.args:
        if isinstance(a, ast.Lambda):
            k = df._lambda_by_line.get((fi.module, a.lineno))
            if k:
                out.append(k)
        elif isinstance(a, ast.Name):
            out.extend(df._body_keys_for(fi.module, a, fi))
    return out


def _closure_has_collective(df: dfmod.DeviceDataflow, key: str) -> bool:
    for k in df.closure_of([key]):
        fi = df.cg.funcs.get(k)
        if fi is None:
            continue
        for node in _own_nodes(fi.node):
            if isinstance(node, ast.Call) and _is_collective(node):
                return True
    return False


# -- partition-spec consistency ----------------------------------------------


def _module_imports_pspec(mod: ModuleSource) -> bool:
    for node in walk_nodes(mod.tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "PartitionSpec":
                    return True
    return False


def _return_arities(df: dfmod.DeviceDataflow, key: str) -> Set[int]:
    fi = df.cg.funcs.get(key)
    if fi is None or isinstance(fi.node, ast.Lambda):
        if fi is not None and isinstance(fi.node.body, ast.Tuple):
            return {len(fi.node.body.elts)}
        return set()
    out: Set[int] = set()
    for node in _own_nodes(fi.node):
        if isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Tuple):
                out.add(len(node.value.elts))
            elif isinstance(node.value, ast.Call):
                return set()        # could return anything — unknown
            else:
                out.add(1)
    return out


def _check_specs(df: dfmod.DeviceDataflow, mods: Sequence[ModuleSource]
                 ) -> List[Tuple[str, Finding]]:
    out: List[Tuple[str, Finding]] = []
    for site in df.sites:
        if site.kind != "shard_map":
            continue
        allowed = set(site.mesh_axes or ()) \
            or df.mesh.module_axes.get(site.module, set()) \
            or df.mesh.project_axes
        order = df.site_order(site)
        for spec in site.all_specs:
            for bad in spec.bad_entries:
                out.append((site.relpath, Finding(
                    rule="partition-spec-consistency", path=site.relpath,
                    line=spec.line or site.line,
                    message=(f"PartitionSpec entry {bad} is neither an "
                             f"axis-name string, a positional axis "
                             f"index, nor None"),
                    context=f"spec:{site.relpath}:{bad}")))
            # positional indices (jax positional-PartitionSpec
            # semantics): resolve against the site's mesh axis order;
            # out-of-range indices and a repeated -1 are the same
            # run-time errors the named-axis checks catch at lint time
            if spec.pos_entries:
                _res, problems = dfmod.resolve_positional(spec, order)
                for why in problems:
                    out.append((site.relpath, Finding(
                        rule="partition-spec-consistency",
                        path=site.relpath,
                        line=spec.line or site.line,
                        message=f"PartitionSpec positional entry: {why}",
                        context=f"spec-pos:{site.relpath}:{why}")))
            if allowed:
                for a in spec.axes:
                    if a not in allowed:
                        out.append((site.relpath, Finding(
                            rule="partition-spec-consistency",
                            path=site.relpath,
                            line=spec.line or site.line,
                            message=(f"PartitionSpec names axis {a!r} "
                                     f"absent from the constructing "
                                     f"mesh axes "
                                     f"({', '.join(sorted(allowed))})"),
                            context=f"spec-axis:{site.relpath}:{a}")))
        if site.in_specs is not None \
                and site.body_param_count is not None \
                and len(site.in_specs) != site.body_param_count:
            out.append((site.relpath, Finding(
                rule="partition-spec-consistency", path=site.relpath,
                line=site.line,
                message=(f"in_specs declares {len(site.in_specs)} "
                         f"specs but the shard_map body takes "
                         f"{site.body_param_count} positional "
                         f"arguments"),
                context=f"in-arity:{site.relpath}:{site.line}")))
        if site.out_specs is not None and site.out_specs_is_tuple \
                and site.body_keys:
            arities = _return_arities(df, site.body_keys[0])
            if arities and all(a != len(site.out_specs)
                               for a in arities):
                got = ", ".join(str(a) for a in sorted(arities))
                out.append((site.relpath, Finding(
                    rule="partition-spec-consistency", path=site.relpath,
                    line=site.line,
                    message=(f"out_specs declares "
                             f"{len(site.out_specs)} specs but the "
                             f"body returns {got} value(s)"),
                    context=f"out-arity:{site.relpath}:{site.line}")))
    # free-floating P(...) literals (NamedSharding args, helper calls):
    # axis typo check against the project mesh universe
    site_lines = {(s.relpath, sp.line) for s in df.sites
                  for sp in s.all_specs}
    if df.mesh.project_axes:
        for mod in mods:
            if not _module_imports_pspec(mod):
                continue
            for node in walk_nodes(mod.tree):
                if isinstance(node, ast.Call) \
                        and dfmod._leaf(node.func) in ("P",
                                                       "PartitionSpec"):
                    if (mod.relpath, node.lineno) in site_lines:
                        continue
                    spec = dfmod.parse_spec(node)
                    for a in spec.axes:
                        if a not in df.mesh.project_axes:
                            out.append((mod.relpath, Finding(
                                rule="partition-spec-consistency",
                                path=mod.relpath, line=node.lineno,
                                message=(f"PartitionSpec names axis "
                                         f"{a!r} which no mesh in the "
                                         f"project declares (axes: "
                                         f"{', '.join(sorted(df.mesh.project_axes))})"),
                                context=f"spec-axis:{mod.relpath}:{a}")))
    return out


# -- donation safety ---------------------------------------------------------


class _DonationScan:
    """Ordered traversal of one function body checking reads of a
    donated name after the donating call (rebinds clear the taint)."""

    def __init__(self, call: ast.Call, name: str):
        self.call = call
        self.name = name
        self.donated = False
        self.read_at: Optional[int] = None

    def run(self, fn_node) -> Optional[int]:
        for stmt in fn_node.body:
            self._visit(stmt)
        return self.read_at

    def _visit(self, node) -> None:
        if self.read_at is not None:
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.Assign):
            self._visit(node.value)
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == self.name:
                    self.donated = False
                else:
                    self._visit(t)
            return
        if isinstance(node, ast.Name) and node.id == self.name \
                and isinstance(node.ctx, ast.Load) and self.donated:
            self.read_at = node.lineno
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child)
        if node is self.call:
            self.donated = True


def _donated_arg_exprs(call: ast.Call,
                       site: dfmod.SpmdSite,
                       body_params: Optional[List[str]]) -> List[ast.expr]:
    out: List[ast.expr] = []
    for i in site.donate_nums:
        if 0 <= i < len(call.args):
            out.append(call.args[i])
    if site.donate_names and body_params:
        for kw in call.keywords:
            if kw.arg in site.donate_names:
                out.append(kw.value)
        for name in site.donate_names:
            if name in body_params:
                i = body_params.index(name)
                if i < len(call.args):
                    out.append(call.args[i])
    return out


def _attr_root_dotted(expr) -> Optional[str]:
    """Dotted form of an attribute/subscript expression rooted at a
    name (``self.buf``, ``obj.cache[k]`` -> ``obj.cache``)."""
    e = expr
    while isinstance(e, ast.Subscript):
        e = e.value
    return dfmod._dotted(e) if isinstance(e, ast.Attribute) else None


def _check_donation(df: dfmod.DeviceDataflow,
                    mods: Sequence[ModuleSource]
                    ) -> List[Tuple[str, Finding]]:
    out: List[Tuple[str, Finding]] = []
    cg = df.cg
    # donating callables bound to names: (module, name) -> site, plus
    # decorator-form sites resolved through the call graph
    bound: Dict[Tuple[str, str], dfmod.SpmdSite] = {}
    plain_jit: Dict[Tuple[str, str], dfmod.SpmdSite] = {}
    body_site: Dict[str, dfmod.SpmdSite] = {}
    for site in df.sites:
        if site.kind not in ("jit", "shard_map"):
            continue
        donating = bool(site.donate_nums or site.donate_names)
        for bk in site.body_keys:
            if donating:
                body_site[bk] = site
        if site.binding:
            tgt = (site.module, site.binding)
            if donating:
                bound[tgt] = site
            else:
                plain_jit.setdefault(tgt, site)
    for mod in mods:
        dotted = cgmod.module_dotted(mod.relpath)
        for node in walk_nodes(mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.value, ast.Call):
                kind = dfmod._wrapper_kind(node.value.func)
                if kind is None:
                    d = dfmod._dotted(node.value.func) or ""
                    if d.rsplit(".", 1)[-1] == "partial" \
                            and node.value.args:
                        kind = dfmod._wrapper_kind(node.value.args[0])
                if kind is None:
                    continue
                nums, names = dfmod._donate_from_kwargs(
                    node.value.keywords)
                t = node.targets[0]
                name = None
                if isinstance(t, ast.Name):
                    name = t.id
                elif isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    name = t.attr
                if name is None:
                    continue
                site = dfmod.SpmdSite(
                    kind=kind, module=dotted, relpath=mod.relpath,
                    line=node.lineno, body_keys=(),
                    donate_nums=nums, donate_names=names,
                    binding=name)
                if nums or names:
                    bound[(dotted, name)] = site
                else:
                    plain_jit.setdefault((dotted, name), site)

    def emit(fi, call, msg, ctx) -> None:
        out.append((fi.relpath, Finding(
            rule="donation-safety", path=fi.relpath, line=call.lineno,
            message=f"{fi.qualname}: {msg}", context=ctx)))

    for fi in cg.funcs.values():
        for node in walk_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            site = self_donating_site(df, fi, node, bound, body_site)
            if site is None:
                continue
            body_params = None
            if site.body_keys:
                bfi = cg.funcs.get(site.body_keys[0])
                if bfi is not None \
                        and not isinstance(bfi.node, ast.Lambda):
                    body_params = [a.arg for a in bfi.node.args.args]
            exprs = _donated_arg_exprs(node, site, body_params)
            donated_ids = {id(e) for e in exprs}
            other_names = {a.id for a in node.args
                           if isinstance(a, ast.Name)
                           and id(a) not in donated_ids}
            other_names |= {kw.value.id for kw in node.keywords
                            if isinstance(kw.value, ast.Name)
                            and id(kw.value) not in donated_ids}
            seen_names: Set[str] = set()
            for e in exprs:
                if isinstance(e, ast.Name):
                    if e.id in seen_names:
                        emit(fi, node,
                             f"{e.id!r} is donated twice in one call — "
                             f"the second donation reads freed memory",
                             f"{fi.qualname}:double:{e.id}")
                        continue
                    if e.id in other_names:
                        emit(fi, node,
                             f"{e.id!r} is donated AND passed as a "
                             f"second (non-donated) argument of the "
                             f"same call — the alias reads the freed "
                             f"buffer",
                             f"{fi.qualname}:double:{e.id}")
                        seen_names.add(e.id)
                        continue
                    seen_names.add(e.id)
                    read = _DonationScan(node, e.id).run(fi.node)
                    if read is not None:
                        emit(fi, node,
                             f"{e.id!r} is read at line {read} after "
                             f"being donated here — donated buffers "
                             f"are deallocated by the callee",
                             f"{fi.qualname}:use-after:{e.id}")
                    continue
                root = _attr_root_dotted(e)
                if root is not None:
                    stmt_target = None
                    # refresh idiom: same attribute rebound from the
                    # result in the same statement — including the
                    # MULTI-BUFFER form `self.a, self.b = step(self.a,
                    # self.b, ...)` (tuple targets), the donated
                    # tile-refresh shape
                    parent = getattr(e, "_filo_parent_stmt", None)
                    if parent is None:
                        parent = _enclosing_assign(fi.node, node)
                    if parent is not None:
                        for t in parent.targets:
                            elts = t.elts if isinstance(
                                t, (ast.Tuple, ast.List)) else (t,)
                            for el in elts:
                                if dfmod._dotted(el) == root:
                                    stmt_target = root
                    if stmt_target is None:
                        emit(fi, node,
                             f"donates {root!r}, which live state "
                             f"still references — the cached/shared "
                             f"buffer is deallocated behind its owner "
                             f"(rebind it from the result in the same "
                             f"statement, or donate a copy)",
                             f"{fi.qualname}:aliased:{root}")
    # advisory: rebind loops without donation
    for fi in cg.funcs.values():
        for loop in walk_nodes(fi.node):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for stmt in ast.walk(loop):
                if not (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and isinstance(stmt.value, ast.Call)):
                    continue
                tname = stmt.targets[0].id
                call = stmt.value
                if not any(isinstance(a, ast.Name) and a.id == tname
                           for a in call.args):
                    continue
                key = None
                if isinstance(call.func, ast.Name):
                    key = (fi.module, call.func.id)
                elif isinstance(call.func, ast.Attribute) \
                        and isinstance(call.func.value, ast.Name) \
                        and call.func.value.id == "self":
                    key = (fi.module, call.func.attr)
                if key is None or key not in plain_jit:
                    continue
                out.append((fi.relpath, Finding(
                    rule="donation-missing", path=fi.relpath,
                    line=stmt.lineno, severity="warning",
                    message=(f"{fi.qualname}: {tname!r} is rebound "
                             f"from a jit call that takes it as input "
                             f"inside a loop — donate_argnums would "
                             f"make the refresh zero-copy"),
                    context=f"{fi.qualname}:missing:{tname}")))
    return out


def self_donating_site(df, fi, call: ast.Call, bound, body_site
                       ) -> Optional[dfmod.SpmdSite]:
    """The donating SpmdSite a call invokes, if any."""
    f = call.func
    if isinstance(f, ast.Name):
        site = bound.get((fi.module, f.id))
        if site is not None:
            return site
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "self":
        site = bound.get((fi.module, f.attr))
        if site is not None:
            return site
    # decorator-form: callee resolves to a donating body
    for s in fi.sites:
        if s.line == call.lineno and s.kind == "call":
            for c in s.callees:
                if c in body_site:
                    return body_site[c]
    return None


def _enclosing_assign(fn_node, call: ast.Call) -> Optional[ast.Assign]:
    for node in walk_nodes(fn_node):
        if isinstance(node, ast.Assign) and node.value is call:
            return node
    return None


# -- entry point -------------------------------------------------------------


def check_project(mods: Sequence[ModuleSource],
                  cg: Optional[cgmod.CallGraph] = None,
                  df: Optional[dfmod.DeviceDataflow] = None
                  ) -> List[Tuple[Optional[str], Finding]]:
    if df is None:
        df = dfmod.build(mods, cg)
    out: List[Tuple[Optional[str], Finding]] = []
    out.extend(_check_collectives(df))
    out.extend(_check_specs(df, mods))
    out.extend(_check_donation(df, mods))
    return out
