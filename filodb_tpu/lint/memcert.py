"""Capacity-certification rail (graftlint v5): every ``@capacity``
residency claim in the tree is dynamically certified, engine-as-
assertion style — the memory twin of :mod:`filodb_tpu.lint.ulpcert`.

:mod:`filodb_tpu.lint.rules_capacity` makes ``@capacity`` annotations
mandatory wherever a device allocation escapes into a long-lived
store; this module makes them HONEST. For each registered claim a
harness builds the annotated structure at seeded sizes and the rail
measures the REAL device bytes it retains (a live-buffer walk over the
store's object graph, deduplicated per buffer), then checks the claim
two-sided:

  * ``measured > claimed`` — the store is bigger than declared: the
    capacity planning the ledger feeds (resident series per 16 GB
    chip) would overcommit HBM;
  * ``claimed > 1.25 x measured`` — the claim pads more than 25% over
    reality: a slack claim hides regressions exactly the way a slack
    ULP tolerance does.

Sharded claims (``sharded=True``) certify at 1/2/4/8 virtual devices —
shard-alignment padding must be priced at every mesh width, not just
the friendly one. A claim with no harness, or whose harness crashes,
fails: an annotation the rail cannot evaluate cannot ship. Failures
surface as error-severity ``capacity-certification`` findings in the
tier-1 gate. Results are memoized per process (claims are fixed at
import time) so repeated ``run_lint`` calls pay the build cost once.

:func:`capacity_ledger` renders the certified inventory for
``CAPACITY.json`` (emitted by ``bench.py``): per family, the certified
bytes budget and the projected resident series per 16 GB chip — the
baseline number the compressed-chunks work must move.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from filodb_tpu.lint import Finding, register_rule
from filodb_tpu.lint import capacity as cmod
from filodb_tpu.lint.ulpcert import ensure_virtual_devices

register_rule("capacity-certification", "capacity",
              "a @capacity residency claim failed dynamic "
              "certification (measured device bytes above the claim, "
              "claim >1.25x over measured, or no harness) — the "
              "declared bytes budget is a lie")

DEVICE_COUNTS = (1, 2, 4, 8)

# a claim may pad at most 25% over the measured footprint
OVERCLAIM_RATIO = 1.25

# claim name -> harness. Sharded harnesses take (ndev) and run per
# device count; others take no argument. Both return
# (store, n_samples, n_series): ``store`` is walked for live device
# bytes (or is already a byte count), ``n_samples``/``n_series`` are
# the PADDED logical sizes the claim is evaluated at.
HARNESSES: Dict[str, Callable] = {}


def capacity_harness(name: str) -> Callable:
    def deco(fn):
        HARNESSES[name] = fn
        return fn
    return deco


@dataclass
class CapResult:
    name: str
    ok: bool
    measured: float             # worst-case live device bytes observed
    claimed: float              # claim total at the harness sizes
    n_samples: int = 0
    n_series: int = 0
    detail: str = ""
    device_counts: Tuple[int, ...] = ()


# ---------------------------------------------------------------------------
# live-buffer walk
# ---------------------------------------------------------------------------


def device_bytes(obj, max_depth: int = 10) -> int:
    """Sum the bytes of every distinct device array reachable from
    ``obj``: dicts, sequences, object attributes (``__dict__`` and
    ``__slots__``), and function closures, deduplicated per buffer so
    aliased references count once. Host numpy arrays do NOT count —
    residency is device memory."""
    import jax
    seen_objs: set = set()
    bufs: Dict[int, int] = {}
    stack: List[Tuple[object, int]] = [(obj, 0)]
    while stack:
        cur, depth = stack.pop()
        if cur is None or depth > max_depth:
            continue
        oid = id(cur)
        if oid in seen_objs:
            continue
        seen_objs.add(oid)
        if isinstance(cur, jax.Array):
            bufs[oid] = int(cur.nbytes)
            continue
        if isinstance(cur, (str, bytes, int, float, bool, complex)):
            continue
        if isinstance(cur, dict):
            stack.extend((v, depth + 1) for v in cur.values())
            continue
        if isinstance(cur, (list, tuple, set, frozenset)):
            stack.extend((v, depth + 1) for v in cur)
            continue
        d = getattr(cur, "__dict__", None)
        if isinstance(d, dict):
            stack.extend((v, depth + 1) for v in d.values())
        for klass in type(cur).__mro__:
            for slot in getattr(klass, "__slots__", ()):
                try:
                    stack.append((getattr(cur, slot), depth + 1))
                except AttributeError:
                    pass
        cells = getattr(cur, "__closure__", None)
        if cells:
            for cell in cells:
                try:
                    stack.append((cell.cell_contents, depth + 1))
                except ValueError:      # empty cell
                    pass
    return sum(bufs.values())


def _as_measurement(store, n_samples: int, n_series: int
                    ) -> Tuple[float, int, int]:
    if isinstance(store, (int, float)):
        return float(store), int(n_samples), int(n_series)
    return float(device_bytes(store)), int(n_samples), int(n_series)


# ---------------------------------------------------------------------------
# certify
# ---------------------------------------------------------------------------

_MEMO: Optional[List[CapResult]] = None


def _check(claim: cmod.CapacityClaim, measured: float, n_samples: int,
           n_series: int, counts: Tuple[int, ...]) -> CapResult:
    claimed = claim.claimed_total(n_samples, n_series)
    if measured > claimed:
        return CapResult(
            claim.name, False, measured, claimed, n_samples, n_series,
            f"store holds {measured:.0f} device bytes, claim covers "
            f"{claimed:.0f} at {n_samples} samples x {n_series} series "
            f"— residency above budget", counts)
    if claimed > OVERCLAIM_RATIO * max(measured, 1.0):
        return CapResult(
            claim.name, False, measured, claimed, n_samples, n_series,
            f"claim {claimed:.0f} is {claimed / max(measured, 1.0):.2f}x "
            f"the measured {measured:.0f} bytes — slack claims hide "
            f"regressions", counts)
    return CapResult(claim.name, True, measured, claimed, n_samples,
                     n_series, f"{measured:.0f} bytes measured vs "
                     f"{claimed:.0f} claimed", counts)


def certify_all(force: bool = False) -> List[CapResult]:
    """Certify every registered @capacity claim. Memoized per process."""
    global _MEMO
    if _MEMO is not None and not force:
        return _MEMO
    ensure_virtual_devices()
    cmod.import_annotated_modules()
    import jax
    avail = len(jax.devices())
    counts = tuple(d for d in DEVICE_COUNTS if d <= avail)
    out: List[CapResult] = []
    for name, claim in sorted(cmod.CAPACITY.items()):
        harness = HARNESSES.get(name)
        if harness is None:
            out.append(CapResult(
                name, False, math.inf, 0.0,
                detail="no certification harness registered — an "
                       "annotation the rail cannot evaluate cannot "
                       "ship"))
            continue
        try:
            if claim.sharded:
                worst: Optional[CapResult] = None
                for n in counts:
                    measured, ns, nr = _as_measurement(*harness(n))
                    r = _check(claim, measured, ns, nr, counts)
                    if worst is None or (not r.ok) or \
                            (worst.ok and r.measured > worst.measured):
                        worst = r
                    if not r.ok:
                        worst.detail += f" (at {n} device(s))"
                        break
                out.append(worst)
            else:
                measured, ns, nr = _as_measurement(*harness())
                out.append(_check(claim, measured, ns, nr, ()))
        except Exception as e:  # noqa: BLE001 — a gate must not crash
            out.append(CapResult(name, False, math.inf, 0.0,
                                 detail=f"harness crashed: "
                                        f"{type(e).__name__}: {e}"))
    _MEMO = out
    return out


def _claim_anchor(claim, mods) -> Tuple[Optional[str], int]:
    relpath = claim.module.replace(".", "/") + ".py"
    for mod in mods or ():
        if mod.relpath == relpath:
            for i, line in enumerate(mod.lines, start=1):
                if claim.name in line:
                    return relpath, i
            return relpath, 1
    return relpath, 1


def check_certifications(mods=None
                         ) -> List[Tuple[Optional[str], Finding]]:
    """Lint-facing entry: one finding per failed certification."""
    out: List[Tuple[Optional[str], Finding]] = []
    for res in certify_all():
        if res.ok:
            continue
        claim = cmod.CAPACITY.get(res.name)
        if claim is None:
            continue
        relpath, line = _claim_anchor(claim, mods)
        out.append((relpath, Finding(
            rule="capacity-certification", path=relpath or "?",
            line=line,
            message=(f"capacity claim {res.name!r} failed "
                     f"certification: measured {res.measured:.4g} vs "
                     f"claimed {res.claimed:.4g} bytes — {res.detail}"),
            context=f"memcert:{res.name}")))
    return out


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------


def capacity_ledger(samples_per_series: int = 2880
                    ) -> List[Dict[str, object]]:
    """Certified inventory for CAPACITY.json: per family the claimed
    budget, the measured bytes at the harness sizes, and the projected
    resident series per 16 GB chip at ``samples_per_series`` retained
    samples (the bench grid's 8h @ 10s default)."""
    rows: List[Dict[str, object]] = []
    results = {r.name: r for r in certify_all()}
    for name, claim in sorted(cmod.CAPACITY.items()):
        r = results.get(name)
        measured_bps = (r.measured / r.n_samples
                        if r and r.n_samples else None)
        rows.append({
            "family": name,
            "module": claim.module,
            "qualname": claim.qualname,
            "sharded": claim.sharded,
            "certified": bool(r and r.ok),
            "claimed_bytes_per_sample": claim.bytes_per_sample,
            "claimed_bytes_per_series": claim.bytes_per_series,
            "claimed_overhead_bytes": claim.overhead_bytes,
            "measured_bytes": (None if r is None or
                               not math.isfinite(r.measured)
                               else r.measured),
            "harness_n_samples": r.n_samples if r else 0,
            "harness_n_series": r.n_series if r else 0,
            "measured_bytes_per_sample": measured_bps,
            "device_counts": list(r.device_counts) if r else [],
            "projected_series_per_chip_16gb":
                claim.projected_series_per_chip(samples_per_series),
            "reason": claim.reason,
        })
    return rows


# ---------------------------------------------------------------------------
# in-tree harnesses
# ---------------------------------------------------------------------------
#
# Each harness builds the annotated store at SEEDED sizes chosen so
# the padded layout is exercised (pow2 slot capacity above the logical
# slot count, series counts divisible by every certified shard width)
# and measurement is deterministic.

_SEED = 0x0DD5


def _seed_tiles(S: int = 16, N: int = 56):
    """Dense counter tiles: S series x N slots (N NOT a power of two,
    so the pow2 capacity pad is live in the measurement)."""
    import numpy as np

    from filodb_tpu.query import tilestore as tst
    rng = np.random.default_rng(_SEED)
    base, dt = 1_000_000_000_000, 10_000
    ts = (base + np.arange(N, dtype=np.float64)[None, :] * dt
          + rng.integers(-2000, 2001, (S, N)))
    vals = np.cumsum(rng.uniform(0, 5, (S, N)), axis=1)
    return tst.AlignedTiles([{"i": str(i)} for i in range(S)], base, dt,
                            np.ones((S, N), bool), ts, vals)


def _shard_mesh(ndev: int):
    import jax

    from filodb_tpu.parallel.mesh import make_mesh
    return make_mesh(n_shard_groups=ndev, time_parallel=1,
                     devices=jax.devices()[:ndev])


@capacity_harness("shardstore-resident-channels")
def _h_shardstore(ndev: int):
    """The resident store itself: [cap, S_pad] int32 rel-ts + raw f64
    + corrected f64 = 20 B per padded slot, at every mesh width."""
    from filodb_tpu.parallel.shardstore import ShardedTiles
    tiles = _seed_tiles(S=16, N=56)     # cap pads 56 -> 64
    st = ShardedTiles(_shard_mesh(ndev), tiles)
    return st, st.cap * st.S_pad, st.S_pad


@capacity_harness("tilestore-aligned-tiles")
def _h_aligned_tiles():
    """Single-device aligned tiles: valid bool + ts f64 + vals f64 =
    17 B per slot (lazy channel caches empty at build)."""
    tiles = _seed_tiles(S=8, N=64)
    return tiles, 8 * 64, 8


@capacity_harness("tilestore-executable-constants")
def _h_exec_constants():
    """Packed-executable cache entries retain the device constants
    their closures capture; the claim prices them per packed slot."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from filodb_tpu.query import tilestore as tst
    const = jnp.asarray(
        np.arange(64 * 8, dtype=np.float64).reshape(64, 8))
    cache: Dict = {}

    def build():
        jit_f = jax.jit(lambda x: (x * const).sum(axis=0))

        def entry(x):
            return jit_f(x)
        # the closure-retained constant inventory the walk measures
        entry.__memcert_consts__ = (const,)
        return entry

    fn = tst._jit_lookup(cache, ("memcert", "exec-const"), build,
                         site="memcert")
    np.asarray(fn(jnp.ones((64, 8), jnp.float64)))
    return cache, 64 * 8, 8


@capacity_harness("device-tile-cache")
def _h_tile_cache():
    """The backend tile cache retains whole AlignedTiles cohorts per
    selection snapshot (FIFO-capped at _TILE_CACHE_MAX)."""
    import numpy as np

    from filodb_tpu.query import tpu as tpumod
    be = tpumod.TpuBackend(batcher=None)
    tiles = _seed_tiles(S=8, N=64)
    entry = tpumod._TileEntry(tiles, np.arange(8), False, [], None)
    be._insert_tile_entry(("memcert", "tile-cache"), None, entry)
    return be._tile_cache, 8 * 64, 8


@capacity_harness("downsample-pack-buffers")
def _h_downsample_pack():
    """The downsampler's padded staging block as the batch eval places
    it on device: int64 ts + f64 vals = 16 B per padded slot."""
    import numpy as np

    import jax

    from filodb_tpu.downsample.job import DownsamplerJob
    rng = np.random.default_rng(_SEED)
    job = DownsamplerJob(None)
    batch = []
    for i in range(4):
        ts = (1_000_000_000_000
              + np.arange(48, dtype=np.int64) * 10_000 + i)
        batch.append((f"pk{i}", None, ts, rng.uniform(0, 1, 48)))
    ts_pad, vals_pad, lens, t_lo, t_hi = job._pack(batch)
    placed = (jax.device_put(ts_pad), jax.device_put(vals_pad))
    return placed, ts_pad.size, len(batch)
