"""Device-memory residency & capacity annotations (graftlint v5).

ROADMAP item 1 says it outright: HBM capacity, not compute, is what
bounds "tens of millions of series per chip" — yet the long-lived
device buffers the resident serving path keeps (the shardstore
slot-major channels, tilestore tiles, packed-executable constants,
downsample staging buffers) had no accounting at all. The reference
system routes every off-heap byte through ``MemFactory``/
``BlockManager``; this module is the JAX-side equivalent: every
allocation that escapes into a long-lived store must DECLARE its
bytes budget, and two rails hold the declaration to account:

  * statically — :mod:`filodb_tpu.lint.rules_capacity` runs a
    residency dataflow over every function and errors on any device
    allocation that escapes into an object attribute, module cache, or
    ``@cache_registry`` store without a ``@capacity`` claim;
  * dynamically — :mod:`filodb_tpu.lint.memcert` builds every
    annotated structure at seeded sizes, measures the real device
    bytes (live-buffer walk + compiled memory analysis), and CERTIFIES
    the claim: measured bytes above the claim, or a claim more than
    1.25x over measured, is an error-severity ``capacity-certification``
    finding. Sharded claims certify at 1/2/4/8 virtual devices.

The claim model is affine in the store's logical contents:

    claimed_bytes(n_samples, n_series) =
        bytes_per_sample * n_samples
        + bytes_per_series * n_series
        + overhead_bytes

``bytes_per_sample`` must price the PADDED layout (pow2 slot capacity,
shard-aligned series padding) — the certifier measures real buffers,
and padding is real HBM. The certified per-family budgets feed the
``CAPACITY.json`` ledger emitted by ``bench.py`` (projected resident
series per 16 GB chip), the baseline the compressed-chunks work must
move.

This module also carries the RUNTIME residency registry: annotated
stores report their live device bytes via :func:`record_resident`, and
a metrics collector exposes them as the
``filodb_device_memory_bytes{family,shard}`` gauge (queryable through
``__selfmon__`` PromQL and surfaced in ``&explain=analyze``).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

HBM_BYTES_PER_CHIP = 16 << 30       # v5e: 16 GiB HBM per chip


@dataclass(frozen=True)
class CapacityClaim:
    """One ``@capacity`` declaration."""
    name: str
    bytes_per_sample: float         # priced at the PADDED device layout
    reason: str
    bytes_per_series: float = 0.0
    overhead_bytes: int = 0
    sharded: bool = False           # certify at 1/2/4/8 virtual devices
    module: str = ""
    qualname: str = ""

    def claimed_total(self, n_samples: int, n_series: int = 0) -> float:
        """Claimed device footprint for a store holding ``n_samples``
        logical samples across ``n_series`` series."""
        return (self.bytes_per_sample * n_samples
                + self.bytes_per_series * n_series
                + self.overhead_bytes)

    def projected_series_per_chip(self, samples_per_series: int,
                                  hbm_bytes: int = HBM_BYTES_PER_CHIP
                                  ) -> int:
        """Resident series one chip can hold under this claim at
        ``samples_per_series`` retained samples each."""
        per_series = (self.bytes_per_sample * samples_per_series
                      + self.bytes_per_series)
        if per_series <= 0:
            return 0
        return int((hbm_bytes - self.overhead_bytes) // per_series)


# claim name -> claim (names are globally unique — the memcert harness
# registry, the runtime residency gauge, and the ledger key on them)
CAPACITY: Dict[str, CapacityClaim] = {}


def _register(claim: CapacityClaim) -> None:
    prev = CAPACITY.get(claim.name)
    if prev is not None and prev.qualname != claim.qualname:
        raise ValueError(
            f"capacity claim {claim.name!r} declared twice "
            f"({prev.qualname} and {claim.qualname})")
    CAPACITY[claim.name] = claim


def capacity(name: Optional[str] = None, *, bytes_per_sample: float,
             reason: str, bytes_per_series: float = 0.0,
             overhead_bytes: int = 0, sharded: bool = False) -> Callable:
    """Declare a long-lived device-resident store's bytes budget (see
    module docstring). Applies to the function or class whose body
    performs the retained allocation; ``reason`` must be non-empty
    prose naming what the bytes buy."""
    if not reason or not reason.strip():
        raise ValueError("@capacity requires a non-empty reason")

    def deco(obj):
        claim = CapacityClaim(
            name=name or getattr(obj, "__qualname__",
                                 getattr(obj, "__name__", "?")),
            bytes_per_sample=float(bytes_per_sample), reason=reason,
            bytes_per_series=float(bytes_per_series),
            overhead_bytes=int(overhead_bytes), sharded=bool(sharded),
            module=getattr(obj, "__module__", "") or "",
            qualname=getattr(obj, "__qualname__",
                             getattr(obj, "__name__", "?")))
        _register(claim)
        try:
            obj.__capacity__ = claim
        except (AttributeError, TypeError):   # functools.partial etc.
            pass
        return obj
    return deco


def capacity_claim(name: str) -> CapacityClaim:
    """Look up a registered ``@capacity`` claim by name (importing the
    engine modules that declare in-tree claims first)."""
    if name not in CAPACITY:
        import_annotated_modules()
    return CAPACITY[name]


# the modules carrying in-tree @capacity annotations; memcert + the
# lookup helpers import these so the registry is populated without
# executing anything device-side
ANNOTATED_MODULES: Tuple[str, ...] = (
    "filodb_tpu.parallel.shardstore",
    "filodb_tpu.query.tilestore",
    "filodb_tpu.query.tpu",
    "filodb_tpu.downsample.job",
)


def import_annotated_modules() -> None:
    import importlib
    for m in ANNOTATED_MODULES:
        importlib.import_module(m)


def claim_inventory() -> Dict[str, CapacityClaim]:
    """All registered claims (README ledger table / debugging)."""
    import_annotated_modules()
    return dict(CAPACITY)


# ---------------------------------------------------------------------------
# runtime residency registry — live device bytes per (family, shard)
# ---------------------------------------------------------------------------

_RES_LOCK = threading.Lock()
# (family, shard) -> (token, bytes); token disambiguates multiple live
# stores of the same family (id-based; paired with a weakref finalizer
# at the annotated store so a collected store drops its bytes)
_RESIDENT: Dict[Tuple[str, str], Dict[int, int]] = {}


def record_resident(family: str, shard: str, token: int,
                    nbytes: int) -> None:
    """Report ``nbytes`` of live device memory held by the store
    instance identified by ``token`` under ``family``/``shard``.
    Re-recording the same token replaces its contribution (append /
    refresh paths)."""
    with _RES_LOCK:
        _RESIDENT.setdefault((family, str(shard)), {})[token] = int(nbytes)


def drop_resident(family: str, shard: str, token: int) -> None:
    """Forget one store instance's contribution (weakref finalizer)."""
    with _RES_LOCK:
        cell = _RESIDENT.get((family, str(shard)))
        if cell is not None:
            cell.pop(token, None)
            if not cell:
                _RESIDENT.pop((family, str(shard)), None)


def residency_snapshot() -> Dict[str, Dict[str, int]]:
    """Live device bytes, family -> shard -> bytes (the
    ``&explain=analyze`` residency section)."""
    out: Dict[str, Dict[str, int]] = {}
    with _RES_LOCK:
        for (family, shard), cell in _RESIDENT.items():
            out.setdefault(family, {})[shard] = sum(cell.values())
    return {f: dict(sorted(s.items())) for f, s in sorted(out.items())}


def _collect_residency(builder) -> None:
    for family, shards in residency_snapshot().items():
        for shard, nbytes in shards.items():
            builder.sample(
                "filodb_device_memory_bytes",
                {"family": family, "shard": shard}, str(nbytes),
                mtype="gauge",
                help="live device bytes held by @capacity-annotated "
                     "resident stores")


_COLLECTOR_REGISTERED = False


def ensure_residency_collector() -> None:
    """Register the ``filodb_device_memory_bytes`` gauge collector with
    the global metrics registry (idempotent; collectors survive
    registry resets)."""
    global _COLLECTOR_REGISTERED
    if _COLLECTOR_REGISTERED:
        return
    from filodb_tpu.obs.metrics import GLOBAL_REGISTRY
    GLOBAL_REGISTRY.register_collector(_collect_residency)
    _COLLECTOR_REGISTERED = True
