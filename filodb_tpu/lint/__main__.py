"""``python -m filodb_tpu.lint`` — run graftlint.

Exit codes: 0 = clean (no new error-severity findings), 1 = findings,
2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Optional

from filodb_tpu.lint import load_baseline, package_root, rules, run_lint


def changed_files(base: Optional[str] = None) -> frozenset:
    """Repo-relative .py paths changed vs ``base`` (default: the
    working tree + index vs HEAD — the pre-commit view). Paths are
    normalized to the forward-slash relpath form findings use."""
    root = package_root()
    out = set()
    cmds = [["git", "diff", "--name-only", base]] if base else \
        [["git", "diff", "--name-only", "HEAD"],
         ["git", "diff", "--name-only", "--cached"],
         ["git", "ls-files", "--others", "--exclude-standard"]]
    for cmd in cmds:
        try:
            text = subprocess.run(
                cmd, cwd=root, capture_output=True, text=True,
                check=True).stdout
        except (OSError, subprocess.CalledProcessError):
            continue
        for line in text.splitlines():
            line = line.strip().replace(os.sep, "/")
            if line.endswith(".py"):
                out.add(line)
    return frozenset(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m filodb_tpu.lint",
        description="graftlint: kernel-contract, trace-safety, "
                    "lock-discipline, SPMD/device-dataflow, "
                    "cache-invalidation, and PromQL-surface (promlint) "
                    "static analysis")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the "
                         "filodb_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit machine-readable findings on stdout")
    ap.add_argument("--github", action="store_true", dest="as_github",
                    help="emit GitHub workflow ::error/::warning "
                         "annotation lines (CI inline PR comments)")
    ap.add_argument("--sarif", action="store_true", dest="as_sarif",
                    help="emit a SARIF 2.1.0 report on stdout (all "
                         "rule families in the tool driver) for "
                         "code-scanning UIs")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: the shipped "
                         "filodb_tpu/lint/baseline.json)")
    ap.add_argument("--no-contracts", action="store_true",
                    help="skip runtime kernel-contract verification "
                         "(AST rules only)")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only findings anchored in files git "
                         "considers changed (working tree + index vs "
                         "HEAD, or vs --diff-base); the interprocedural "
                         "rules still analyze the whole graph")
    ap.add_argument("--diff-base", default=None,
                    help="git ref to diff against for --changed-only "
                         "(default: HEAD incl. staged + untracked)")
    ap.add_argument("--rules", action="store_true", dest="list_rules",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(rules().items()):
            print(f"{rid:26s} [{rule.family}/{rule.severity}] {rule.doc}")
        return 0

    report_only = None
    if args.changed_only:
        report_only = changed_files(args.diff_base)
        if not report_only:
            print("graftlint: --changed-only: no changed .py files",
                  file=sys.stderr)
            return 0
    # the ulp-certification rail needs virtual devices for the
    # 1/2/4/8-device order-insensitivity runs; ask before any backend
    # initialization (no-op once a backend is up, as in tests)
    from filodb_tpu.lint.ulpcert import ensure_virtual_devices
    ensure_virtual_devices()
    result = run_lint(args.paths or None,
                      baseline=load_baseline(args.baseline),
                      check_contracts=not args.no_contracts,
                      report_only=report_only)
    if args.as_github:
        from filodb_tpu.lint.ci_annotations import github_annotations
        for line in github_annotations(result.to_json()):
            print(line)
        print(f"graftlint: {result.files} file(s), "
              f"{len(result.errors)} error(s)", file=sys.stderr)
    elif args.as_sarif:
        from filodb_tpu.lint.ci_annotations import sarif_report
        print(json.dumps(sarif_report(result.to_json()), indent=2,
                         sort_keys=True))
        print(f"graftlint: {result.files} file(s), "
              f"{len(result.errors)} error(s)", file=sys.stderr)
    elif args.as_json:
        print(json.dumps(result.to_json(), indent=2, sort_keys=True))
    else:
        for f in result.findings:
            print(f.render())
        for f in result.baselined:
            print(f"{f.render()}  (baselined)")
        status = "clean" if not result.errors else \
            f"{len(result.errors)} error(s)"
        print(f"graftlint: {result.files} file(s), {status}, "
              f"{len(result.baselined)} baselined, "
              f"{result.suppressed} suppressed", file=sys.stderr)
    return 1 if result.errors else 0


if __name__ == "__main__":
    sys.exit(main())
