"""Hot-path annotations for the ``host-transfer-in-hot-loop`` rule.

:func:`hot_path` marks a function as part of the per-query serving
fast path. Inside marked functions (and their lexically nested
helpers) graftlint flags device→host transfer calls — ``np.asarray`` /
``np.array`` / ``.item()`` / ``.block_until_ready()`` /
``jax.device_get`` — because an implicit sync on a device array stalls
the async dispatch pipeline and holds the GIL through device compute.
A *deliberate* sync point (e.g. the one amortized per-batch conversion
in ``SplitResult.get``) carries a
``# graftlint: disable=host-transfer-in-hot-loop (reason)`` pragma.

The decorator is runtime-neutral: it only records the function in
``HOT_PATHS`` (qualname registry, useful for docs/tests) and returns
it unchanged. Modules can alternatively declare
``__hot_path__ = ("fn_name", ...)`` for functions they cannot
decorate.
"""

from __future__ import annotations

from typing import Callable, List

HOT_PATHS: List[str] = []


def hot_path(fn: Callable) -> Callable:
    """Mark ``fn`` as per-query hot-path code (see module docstring)."""
    HOT_PATHS.append(getattr(fn, "__qualname__", getattr(fn, "__name__",
                                                         str(fn))))
    return fn
