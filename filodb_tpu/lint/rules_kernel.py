"""Kernel-contract rules.

AST side: every ``pallas_call`` site must sit inside a function (or a
lexically enclosing function) decorated with
:func:`filodb_tpu.lint.contracts.kernel_contract`.

Runtime side (still CPU-only — nothing executes on device): every
registered contract is re-verified from its declaration:

  * ``kernel-contract-missing`` — a ``pallas_call`` with no enclosing
    contract declaration.
  * ``kernel-vmem-budget`` — the declared worst-case blocks + scratch +
    outputs don't fit the declared VMEM budget (or a Pallas contract
    declares no budget at all, or budgets past physical VMEM).
  * ``kernel-tile-alignment`` — a VMEM block's trailing dims don't tile
    to (sublane, 128) for its dtype, or an 8-byte dtype is placed in
    VMEM (Mosaic legalizes neither f64 nor i64 vectors).
  * ``kernel-grid-bounds`` — a declared index_map sends some grid point
    out of its array's bounds.
  * ``kernel-span-guard`` — a contract declares int31 relative
    timestamps but names no resolvable dispatcher predicate proving the
    span fits.
  * ``kernel-abstract-eval`` — ``jax.eval_shape`` of the entry point
    over the contract's example inputs fails or disagrees with the
    declared outputs.
  * ``kernel-module-import`` — a kernel module failed to import, so its
    contracts could not be checked.
"""

from __future__ import annotations

import ast
import importlib
import inspect
from typing import Iterable, List, Optional, Tuple

from filodb_tpu.lint import Finding, ModuleSource, register_rule
from filodb_tpu.lint.contracts import (SUBLANE_BY_ITEMSIZE, VMEM_BYTES,
                                       KernelContract, contracts_for_module)

register_rule("kernel-contract-missing", "kernel",
              "pallas_call site without an enclosing @kernel_contract "
              "declaration")
register_rule("kernel-vmem-budget", "kernel",
              "declared blocks+scratch exceed the kernel's VMEM budget")
register_rule("kernel-tile-alignment", "kernel",
              "VMEM block trailing dims must tile to (sublane, 128)")
register_rule("kernel-grid-bounds", "kernel",
              "grid/index-map sends a block out of its array's bounds")
register_rule("kernel-span-guard", "kernel",
              "int31 relative-timestamp kernel without a resolvable "
              "dispatcher span guard")
register_rule("kernel-abstract-eval", "kernel",
              "jax.eval_shape of the kernel entry point fails or "
              "disagrees with the declared outputs")
register_rule("kernel-module-import", "kernel",
              "kernel module failed to import; contracts unchecked")

_GRID_POINT_CAP = 1 << 16


def _is_kernel_contract_deco(d: ast.expr) -> bool:
    target = d.func if isinstance(d, ast.Call) else d
    if isinstance(target, ast.Attribute):
        return target.attr == "kernel_contract"
    return isinstance(target, ast.Name) and target.id == "kernel_contract"


def _has_contract(stack: List[ast.AST]) -> bool:
    for node in stack:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_kernel_contract_deco(d) for d in node.decorator_list):
                return True
    return False


def check_module(mod: ModuleSource) -> Iterable[Finding]:
    """AST pass: pallas_call sites must carry a contract."""
    findings: List[Finding] = []

    def walk(node: ast.AST, stack: List[ast.AST]) -> None:
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else None
            if name == "pallas_call" and not _has_contract(stack):
                qual = ".".join(
                    n.name for n in stack
                    if isinstance(n, (ast.FunctionDef, ast.ClassDef)))
                findings.append(Finding(
                    rule="kernel-contract-missing", path=mod.relpath,
                    line=node.lineno,
                    message="pallas_call site has no enclosing "
                            "@kernel_contract declaration",
                    context=qual or "<module>"))
        stack.append(node)
        for child in ast.iter_child_nodes(node):
            walk(child, stack)
        stack.pop()

    walk(mod.tree, [])
    return findings


# -- runtime contract verification ------------------------------------------

def _contract_line(c: KernelContract) -> int:
    try:
        return inspect.getsourcelines(inspect.unwrap(c.fn))[1]
    except (OSError, TypeError):
        return 1


def _finding(c: KernelContract, relpath: str, rule: str, check: str,
             message: str) -> Finding:
    return Finding(rule=rule, path=relpath, line=_contract_line(c),
                   message=f"contract {c.name!r}: {message}",
                   context=f"contract:{c.name}:{check}")


def check_contract(c: KernelContract, relpath: str = "") -> List[Finding]:
    """Verify one contract declaration. Pure CPU: block arithmetic plus
    ``jax.eval_shape`` — the kernel is never executed."""
    out: List[Finding] = []
    relpath = relpath or (c.module.replace(".", "/") + ".py")

    # VMEM budget
    if c.kind == "pallas" and c.vmem_budget is None:
        out.append(_finding(c, relpath, "kernel-vmem-budget", "declared",
                            "pallas kernel declares no VMEM budget"))
    if c.vmem_budget is not None:
        if c.vmem_budget > VMEM_BYTES:
            out.append(_finding(
                c, relpath, "kernel-vmem-budget", "physical",
                f"budget {c.vmem_budget} exceeds physical VMEM "
                f"{VMEM_BYTES}"))
        fp = c.vmem_footprint()
        if fp > c.vmem_budget:
            out.append(_finding(
                c, relpath, "kernel-vmem-budget", "footprint",
                f"worst-case VMEM footprint {fp} bytes exceeds the "
                f"declared budget {c.vmem_budget}"))

    # tiling (pallas only: XLA kernels have no Mosaic tiling constraint)
    if c.kind == "pallas":
        for b in c.all_vmem_blocks():
            if b.itemsize() > 4:
                out.append(_finding(
                    c, relpath, "kernel-tile-alignment",
                    f"dtype:{b.name}",
                    f"block {b.name!r} places 8-byte dtype {b.dtype} "
                    f"in VMEM (Mosaic has no f64/i64 vectors)"))
                continue
            if not b.tiled or len(b.shape) < 2:
                continue
            sub = SUBLANE_BY_ITEMSIZE.get(b.itemsize(), 8)
            if b.shape[-1] % 128 or b.shape[-2] % sub:
                out.append(_finding(
                    c, relpath, "kernel-tile-alignment", f"tile:{b.name}",
                    f"block {b.name!r} shape {b.shape} trailing dims "
                    f"must be multiples of ({sub}, 128) for {b.dtype}"))

    # grid/index-map bounds
    if c.grid:
        npoints = 1
        for g in c.grid:
            npoints *= max(int(g), 1)
        points: List[Tuple[int, ...]] = []
        if npoints <= _GRID_POINT_CAP:
            idx = [0] * len(c.grid)
            for _ in range(npoints):
                points.append(tuple(idx))
                for d in range(len(c.grid) - 1, -1, -1):
                    idx[d] += 1
                    if idx[d] < c.grid[d]:
                        break
                    idx[d] = 0
        else:   # corners only for very large grids
            points = [tuple(0 for _ in c.grid),
                      tuple(g - 1 for g in c.grid)]
        for b in (*c.blocks, *c.outputs):
            if b.index_map is None or b.array_shape is None:
                continue
            for pt in points:
                bi = b.index_map(*pt)
                if not isinstance(bi, tuple):
                    bi = (bi,)
                if len(bi) != len(b.shape) or len(bi) != len(b.array_shape):
                    out.append(_finding(
                        c, relpath, "kernel-grid-bounds", f"rank:{b.name}",
                        f"block {b.name!r} index_map rank {len(bi)} != "
                        f"block rank {len(b.shape)}"))
                    break
                bad = any(
                    i < 0 or i * bd >= ad
                    for i, bd, ad in zip(bi, b.shape, b.array_shape))
                if bad:
                    out.append(_finding(
                        c, relpath, "kernel-grid-bounds",
                        f"bounds:{b.name}",
                        f"block {b.name!r} index_map{pt} -> {bi} starts "
                        f"outside array {b.array_shape}"))
                    break

    # int31 span guard: `name` resolves in the contract's module,
    # `pkg.mod:name` in the named module (guards usually live in the
    # dispatcher, not next to the kernel)
    if c.rel_time_bits is not None:
        ok = False
        if c.span_guard:
            modname, _, attr = c.span_guard.rpartition(":")
            modname = modname or c.module
            try:
                target = importlib.import_module(modname)
                for part in attr.split("."):
                    target = getattr(target, part)
                ok = callable(target)
            except (ImportError, AttributeError):
                ok = False
        if not ok:
            out.append(_finding(
                c, relpath, "kernel-span-guard", "guard",
                f"declares int{c.rel_time_bits} relative timestamps but "
                f"span guard {c.span_guard!r} does not resolve to a "
                f"callable in {c.module}"))

    # abstract evaluation (jax.eval_shape — traces, never runs)
    if c.check is not None:
        try:
            err = c.check()
        except Exception as e:      # noqa: BLE001 — report, don't crash
            err = f"{type(e).__name__}: {e}"
        if err:
            out.append(_finding(c, relpath, "kernel-abstract-eval",
                                "check", str(err)))
    elif c.example is not None:
        try:
            import jax
            args, kwargs = c.example()
            # only ShapeDtypeStructs (or containers of them) become
            # abstract arrays; everything else (mode flags, static
            # shapes, window params) binds concretely, the way the
            # dispatcher passes them
            def _is_abstract(a):
                if isinstance(a, jax.ShapeDtypeStruct):
                    return True
                if isinstance(a, (tuple, list, dict)):
                    return any(isinstance(x, jax.ShapeDtypeStruct)
                               for x in jax.tree_util.tree_leaves(a))
                return False

            abstract = [a for a in args if _is_abstract(a)]

            def _bound(*arrs, _args=tuple(args), _kw=kwargs):
                it = iter(arrs)
                full = [next(it) if _is_abstract(a) else a
                        for a in _args]
                return c.fn(*full, **_kw)

            res = jax.eval_shape(_bound, *abstract)
            err = c.expect(res) if c.expect is not None else None
        except Exception as e:      # noqa: BLE001 — report, don't crash
            err = f"{type(e).__name__}: {e}"
        if err:
            out.append(_finding(c, relpath, "kernel-abstract-eval",
                                "eval_shape", str(err)))
    return out


def _module_name(relpath: str) -> Optional[str]:
    if not relpath.endswith(".py"):
        return None
    parts = relpath[:-3].split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    if not parts or parts[0] != "filodb_tpu":
        return None
    return ".".join(parts)


def check_contracts(mods, root: str
                    ) -> Iterable[Tuple[str, Finding]]:
    """Import every linted package module and verify the contracts it
    registered."""
    out: List[Tuple[str, Finding]] = []
    for mod in mods:
        name = _module_name(mod.relpath)
        if name is None:
            continue
        # cheap AST gate: only import modules that mention the decorator
        # or pallas_call (importing the whole package pulls optional deps)
        if "kernel_contract" not in mod.source \
                and "pallas_call" not in mod.source:
            continue
        try:
            modobj = importlib.import_module(name)
        except Exception as e:      # noqa: BLE001 — surface, don't crash
            out.append((mod.relpath, Finding(
                rule="kernel-module-import", path=mod.relpath, line=1,
                message=f"import failed, contracts unchecked: "
                        f"{type(e).__name__}: {e}",
                context=f"import:{name}")))
            continue
        for c in contracts_for_module(name):
            for f in check_contract(c, mod.relpath):
                out.append((mod.relpath, f))
    return out
