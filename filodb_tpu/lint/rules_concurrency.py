"""Interprocedural concurrency rules (graftlint v2).

Built on the project call graph (``lint/callgraph.py``), which
propagates held-lock sets across calls, discovers thread roots
(``Thread(target=...)``, executor ``.submit``, ``@thread_root``), and
summarizes blocking behavior transitively. Three families:

  * ``lock-order-cycle`` — the observed acquisition-order graph (lock A
    held while lock B is acquired, across all call paths) contains a
    cycle: two threads taking the locks in opposite orders deadlock.
    Reported once per cycle, anchored at one of its acquisition sites.
  * ``lock-order-policy`` — an observed pair contradicts the declared
    canonical order in ``lint/lockorder.py`` (outermost-first). Fires
    even while the order graph is still acyclic: the policy is what
    keeps it acyclic as code grows.
  * ``lock-blocking-reachable`` — a call made while holding a lock
    transitively reaches a blocking primitive (peer RPC / urlopen,
    fsync, device sync, sleep, unbounded ``Queue.get`` / ``Event.wait``,
    ``Future.result``) any number of frames down. The per-function rule
    (``lock-blocking-call``) catches the same-frame case; this one
    reports at the call site in the lock-holding function with the
    chain to the primitive.
  * ``thread-unguarded-shared-state`` — an instance attribute or module
    global is compound-mutated (append/pop/setitem/del/augassign/
    read-modify-write — NOT the GIL-atomic single-rebind publish idiom)
    from two or more thread roots, with no lock held in common across
    all mutation sites and no ``@guarded_by`` declaration. This infers
    MISSING annotations instead of only checking declared ones.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from filodb_tpu.lint import Finding, ModuleSource, register_rule
from filodb_tpu.lint import callgraph as cgmod
from filodb_tpu.lint.lockorder import policy_violation

register_rule("lock-order-cycle", "concurrency",
              "lock acquisition-order graph contains a cycle "
              "(potential deadlock)")
register_rule("lock-order-policy", "concurrency",
              "lock pair acquired against the canonical order "
              "(lint/lockorder.py)")
register_rule("lock-blocking-reachable", "concurrency",
              "a blocking primitive is reachable through calls made "
              "while a lock is held")
register_rule("thread-unguarded-shared-state", "concurrency",
              "state compound-mutated from >=2 thread roots with no "
              "common lock and no @guarded_by")


def _fmt_chain(cg: cgmod.CallGraph,
               chain: Sequence[Tuple[str, int]]) -> str:
    parts = []
    for key, line in chain:
        fi = cg.funcs.get(key)
        if fi is None:
            continue
        parts.append(f"{fi.qualname} ({fi.relpath}:{line})")
    return " -> ".join(parts)


# -- lock order --------------------------------------------------------------

def _cycles(pairs: Dict[Tuple[str, str], Tuple[str, int, Tuple[str, ...]]]
            ) -> List[Tuple[str, ...]]:
    """Strongly connected components of size >= 2 in the order graph."""
    succ: Dict[str, Set[str]] = {}
    for (a, b) in pairs:
        succ.setdefault(a, set()).add(b)
        succ.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[Tuple[str, ...]] = []
    counter = [0]

    def strong(v: str) -> None:     # iterative Tarjan
        work = [(v, iter(sorted(succ.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(succ.get(w, ())))))
                    advanced = True
                    break
                if w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) >= 2:
                    out.append(tuple(sorted(comp)))

    for v in sorted(succ):
        if v not in index:
            strong(v)
    return out


def _check_lock_order(cg: cgmod.CallGraph) -> Iterable[Finding]:
    pairs = cg.order_pairs()
    findings: List[Finding] = []
    for cyc in _cycles(pairs):
        # anchor at the first in-cycle acquisition we observed
        anchor = None
        detail = []
        cyc_set = set(cyc)
        for (a, b), (fkey, line, chain) in sorted(pairs.items()):
            if a in cyc_set and b in cyc_set:
                fi = cg.funcs[fkey]
                via = f" via {chain[0]}" if chain else ""
                detail.append(f"{a} -> {b} at {fi.qualname} "
                              f"({fi.relpath}:{line}){via}")
                if anchor is None:
                    anchor = (fi, line)
        if anchor is None:
            continue
        fi, line = anchor
        findings.append(Finding(
            rule="lock-order-cycle", path=fi.relpath, line=line,
            message=(f"lock-order cycle among {', '.join(cyc)}: "
                     + "; ".join(detail[:4])),
            context=f"cycle:{'|'.join(cyc)}"))
    for (a, b), (fkey, line, chain) in sorted(pairs.items()):
        msg = policy_violation(a, b)
        if msg is None:
            continue
        fi = cg.funcs[fkey]
        via = f" ({a} held via {chain[0]})" if chain else ""
        findings.append(Finding(
            rule="lock-order-policy", path=fi.relpath, line=line,
            message=f"{fi.qualname} {msg}{via}",
            context=f"{fi.qualname}:{a}->{b}"))
    return findings


# -- blocking under lock, interprocedural ------------------------------------

def _check_blocking_reachable(cg: cgmod.CallGraph) -> Iterable[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for fi in cg.funcs.values():
        for s in fi.sites:
            if s.kind != "call" or not s.held or s.blocking:
                continue        # same-frame primitive: rules_lock's job
            for c in s.callees:
                summary = cg.blocks.get(c)
                if summary is None:
                    continue
                label, chain = summary
                locks = ", ".join(sorted(s.held))
                key = (fi.key, s.line, c)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    rule="lock-blocking-reachable", path=fi.relpath,
                    line=s.line,
                    message=(f"{fi.qualname} calls "
                             f"{cg.funcs[c].qualname} while holding "
                             f"{locks}; it reaches {label}: "
                             f"{_fmt_chain(cg, chain)}"),
                    context=f"{fi.qualname}:{c}:{label}"))
                break       # one finding per call site is enough
    return findings


# -- unguarded shared state --------------------------------------------------

def _check_shared_state(cg: cgmod.CallGraph) -> Iterable[Finding]:
    # func key -> roots that reach it on their own thread
    roots_of: Dict[str, Set[str]] = {}
    for r, reach in cg.reachable_from.items():
        for f in reach:
            roots_of.setdefault(f, set()).add(r)
    # target -> [(root display, FuncInfo, Mutation, full held)]
    by_target: Dict[str, List[Tuple[str, cgmod.FuncInfo, cgmod.Mutation,
                                    frozenset]]] = {}
    for fi in cg.funcs.values():
        if not fi.mutations:
            continue
        roots = roots_of.get(fi.key, set())
        if not roots:
            continue
        must = cg.must_held.get(fi.key, frozenset())
        for m in fi.mutations:
            full = frozenset(m.held | must)
            for r in roots:
                by_target.setdefault(m.target, []).append(
                    (cg.roots[r], fi, m, full))
    findings: List[Finding] = []
    for target, sites in sorted(by_target.items()):
        root_names = {r for r, _, _, _ in sites}
        if len(root_names) < 2:
            continue
        if cg.guarded_decl(target) is not None:
            continue        # declared: rules_lock enforces it
        if cg.single_writer_decl(target) is not None:
            # instances are owned by ONE thread at a time by design
            # (per-shard single-writer invariant); the class-level
            # abstraction cannot see instance disjointness
            continue
        common = None
        for _, _, _, full in sites:
            common = set(full) if common is None else (common & full)
        if common:
            continue        # a common guard exists at every site
        # report at the first mutation site (stable, suppressible)
        sites.sort(key=lambda t: (t[1].relpath, t[2].line))
        _, fi, m, _ = sites[0]
        locs = []
        seen_locs: Set[Tuple[str, int]] = set()
        for r, sfi, sm, _ in sites:
            lk = (sfi.relpath, sm.line)
            if lk in seen_locs:
                continue
            seen_locs.add(lk)
            locs.append(f"{sfi.qualname} ({sfi.relpath}:{sm.line}, "
                        f"root {r})")
        findings.append(Finding(
            rule="thread-unguarded-shared-state", path=fi.relpath,
            line=m.line,
            message=(f"{target} is compound-mutated from "
                     f"{len(root_names)} thread roots "
                     f"({', '.join(sorted(root_names))}) with no common "
                     f"lock and no @guarded_by: "
                     + "; ".join(locs[:4])),
            context=f"shared:{target}"))
    return findings


# -- entry point -------------------------------------------------------------

def check_project(mods: Sequence[ModuleSource],
                  cg: Optional[cgmod.CallGraph] = None
                  ) -> List[Tuple[Optional[str], Finding]]:
    """Run all three families over the module set. Returns
    (relpath, finding) pairs so the runner can route pragma
    suppression to the right file. ``cg`` lets the runner share one
    call graph across the interprocedural families."""
    if cg is None:
        cg = cgmod.build(mods)
    out: List[Tuple[Optional[str], Finding]] = []
    for f in _check_lock_order(cg):
        out.append((f.path, f))
    for f in _check_blocking_reachable(cg):
        out.append((f.path, f))
    for f in _check_shared_state(cg):
        out.append((f.path, f))
    return out
