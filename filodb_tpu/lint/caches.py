"""Cache-inventory annotations (graftlint v3).

Every cache-soundness bug shipped so far — the PR 5 review's missing
dispatch-scope key component, PR 6's watermark-coverage hole — was an
*invalidation-completeness* miss: some world-mutation event existed
that the cache's key or invalidation hooks did not account for, and a
human had to notice at review time. These annotations mechanize that
review. A cache DECLARES the events that affect its keys; event
publishers and authoritative state readers are marked; and graftlint's
``cache-invalidation-completeness`` rule checks, over the project call
graph, that the wiring is complete:

  * :func:`cache_registry` — class decorator declaring one cache the
    class owns, with the events that can change the world its entries
    were computed against:

      - ``invalidated_by={event: hook_method}`` — **push** events: the
        rule requires every ``@publishes(event)`` function in the
        project to REACH ``hook_method`` through the call graph
        (including listener/subscriber indirection — see the
        registration-bridge inference in ``lint/dataflow.py``).
      - ``validated_by={event: hook_methods}`` — **pull** events,
        checked at lookup time rather than pushed: the rule requires
        each named hook to reach an ``@event_source(event)`` function
        (the authoritative read of that event's state), so the check
        cannot silently rot out of the lookup path.
      - ``keyed=(...)`` — key components that make the cache immune to
        an event class by construction (a chunk-count in the key needs
        no chunk invalidation hook). Documentation + inventory only.

    Decorators stack for classes owning several caches.

  * :func:`publishes` — marks a function as a mutation publisher of an
    event (the topology-epoch bump, the backfill-epoch bump, a schema
    invalidation broadcast). Every publisher of a push event must reach
    every registered cache's hook for it.

  * :func:`event_source` — marks the authoritative reader of a pull
    event's state (``shards_epoch``, ``shards_watermark``). Pull hooks
    must reach one.

Classes whose name or dict-attribute names say "cache" but carry no
registry are themselves a finding (``cache-unregistered``): an
unregistered cache is one nobody has thought about invalidation for.

Module-level caches (the tilestore executable tables) declare through a
plain assignment the checker reads the same way::

    __cache_registry__ = {
        "tilestore-executables": {"keyed": ("kernel", "shape-bucket")},
    }

All decorators are runtime-neutral: they only record attributes
(``cls.__cache_registry__``, ``fn.__publishes__``,
``fn.__event_source__``) and feed the runtime inventory behind the
README's cache table.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Optional, Union

# runtime inventory: cache name -> declaration (module, class, events)
CACHES: Dict[str, Dict[str, object]] = {}


def _norm_hooks(v: Union[str, Iterable[str], None]) -> tuple:
    if v is None:
        return ()
    if isinstance(v, str):
        return (v,)
    return tuple(v)


def cache_registry(name: str,
                   invalidated_by: Optional[Dict[str, str]] = None,
                   validated_by: Optional[Dict[str, object]] = None,
                   keyed: Iterable[str] = ()):
    """Declare one cache owned by the decorated class (see module
    docstring). ``invalidated_by`` maps push events to the hook method
    called on them; ``validated_by`` maps pull events to the lookup
    method(s) that check them; ``keyed`` names key components."""
    def deco(cls):
        reg = dict(getattr(cls, "__cache_registry__", {}) or {})
        entry = {
            "invalidated_by": dict(invalidated_by or {}),
            "validated_by": {k: _norm_hooks(v)
                             for k, v in (validated_by or {}).items()},
            "keyed": tuple(keyed),
            "owner": cls.__name__,
            "module": cls.__module__,
        }
        reg[name] = entry
        cls.__cache_registry__ = reg
        CACHES[name] = entry
        return cls
    return deco


def publishes(event: str) -> Callable:
    """Mark a function as a mutation publisher of ``event``."""
    def deco(fn):
        evs = list(getattr(fn, "__publishes__", ()) or ())
        evs.append(event)
        fn.__publishes__ = tuple(evs)
        return fn
    return deco


def event_source(event: str) -> Callable:
    """Mark a function as the authoritative read of ``event``'s
    state (what pull-model validation hooks must consult)."""
    def deco(fn):
        evs = list(getattr(fn, "__event_source__", ()) or ())
        evs.append(event)
        fn.__event_source__ = tuple(evs)
        return fn
    return deco


def cache_inventory() -> Dict[str, Dict[str, object]]:
    """The runtime cache inventory (registered declarations seen by
    imported modules) — the README table's source of truth."""
    return dict(CACHES)
