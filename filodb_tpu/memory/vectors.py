"""Columnar chunk vectors: the immutable, compressed per-chunk column format.

TPU-native re-design of the reference's BinaryVector family
(memory/src/main/scala/filodb.memory/format/BinaryVector.scala:19,
vectors/DeltaDeltaVector.scala:28, vectors/DoubleVector.scala:14,
vectors/LongBinaryVector.scala:15).  Semantics preserved:

- Timestamps / longs: **delta-delta** — value modeled as ``init + slope*i``
  with NibblePacked residuals; perfectly regular series collapse to a
  16-byte const vector (DeltaDeltaVector.scala "const variant").
- Doubles: XOR-predictor NibblePack (Gorilla-style), or a delta-delta long
  vector when all values are integral.
- Counter doubles: same encoding, tagged so readers apply **counter
  correction** (reset detection) at decode — the reference does this row-wise
  in CorrectingDoubleVectorReader (DoubleVector.scala:301); here correction is
  computed vectorized over the whole decoded chunk (cumsum of drops), which is
  the TPU-friendly formulation.

Wire layout (little-endian), one vector = ``bytes``::

    u8  kind
    u32 num_rows
    kind-specific payload

This is this framework's interchange format; the inner bit codec (NibblePack)
is bit-compatible with the reference so chunk payloads can be transcoded
losslessly at the host boundary.
"""

from __future__ import annotations

import struct
from typing import Tuple

import numpy as np

from filodb_tpu.memory import nibblepack as nbp

# vector kinds
K_TS_CONST = 1       # init i64, slope i64 : value(i) = init + slope * i
K_TS_DELTA_DELTA = 2  # init i64, slope i64, min_resid i64, packed residuals
K_DOUBLE_XOR = 3      # pack_doubles payload
K_DOUBLE_COUNTER = 4  # pack_doubles payload, counter semantics (apply correction)
K_LONG_AS_DOUBLE = 5  # delta-delta longs holding integral doubles
K_DOUBLE_CONST = 6    # f64 value repeated num_rows times
K_STR_CONST = 7       # one UTF-8 value repeated num_rows times
K_STR_DICT = 8        # dict UTF-8 + multi-width (8/16-bit) index stream
K_STR_UTF8 = 9        # u32 offsets (n+1) + UTF-8 blob

_HDR = struct.Struct("<BI")


def _header(kind: int, n: int) -> bytes:
    return _HDR.pack(kind, n)


def parse_header(buf: bytes) -> Tuple[int, int]:
    """Returns (kind, num_rows)."""
    return _HDR.unpack_from(buf, 0)


# ---------------------------------------------------------------------------
# Long / timestamp vectors (delta-delta)
# ---------------------------------------------------------------------------

def encode_longs(values: np.ndarray) -> bytes:
    """Encode int64 values with delta-delta + NibblePack
    (DeltaDeltaVector.scala:28; appender :293)."""
    values = np.asarray(values, dtype=np.int64)
    n = values.size
    if n == 0:
        return _header(K_TS_CONST, 0) + struct.pack("<qq", 0, 0)
    init = int(values[0])
    slope = int((int(values[-1]) - init) // (n - 1)) if n > 1 else 0
    predicted = init + slope * np.arange(n, dtype=np.int64)
    resid = values - predicted
    if not resid.any():
        return _header(K_TS_CONST, n) + struct.pack("<qq", init, slope)
    min_resid = int(resid.min())
    out = bytearray(_header(K_TS_DELTA_DELTA, n))
    out.extend(struct.pack("<qqq", init, slope, min_resid))
    nbp.pack_non_increasing((resid - min_resid).astype(np.uint64), out)
    return bytes(out)


def decode_longs(buf: bytes) -> np.ndarray:
    kind, n = parse_header(buf)
    off = _HDR.size
    if kind == K_TS_CONST:
        init, slope = struct.unpack_from("<qq", buf, off)
        return init + slope * np.arange(n, dtype=np.int64)
    if kind == K_TS_DELTA_DELTA:
        init, slope, min_resid = struct.unpack_from("<qqq", buf, off)
        words, _ = nbp.unpack_to_words(buf, off + 24, n)
        resid = np.array(words, dtype=np.uint64).astype(np.int64) + min_resid
        return init + slope * np.arange(n, dtype=np.int64) + resid
    raise ValueError(f"not a long vector kind: {kind}")


# ---------------------------------------------------------------------------
# Double vectors
# ---------------------------------------------------------------------------

def encode_doubles(values: np.ndarray, counter: bool = False) -> bytes:
    """Encode float64 values (DoubleVector.scala:14).

    Picks the smallest of: const, integral-as-delta-delta-long, XOR-packed —
    mirroring the reference's ``optimize()`` choice
    (format/BinaryVector.scala:496 OptimizingPrimitiveAppender).
    """
    values = np.asarray(values, dtype=np.float64)
    n = values.size
    kind = K_DOUBLE_COUNTER if counter else K_DOUBLE_XOR
    if n == 0:
        return _header(K_DOUBLE_CONST, 0) + struct.pack("<d", 0.0)
    if not counter and n > 0 and np.all(values == values[0]):
        return _header(K_DOUBLE_CONST, n) + struct.pack("<d", float(values[0]))
    finite = np.isfinite(values)
    if finite.all() and np.all(values == np.floor(values)) \
            and np.all(np.abs(values) < 2**62):
        inner = encode_longs(values.astype(np.int64))
        out = _header(K_LONG_AS_DOUBLE, n) + bytes([1 if counter else 0]) + inner
    else:
        out = None
    xor = bytearray(_header(kind, n))
    nbp.pack_doubles(values, xor)
    xor = bytes(xor)
    if out is not None and len(out) < len(xor):
        return out
    return xor


def decode_doubles(buf: bytes) -> np.ndarray:
    """Decode to raw (uncorrected) float64 values."""
    kind, n = parse_header(buf)
    off = _HDR.size
    if kind == K_DOUBLE_CONST:
        (v,) = struct.unpack_from("<d", buf, off)
        return np.full(n, v, dtype=np.float64)
    if kind in (K_DOUBLE_XOR, K_DOUBLE_COUNTER):
        vals, _ = nbp.unpack_double_xor(buf, off, n)
        return vals
    if kind == K_LONG_AS_DOUBLE:
        return decode_longs(buf[off + 1 :]).astype(np.float64)
    raise ValueError(f"not a double vector kind: {kind}")


# ---------------------------------------------------------------------------
# String vectors (UTF8Vector.scala / DictUTF8Vector.scala /
# ConstVector.scala): const when every row repeats one value,
# dict-encoded with MULTI-WIDTH integer indices (IntBinaryVector.scala's
# 8/16-bit packing applied to the code stream) at low cardinality, raw
# offsets + blob otherwise.
# ---------------------------------------------------------------------------

def encode_strings(values) -> bytes:
    """Encode a string column chunk. None encodes as ""."""
    vals = ["" if v is None else str(v) for v in values]
    n = len(vals)
    if n and all(v == vals[0] for v in vals):
        b = vals[0].encode()
        if len(b) <= 0xFFFFFFFF:
            return (_header(K_STR_CONST, n)
                    + struct.pack("<I", len(b)) + b)
    uniq = list(dict.fromkeys(vals))
    # dict only pays when values repeat (DictUTF8Vector's shouldMakeDict
    # samples cardinality before committing to the dict form)
    if n and len(uniq) <= 0x10000 and 2 * len(uniq) <= n \
            and all(len(v.encode()) <= 0xFFFF for v in uniq):
        idx_of = {v: i for i, v in enumerate(uniq)}
        width = 1 if len(uniq) <= 0x100 else 2
        out = bytearray(_header(K_STR_DICT, n))
        out += struct.pack("<IB", len(uniq), width)
        for v in uniq:
            vb = v.encode()
            out += struct.pack("<H", len(vb))
            out += vb
        dt = np.uint8 if width == 1 else np.uint16
        out += np.asarray([idx_of[v] for v in vals], dtype=dt).tobytes()
        return bytes(out)
    blob = bytearray()
    offs = np.zeros(n + 1, dtype=np.uint32)
    for i, v in enumerate(vals):
        blob += v.encode()
        offs[i + 1] = len(blob)
    return (bytes(_header(K_STR_UTF8, n)) + offs.tobytes() + bytes(blob))


def decode_strings(buf: bytes) -> np.ndarray:
    """Decode to a numpy object array of str."""
    kind, n = parse_header(buf)
    off = _HDR.size
    if kind == K_STR_CONST:
        (blen,) = struct.unpack_from("<I", buf, off)
        v = buf[off + 4:off + 4 + blen].decode()
        out = np.empty(n, dtype=object)
        out[:] = v
        return out
    if kind == K_STR_DICT:
        nuniq, width = struct.unpack_from("<IB", buf, off)
        off += 5
        uniq = []
        for _ in range(nuniq):
            (vlen,) = struct.unpack_from("<H", buf, off)
            off += 2
            uniq.append(buf[off:off + vlen].decode())
            off += vlen
        dt = np.uint8 if width == 1 else np.uint16
        idx = np.frombuffer(buf, dtype=dt, count=n, offset=off)
        out = np.empty(n, dtype=object)
        for i, code in enumerate(idx):
            out[i] = uniq[code]
        return out
    if kind == K_STR_UTF8:
        offs = np.frombuffer(buf, dtype=np.uint32, count=n + 1,
                             offset=off)
        base = off + 4 * (n + 1)
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = buf[base + offs[i]:base + offs[i + 1]].decode()
        return out
    raise ValueError(f"not a string vector kind: {kind}")


def is_counter_vector(buf: bytes) -> bool:
    kind, _ = parse_header(buf)
    if kind == K_DOUBLE_COUNTER:
        return True
    if kind == K_LONG_AS_DOUBLE:
        return buf[_HDR.size] == 1
    return False


def counter_correction(values: np.ndarray) -> np.ndarray:
    """Per-row accumulated counter-reset correction for a decoded chunk.

    corrected = values + counter_correction(values).  Vectorized equivalent of
    the reference's row-at-a-time drop detection
    (DoubleVector.scala:301 CorrectingDoubleVectorReader).
    NaNs (stale markers) do not participate in drop detection.
    """
    v = np.asarray(values, dtype=np.float64)
    if v.size == 0:
        return np.zeros(0)
    filled = v.copy()
    mask = np.isnan(filled)
    if mask.any():
        # forward-fill NaNs so they don't create artificial drops
        idx = np.where(~mask, np.arange(v.size), 0)
        np.maximum.accumulate(idx, out=idx)
        filled = filled[idx]
        filled[np.isnan(filled)] = 0.0
    diffs = np.diff(filled)
    drops = np.where(diffs < 0, filled[:-1], 0.0)
    corr = np.zeros_like(v)
    corr[1:] = np.cumsum(drops)
    return corr


# ---------------------------------------------------------------------------
# Generic dispatch
# ---------------------------------------------------------------------------

def num_rows(buf: bytes) -> int:
    return parse_header(buf)[1]


def decode(buf: bytes) -> np.ndarray:
    """Decode any vector to a numpy array (longs -> int64, doubles -> f64)."""
    kind, _ = parse_header(buf)
    if kind in (K_TS_CONST, K_TS_DELTA_DELTA):
        return decode_longs(buf)
    return decode_doubles(buf)
