"""First-class histogram columns: bucket schemes, histogram values, and the
2D-delta compressed histogram vector.

Re-design of the reference's histogram support
(memory/format/vectors/Histogram.scala:17,456,488 and
HistogramVector.scala:34,378 "2D delta" — delta across time AND buckets; spec
in doc/compression.md).  Buckets are cumulative (Prometheus ``le`` semantics).

Vector wire layout (little-endian)::

    u8  kind (K_HIST_2D)
    u32 num_rows
    u8  counter (1 = increasing counter histogram)
    bucket scheme:
        u8 scheme (0 = geometric, 1 = custom)
        geometric: f64 firstBucket, f64 multiplier, u16 numBuckets
        custom:    u16 numBuckets, f64 * numBuckets (le values)
    row 0:   pack_delta over bucket values (increasing within a histogram)
    rows 1+: pack_non_increasing over two's-complement time-deltas per bucket
             (DeltaDiffPackSink semantics, NibblePack.scala:259)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.memory import nibblepack as nbp

K_HIST_2D = 16
# Sectioned 2D-delta: same payload, plus an explicit drop-section table
# (row indices where ANY bucket decreased) recorded at encode time — the
# reader applies counter correction without rescanning buckets
# (HistogramVector.scala:427 SectDelta / Section.scala drop sections).
K_HIST_SECT = 17

_U64 = (1 << 64) - 1


@dataclass(frozen=True)
class GeometricBuckets:
    """le_i = firstBucket * multiplier**i (Histogram.scala:456)."""
    first: float
    multiplier: float
    num: int

    def les(self) -> np.ndarray:
        return self.first * self.multiplier ** np.arange(self.num)


@dataclass(frozen=True)
class CustomBuckets:
    """Explicit le values (Histogram.scala:488)."""
    le_values: Tuple[float, ...]

    @property
    def num(self) -> int:
        return len(self.le_values)

    def les(self) -> np.ndarray:
        return np.asarray(self.le_values, dtype=np.float64)


def _encode_scheme(scheme) -> bytes:
    if isinstance(scheme, GeometricBuckets):
        return struct.pack("<BddH", 0, scheme.first, scheme.multiplier, scheme.num)
    return struct.pack("<BH", 1, scheme.num) + np.asarray(
        scheme.le_values, dtype="<f8").tobytes()


def _decode_scheme(buf: bytes, off: int):
    kind = buf[off]
    if kind == 0:
        first, mult, num = struct.unpack_from("<ddH", buf, off + 1)
        return GeometricBuckets(first, mult, num), off + 1 + 18
    (num,) = struct.unpack_from("<H", buf, off + 1)
    les = np.frombuffer(buf, dtype="<f8", count=num, offset=off + 3)
    return CustomBuckets(tuple(les.tolist())), off + 3 + 8 * num


def detect_drop_rows(rows: np.ndarray) -> np.ndarray:
    """Row indices i>0 where ANY bucket decreased vs row i-1 — a counter
    reset. Per-bucket detection catches partial drops the +Inf-only check
    misses (HistogramVector.scala:427 SectDelta drop sections)."""
    rows = np.asarray(rows)
    if rows.shape[0] < 2:
        return np.zeros(0, dtype=np.int64)
    dropped = (np.diff(rows, axis=0) < 0).any(axis=1)
    return np.nonzero(dropped)[0] + 1


def encode_histograms(scheme, rows: np.ndarray, counter: bool = True,
                      sectioned: bool = True) -> bytes:
    """Encode [num_rows, num_buckets] int64 bucket counts as a 2D-delta vector
    (HistogramVector.scala:378 appendHistogram / DeltaDiffPackSink).

    ``sectioned`` (the default, SectDelta equivalent) additionally records
    the drop-section table so readers get reset positions for free."""
    rows = np.asarray(rows, dtype=np.int64)
    n, nb = rows.shape if rows.size else (0, scheme.num)
    kind = K_HIST_SECT if sectioned else K_HIST_2D
    out = bytearray(struct.pack("<BIB", kind, n, 1 if counter else 0))
    out.extend(_encode_scheme(scheme))
    if sectioned:
        drops = detect_drop_rows(rows) if counter and n else \
            np.zeros(0, dtype=np.int64)
        out.extend(struct.pack("<H", drops.size))
        out.extend(drops.astype("<u4").tobytes())
    if n == 0:
        return bytes(out)
    nbp.pack_delta(rows[0].astype(np.int64), out)
    for t in range(1, n):
        diffs = (rows[t] - rows[t - 1]).astype(np.int64)
        nbp.pack_non_increasing(
            (diffs.astype(np.int64).view(np.uint64)), out)
    return bytes(out)


def decode_histograms_full(buf: bytes):
    """Decode to (scheme, counter_flag, [num_rows, num_buckets] float64,
    drop_rows). For sectioned vectors drop_rows comes from the encoded
    section table; for plain 2D vectors it is None (caller rescans)."""
    kind, n, counter = struct.unpack_from("<BIB", buf, 0)
    if kind not in (K_HIST_2D, K_HIST_SECT):
        raise ValueError(f"not a histogram vector: kind={kind}")
    scheme, off = _decode_scheme(buf, 6)
    drops = None
    if kind == K_HIST_SECT:
        (n_drops,) = struct.unpack_from("<H", buf, off)
        off += 2
        drops = np.frombuffer(buf, dtype="<u4", count=n_drops,
                              offset=off).astype(np.int64)
        off += 4 * n_drops
    nb = scheme.num
    rows = np.zeros((n, nb), dtype=np.int64)
    if n > 0:
        first, off = nbp.unpack_delta(buf, off, nb)
        rows[0] = first
        for t in range(1, n):
            words, off = nbp.unpack_to_words(buf, off, nb)
            diffs = np.array(words, dtype=np.uint64).view(np.int64)
            rows[t] = rows[t - 1] + diffs
    return scheme, bool(counter), rows.astype(np.float64), drops


def decode_histograms(buf: bytes):
    """Decode to (scheme, counter_flag, [num_rows, num_buckets] float64)."""
    scheme, counter, rows, _ = decode_histograms_full(buf)
    return scheme, counter, rows


def hist_scheme_of(buf: bytes):
    """Bucket scheme from a histogram vector's header alone (no payload
    decode) — used when paging persisted chunks back into a partition."""
    scheme, _ = _decode_scheme(buf, 6)
    return scheme


def hist_counter_correction(rows: np.ndarray,
                            drop_rows: Optional[np.ndarray] = None
                            ) -> np.ndarray:
    """Per-bucket reset correction, analogous to
    vectors.counter_correction but on [n, nb] matrices. A reset is any
    row where ANY bucket decreased (partial per-bucket drops count —
    HistogramVector.scala:427 sectioned drop detection); the correction
    adds back the full pre-reset histogram, Prometheus counter-reset
    semantics applied bucket-wise. ``drop_rows`` (from a sectioned
    vector's table) skips re-detection."""
    rows = np.asarray(rows, dtype=np.float64)
    if rows.shape[0] == 0:
        return np.zeros_like(rows)
    if drop_rows is None:
        drop_rows = detect_drop_rows(rows)
    dropped = np.zeros(rows.shape[0], dtype=bool)
    dropped[drop_rows] = True
    drops = np.where(dropped[1:, None], rows[:-1], 0.0)
    corr = np.zeros_like(rows)
    corr[1:] = np.cumsum(drops, axis=0)
    return corr


def quantile(q: float, les: np.ndarray, bucket_values: np.ndarray) -> float:
    """Prometheus histogram_quantile interpolation over one cumulative
    histogram (Histogram.scala:17 quantile; matches Prometheus' bucketQuantile).
    """
    if not 0 <= q <= 1:
        return float("inf") if q > 1 else float("-inf")
    if len(les) < 2 or not np.isposinf(les[-1]):
        if len(les) < 2:
            return float("nan")
    total = bucket_values[-1]
    if total == 0 or np.isnan(total):
        return float("nan")
    rank = q * total
    b = int(np.searchsorted(bucket_values, rank, side="left"))
    b = min(b, len(les) - 1)
    if b == len(les) - 1:
        return float(les[-2])
    if b == 0 and les[0] <= 0:
        return float(les[0])
    bucket_start = 0.0 if b == 0 else float(les[b - 1])
    bucket_end = float(les[b])
    count_start = 0.0 if b == 0 else float(bucket_values[b - 1])
    count_end = float(bucket_values[b])
    if count_end == count_start:
        return bucket_end
    return bucket_start + (bucket_end - bucket_start) * \
        (rank - count_start) / (count_end - count_start)
