"""NibblePack: nibble-granularity packing of groups of 8 u64 words.

Bit-compatible re-implementation of the reference algorithm
(memory/src/main/scala/filodb.memory/format/NibblePack.scala:12; spec in
doc/compression.md "Predictive NibblePacking").  The wire format:

For each group of 8 input u64 words::

    byte 0: bitmask — bit i set if word i is nonzero
    (if bitmask != 0)
    byte 1: nibble word — high 4 bits = (numNibbles - 1),
                          low 4 bits  = trailing zero nibbles
    then: the nonzero words, each stripped of trailing zero nibbles and
          truncated to numNibbles nibbles, bit-packed little-endian back to
          back; final partial u64 written with only ceil(bits/8) bytes.

Three predictors transform values before packing (NibblePack.scala:16,37,70):

- ``pack_non_increasing``: raw u64s (used for chunk-metadata style data).
- ``pack_delta``: positive increasing longs stored as deltas from previous
  (negative deltas clamped to 0).
- ``pack_doubles``: first double stored raw (8 bytes LE), successive values
  XORed against previous bit pattern.

This module is the *interchange* codec; the TPU query path does not run this
bit-twiddling per query — chunks are decoded once into dense device tiles at
flush/upload time (see filodb_tpu.query.tpu).
"""

from __future__ import annotations

import struct

import numpy as np

_U64_MASK = (1 << 64) - 1


class InputTooShort(Exception):
    """Compressed input ended before all values could be unpacked."""


def _nlz64(x: int) -> int:
    """Number of leading zeros of x as u64 (64 for x == 0)."""
    if x == 0:
        return 64
    return 64 - x.bit_length()


def _ntz64(x: int) -> int:
    """Number of trailing zeros of x as u64 (64 for x == 0)."""
    if x == 0:
        return 64
    return (x & -x).bit_length() - 1


def pack8(words, out: bytearray) -> None:
    """Pack 8 u64 words into ``out`` (NibblePack.scala:105 pack8)."""
    bitmask = 0
    for i in range(8):
        if words[i] != 0:
            bitmask |= 1 << i
    out.append(bitmask)
    if bitmask == 0:
        return

    min_lz = 64
    min_tz = 64
    for i in range(8):
        w = words[i]
        lz = _nlz64(w)
        tz = _ntz64(w)
        if lz < min_lz:
            min_lz = lz
        if tz < min_tz:
            min_tz = tz

    trailing_nibbles = min_tz // 4
    num_nibbles = 16 - (min_lz // 4) - trailing_nibbles
    out.append(((num_nibbles - 1) << 4) | trailing_nibbles)

    # Pack nonzero words back to back, numNibbles*4 bits each, little-endian
    # (NibblePack.scala:140 packUniversal).
    trailing_shift = trailing_nibbles * 4
    num_bits = num_nibbles * 4
    out_word = 0
    bit_cursor = 0
    for i in range(8):
        w = words[i]
        if w == 0:
            continue
        remaining = 64 - bit_cursor
        shifted = w >> trailing_shift
        out_word = (out_word | (shifted << bit_cursor)) & _U64_MASK
        if remaining <= num_bits:
            out.extend(out_word.to_bytes(8, "little"))
            out_word = (shifted >> remaining) if remaining < num_bits else 0
        bit_cursor = (bit_cursor + num_bits) % 64
    if bit_cursor > 0:
        out.extend(out_word.to_bytes(8, "little")[: (bit_cursor + 7) // 8])


def unpack8(buf, pos: int, out):
    """Unpack one 8-word group from ``buf`` at ``pos`` into list ``out`` (len 8).

    Returns the new position.  (NibblePack.scala:373 unpack8.)
    """
    n = len(buf)
    if pos >= n:
        raise InputTooShort()
    bitmask = buf[pos]
    if bitmask == 0:
        for i in range(8):
            out[i] = 0
        return pos + 1
    if pos + 1 >= n:
        raise InputTooShort()
    nib = buf[pos + 1]
    num_bits = ((nib >> 4) + 1) * 4
    trailing_zeroes = (nib & 0x0F) * 4
    total_bytes = 2 + (num_bits * bin(bitmask).count("1") + 7) // 8
    mask = _U64_MASK if num_bits >= 64 else (1 << num_bits) - 1
    buf_index = pos + 2
    bit_cursor = 0

    def read_word(idx: int) -> int:
        if idx + 8 <= n:
            return int.from_bytes(buf[idx : idx + 8], "little")
        return int.from_bytes(buf[idx:n], "little")

    in_word = read_word(buf_index)
    buf_index += 8
    for bit in range(8):
        if bitmask & (1 << bit):
            remaining = 64 - bit_cursor
            out_word = (in_word >> bit_cursor) & mask
            if remaining <= num_bits and (buf_index - pos) < total_bytes:
                if buf_index < n:
                    in_word = read_word(buf_index)
                    buf_index += 8
                    if remaining < num_bits:
                        out_word |= (in_word << remaining) & mask
                else:
                    raise InputTooShort()
            out[bit] = (out_word << trailing_zeroes) & _U64_MASK
            bit_cursor = (bit_cursor + num_bits) % 64
        else:
            out[bit] = 0
    return pos + total_bytes


# ---------------------------------------------------------------------------
# Predictor-level pack/unpack on whole arrays
# ---------------------------------------------------------------------------

def pack_non_increasing(values, out: bytearray) -> None:
    """Pack raw u64 values (NibblePack.scala:16 packNonIncreasing)."""
    group = [0] * 8
    i = 0
    for v in values:
        group[i % 8] = int(v) & _U64_MASK
        i += 1
        if i % 8 == 0:
            pack8(group, out)
    if i % 8 != 0:
        for j in range(i % 8, 8):
            group[j] = 0
        pack8(group, out)


def pack_delta(values, out: bytearray) -> None:
    """Pack positive increasing longs as deltas (NibblePack.scala:37 packDelta).

    A value lower than its predecessor is stored as delta 0.
    """
    group = [0] * 8
    last = 0
    i = 0
    for v in values:
        v = int(v)
        delta = v - last if v >= last else 0
        last = v
        group[i % 8] = delta
        i += 1
        if i % 8 == 0:
            pack8(group, out)
    if i % 8 != 0:
        for j in range(i % 8, 8):
            group[j] = 0
        pack8(group, out)


def pack_doubles(values, out: bytearray) -> None:
    """XOR-pack doubles; first value raw LE (NibblePack.scala:70 packDoubles)."""
    values = np.asarray(values, dtype=np.float64)
    if values.size == 0:
        raise ValueError("pack_doubles requires at least one value")
    out.extend(struct.pack("<d", values[0]))
    bits = values.view(np.uint64)
    group = [0] * 8
    last = int(bits[0])
    i = 0
    for k in range(1, values.size):
        b = int(bits[k])
        group[i % 8] = b ^ last
        last = b
        i += 1
        if i % 8 == 0:
            pack8(group, out)
    if i % 8 != 0:
        for j in range(i % 8, 8):
            group[j] = 0
        pack8(group, out)


def unpack_to_words(buf, pos: int, num_values: int):
    """Unpack ``num_values`` raw u64 words; returns (u64 ndarray, new_pos)."""
    out = []
    group = [0] * 8
    left = num_values
    while left > 0:
        pos = unpack8(buf, pos, group)
        take = min(left, 8)
        out.extend(group[:take])
        left -= take
    return np.array(out, dtype=np.uint64), pos


def unpack_delta(buf, pos: int, num_values: int):
    """Unpack delta-packed values back to absolute longs (DeltaSink semantics,
    NibblePack.scala:205).  Returns (np.ndarray[int64], new_pos)."""
    words, pos = unpack_to_words(buf, pos, num_values)
    arr = np.array(words, dtype=np.uint64)
    return np.cumsum(arr.astype(np.int64)), pos


def unpack_double_xor(buf, pos: int, num_values: int):
    """Unpack XOR-packed doubles (DoubleXORSink, NibblePack.scala:225/:352).

    Returns (np.ndarray[float64], new_pos).
    """
    if len(buf) - pos < 8:
        raise InputTooShort()
    first_bits = int.from_bytes(buf[pos : pos + 8], "little")
    pos += 8
    if num_values == 1:
        words = []
    else:
        words, pos = unpack_to_words(buf, pos, num_values - 1)
    bits = np.empty(num_values, dtype=np.uint64)
    bits[0] = first_bits
    if num_values > 1:
        # running XOR: bits[i] = bits[i-1] ^ words[i-1]; XOR-scan via ufunc
        xors = np.array(words, dtype=np.uint64)
        bits[1:] = np.bitwise_xor.accumulate(xors)
        bits[1:] ^= np.uint64(first_bits)
    return bits.view(np.float64).copy(), pos


# ---------------------------------------------------------------------------
# Native (C++) fast path — same wire format, same signatures
# ---------------------------------------------------------------------------
# The Python functions above are the behavioral oracle (and the fallback
# when no compiler exists); when the native codec builds, the public names
# below are rebound to ctypes wrappers. Parity is pinned by
# tests/test_nibblepack.py, which compares both implementations.

pack_non_increasing_py = pack_non_increasing
pack_delta_py = pack_delta
pack_doubles_py = pack_doubles
unpack_to_words_py = unpack_to_words
unpack_delta_py = unpack_delta
unpack_double_xor_py = unpack_double_xor

try:
    from filodb_tpu.native import load_nibblepack as _load_native
    _native = _load_native()
except Exception:       # pragma: no cover — build env without g++
    _native = None

if _native is not None:
    import ctypes as _ct

    _U8P = _ct.POINTER(_ct.c_uint8)
    _U64P = _ct.POINTER(_ct.c_uint64)
    _I64P = _ct.POINTER(_ct.c_int64)
    _F64P = _ct.POINTER(_ct.c_double)

    def _cap(n: int) -> int:
        # worst case per 8-word group: 2 header + 64 payload bytes
        return 8 + ((n + 7) // 8) * 66

    def pack_non_increasing(values, out: bytearray) -> None:
        arr = np.ascontiguousarray(np.asarray(values, dtype=np.uint64))
        buf = np.empty(_cap(arr.size), dtype=np.uint8)
        n = _native.np_pack_non_increasing(
            arr.ctypes.data_as(_U64P), arr.size,
            buf.ctypes.data_as(_U8P))
        out.extend(buf[:n].tobytes())

    def pack_delta(values, out: bytearray) -> None:
        arr = np.ascontiguousarray(np.asarray(values, dtype=np.int64))
        buf = np.empty(_cap(arr.size), dtype=np.uint8)
        n = _native.np_pack_delta(
            arr.ctypes.data_as(_I64P), arr.size,
            buf.ctypes.data_as(_U8P))
        out.extend(buf[:n].tobytes())

    def pack_doubles(values, out: bytearray) -> None:
        arr = np.ascontiguousarray(np.asarray(values, dtype=np.float64))
        if arr.size == 0:
            raise ValueError("pack_doubles requires at least one value")
        buf = np.empty(_cap(arr.size), dtype=np.uint8)
        n = _native.np_pack_doubles(
            arr.ctypes.data_as(_F64P), arr.size,
            buf.ctypes.data_as(_U8P))
        out.extend(buf[:n].tobytes())

    def _in_buf(buf) -> np.ndarray:
        return np.frombuffer(buf, dtype=np.uint8) \
            if not isinstance(buf, np.ndarray) else buf

    def unpack_to_words(buf, pos: int, num_values: int):
        b = _in_buf(buf)
        out = np.empty(num_values, dtype=np.uint64)
        new_pos = _native.np_unpack_words(
            b.ctypes.data_as(_U8P), b.size, pos, num_values,
            out.ctypes.data_as(_U64P))
        if new_pos < 0:
            raise InputTooShort()
        return out, new_pos

    def unpack_delta(buf, pos: int, num_values: int):
        b = _in_buf(buf)
        out = np.empty(num_values, dtype=np.int64)
        new_pos = _native.np_unpack_delta(
            b.ctypes.data_as(_U8P), b.size, pos, num_values,
            out.ctypes.data_as(_I64P))
        if new_pos < 0:
            raise InputTooShort()
        return out, new_pos

    def unpack_double_xor(buf, pos: int, num_values: int):
        b = _in_buf(buf)
        out = np.empty(num_values, dtype=np.float64)
        new_pos = _native.np_unpack_double_xor(
            b.ctypes.data_as(_U8P), b.size, pos, num_values,
            out.ctypes.data_as(_F64P))
        if new_pos < 0:
            raise InputTooShort()
        return out, new_pos
