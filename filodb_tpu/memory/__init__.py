"""Columnar chunk codecs and memory formats (TPU-native analogue of FiloDB's
``memory/`` module — reference: memory/src/main/scala/filodb.memory/format/*).

The reference implements these as off-heap byte manipulation via
``sun.misc.Unsafe``; here the interchange bit formats are implemented with
numpy/Python (bulk paths vectorized), with a C++ fast path for the ingest-side
encoders, and decode lowering to dense device tiles for the TPU query path.
"""

from filodb_tpu.memory import nibblepack  # noqa: F401
