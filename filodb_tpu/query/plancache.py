"""Plan cache: skip PromQL parse + logical-plan construction on repeat
queries.

Dashboards re-issue the SAME query text every refresh with a sliding
(start, end); today each hit replans from scratch. The cache keys on
(dataset, query text, step) with the evaluation range abstracted out of
the key: a hit stores the plan parsed at some canonical range and
REBASES it onto the request's range via
:func:`filodb_tpu.query.engine.lp_replace_range` — the same rewrite the
raw/downsample tier split and subquery evaluation already rely on, so a
rebased plan is exactly what a fresh parse would have produced (the
plan-cache correctness tests pin this as a golden comparison).

Only rebasable shapes are cached: ``_splittable`` plans (the
lp_replace_range-rewritable closure — no @-pinned selectors, no
subqueries) that carry an evaluation grid (``plan_range`` is not None —
this excludes top-level raw exports, whose fetch bounds
lp_replace_range does not rewrite). Everything else parses fresh on
every request; ``uncacheable`` counts those.

Invalidation: parsing itself is topology- and schema-independent, but
cached plans must never outlive a world they were built against —
``invalidate()`` is the explicit hook. The HTTP server wires it to
shard-topology changes (ShardMapper events) and exposes it for schema
changes; both clear the cache and bump ``invalidations``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from filodb_tpu.lint.caches import cache_registry
from filodb_tpu.lint.locks import guarded_by


def _cacheable(plan) -> bool:
    from filodb_tpu.query.planner import _splittable, plan_range
    return _splittable(plan) and plan_range(plan) is not None


def range_abstracted_key(dataset: str, query: str, step_ms: int) -> Tuple:
    """The shared range-abstracted cache key: (dataset, normalized query
    text, step). Both the plan cache and the results cache key on it —
    dashboards re-issue the SAME text with a sliding (start, end), so
    the range must stay out of the key (the results cache additionally
    sub-keys on step alignment, ``start % step``)."""
    return (dataset, query, int(step_ms))


# inventory declaration (graftlint cache-invalidation-completeness):
# parsed plans are topology- and schema-dependent ONLY — the evaluation
# range is abstracted out of the key, so watermark/backfill events
# cannot affect an entry. Every @publishes of these events must reach
# `invalidate` through the call graph (the ShardMapper subscription and
# the explicit schema hook), or the lint gate fails.
@cache_registry("plan",
                invalidated_by={"topology-epoch": "invalidate",
                                "schema": "invalidate"},
                keyed=("dataset", "query-text", "step"))
@guarded_by("_lock", "_entries", "hits", "misses", "uncacheable",
            "invalidations", "rebases", "invalidations_by_reason")
class PlanCache:
    """LRU of parsed logical plans, keyed (dataset, query, step_ms)."""

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        # (dataset, query, step_ms) -> (plan, start_ms, end_ms)
        self._entries: "OrderedDict[Tuple, Tuple]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.uncacheable = 0
        self.invalidations = 0
        # observability: WHY the cache was cleared (topology vs schema
        # vs explicit) — a flapping mapper shows as topology churn here
        self.invalidations_by_reason: Dict[str, int] = {}
        self.rebases = 0
        # downstream caches keyed on the same world (the results cache)
        # ride this cache's invalidation events: any reason that clears
        # cached plans also clears cached results. Listeners are called
        # OUTSIDE the lock (they take their own).
        self._listeners: list = []

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def lookup(self, dataset: str, query: str, start_ms: int,
               step_ms: int, end_ms: int):
        """Cached plan rebased onto [start, end], or None (parse fresh +
        ``store``). The cached canonical plan is never mutated —
        lp_replace_range builds a fresh dataclass tree."""
        if not self.enabled:
            return None
        key = (dataset, query, int(step_ms))
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            plan, c_start, c_end = entry
        if c_start == start_ms and c_end == end_ms:
            return plan
        from filodb_tpu.query.engine import lp_replace_range
        with self._lock:
            self.rebases += 1
        return lp_replace_range(plan, int(start_ms), int(step_ms),
                                int(end_ms))

    def store(self, dataset: str, query: str, start_ms: int,
              step_ms: int, end_ms: int, plan) -> None:
        if not self.enabled:
            return
        if not _cacheable(plan):
            with self._lock:
                self.uncacheable += 1
            return
        key = (dataset, query, int(step_ms))
        with self._lock:
            self._entries[key] = (plan, int(start_ms), int(end_ms))
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def add_invalidation_listener(self, fn) -> None:
        """Register ``fn(reason)`` to run after every invalidation —
        the hook the results cache uses to share this cache's topology/
        schema invalidation events."""
        self._listeners.append(fn)

    def invalidate(self, reason: str = "") -> None:
        """Explicit invalidation hook: shard-topology or schema change.
        Clears every cached plan and notifies listeners (result cache)."""
        with self._lock:
            self._entries.clear()
            self.invalidations += 1
            key = reason or "unspecified"
            self.invalidations_by_reason[key] = \
                self.invalidations_by_reason.get(key, 0) + 1
        for fn in list(self._listeners):
            fn(reason)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._entries), "hits": self.hits,
                    "misses": self.misses, "rebases": self.rebases,
                    "uncacheable": self.uncacheable,
                    "invalidations": self.invalidations,
                    "invalidations_by_reason":
                        dict(self.invalidations_by_reason)}
