"""Query result model: range vectors as dense grid batches.

Replaces the reference's RangeVector / SerializedRangeVector
(core/src/main/scala/filodb.core/query/RangeVector.scala:124,452) with a
columnar, device-friendly representation: after windowing, every series in a
result shares one step grid, so a whole result is ``[num_series, num_steps]``
matrices + per-series label keys.  No per-row serialization is ever needed
intra-process (the reference's Kryo path exists only because of the JVM actor
boundary)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np


@dataclass(frozen=True)
class RangeParams:
    """start/step/end in **milliseconds** (query/TimeStepParams at the edge is
    seconds; converted at the HTTP layer)."""
    start_ms: int
    step_ms: int
    end_ms: int

    @property
    def steps(self) -> np.ndarray:
        if self.step_ms <= 0:
            return np.array([self.start_ms], dtype=np.int64)
        return np.arange(self.start_ms, self.end_ms + 1, self.step_ms,
                         dtype=np.int64)

    @property
    def num_steps(self) -> int:
        if self.step_ms <= 0:
            return 1
        return (self.end_ms - self.start_ms) // self.step_ms + 1


@dataclass
class RawSeries:
    """One series' raw samples (RawDataRangeVector equivalent).

    ``snapshot_key`` identifies the immutable chunk-backed prefix of this
    series in its store — (dataset, shard, part_id, num_chunks). Device tile
    caches key on it: the prefix content is pinned by num_chunks (chunks are
    append-only and immutable), so repeated queries over an unchanged store
    snapshot reuse device tiles with zero rebuilds. ``chunk_len`` is the
    length of that prefix; samples beyond it are the mutable write-buffer
    tail (merged host-side / via the general path at query time)."""
    labels: Mapping[str, str]
    ts: np.ndarray          # int64 ms, sorted
    values: np.ndarray      # f64 [n] or f64 [n, num_buckets] for histograms
    is_counter: bool = False
    bucket_les: Optional[np.ndarray] = None  # for histogram series
    snapshot_key: Optional[Tuple] = None
    chunk_len: int = -1     # -1: everything is immutable (no tail)
    # histogram reset rows from the sectioned drop tables (row i = reset
    # between rows i-1 and i); None = caller rescans buckets
    hist_drop_rows: Optional[np.ndarray] = None


@dataclass
class GridResult:
    """A periodic (windowed) result: shared step grid + per-series rows.

    ``values`` is [num_series, num_steps] float64 (NaN = no sample — carries
    the reference's NaN/staleness semantics through the pipeline).
    For histogram results, ``hist_values`` is [num_series, num_steps, nb].

    ``partial``/``warnings`` carry degraded-mode provenance (the
    Thanos/M3 partial-response analogue): a result assembled while some
    shard group was unreachable is flagged, and every aggregation /
    concatenation / stitch step propagates the flag upward so the Prom
    JSON edge can surface ``"partial": true`` + per-shard warnings."""
    steps: np.ndarray                       # int64 [num_steps] ms
    keys: List[Dict[str, str]]              # per-series labels
    values: np.ndarray                      # f64 [S, T]
    hist_values: Optional[np.ndarray] = None  # f64 [S, T, NB]
    bucket_les: Optional[np.ndarray] = None
    partial: bool = False                   # some shard group missing
    warnings: List[str] = field(default_factory=list)

    @property
    def num_series(self) -> int:
        return len(self.keys)

    def is_hist(self) -> bool:
        return self.hist_values is not None

    def absorb_degraded(self, *parts: "GridResult") -> "GridResult":
        """Fold children's partial flags/warnings into this result
        (returns self for chaining)."""
        for p in parts:
            if isinstance(p, GridResult):
                self.partial = self.partial or p.partial
                self.warnings.extend(w for w in p.warnings
                                     if w not in self.warnings)
        return self

    @staticmethod
    def empty(steps: np.ndarray) -> "GridResult":
        return GridResult(steps, [], np.zeros((0, steps.size)))


@dataclass
class ScalarResult:
    """scalar(...) / literal results: one value per step."""
    steps: np.ndarray
    values: np.ndarray  # f64 [T]


@dataclass
class QueryStats:
    """(core/query/QueryStats equivalent) threaded through execution."""
    series_scanned: int = 0
    samples_scanned: int = 0
    result_bytes: int = 0
    # partial-result notes surfaced in the Prometheus response's
    # `warnings` array (e.g. a shard still bootstrapping on its adopter)
    warnings: list = field(default_factory=list)
    # True when a shard group was dropped from this result (breaker
    # open / peer exhausted under allow_partial) — drives the response's
    # top-level "partial": true
    partial: bool = False

    def add(self, other: "QueryStats") -> None:
        self.series_scanned += other.series_scanned
        self.samples_scanned += other.samples_scanned
        self.result_bytes += other.result_bytes
        self.warnings.extend(other.warnings)
        self.partial = self.partial or other.partial


class QueryError(Exception):
    pass


class StaleRoutingError(QueryError):
    """A peer was asked for shards it no longer serves: the caller's
    routing table lags a planned shard handoff (topology epoch moved).

    Raised server-side by ``leaf_select``/the pushdown expect-shards
    check; the entry node catches it, applies the responder's ``owners``
    hint to its ShardMapper, invalidates plan/results caches, and
    re-materializes against fresh routing instead of returning the
    stale (silently incomplete) response to the client.

    ``__str__`` renders a machine-parseable sentinel so the error
    round-trips losslessly through BOTH peer planes (the JSON control
    plane's ``error`` string and the gRPC response's error field);
    :meth:`parse` recovers it on the caller."""

    PREFIX = "stale_routing:"

    def __init__(self, owners=None, epoch: int = 0, node: str = "",
                 detail: str = ""):
        # shard -> owning node, per the RESPONDER's mapper (it is the
        # former owner and witnessed the handoff)
        self.owners = {int(k): v for k, v in (owners or {}).items()}
        self.epoch = int(epoch)
        self.node = node
        self.detail = detail
        super().__init__(self._render())

    def _render(self) -> str:
        import json as _json
        return self.PREFIX + _json.dumps(
            {"owners": {str(k): v for k, v in self.owners.items()},
             "epoch": self.epoch, "node": self.node,
             "detail": self.detail}, sort_keys=True)

    def __str__(self) -> str:
        return self._render()

    @classmethod
    def parse(cls, s) -> "Optional[StaleRoutingError]":
        """Recover a StaleRoutingError from an error string carrying
        the sentinel (possibly wrapped, e.g. ``remote node n: ...``);
        None when the string is not one."""
        import json as _json
        if not isinstance(s, str):
            return None
        i = s.find(cls.PREFIX)
        if i < 0:
            return None
        try:
            d = _json.loads(s[i + len(cls.PREFIX):])
        except ValueError:
            return None
        return cls(owners=d.get("owners"), epoch=d.get("epoch", 0),
                   node=d.get("node", ""), detail=d.get("detail", ""))


class QueryLimitError(QueryError):
    """A per-query guardrail tripped (ExecPlan.scala:46 enforceLimits —
    the reference aborts plans exceeding sample/series budgets)."""


@dataclass(frozen=True)
class QueryLimits:
    """Per-query guardrails, enforced at series-selection time
    (core/query/QueryContext PlannerParams enforcedLimits). 0 = off."""
    series_limit: int = 0
    sample_limit: int = 0

    def check(self, stats: "QueryStats") -> None:
        if self.series_limit and stats.series_scanned > self.series_limit:
            raise QueryLimitError(
                f"query matched {stats.series_scanned} series, exceeding "
                f"the limit of {self.series_limit}")
        if self.sample_limit and stats.samples_scanned > self.sample_limit:
            raise QueryLimitError(
                f"query would scan more than {self.sample_limit} samples "
                f"(scanned {stats.samples_scanned} so far)")


@dataclass
class QueryWarnings:
    messages: List[str] = field(default_factory=list)
