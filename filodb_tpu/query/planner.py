"""Query planner: materializes LogicalPlans into executable plans with
shard pruning and distributed (mesh) lowering.

TPU-native counterpart of the reference planner stack
(coordinator/queryplanner/SingleClusterPlanner.scala:253 materialize,
:430 walkLogicalPlanTree, :872 shardsFromFilters + dispatcherForShard :138;
DefaultPlanner's aggregate lowering). Differences by design:

- Shard pruning is identical in spirit: equality filters on the shard-key
  columns (_ws_, _ns_, metric) hash to a shard subset via the bit-compatible
  `query_shards` (RecordBuilder.scala:667 shardKeyHash + spread bit split);
  anything else fans out to all queryable shards.

- Instead of serializing an ExecPlan tree to per-shard actors
  (ActorPlanDispatcher + Kryo), the scatter-gather IS a device-mesh program:
  the `agg(rangefunc(selector[w])) by (...)` shape lowers onto
  `MeshExecutor.window_aggregate` — per-shard leaf evaluation rides the mesh
  'shard' axis, the reduce is a psum-tree collective over ICI
  (ReduceAggregateExec ≡ the collective), and only the tiny [groups, steps]
  grid returns to the host.

- Every other plan shape falls back to `LocalEngineExec`: the single-process
  engine over the pruned shard subset (InProcessPlanDispatcher equivalent).
"""

from __future__ import annotations

import itertools
import math
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.core.index import ColumnFilter
from filodb_tpu.core.record import shard_key_hash
from filodb_tpu.lint.caches import publishes
from filodb_tpu.query import logical as lp
from filodb_tpu.query.engine import (METRIC_LABELS, QueryEngine,
                                     select_raw_series)
from filodb_tpu.query.model import (GridResult, QueryError, QueryLimits,
                                    QueryStats, RangeParams,
                                    StaleRoutingError)

# aggregations executable as mesh collectives (parallel/mesh.py MESH_AGGS)
_MESH_AGGS = frozenset({"sum", "count", "avg", "min", "max", "group"})

# a regex that is just literal alternations (no metacharacters beyond |)
_LITERAL_ALT = re.compile(r"[A-Za-z0-9_\-:, ]+$")


def _shard_key_candidates(f: ColumnFilter) -> Optional[List[str]]:
    """Concrete candidate values a filter pins its label to, or None."""
    if f.op == "eq":
        return [f.value]
    if f.op == "in":
        vals = f.value if isinstance(f.value, (list, tuple)) \
            else str(f.value).split(",")
        return [str(v) for v in vals]
    if f.op == "re" and "|" in f.value:
        parts = f.value.split("|")
        if all(p and _LITERAL_ALT.match(p) for p in parts):
            return parts
    return None


def walk_plan_tree(plan, visit) -> None:
    """Depth-first walk over a LogicalPlan's dataclass tree (the shared
    recursion of walkLogicalPlanTree). ``visit(node) -> bool``: return
    True to stop descending into that node's children."""
    if plan is None or not hasattr(plan, "__dataclass_fields__"):
        return
    if visit(plan):
        return
    for f in plan.__dataclass_fields__:
        v = getattr(plan, f)
        if isinstance(v, tuple):
            for item in v:
                walk_plan_tree(item, visit)
        else:
            walk_plan_tree(v, visit)


def walk_leaf_filters(plan) -> List[Tuple[ColumnFilter, ...]]:
    """Collect the filter sets of every RawSeries leaf under a plan
    (walkLogicalPlanTree's shard resolution inputs)."""
    out: List[Tuple[ColumnFilter, ...]] = []

    def visit(p):
        if isinstance(p, lp.RawSeriesPlan):
            out.append(tuple(p.filters))
            return True
        return False

    walk_plan_tree(plan, visit)
    return out


@dataclass
class PlannerParams:
    """(core/query/QueryContext PlannerParams equivalent)."""
    spread: int = 0
    sample_limit: int = 0       # 0 = unlimited (guardrails layer)
    series_limit: int = 0


def plan_range(plan) -> Optional[Tuple[int, int, int, int, int]]:
    """(start_ms, step_ms, end_ms, min_window_ms, max_lookback_ms) of the
    evaluation grid shared by all periodic nodes, or None when the plan has
    no periodic node or the nodes disagree (e.g. nested subquery grids).
    min_window governs downsample resolution choice (every selector must
    tolerate the chosen period); max_lookback additionally includes
    offsets — the earliest data instant any step can touch is
    ``start - max_lookback``."""
    grids: List[Tuple[int, int, int]] = []
    window = [1 << 62]
    lookback = [0]

    def visit(p):
        if isinstance(p, (lp.PeriodicSeries, lp.PeriodicSeriesWithWindowing)):
            grids.append((p.start_ms, p.step_ms, p.end_ms))
            w = p.lookback_ms if isinstance(p, lp.PeriodicSeries) \
                else p.window_ms
            window[0] = min(window[0], w)
            lookback[0] = max(lookback[0], w + p.offset_ms)
            return True
        return False

    walk_plan_tree(plan, visit)
    if not grids or any(g != grids[0] for g in grids[1:]):
        return None
    s, st, e = grids[0]
    return s, st, e, window[0], lookback[0]


def _collect_at(plan) -> Tuple[List[int], int]:
    """(@-pinned instants, total periodic-node count) under a plan."""
    ats: List[int] = []
    count = [0]

    def visit(p):
        if isinstance(p, (lp.PeriodicSeries,
                          lp.PeriodicSeriesWithWindowing)):
            count[0] += 1
            if p.at_ms is not None:
                ats.append(p.at_ms)
            return True
        return False

    walk_plan_tree(plan, visit)
    return ats, count[0]


# plan node types whose evaluation range lp_replace_range can rewrite —
# only these shapes may be split across the raw/downsample boundary
_SPLITTABLE = (
    lp.PeriodicSeries, lp.PeriodicSeriesWithWindowing, lp.Aggregate,
    lp.BinaryJoin, lp.ScalarVectorBinaryOperation, lp.ApplyInstantFunction,
    lp.ApplyMiscellaneousFunction, lp.ApplySortFunction,
    lp.ApplyLimitFunction, lp.ApplyAbsentFunction, lp.ScalarTimeBasedPlan,
    lp.ScalarFixedDoublePlan, lp.ScalarVaryingDoublePlan,
    lp.ScalarBinaryOperation, lp.VectorPlan, lp.RawSeriesPlan,
)


def _splittable(plan) -> bool:
    if not hasattr(plan, "__dataclass_fields__") \
            or isinstance(plan, ColumnFilter):
        return True     # literals / filters
    if not isinstance(plan, _SPLITTABLE):
        return False
    if getattr(plan, "at_ms", None) is not None:
        return False    # @-pinned evaluation doesn't split on the grid
    for f in plan.__dataclass_fields__:
        v = getattr(plan, f)
        if isinstance(v, tuple):
            if not all(_splittable(x) for x in v):
                return False
        elif hasattr(v, "__dataclass_fields__"):
            if not _splittable(v):
                return False
    return True


def stitch_grids(first: GridResult, second: GridResult) -> GridResult:
    """Merge two grid results onto the union step grid, matching series by
    label key; on a shared step the first's non-NaN sample wins
    (StitchRvsExec.scala:116 / :105 merge semantics)."""
    if first.num_series == 0 and first.steps.size == 0:
        return second
    if second.num_series == 0 and second.steps.size == 0:
        return first
    steps = np.union1d(first.steps, second.steps)
    hist = first.is_hist() or second.is_hist()
    if hist:
        les = first.bucket_les if first.is_hist() else second.bucket_les
        if (first.is_hist() and second.is_hist()
                and not np.array_equal(first.bucket_les,
                                       second.bucket_les)):
            raise QueryError("cannot stitch histogram results with "
                             "different bucket schemes")
        nb = les.size
    key_ix: Dict[Tuple, int] = {}
    keys: List[Dict[str, str]] = []
    rows: List[np.ndarray] = []
    hrows: List[np.ndarray] = []
    for side in (first, second):
        if side.num_series == 0:
            continue
        pos = np.searchsorted(steps, side.steps)
        for i, k in enumerate(side.keys):
            fk = tuple(sorted(k.items()))
            j = key_ix.get(fk)
            if j is None:
                j = len(keys)
                key_ix[fk] = j
                keys.append(dict(k))
                rows.append(np.full(steps.size, np.nan))
                if hist:
                    hrows.append(np.full((steps.size, nb), np.nan))
            cur = rows[j][pos]
            rows[j][pos] = np.where(np.isnan(cur), side.values[i], cur)
            if hist and side.is_hist():
                curh = hrows[j][pos]
                hrows[j][pos] = np.where(np.isnan(curh),
                                         side.hist_values[i], curh)
    values = np.vstack([r[None] for r in rows]) if rows else \
        np.zeros((0, steps.size))
    hv = np.stack(hrows) if hist and hrows else None
    return GridResult(steps, keys, values, hist_values=hv,
                      bucket_les=les if hist else None)


class ExecPlan:
    """Materialized plan node (query/exec/ExecPlan.scala:46)."""

    def execute(self):
        raise NotImplementedError

    def plan_tree(self, indent: int = 0) -> str:
        return " " * indent + type(self).__name__


@dataclass
class ConcatExec(ExecPlan):
    """Concatenate children's series onto one grid (the reference's
    LocalPartitionDistConcatExec over pushed-down per-shard plans,
    exec/DistConcatExec.scala). Children evaluate disjoint series sets
    (each series lives on exactly one shard), so plain concatenation is
    the correct union.

    Degraded mode: with ``allow_partial`` a child that fails with a
    QueryError (peer exhausted, breaker open) is dropped and the result
    is flagged partial with a warning naming the lost child; default
    remains fail-fast. ``deadline`` is checked between children so an
    exhausted budget stops the fan-out cleanly."""
    children: Sequence[ExecPlan]
    stats: QueryStats
    allow_partial: bool = False
    deadline: Optional[object] = None

    def execute(self):
        import numpy as np
        outs = []
        dropped: List[str] = []
        for c in self.children:
            if self.deadline is not None:
                self.deadline.check("ConcatExec fan-out")
            try:
                outs.append(c.execute())
            except StaleRoutingError:
                # never absorbed into a partial result: the entry node
                # re-resolves routing and retries the whole query
                raise
            except QueryError as e:
                if not self.allow_partial:
                    raise
                who = c.plan_tree().strip()
                dropped.append(f"partial result: {who} failed ({e})")
        if not outs:
            if dropped:
                raise QueryError(
                    "all shard groups failed: " + "; ".join(dropped))
            raise QueryError("ConcatExec has no children")
        grids = [o for o in outs if isinstance(o, GridResult)]
        if not grids:
            return outs[0]
        steps = grids[0].steps
        keys = [k for g in grids for k in g.keys]
        vals = (np.concatenate([g.values for g in grids], axis=0)
                if grids else np.zeros((0, steps.size)))
        hv = None
        les = None
        if any(g.hist_values is not None for g in grids):
            hvs = [g.hist_values for g in grids
                   if g.hist_values is not None]
            nb = max(h.shape[2] for h in hvs)
            # children must agree on the bucket scheme: the les of every
            # child must be a prefix of the max-width child's, or the
            # padded concat would silently mix incompatible buckets
            les = max((g.bucket_les for g in grids
                       if g.bucket_les is not None), key=len)
            for g in grids:
                gl = g.bucket_les
                if gl is not None and not np.array_equal(
                        np.asarray(gl), np.asarray(les)[:len(gl)]):
                    raise QueryError(
                        "cannot concatenate histogram results with "
                        f"mismatched bucket schemes ({list(gl)} vs "
                        f"{list(les)})")
            hv = np.concatenate(
                [np.pad(h, ((0, 0), (0, 0), (0, nb - h.shape[2])),
                        constant_values=np.nan) for h in hvs], axis=0)
        out = GridResult(steps, keys, vals, hist_values=hv,
                         bucket_les=les).absorb_degraded(*grids)
        if dropped:
            out.partial = True
            out.warnings.extend(dropped)
            self.stats.partial = True
            self.stats.warnings.extend(dropped)
        return out

    def plan_tree(self, indent: int = 0) -> str:
        pads = " " * indent
        kids = "\n".join(c.plan_tree(indent + 2) for c in self.children)
        return f"{pads}ConcatExec\n{kids}"


@dataclass
class LocalEngineExec(ExecPlan):
    """Evaluate a LogicalPlan on the single-process engine over a pruned
    shard subset (InProcessPlanDispatcher.scala:25 semantics)."""
    plan: object
    shards: Sequence[object]
    backend: Optional[object]
    stats: QueryStats
    limits: Optional[QueryLimits] = None

    def execute(self):
        eng = QueryEngine(self.shards, backend=self.backend,
                          limits=self.limits)
        out = eng.execute(self.plan)
        self.stats.add(eng.stats)
        if isinstance(out, GridResult) and eng.stats.partial:
            # degraded leaf dispatch inside the engine (a shard group
            # dropped under allow_partial): stamp the grid so every
            # aggregation above carries the flag
            out.partial = True
            out.warnings.extend(w for w in eng.stats.warnings
                                if w not in out.warnings)
        return out

    def plan_tree(self, indent: int = 0) -> str:
        pads = " " * indent
        shard_nums = [getattr(s, "shard_num", "?") for s in self.shards]
        return (f"{pads}LocalEngineExec(shards={shard_nums}, "
                f"plan={type(self.plan).__name__})")


@dataclass
class MeshTileExec(ExecPlan):
    """A tilestore-servable shape lowered onto the device-RESIDENT
    sharded tile path: the bare windowed counter/aligned shape
    (rangefunc(selector[w]), instant or range) and the fused grouped
    shape (sum/count/avg by of rate/increase/delta). Evaluation runs
    through the normal engine over the local shards, and the backend's
    sharded tile evaluator (TpuBackend.mesh_eval,
    parallel/shardstore.py) dispatches the slot-major evaluator under
    shard_map — series on the 'shard' axis, output step-grid slices on
    the 'time' axis, grouped reduction as the one-hot matmul + psum
    collective — from tiles already living in device HBM (no per-query
    re-pack, unlike MeshAggregateExec's scatter-gather). Per-series
    response bytes are identical to the single-device path by
    construction (the sharded program computes the same evaluator body
    element values bit-for-bit); this node pins the shapes the sharded
    store serves at plan time and surfaces the mesh disposition in
    plan trees/explain."""
    plan: object
    shards: Sequence[object]
    backend: Optional[object]
    stats: QueryStats
    limits: Optional[QueryLimits] = None

    def execute(self):
        eng = QueryEngine(self.shards, backend=self.backend,
                          limits=self.limits)
        out = eng.execute(self.plan)
        self.stats.add(eng.stats)
        if isinstance(out, GridResult) and eng.stats.partial:
            out.partial = True
            out.warnings.extend(w for w in eng.stats.warnings
                                if w not in out.warnings)
        return out

    def plan_tree(self, indent: int = 0) -> str:
        pads = " " * indent
        shard_nums = [getattr(s, "shard_num", "?") for s in self.shards]
        shape = getattr(self.plan, "op", None) \
            or getattr(self.plan, "function", None)
        return (f"{pads}MeshTileExec(shape={shape}, "
                f"shards={shard_nums})")


@dataclass
class MeshAggregateExec(ExecPlan):
    """agg(rangefunc(selector[w])) by (labels) on the device mesh.

    Fuses SelectRawPartitions + PeriodicSamplesMapper + AggregateMapReduce +
    ReduceAggregateExec into one pjit'd program with collectives
    (parallel/mesh.py MeshExecutor.window_aggregate)."""
    agg_op: str
    by: Tuple[str, ...]
    without: Tuple[str, ...]
    agg_params: Tuple
    function: str
    window_ms: int
    func_args: Tuple[float, ...]
    offset_ms: int
    params: RangeParams
    raw: lp.RawSeriesPlan
    shards: Sequence[object]
    mesh_executor: object
    stats: QueryStats
    limits: Optional[QueryLimits] = None
    hist_les: Optional[np.ndarray] = None
    deadline: Optional[object] = None

    def execute(self) -> GridResult:
        from filodb_tpu.query.engine import clip_series

        n_mesh = self.mesh_executor.mesh.shape["shard"]
        series_by_shard: List[List] = []
        # limits budget is per-query: check against fresh stats, then fold
        # into the planner-lifetime counters
        qstats = QueryStats()
        for shard in self.shards:
            if self.deadline is not None:
                self.deadline.check("MeshAggregateExec data selection")
            row = select_raw_series(
                [shard], self.raw.filters, self.raw.start_ms,
                self.raw.end_ms, self.raw.column, qstats, full=True,
                limits=self.limits)
            # pack/ship only the query span, not the whole retention
            series_by_shard.append(
                clip_series(row, self.raw.start_ms, self.raw.end_ms))
        self.stats.add(qstats)
        nb = len(self.hist_les) if self.hist_les is not None else 1
        if self.hist_les is not None:
            series_by_shard = [self._expand_hist(row)
                               for row in series_by_shard]
        # pad the shard list to a multiple of the mesh shard axis
        while len(series_by_shard) % n_mesh:
            series_by_shard.append([])
        # global group table: grouping-label tuple -> group id (`by` keeps
        # the named labels, `without` drops its labels + metric, matching
        # AggregateMapReduce grouping); histogram buckets ride as extra
        # group lanes (gid*nb + bucket), folded back into [G, T, NB] after
        # the collective
        from filodb_tpu.query.engine import strip_metric
        group_keys: Dict[Tuple, int] = {}
        gids_by_shard: List[List[int]] = []
        for row in series_by_shard:
            gids = []
            for j, s in enumerate(row):
                if self.without:
                    k2 = strip_metric(s.labels)
                    key = tuple(sorted((l, v) for l, v in k2.items()
                                       if l not in self.without))
                else:
                    key = tuple((l, s.labels.get(l, ""))
                                for l in self.by)
                gid = group_keys.setdefault(key, len(group_keys))
                gids.append(gid * nb + (j % nb) if nb > 1 else gid)
            gids_by_shard.append(gids)
        steps = self.params.steps
        if not group_keys:
            return GridResult(steps, [],
                              np.zeros((0, steps.size), dtype=np.float64))
        if self.agg_op in ("topk", "bottomk"):
            return self._execute_topk(series_by_shard, gids_by_shard,
                                      len(group_keys), steps)
        out = self.mesh_executor.window_aggregate(
            series_by_shard, self.params, self.function, self.window_ms,
            self.agg_op, gids_by_shard, len(group_keys) * nb,
            func_args=self.func_args, offset_ms=self.offset_ms)
        keys = [dict(k) for k in group_keys]
        out = np.asarray(out)
        if self.hist_les is not None:
            hv = out.reshape(len(keys), nb, steps.size).transpose(0, 2, 1)
            return GridResult(steps, keys,
                              np.full((len(keys), steps.size), np.nan),
                              hist_values=hv, bucket_les=self.hist_les)
        return GridResult(steps, keys, out)

    def _execute_topk(self, series_by_shard, gids_by_shard, num_groups,
                      steps) -> GridResult:
        """Assemble per-series topk/bottomk output from the mesh kernel's
        [G, T, k] winner values + row ids (TopBottomKRowAggregator present
        semantics: union of winning series, NaN at non-winning steps)."""
        vals, ids, s_pad = self.mesh_executor.window_topk(
            series_by_shard, self.params, self.function, self.window_ms,
            int(self.params_k), self.agg_op == "bottomk", gids_by_shard,
            num_groups, func_args=self.func_args, offset_ms=self.offset_ms)
        T = steps.size
        mask = (ids >= 0) & ~np.isnan(vals)
        sel = ids[mask]
        uniq, inv = np.unique(sel, return_inverse=True)
        out = np.full((uniq.size, T), np.nan)
        _, t_idx, _ = np.nonzero(mask)
        out[inv, t_idx] = vals[mask]
        keys = []
        for rid in uniq:
            row = series_by_shard[rid // s_pad]
            keys.append(dict(row[rid % s_pad].labels))
        return GridResult(steps, keys, out)

    @property
    def params_k(self) -> float:
        return self.agg_params[0] if self.agg_params else 0

    def _expand_hist(self, row: List) -> List:
        """Expand each histogram series into NB per-bucket pseudo-series.
        Reset correction (any-bucket drop, sectioned semantics) is applied
        HOST-side on the full matrix so the per-bucket device rows carry no
        dips — the device counter correction is then the identity and the
        result matches the oracle exactly."""
        import dataclasses

        from filodb_tpu.memory import histogram as bh
        out: List = []
        nb = len(self.hist_les)
        for s in row:
            mat = s.values
            if s.is_counter and mat.size:
                mat = mat + bh.hist_counter_correction(
                    mat, drop_rows=s.hist_drop_rows)
            for b in range(nb):
                out.append(dataclasses.replace(
                    s, values=mat[:, b] if mat.size else
                    np.zeros(0, dtype=np.float64),
                    bucket_les=None, snapshot_key=None,
                    hist_drop_rows=None))
        return out

    def plan_tree(self, indent: int = 0) -> str:
        pads = " " * indent
        shard_nums = [getattr(s, "shard_num", "?") for s in self.shards]
        return (f"{pads}MeshAggregateExec(agg={self.agg_op}, by={self.by},\n"
                f"{pads}  func={self.function}, shards={shard_nums})")


@dataclass
class StitchExec(ExecPlan):
    """Raw/downsample time-split: the downsample exec covers the steps
    whose lookback windows fall beyond raw retention, the raw exec covers
    the recent steps; results merge on the step grid
    (LongTimeRangePlanner.scala:30 + StitchRvsExec.scala:116)."""
    ds_exec: Optional[ExecPlan]
    raw_exec: Optional[ExecPlan]

    def execute(self):
        parts = [e.execute() for e in (self.ds_exec, self.raw_exec)
                 if e is not None]
        parts = [p for p in parts if isinstance(p, GridResult)]
        if not parts:
            raise QueryError("stitch produced no grid results")
        if len(parts) == 1:
            return parts[0]
        return stitch_grids(parts[0], parts[1]).absorb_degraded(*parts)

    def plan_tree(self, indent: int = 0) -> str:
        pads = " " * indent
        kids = [e.plan_tree(indent + 2)
                for e in (self.ds_exec, self.raw_exec) if e is not None]
        return f"{pads}StitchExec(\n" + "\n".join(kids) + ")"


class QueryPlanner:
    """materialize(LogicalPlan) -> ExecPlan (QueryPlanner.scala:17;
    SingleClusterPlanner.scala:52). Also the execution facade the HTTP
    layer calls (`execute` = materialize + run)."""

    def __init__(self, shards: Sequence[object],
                 backend: Optional[object] = None,
                 shard_mapper: Optional[object] = None,
                 mesh_executor: Optional[object] = None,
                 spread: int = 1,   # system default-spread; must match ingest
                 shard_key_columns: Tuple[str, ...] = ("_ws_", "_ns_"),
                 metric_column: str = "_metric_",
                 ds_store: Optional[object] = None,
                 raw_retention_ms: int = 0,
                 now_ms=None,
                 limits: Optional[QueryLimits] = None,
                 spread_provider: Optional[object] = None,
                 node_id: Optional[str] = None,
                 peers: Optional[Dict[str, str]] = None,
                 buddies: Optional[Dict[str, str]] = None,
                 partitions: Optional[Dict[str, str]] = None,
                 local_partitions: Optional[Sequence[str]] = None,
                 dataset: str = "timeseries",
                 grpc_peers: Optional[Dict[str, str]] = None,
                 grpc_partitions: Optional[Dict[str, str]] = None,
                 deadline: Optional[object] = None,
                 allow_partial: bool = False,
                 resilience: Optional[object] = None,
                 no_result_cache: bool = False,
                 local_dispatch: bool = False,
                 handoff_sources: Optional[Dict[int, Tuple[str, str]]]
                 = None,
                 peer_watermarks: Optional[Dict[str, Dict]] = None):
        self.shards = list(shards)
        self._by_num = {getattr(s, "shard_num", i): s
                        for i, s in enumerate(self.shards)}
        self.backend = backend
        self.mapper = shard_mapper
        self.mesh = mesh_executor
        self.spread = spread
        # per-shard-key spread overrides (core/SpreadProvider.scala); must
        # be the same provider the ingest edge routes with
        self.spread_provider = spread_provider
        self.shard_key_columns = tuple(shard_key_columns)
        self.metric_column = metric_column
        # raw/downsample tiering (LongTimeRangePlanner.scala:30): queries
        # reaching beyond `now - raw_retention_ms` split to the ds_store
        self.ds_store = ds_store
        self.raw_retention_ms = int(raw_retention_ms)
        self.now_ms = now_ms        # int | callable | None (= wall clock)
        self.limits = limits        # per-query guardrails (None = off)
        # multi-process: this node's id + peer node_id -> base URL; shard
        # numbers the mapper assigns to peers dispatch remotely
        # (FiloDbClusterDiscovery.scala:50 / PlanDispatcher.scala:21)
        self.node_id = node_id
        self.peers = dict(peers or {})
        # HA replica cluster: node_id -> buddy base URL holding the same
        # shard layout; DOWN shards route there instead of dropping out
        # (HighAvailabilityPlanner.scala:31,285 / BuddyShardMapper)
        self.buddies = dict(buddies or {})
        # cross-cluster federation: workspace (_ws_) value -> base URL of
        # the cluster owning that partition (MultiPartitionPlanner.scala:53
        # / SinglePartitionPlanner.scala:17 — pick the cluster by key and
        # forward the whole query; the remote cluster plans freely)
        self.partitions = dict(partitions or {})
        # workspaces THIS cluster serves; never forwarded (self-loop guard)
        self.local_partitions = frozenset(local_partitions or ())
        self.dataset = dataset
        # binary data plane: node/workspace -> grpc host:port; when a peer
        # advertises one, leaf dispatch and pushdown ride protobuf +
        # NibblePack over a persistent channel instead of base64-JSON
        # (grpcsvc; PromQLGrpcServer.scala:44)
        self.grpc_peers = dict(grpc_peers or {})
        self.grpc_partitions = dict(grpc_partitions or {})
        # degraded-mode execution (parallel/resilience.py): per-query
        # deadline budget + opt-in partial results; the retry policy and
        # breaker registry are server-lifetime (breaker state must
        # outlive one query)
        self.deadline = deadline
        self.allow_partial = bool(allow_partial)
        # &cache=false propagation: a bypassed query must stay bypassed
        # across whole-query pushdown hops (the peer consults its OWN
        # results cache otherwise)
        self.no_result_cache = bool(no_result_cache)
        # dispatch scope: True when this planner is pinned to local
        # shards (&dispatch=local pushdown hop / gRPC local_only). A
        # local-only evaluation sees a SUBSET of the world a fan-out
        # query sees — the results cache keys on this so the two can
        # never serve each other's extents
        self.local_dispatch = bool(local_dispatch)
        # mid-handoff read redirect (parallel/membership.py): shard ->
        # (previous owner node, base URL) for shards THIS node is
        # adopting but has not finished replaying — reads route to the
        # still-serving previous owner so no query sees a half-replayed
        # copy (the make-before-break read path)
        self.handoff_sources = dict(handoff_sources or {})
        # gossiped per-peer ingest watermarks + backfill epochs (health
        # body, ROADMAP 4a): stamped onto remote shard groups so the
        # results cache's freshness horizon covers fan-out extents
        self.peer_watermarks = dict(peer_watermarks or {})
        if resilience is None:
            from filodb_tpu.parallel.resilience import PeerResilience
            resilience = PeerResilience.default()
        self.resilience = resilience
        self.stats = QueryStats()
        # tenant QoS (query/qos.py): the node's TenantMetering snapshot,
        # when wired, prices remote shard groups in estimate_cost (local
        # cardinality trackers only know local shards)
        self.metering = None

    def estimate_cost(self, plan):
        """Pre-admission price of a plan over THIS planner's shard view
        (query/qos.py): shard-key cardinality from the local trackers /
        tag-index postings, the metering snapshot for fan-out groups,
        grid step count and plan shape. The one facade both the HTTP
        edge and the gRPC exec service charge budgets through."""
        from filodb_tpu.query import qos
        return qos.estimate_plan_cost(plan, self.shards,
                                      metering=self.metering)

    def static_cost_bound(self, plan):
        """Static ceiling on :meth:`estimate_cost` for the same plan
        (promql/semant.py cost lattice): bound.total >= estimate_cost
        (plan).total for every plan shape — the QoS cross-check pinned
        by tests/test_promql_cost_bound.py, surfaced under
        ``&explain=analyze``."""
        from filodb_tpu.promql.semant import static_cost_bound
        return static_cost_bound(plan, self.shards,
                                 metering=self.metering)

    def _remote_kw(self) -> Dict:
        """Resilience kwargs shared by every remote shard group."""
        return dict(retry=self.resilience.retry,
                    breakers=self.resilience.breakers,
                    deadline=self.deadline,
                    allow_partial=self.allow_partial)

    def _exec_kw(self) -> Dict:
        """Resilience kwargs for whole-query remote exec nodes (partial
        tolerance lives in the surrounding ConcatExec, not the hop)."""
        return dict(retry=self.resilience.retry,
                    breakers=self.resilience.breakers,
                    deadline=self.deadline,
                    no_cache=self.no_result_cache)

    # -- shard pruning (shardsFromFilters, SingleClusterPlanner.scala:872) --
    def shards_from_filters(self, filters: Sequence[ColumnFilter]
                            ) -> Optional[List[int]]:
        """Shard subset for one leaf, or None when filters can't resolve a
        shard key (fan out to all).

        Shard-key columns matched by a regex of LITERAL ALTERNATIONS
        (``App-0|App-1``) or an explicit ``in`` list expand into per-value
        shard sets and union — the ShardKeyRegexPlanner.scala:31 fan-out
        (the reference likewise only supports | of literals)."""
        if self.mapper is None:
            return None
        by_label: Dict[str, List[str]] = {}
        for f in filters:
            vals = _shard_key_candidates(f)
            if vals is not None and f.label not in by_label:
                by_label[f.label] = vals
        metric_vals = None
        for ml in (self.metric_column,) + METRIC_LABELS:
            if ml in by_label:
                metric_vals = by_label[ml]
                break
        if metric_vals is None:
            return None
        key_cols = [c for c in self.shard_key_columns
                    if c != self.metric_column]
        per_col = []
        for c in key_cols:
            if c not in by_label:
                return None
            per_col.append(by_label[c])
        # cartesian fan-out over the candidate key tuples (bounded small;
        # math.prod: exact Python ints — np.prod would wrap at 2^64 and
        # could sneak a huge fan-out past the cap)
        if math.prod(len(v) for v in per_col + [metric_vals]) > 256:
            return None     # oversized fan-out: just use all shards
        nums: set = set()
        for combo in itertools.product(*per_col):
            spread = self.spread_provider.spread_for(list(combo)) \
                if self.spread_provider is not None else self.spread
            for metric in metric_vals:
                skh = shard_key_hash(list(combo), metric)
                nums.update(self.mapper.query_shards(skh, spread))
        return sorted(nums)

    def _resolve_shards(self, plan) -> List[object]:
        """Union of pruned shard subsets across all leaves; all shards when
        any leaf can't be pruned."""
        leaves = walk_leaf_filters(plan)
        if not leaves:
            return self._queryable(None)
        nums: set = set()
        for filters in leaves:
            subset = self.shards_from_filters(filters)
            if subset is None:
                return self._queryable(None)
            nums.update(subset)
        return self._queryable(sorted(nums))

    def _queryable(self, nums: Optional[List[int]]) -> List[object]:
        if nums is None:
            nums = sorted(self._by_num) if not self.peers else \
                list(range(self.mapper.num_shards)) if self.mapper \
                else sorted(self._by_num)
        down: List[int] = []
        if self.mapper is not None:
            from filodb_tpu.parallel.shardmapper import ShardStatus
            ok = set(self.mapper.active_shards(nums))
            down = [n for n in nums if n not in ok]
            nums = [n for n in nums if n in ok]
            # flag, don't hide: a peer-owned shard still in RECOVERY
            # (its adopter is bootstrapping/replaying) serves what it
            # has — the response carries a partial-result warning
            for n in nums:
                if n not in self._by_num and \
                        self.mapper.status(n) is ShardStatus.RECOVERY:
                    self.stats.warnings.append(
                        f"shard {n} is recovering on "
                        f"{self.mapper.node_of(n)}; results may be "
                        f"partial")
            if down and not self.buddies:
                self.stats.warnings.append(
                    "shards " + ",".join(map(str, down))
                    + " are down with no replica; results are partial")
        # make-before-break read path: shards mid-adoption here are
        # served by their previous owner until the replay flips ACTIVE
        redirect: Dict[Tuple[str, str], List[int]] = {}
        redirected: set = set()
        for n in nums:
            if n in self._by_num and n in self.handoff_sources:
                node, url = self.handoff_sources[n]
                redirect.setdefault((node, url), []).append(n)
                redirected.add(n)
        local = [self._by_num[n] for n in nums
                 if n in self._by_num and n not in redirected]
        if redirect:
            from filodb_tpu.parallel.cluster import RemoteShardGroup
            for (node, url), group in sorted(redirect.items()):
                grp = RemoteShardGroup(node, url, self.dataset, group,
                                       **self._remote_kw())
                self._stamp_peer_freshness(grp, node, group)
                local.append(grp)
        if down and self.buddies:
            # failover: serve a down shard from the buddy replica of its
            # owning node (the replica ingests the same stream)
            from filodb_tpu.parallel.cluster import RemoteShardGroup
            by_buddy: Dict[str, List[int]] = {}
            for n in down:
                node = self.mapper.node_of(n)
                url = self.buddies.get(node or "")
                if url:
                    by_buddy.setdefault(url, []).append(n)
            for i, (url, group) in enumerate(sorted(by_buddy.items())):
                local.append(RemoteShardGroup(f"buddy:{url}", url,
                                              self.dataset, group,
                                              **self._remote_kw()))
        if not self.peers or self.mapper is None:
            return local
        # group non-local shard numbers by their owning peer node
        from filodb_tpu.parallel.cluster import RemoteShardGroup
        by_node: Dict[str, List[int]] = {}
        for n in nums:
            if n in self._by_num:
                continue
            node = self.mapper.node_of(n)
            if node is None or node == self.node_id \
                    or node not in self.peers:
                continue
            by_node.setdefault(node, []).append(n)
        for node, group in sorted(by_node.items()):
            gaddr = self.grpc_peers.get(node)
            if gaddr:
                from filodb_tpu.grpcsvc import GrpcShardGroup
                grp = GrpcShardGroup(
                    node, gaddr, self.dataset, group,
                    http_fallback=self.peers.get(node),
                    **self._remote_kw())
            else:
                grp = RemoteShardGroup(node, self.peers[node],
                                       self.dataset, group,
                                       **self._remote_kw())
            self._stamp_peer_freshness(grp, node, group)
            local.append(grp)
        return local

    # remote-group twin of the memstore's watermark/backfill publishers:
    # gossip-stamped attributes the results cache reads through its
    # @event_source functions exactly like local shard state
    @publishes("watermark")
    @publishes("backfill-epoch")
    def _stamp_peer_freshness(self, grp, node: str,
                              group: Sequence[int]) -> None:
        """Stamp a remote shard group with the peer's gossiped ingest
        watermark + backfill-epoch sum (health-body exchange, ROADMAP
        4a) when the gossip covers EVERY shard in the group. The
        results cache reads these exactly like local shard attributes,
        so fan-out extents gain the same settled-time bound local
        extents have had — instead of leaning on the hot window alone.
        Partial coverage stamps nothing (conservative: the group stays
        invisible to the freshness horizon, as before)."""
        pw = self.peer_watermarks.get(node)
        if not pw:
            return
        wms = [pw.get("watermarks", {}).get(int(n)) for n in group]
        if not wms or any(w is None for w in wms):
            return
        # -1 entries are never-ingested peer shards: they constrain
        # nothing (mirroring local semantics) but are COUNTED OUT of
        # the coverage, so the results cache sees the moment one of
        # them starts ingesting even if the min never moves
        nonneg = [int(w) for w in wms if int(w) >= 0]
        grp.ingest_watermark_ms = min(nonneg) if nonneg else -1
        grp.ingest_watermark_coverage = len(nonneg)
        grp.ingest_backfill_epoch = sum(
            int(pw.get("epochs", {}).get(int(n), 0)) for n in group)

    # -- materialization -------------------------------------------------
    def materialize(self, plan) -> ExecPlan:
        """(SingleClusterPlanner.scala:253). Cross-cluster partition
        routing first, then raw/downsample tiering (LongTimeRangePlanner),
        then the mesh-lowerable aggregate shape; everything else runs
        locally over the pruned shard subset."""
        fed = self._try_partition_routing(plan)
        if fed is not None:
            return fed
        tiered = self._try_tiering(plan)
        if tiered is not None:
            return tiered
        return self._materialize_raw(plan)

    def _materialize_raw(self, plan) -> ExecPlan:
        pushed = self._try_remote_pushdown(plan)
        if pushed is not None:
            return pushed
        pushed = self._try_pushdown_join(plan)
        if pushed is not None:
            return pushed
        mesh_plan = self._try_mesh_lowering(plan)
        if mesh_plan is not None:
            return mesh_plan
        return LocalEngineExec(plan, self._resolve_shards(plan),
                               self.backend, self.stats, self.limits)

    def _plan_shard_set(self, plan) -> Optional[frozenset]:
        """Pruned shard-number set of a (sub)plan, or None when any leaf
        can't prune."""
        leaves = walk_leaf_filters(plan)
        if not leaves:
            return None
        nums: set = set()
        for filters in leaves:
            subset = self.shards_from_filters(filters)
            if subset is None:
                return None
            nums.update(subset)
        return frozenset(nums)

    def _try_pushdown_join(self, plan) -> Optional[ExecPlan]:
        """Per-node shard-aligned binary-join pushdown
        (SingleClusterPlanner.scala:649 materializeWithPushdown /
        LogicalPlanUtils.getPushdownKeys): when every matching pair of
        series is provably CO-LOCATED, each owning node evaluates the
        join over its local shards and the entry node concatenates
        joined results — raw series never cross the network.

        Co-location proof under this framework's shard routing
        (ingestion_shard hashes ws/ns/METRIC plus the part hash): both
        sides must select the SAME single metric and match on the full
        label set (no on/ignoring) — then matching series have identical
        labels, identical hashes, and the same shard. The reference
        proves the on-clause case via target schemas
        (sameRawSeriesTargetSchemaColumns); without target schemas those
        joins stay on the entry node."""
        if not isinstance(plan, lp.BinaryJoin) or not self.peers \
                or self.mapper is None:
            return None
        if getattr(plan, "on", None) or getattr(plan, "ignoring", ()):
            return None
        metrics = set()
        for filters in walk_leaf_filters(plan):
            got = [f.value for f in filters
                   if f.label in (self.metric_column,) + METRIC_LABELS
                   and f.op == "eq"]
            if len(got) != 1:
                return None
            metrics.add(got[0])
        if len(metrics) != 1:
            return None
        lshards = self._plan_shard_set(plan.lhs)
        rshards = self._plan_shard_set(plan.rhs)
        if lshards is None or rshards is None or lshards != rshards:
            return None
        nums = sorted(lshards)
        if set(self.mapper.active_shards(nums)) != set(nums):
            return None          # down shards: let the general path warn
        by_node: Dict[str, List[int]] = {}
        for n in nums:
            node = self.mapper.node_of(n)
            if node is None:
                return None
            by_node.setdefault(node, []).append(n)
        if len(by_node) < 2:
            return None          # single node: whole-query pushdown owns it
        fw = self._forwardable(plan)
        if fw is None:
            return None
        query, start, step, end = fw
        children: List[ExecPlan] = []
        for node, group in sorted(by_node.items()):
            if node == self.node_id:
                local = [self._by_num[n] for n in group
                         if n in self._by_num]
                children.append(LocalEngineExec(
                    plan, local, self.backend, self.stats, self.limits))
                continue
            gaddr = self.grpc_peers.get(node)
            if gaddr:
                from filodb_tpu.grpcsvc import GrpcRemoteExec
                pw = self._plan_wire_of(plan)
                children.append(GrpcRemoteExec(
                    query, start, step, end, node, gaddr, self.dataset,
                    stats=self.stats, local_only=True,
                    plan_wire=pw[0] if pw else b"",
                    http_fallback=self.peers.get(node),
                    expect_shards=group,
                    **self._exec_kw()))
            elif node in self.peers:
                from filodb_tpu.parallel.cluster import PromQlRemoteExec
                children.append(PromQlRemoteExec(
                    query, start, step, end, node, self.peers[node],
                    self.dataset, stats=self.stats, local_only=True,
                    expect_shards=group,
                    **self._exec_kw()))
            else:
                return None
        return ConcatExec(children, self.stats,
                          allow_partial=self.allow_partial,
                          deadline=self.deadline)

    def _try_remote_pushdown(self, plan) -> Optional[ExecPlan]:
        """Whole-query forwarding when EVERY pruned shard lives on ONE
        peer node and the plan prints back to PromQL — this is also the
        shard-aligned binary-join pushdown (SingleClusterPlanner.scala:649:
        joins execute where the data is when both sides target the same
        shards; here "where the data is" is the owning peer)."""
        if not self.peers or self.mapper is None:
            return None
        if lp.is_metadata_plan(plan) or lp.is_scalar_plan(plan):
            return None
        shards = self._resolve_shards(plan)
        if not shards or not all(hasattr(s, "fetch_raw") for s in shards):
            return None
        nodes = {s.node_id for s in shards}
        if len(nodes) != 1:
            return None
        g = shards[0]
        gaddr = self.grpc_peers.get(g.node_id)
        fw = self._forwardable(plan)
        expect = list(g.shard_nums) if g.shard_nums is not None else None
        if gaddr:
            # gRPC peers take the STRUCTURAL plan tree (exec_plan.proto
            # capability): no dependence on the PromQL printer, so even
            # unprintable plans (subqueries etc.) push down whole
            pw = self._plan_wire_of(plan)
            if pw is not None:
                wire_bytes, start, step, end = pw
                from filodb_tpu.grpcsvc import GrpcRemoteExec
                return GrpcRemoteExec(
                    fw[0] if fw else f"<plan:{type(plan).__name__}>",
                    start, step, end, g.node_id, gaddr, g.dataset,
                    stats=self.stats, plan_wire=wire_bytes,
                    http_fallback=(self.peers.get(g.node_id)
                                   if fw else None),
                    expect_shards=expect,
                    **self._exec_kw())
        if fw is None:
            return None
        query, start, step, end = fw
        if gaddr:
            from filodb_tpu.grpcsvc import GrpcRemoteExec
            return GrpcRemoteExec(query, start, step, end, g.node_id,
                                  gaddr, g.dataset, stats=self.stats,
                                  http_fallback=self.peers.get(g.node_id),
                                  expect_shards=expect,
                                  **self._exec_kw())
        from filodb_tpu.parallel.cluster import PromQlRemoteExec
        return PromQlRemoteExec(query, start, step, end, g.node_id,
                                g.base_url, g.dataset, stats=self.stats,
                                expect_shards=expect,
                                **self._exec_kw())

    def _plan_wire_of(self, plan):
        """(wire_bytes, start, step, end) when the plan serializes
        structurally and carries an evaluation range, else None."""
        rng = plan_range(plan)
        if rng is None:
            return None
        start, step, end, _, _ = rng
        try:
            from filodb_tpu.query.planwire import plan_to_wire
            return plan_to_wire(plan), start, step, end
        except ValueError:
            return None

    def execute(self, plan):
        return self.materialize(plan).execute()

    def _forwardable(self, plan):
        """(query_text, start, step, end) when the whole plan can ride the
        HTTP edge to another node/cluster, else None — shared eligibility
        for pushdown and federation."""
        if lp.is_metadata_plan(plan) or lp.is_scalar_plan(plan):
            return None
        rng = plan_range(plan)
        if rng is None:
            return None
        start, step, end, _, _ = rng
        if start % 1000 or end % 1000 or (step > 0 and step % 1000):
            return None     # the HTTP edge carries second granularity
        from filodb_tpu.query.planparser import plan_to_promql
        query = plan_to_promql(plan)
        if query is None:
            return None
        return query, start, step, end

    def _try_partition_routing(self, plan) -> Optional[ExecPlan]:
        """Forward a query whose every leaf pins _ws_ to ONE remote
        partition's cluster (SinglePartitionPlanner: cluster by key).
        Workspaces this cluster serves itself are never forwarded."""
        if not self.partitions:
            return None
        if lp.is_metadata_plan(plan) or lp.is_scalar_plan(plan):
            return None
        ws_values = set()
        for filters in walk_leaf_filters(plan):
            got = [f.value for f in filters
                   if f.label == "_ws_" and f.op == "eq"]
            if len(got) != 1:
                return None     # unpinned / multi: local planning
            ws_values.add(got[0])
        if len(ws_values) != 1:
            return None         # cross-partition joins stay local
        ws = ws_values.pop()
        if ws in self.local_partitions:
            return None         # our own partition: plan locally
        url = self.partitions.get(ws)
        if not url:
            return None
        fw = self._forwardable(plan)
        if fw is None:
            return None
        query, start, step, end = fw
        gaddr = self.grpc_partitions.get(ws)
        if gaddr:
            from filodb_tpu.grpcsvc import GrpcRemoteExec
            return GrpcRemoteExec(query, start, step, end,
                                  f"partition:{gaddr}", gaddr,
                                  self.dataset, stats=self.stats,
                                  local_only=False, http_fallback=url,
                                  **self._exec_kw())
        from filodb_tpu.parallel.cluster import PromQlRemoteExec
        return PromQlRemoteExec(query, start, step, end,
                                f"partition:{url}", url, self.dataset,
                                stats=self.stats, local_only=False,
                                **self._exec_kw())

    # -- raw/downsample tiering (LongTimeRangePlanner.scala:30) -----------
    def _earliest_raw_ms(self) -> int:
        import time as _time
        if callable(self.now_ms):
            now = int(self.now_ms())
        elif self.now_ms is not None:
            now = int(self.now_ms)
        else:
            now = int(_time.time() * 1000)
        return now - self.raw_retention_ms

    def _try_tiering(self, plan) -> Optional[ExecPlan]:
        """Split a plan whose step windows reach beyond raw retention into
        a downsample-side exec + a raw-side exec, stitched. Returns None
        when tiering doesn't apply (all-raw, untierable shape, or no exact
        downsample mapping — those fall back to the raw store)."""
        from filodb_tpu.query.engine import lp_replace_range

        if self.ds_store is None or self.raw_retention_ms <= 0:
            return None
        if lp.is_metadata_plan(plan) or lp.is_scalar_plan(plan):
            return None
        rng = plan_range(plan)
        if rng is None:
            return None
        start, step, end, window, lookback = rng
        earliest_raw = self._earliest_raw_ms()
        ats, n_periodic = _collect_at(plan)
        if ats:
            # @-pinned selectors read at the pinned instant, not the grid:
            # when every selector is pinned beyond raw retention, the whole
            # plan routes to the downsample tier (no split — @ evaluates
            # at one instant and broadcasts)
            if len(ats) != n_periodic:
                return None                 # mixed pinned/unpinned: raw
            if min(ats) - lookback >= earliest_raw:
                return None                 # pinned data still in raw
            if max(ats) - lookback >= earliest_raw:
                # instants straddle the boundary: the ds tier may not
                # cover the recent one yet -> answer from raw (partial
                # for the old instant, never silently empty for recent)
                return None
            eff_step = step if step > 0 else max(window, 1)
            picked = self.ds_store.plan_query(plan, max(window, 1),
                                              eff_step)
            if picked is None:
                return None
            ds_shards, ds_rewritten = picked
            return StitchExec(
                ds_exec=LocalEngineExec(ds_rewritten, ds_shards,
                                        self.backend, self.stats,
                                        self.limits),
                raw_exec=None)
        if start - lookback >= earliest_raw:
            return None                                  # fully in raw
        if not _splittable(plan):
            return None
        # first step whose whole lookback window sits inside raw retention
        if step > 0 and end - lookback >= earliest_raw:
            k = -((start - lookback - earliest_raw) // step)   # ceil div
            boundary = start + k * step
        elif end - lookback >= earliest_raw:
            boundary = start                             # single instant, raw
        else:
            boundary = None                              # fully beyond raw
        if boundary is not None and boundary <= start:
            return None                                  # fully in raw
        if boundary is None:
            ds_plan = plan
        else:
            ds_plan = lp_replace_range(plan, start, step, boundary - step)
        # instant queries (step<=0) have a single evaluation: resolution
        # choice is governed by the window alone
        eff_step = step if step > 0 else max(window, 1)
        picked = self.ds_store.plan_query(ds_plan, max(window, 1), eff_step)
        if picked is None:
            return None     # no exact ds mapping: answer from raw only
        ds_shards, ds_rewritten = picked
        ds_exec = LocalEngineExec(ds_rewritten, ds_shards, self.backend,
                                  self.stats, self.limits)
        raw_exec = None
        if boundary is not None and boundary <= end:
            raw_plan = lp_replace_range(plan, boundary, step, end)
            raw_exec = self._materialize_raw(raw_plan)
        return StitchExec(ds_exec=ds_exec, raw_exec=raw_exec)

    def _try_mesh_lowering(self, plan) -> Optional[ExecPlan]:
        from filodb_tpu.query.tpu import DEVICE_FUNCS

        window = self._try_mesh_window(plan)
        if window is not None:
            return window
        if self.mesh is None:
            return None
        topk = plan.op in ("topk", "bottomk") if isinstance(
            plan, lp.Aggregate) else False
        if not isinstance(plan, lp.Aggregate) or \
                (plan.op not in _MESH_AGGS and not topk):
            return None
        if plan.params and not topk:
            return None
        if topk:
            try:
                k_ok = (len(plan.params) == 1
                        and float(plan.params[0]).is_integer()
                        and int(plan.params[0]) >= 1)
            except (TypeError, ValueError):
                k_ok = False
            if not k_ok:
                return None
        inner = plan.inner
        if not isinstance(inner, lp.PeriodicSeriesWithWindowing):
            return None
        if inner.at_ms is not None:
            return None
        if inner.function not in DEVICE_FUNCS:
            return None
        raw = inner.raw
        if not isinstance(raw, lp.RawSeriesPlan):
            return None
        shards = self._resolve_shards(plan)
        if not shards:
            return None
        # cross-node leaves dispatch over HTTP, not the local device mesh
        if any(hasattr(s, "fetch_raw") for s in shards):
            return None
        # histogram selections ride the mesh by bucket-expansion, but only
        # for the sum(rate|increase(hist[w])) shape with one consistent
        # bucket scheme; anything else falls back to the local engine
        hist_kind, hist_les = self._hist_selection(shards, raw)
        if hist_kind == "mixed":
            return None
        if hist_kind == "hist":
            if plan.op != "sum" or inner.function not in ("rate",
                                                          "increase"):
                return None
            if hist_les is None:
                return None
        if topk and hist_kind != "none":
            return None
        # prefer the device-RESIDENT tile path over scatter-gather for
        # the fused grouped shape: the engine's fused_groupsum routes
        # to the sharded one-hot-matmul + psum collective off tiles
        # already living in HBM (falling back in-engine when the
        # cohort doesn't qualify) — re-pack-per-query is the dry-run
        # design, not the serving path
        if not topk and hist_kind == "none" \
                and plan.op in ("sum", "count", "avg") \
                and not plan.params \
                and self.backend is not None \
                and getattr(self.backend, "mesh_eval", None) is not None:
            return MeshTileExec(plan, shards, self.backend, self.stats,
                                self.limits)
        return MeshAggregateExec(
            agg_op=plan.op, by=tuple(plan.by),
            without=tuple(plan.without), agg_params=tuple(plan.params),
            function=inner.function,
            window_ms=inner.window_ms, func_args=tuple(inner.func_args),
            offset_ms=inner.offset_ms,
            params=RangeParams(inner.start_ms, inner.step_ms, inner.end_ms),
            raw=raw, shards=shards, mesh_executor=self.mesh,
            stats=self.stats, limits=self.limits, hist_les=hist_les,
            deadline=self.deadline)

    def _try_mesh_window(self, plan) -> Optional[MeshTileExec]:
        """The bare windowed shape (instant/range rangefunc over a raw
        selector — the tilestore counter path) lowers for mesh
        execution when the backend serves device-resident sharded
        tiles. The historical mesh lowering only caught the
        scatter-gather aggregate shape; this covers the per-series
        serving path the sharded tile store exists for."""
        from filodb_tpu.query import tilestore as tst

        be = self.backend
        if be is None or getattr(be, "mesh_eval", None) is None:
            return None
        if not isinstance(plan, lp.PeriodicSeriesWithWindowing):
            return None
        if plan.at_ms is not None or plan.func_args:
            return None
        if plan.function not in tst.ALIGNED_FUNCS:
            return None     # gather/order-statistics families stay local
        raw = plan.raw
        if not isinstance(raw, lp.RawSeriesPlan):
            return None
        shards = self._resolve_shards(plan)
        if not shards:
            return None
        # cross-node leaves dispatch over HTTP, not the local mesh
        if any(hasattr(s, "fetch_raw") for s in shards):
            return None
        hist_kind, _ = self._hist_selection(shards, raw)
        if hist_kind != "none":
            return None     # per-series histogram grids stay local
        return MeshTileExec(plan, shards, self.backend, self.stats,
                             self.limits)

    @staticmethod
    def _hist_selection(shards, raw: lp.RawSeriesPlan):
        """("none"|"hist"|"mixed", les or None): whether the selection hits
        histogram columns, and the shared bucket scheme if consistent."""
        from filodb_tpu.core.schemas import ColumnType
        saw_hist = saw_scalar = False
        les = None
        consistent = True
        for shard in shards:
            for part in shard.lookup_partitions(raw.filters, raw.start_ms,
                                                raw.end_ms):
                name = raw.column or part.schema.value_column
                for c in part.schema.columns:
                    if c.name == name:
                        if c.col_type == ColumnType.HISTOGRAM:
                            saw_hist = True
                            sch = part._hist_scheme
                            cur = sch.les() if sch is not None else None
                            if cur is None:
                                consistent = False
                            elif les is None:
                                les = cur
                            elif not np.array_equal(les, cur):
                                consistent = False
                        else:
                            saw_scalar = True
                        break
        if saw_hist and saw_scalar:
            return "mixed", None
        if saw_hist:
            return "hist", (les if consistent else None)
        return "none", None
