"""Query planner: materializes LogicalPlans into executable plans with
shard pruning and distributed (mesh) lowering.

TPU-native counterpart of the reference planner stack
(coordinator/queryplanner/SingleClusterPlanner.scala:253 materialize,
:430 walkLogicalPlanTree, :872 shardsFromFilters + dispatcherForShard :138;
DefaultPlanner's aggregate lowering). Differences by design:

- Shard pruning is identical in spirit: equality filters on the shard-key
  columns (_ws_, _ns_, metric) hash to a shard subset via the bit-compatible
  `query_shards` (RecordBuilder.scala:667 shardKeyHash + spread bit split);
  anything else fans out to all queryable shards.

- Instead of serializing an ExecPlan tree to per-shard actors
  (ActorPlanDispatcher + Kryo), the scatter-gather IS a device-mesh program:
  the `agg(rangefunc(selector[w])) by (...)` shape lowers onto
  `MeshExecutor.window_aggregate` — per-shard leaf evaluation rides the mesh
  'shard' axis, the reduce is a psum-tree collective over ICI
  (ReduceAggregateExec ≡ the collective), and only the tiny [groups, steps]
  grid returns to the host.

- Every other plan shape falls back to `LocalEngineExec`: the single-process
  engine over the pruned shard subset (InProcessPlanDispatcher equivalent).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.core.index import ColumnFilter
from filodb_tpu.core.record import shard_key_hash
from filodb_tpu.query import logical as lp
from filodb_tpu.query.engine import (METRIC_LABELS, QueryEngine,
                                     select_raw_series)
from filodb_tpu.query.model import (GridResult, QueryError, QueryStats,
                                    RangeParams)

# aggregations executable as mesh collectives (parallel/mesh.py MESH_AGGS)
_MESH_AGGS = frozenset({"sum", "count", "avg", "min", "max", "group"})


def walk_leaf_filters(plan) -> List[Tuple[ColumnFilter, ...]]:
    """Collect the filter sets of every RawSeries leaf under a plan
    (walkLogicalPlanTree's shard resolution inputs)."""
    out: List[Tuple[ColumnFilter, ...]] = []

    def rec(p):
        if p is None or isinstance(p, (int, float, str)):
            return
        if isinstance(p, lp.RawSeriesPlan):
            out.append(tuple(p.filters))
            return
        for f in getattr(p, "__dataclass_fields__", {}):
            v = getattr(p, f)
            if isinstance(v, tuple):
                for item in v:
                    rec(item)
            else:
                rec(v)

    rec(plan)
    return out


@dataclass
class PlannerParams:
    """(core/query/QueryContext PlannerParams equivalent)."""
    spread: int = 0
    sample_limit: int = 0       # 0 = unlimited (guardrails layer)
    series_limit: int = 0


class ExecPlan:
    """Materialized plan node (query/exec/ExecPlan.scala:46)."""

    def execute(self):
        raise NotImplementedError

    def plan_tree(self, indent: int = 0) -> str:
        return " " * indent + type(self).__name__


@dataclass
class LocalEngineExec(ExecPlan):
    """Evaluate a LogicalPlan on the single-process engine over a pruned
    shard subset (InProcessPlanDispatcher.scala:25 semantics)."""
    plan: object
    shards: Sequence[object]
    backend: Optional[object]
    stats: QueryStats

    def execute(self):
        eng = QueryEngine(self.shards, backend=self.backend)
        out = eng.execute(self.plan)
        self.stats.add(eng.stats)
        return out

    def plan_tree(self, indent: int = 0) -> str:
        pads = " " * indent
        shard_nums = [getattr(s, "shard_num", "?") for s in self.shards]
        return (f"{pads}LocalEngineExec(shards={shard_nums}, "
                f"plan={type(self.plan).__name__})")


@dataclass
class MeshAggregateExec(ExecPlan):
    """agg(rangefunc(selector[w])) by (labels) on the device mesh.

    Fuses SelectRawPartitions + PeriodicSamplesMapper + AggregateMapReduce +
    ReduceAggregateExec into one pjit'd program with collectives
    (parallel/mesh.py MeshExecutor.window_aggregate)."""
    agg_op: str
    by: Tuple[str, ...]
    function: str
    window_ms: int
    func_args: Tuple[float, ...]
    offset_ms: int
    params: RangeParams
    raw: lp.RawSeriesPlan
    shards: Sequence[object]
    mesh_executor: object
    stats: QueryStats

    def execute(self) -> GridResult:
        from filodb_tpu.query.engine import clip_series

        n_mesh = self.mesh_executor.mesh.shape["shard"]
        series_by_shard: List[List] = []
        for shard in self.shards:
            row = select_raw_series(
                [shard], self.raw.filters, self.raw.start_ms,
                self.raw.end_ms, self.raw.column, self.stats, full=True)
            # pack/ship only the query span, not the whole retention
            series_by_shard.append(
                clip_series(row, self.raw.start_ms, self.raw.end_ms))
        # histograms are not mesh-lowerable; caller pre-checked 1-D only
        # pad the shard list to a multiple of the mesh shard axis
        while len(series_by_shard) % n_mesh:
            series_by_shard.append([])
        # global group table: by-labels value tuple -> group id
        group_keys: Dict[Tuple, int] = {}
        gids_by_shard: List[List[int]] = []
        for row in series_by_shard:
            gids = []
            for s in row:
                key = tuple((l, s.labels.get(l, "")) for l in self.by)
                gid = group_keys.setdefault(key, len(group_keys))
                gids.append(gid)
            gids_by_shard.append(gids)
        steps = self.params.steps
        if not group_keys:
            return GridResult(steps, [],
                              np.zeros((0, steps.size), dtype=np.float64))
        out = self.mesh_executor.window_aggregate(
            series_by_shard, self.params, self.function, self.window_ms,
            self.agg_op, gids_by_shard, len(group_keys),
            func_args=self.func_args, offset_ms=self.offset_ms)
        keys = [dict(k) for k in group_keys]
        return GridResult(steps, keys, np.asarray(out))

    def plan_tree(self, indent: int = 0) -> str:
        pads = " " * indent
        shard_nums = [getattr(s, "shard_num", "?") for s in self.shards]
        return (f"{pads}MeshAggregateExec(agg={self.agg_op}, by={self.by},\n"
                f"{pads}  func={self.function}, shards={shard_nums})")


class QueryPlanner:
    """materialize(LogicalPlan) -> ExecPlan (QueryPlanner.scala:17;
    SingleClusterPlanner.scala:52). Also the execution facade the HTTP
    layer calls (`execute` = materialize + run)."""

    def __init__(self, shards: Sequence[object],
                 backend: Optional[object] = None,
                 shard_mapper: Optional[object] = None,
                 mesh_executor: Optional[object] = None,
                 spread: int = 1,   # system default-spread; must match ingest
                 shard_key_columns: Tuple[str, ...] = ("_ws_", "_ns_"),
                 metric_column: str = "_metric_"):
        self.shards = list(shards)
        self._by_num = {getattr(s, "shard_num", i): s
                        for i, s in enumerate(self.shards)}
        self.backend = backend
        self.mapper = shard_mapper
        self.mesh = mesh_executor
        self.spread = spread
        self.shard_key_columns = tuple(shard_key_columns)
        self.metric_column = metric_column
        self.stats = QueryStats()

    # -- shard pruning (shardsFromFilters, SingleClusterPlanner.scala:872) --
    def shards_from_filters(self, filters: Sequence[ColumnFilter]
                            ) -> Optional[List[int]]:
        """Shard subset for one leaf, or None when filters can't resolve a
        shard key (fan out to all)."""
        if self.mapper is None:
            return None
        eqs = {f.label: f.value for f in filters if f.op == "eq"}
        metric = None
        for ml in (self.metric_column,) + METRIC_LABELS:
            if ml in eqs:
                metric = eqs[ml]
                break
        if metric is None:
            return None
        values = []
        for c in self.shard_key_columns:
            if c == self.metric_column:
                continue
            if c not in eqs:
                return None
            values.append(eqs[c])
        skh = shard_key_hash(values, metric)
        return self.mapper.query_shards(skh, self.spread)

    def _resolve_shards(self, plan) -> List[object]:
        """Union of pruned shard subsets across all leaves; all shards when
        any leaf can't be pruned."""
        leaves = walk_leaf_filters(plan)
        if not leaves:
            return self._queryable(None)
        nums: set = set()
        for filters in leaves:
            subset = self.shards_from_filters(filters)
            if subset is None:
                return self._queryable(None)
            nums.update(subset)
        return self._queryable(sorted(nums))

    def _queryable(self, nums: Optional[List[int]]) -> List[object]:
        if nums is None:
            nums = sorted(self._by_num)
        if self.mapper is not None:
            ok = set(self.mapper.active_shards(nums))
            nums = [n for n in nums if n in ok]
        return [self._by_num[n] for n in nums if n in self._by_num]

    # -- materialization -------------------------------------------------
    def materialize(self, plan) -> ExecPlan:
        """(SingleClusterPlanner.scala:253). Pattern-matches the mesh-
        lowerable aggregate shape; everything else runs locally over the
        pruned shard subset."""
        mesh_plan = self._try_mesh_lowering(plan)
        if mesh_plan is not None:
            return mesh_plan
        return LocalEngineExec(plan, self._resolve_shards(plan),
                               self.backend, self.stats)

    def execute(self, plan):
        return self.materialize(plan).execute()

    def _try_mesh_lowering(self, plan) -> Optional[MeshAggregateExec]:
        from filodb_tpu.query.tpu import DEVICE_FUNCS

        if self.mesh is None:
            return None
        if not isinstance(plan, lp.Aggregate) or plan.op not in _MESH_AGGS:
            return None
        if plan.without or plan.params:
            return None
        inner = plan.inner
        if not isinstance(inner, lp.PeriodicSeriesWithWindowing):
            return None
        if inner.at_ms is not None:
            return None
        if inner.function not in DEVICE_FUNCS:
            return None
        raw = inner.raw
        if not isinstance(raw, lp.RawSeriesPlan):
            return None
        shards = self._resolve_shards(plan)
        if not shards:
            return None
        # histogram columns can't ride the [S,N] mesh tiles (yet)
        if self._selects_histograms(shards, raw):
            return None
        return MeshAggregateExec(
            agg_op=plan.op, by=tuple(plan.by), function=inner.function,
            window_ms=inner.window_ms, func_args=tuple(inner.func_args),
            offset_ms=inner.offset_ms,
            params=RangeParams(inner.start_ms, inner.step_ms, inner.end_ms),
            raw=raw, shards=shards, mesh_executor=self.mesh,
            stats=self.stats)

    @staticmethod
    def _selects_histograms(shards, raw: lp.RawSeriesPlan) -> bool:
        from filodb_tpu.core.schemas import ColumnType
        for shard in shards:
            for part in shard.lookup_partitions(raw.filters, raw.start_ms,
                                                raw.end_ms):
                name = raw.column or part.schema.value_column
                for c in part.schema.columns:
                    if c.name == name:
                        if c.col_type == ColumnType.HISTOGRAM:
                            return True
                        break
        return False
