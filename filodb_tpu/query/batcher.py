"""Concurrent-query micro-batching + async device dispatch.

The serving fast path's admission layer in front of the TPU backend
(the Orca-style iteration-batching idea from the accelerator-serving
literature, applied to a TSDB's query kernels): requests that arrive
while the device executor is busy and resolve to the same bucketed
kernel shape are stacked — along the grid axis for the aligned
tilestore evaluators (one vmapped dispatch computes B step grids over
shared device tiles), along the series axis for the packed general
path (one kernel launch over the concatenated [S_total, N] tile with
per-row window vectors and per-query segment offsets) — executed as
ONE device dispatch, and split back per request.

Three cooperating pieces:

  * :class:`MicroBatcher` — admission. The first thread to submit a
    given batch key becomes the *leader*; when other query threads are
    concurrently inside the backend, the open batch is queued to the
    device executor and later arrivals keep joining it until the
    executor actually picks it up — the executor's busy time IS the
    gather window (continuous batching), so batching emerges exactly
    when there is queueing and costs nothing when there is none. When
    the executor is idle, an explicit residual gather window
    (``gather_window_s``, default 1ms, configurable) holds the batch
    open briefly so a concurrent same-shape arrival can still pair.
    A lone request (no concurrent traffic) bypasses all of it and runs
    the single-query kernel path inline.
  * :class:`DeviceExecutor` — a single dedicated thread that owns
    device submission. Batched dispatches run here; JAX async dispatch
    returns device futures immediately, so the executor is free to
    close and submit the NEXT batch while the device still computes
    the current one — host-side pack/stack overlaps device compute.
  * :class:`SplitResult` — the per-batch result holder. The device →
    host sync (``np.asarray`` on the stacked output) happens ONCE per
    batch, lazily, on the first *worker* thread that asks — never on
    the executor thread, and never per member.

Latency/deadline semantics: batching adds at most one gather window
(plus executor queueing that concurrent singles would pay as lock
contention anyway) to a query; a query whose deadline budget expires
fails in its own exec tree — a query hitting its deadline leaves the
batch, not the reverse.

Failure semantics: an exception in a batched dispatch fails every
member (they would all have taken the same kernel); callers surface it
exactly as a single-query kernel failure.

Priority classes (tenant QoS, query/qos.py): the executor's dispatch
queue orders by the submitting query's priority class — interactive <
rules/background < over-budget best-effort — so a brownout's monster
scans never head-of-line block cheap interactive queries. A batch's
class is the BEST (lowest) among its members at queue time: an
interactive arrival joining an open best-effort batch rides that
batch's already-queued position (PriorityQueue entries are immutable),
but the common case — a best-effort leader queueing behind interactive
leaders — reorders exactly as intended. On the CPU-inline path there
is no queue to reorder; best-effort leaders instead yield the GIL a
few extra rounds under concurrency so interactive threads pass them.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.lint.hotpath import hot_path
from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.obs import trace as obs_trace
from filodb_tpu.query import qos

_QWAIT_HELP = ("Wall seconds a query spent parked on the micro-batcher "
               "(executor queueing + residual gather window); 0 for "
               "inline single-query dispatches")
_OCC_HELP = "Members per micro-batch dispatch (batch occupancy)"


class DeviceExecutor:
    """One dedicated thread owns device submission (the async-dispatch
    pipeline): HTTP worker threads enqueue batch closures and park on
    futures instead of holding the GIL through device sync.

    The queue orders by ``(priority, arrival)``: within a class it
    stays FIFO, across classes a waiting interactive dispatch always
    precedes a waiting best-effort one — the executor's busy time IS
    the gather window, so under brownout queueing this is exactly
    where head-of-line blocking would otherwise happen."""

    def __init__(self, name: str = "filodb-device-exec"):
        self._q: "queue.PriorityQueue[Tuple[int, int, Optional[Callable[[], None]]]]" \
            = queue.PriorityQueue()
        self._seq = itertools.count()   # FIFO tiebreak within a class
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._started = False
        self._start_lock = threading.Lock()

    def submit(self, fn: Callable[[], None],
               priority: int = qos.PRIORITY_INTERACTIVE) -> None:
        """Enqueue a closure for the executor thread (fire-and-forget:
        result delivery is the closure's business)."""
        with self._start_lock:
            if not self._started:
                self._started = True
                self._thread.start()
        self._q.put((int(priority), next(self._seq), fn))

    def idle(self) -> bool:
        """True when nothing is queued (the executor may still be
        finishing its current closure)."""
        return self._q.empty()

    @thread_root("device-executor")
    def _run(self) -> None:
        while True:
            _prio, _seq, fn = self._q.get()
            if fn is None:
                return
            try:
                fn()
            except BaseException:  # noqa: BLE001 — closures own delivery
                pass

    def stop(self) -> None:
        if self._started:
            # sorts behind every real priority class: queued work
            # drains before the executor exits
            self._q.put((1 << 30, next(self._seq), None))


class SplitResult:
    """Stacked device output of one batch, split back per member.

    ``get(i)`` returns member *i*'s numpy slice; the single device→host
    sync for the whole batch happens under ``_lock`` on the first
    caller's thread."""

    def __init__(self, stacked, n: int,
                 split: Optional[Callable[[np.ndarray, int], np.ndarray]]
                 = None):
        self._stacked = stacked
        self._n = n
        self._split = split
        self._host: Optional[np.ndarray] = None
        self._lock = threading.Lock()

    @hot_path
    def get(self, i: int) -> np.ndarray:
        with self._lock:
            if self._host is None:
                # the one amortized sync point for the whole batch
                # graftlint: disable=host-transfer-in-hot-loop,oversized-transfer (single per-batch sync for the whole batch; the device buffer is dropped right after, so no resident channel is being re-pulled)
                self._host = np.asarray(self._stacked)
                self._stacked = None
        if self._split is not None:
            return self._split(self._host, i)
        return self._host[i]


@guarded_by("_lock", "batches", "queries", "batched_queries",
            "occupancy_sum", "occupancy_max", "gather_wait_ns",
            "by_size", "by_priority")
class BatchStats:
    """Occupancy/throughput counters surfaced in /metrics."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.batches = 0            # dispatches issued
        self.queries = 0            # member queries admitted
        self.batched_queries = 0    # members of batches with size >= 2
        self.occupancy_sum = 0      # sum of batch sizes
        self.occupancy_max = 0
        self.gather_wait_ns = 0     # total residual gather-window time
        self.by_size: Dict[int, int] = {}
        # dispatches per priority class (tenant QoS): operators read
        # the brownout's best-effort share straight off /metrics
        self.by_priority: Dict[int, int] = {}

    def record(self, size: int, wait_ns: int,
               priority: int = qos.PRIORITY_INTERACTIVE) -> None:
        with self._lock:
            self.batches += 1
            self.queries += size
            if size >= 2:
                self.batched_queries += size
            self.occupancy_sum += size
            self.occupancy_max = max(self.occupancy_max, size)
            self.gather_wait_ns += wait_ns
            self.by_size[size] = self.by_size.get(size, 0) + 1
            self.by_priority[priority] = \
                self.by_priority.get(priority, 0) + size
        # occupancy distribution: p50/p95 batch sizes straight off a
        # /metrics scrape instead of the avg/max point gauges alone
        obs_metrics.observe("filodb_batcher_batch_size", _OCC_HELP,
                            float(size), obs_metrics.OCCUPANCY_BUCKETS)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            avg = (self.occupancy_sum / self.batches) if self.batches \
                else 0.0
            return {"batches": self.batches, "queries": self.queries,
                    "batched_queries": self.batched_queries,
                    "occupancy_avg": round(avg, 4),
                    "occupancy_max": self.occupancy_max,
                    "gather_wait_ms":
                        round(self.gather_wait_ns / 1e6, 3),
                    "by_size": dict(self.by_size),
                    "by_priority": {
                        qos.PRIORITY_NAMES.get(p, str(p)): n
                        for p, n in self.by_priority.items()}}


class _Pending:
    """One open batch: members join under the batcher lock until the
    executor closes it; the result flows through one shared future.
    ``priority`` is the best (lowest) class among members — set at
    open, promoted by joins under the batcher lock."""

    __slots__ = ("members", "future", "closed", "opened_ns", "priority")

    def __init__(self, priority: int = qos.PRIORITY_INTERACTIVE) -> None:
        self.members: List[object] = []
        self.future: Future = Future()
        self.closed = False
        self.opened_ns = time.perf_counter_ns()
        self.priority = int(priority)


@guarded_by("_lock", "_pending", "_active")
class MicroBatcher:
    """Gathers concurrent same-key kernel dispatches into one device
    submission (see module docstring).

    ``submit(key, member, run_batch)`` blocks until the member's result
    is available. ``run_batch(members) -> SplitResult`` executes the
    whole batch; with one member it routes to the single-query kernel
    path (bit-for-bit identical — the batched-vs-unbatched parity test
    pins this)."""

    def __init__(self, gather_window_s: float = 1e-3,
                 max_batch: int = 8, enabled: bool = True,
                 executor: Optional[DeviceExecutor] = None,
                 use_executor: Optional[bool] = None):
        self.gather_window_s = float(gather_window_s)
        self.max_batch = int(max_batch)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._pending: Dict[object, _Pending] = {}
        self._active = 0        # query threads currently inside the backend
        # On an accelerator, ONE thread must own device submission (the
        # async-dispatch pipeline: queueing there is also the natural
        # gather window). On the CPU backend the "device" compute runs
        # inside the dispatch call on whatever thread makes it, GIL-
        # free — funnelling through one executor thread would serialize
        # compute that otherwise runs on multiple cores, so leaders
        # execute inline and gather via a bounded GIL yield instead.
        if use_executor is None:
            import jax
            use_executor = jax.default_backend() != "cpu"
        self.use_executor = bool(use_executor)
        self.executor = executor or DeviceExecutor()
        self.stats = BatchStats()

    # -- concurrency tracking --------------------------------------------
    def enter(self) -> None:
        """A query thread entered the backend (one per periodic_samples)."""
        with self._lock:
            self._active += 1

    def exit(self) -> None:
        with self._lock:
            self._active -= 1

    # -- admission --------------------------------------------------------
    @hot_path
    def submit(self, key: object, member: object,
               run_batch: Callable[[Sequence[object]], SplitResult],
               use_executor: Optional[bool] = None) -> np.ndarray:
        """Join (or open) the batch for ``key``; returns this member's
        split of the batch result.

        ``use_executor`` overrides the batcher-wide executor choice for
        this batch key: mesh-sharded dispatches pass True so ONE thread
        owns multi-device submission even on the CPU backend — a
        sharded program already spans every device, and N query threads
        running sharded programs inline would only oversubscribe the
        per-device compute threads (single-device CPU dispatches keep
        the inline path: there, per-thread execution IS the
        parallelism)."""
        prio = qos.current_priority()
        exec_here = self.use_executor if use_executor is None \
            else bool(use_executor)
        if not self.enabled:
            res = run_batch([member])
            self.stats.record(1, 0, prio)
            obs_metrics.observe("filodb_batcher_queue_wait_seconds",
                                _QWAIT_HELP, 0.0)
            return res.get(0)
        idx = None
        with self._lock:
            p = self._pending.get(key)
            if p is not None and not p.closed \
                    and len(p.members) < self.max_batch:
                idx = len(p.members)
                p.members.append(member)
                # a higher-class join promotes the OPEN batch's class
                # (an already-queued entry keeps its position — the
                # PriorityQueue entry is immutable; see module doc)
                if prio < p.priority:
                    p.priority = prio
            else:
                p = _Pending(priority=prio)
                p.members.append(member)
                concurrent = self._active > 1
                if concurrent:
                    self._pending[key] = p
        if idx is not None:     # follower: park outside the lock
            return self._wait(p, idx)
        if not concurrent:
            # lone request: single-query kernel path, inline — no
            # executor hop, no gather window
            obs_metrics.observe("filodb_batcher_queue_wait_seconds",
                                _QWAIT_HELP, 0.0)
            return self._execute(key, p, run_batch, queued=False)
        if exec_here:
            # leader under concurrency: queue the OPEN batch — arrivals
            # keep joining until the executor picks it up (its busy
            # time is the gather window), then park on the future.
            # The trace context hops threads with the closure so device
            # spans recorded on the executor land in the same trace;
            # the executor queue orders by the batch's priority class.
            tctx = obs_trace.capture()
            self.executor.submit(
                lambda: self._execute(key, p, run_batch, queued=True,
                                      tctx=tctx),
                priority=p.priority)
            return self._wait(p, 0)
        # CPU: gather by yielding the GIL a few times (concurrent
        # same-shape submitters join during the yields; no fixed sleep
        # enters the latency path), then execute on THIS thread so the
        # XLA-CPU compute of independent batches still uses all cores.
        # Best-effort work yields extra rounds under concurrency so
        # interactive threads overtake it at the GIL (there is no
        # dispatch queue to reorder on this path).
        yields = 3 if prio < qos.PRIORITY_BEST_EFFORT else 12
        for _ in range(yields):
            if len(p.members) >= self.max_batch:
                break
            time.sleep(0)
        obs_metrics.observe("filodb_batcher_queue_wait_seconds",
                            _QWAIT_HELP, 0.0)
        return self._execute(key, p, run_batch, queued=False)

    @hot_path
    def _wait(self, p: _Pending, idx: int) -> np.ndarray:
        t0 = time.perf_counter()
        with obs_trace.span("batcher-queue-wait"):
            res = p.future.result()
        obs_metrics.observe("filodb_batcher_queue_wait_seconds",
                            _QWAIT_HELP, time.perf_counter() - t0)
        with obs_trace.span("device-sync"):
            return res.get(idx)

    def _execute(self, key: object, p: _Pending, run_batch,
                 queued: bool, tctx=None) -> np.ndarray:
        """Close + run one batch; on the executor thread when
        ``queued`` (leader parks on the future), inline otherwise."""
        wait_ns = 0
        if queued and self.gather_window_s > 0 and self.executor.idle():
            # idle executor: hold the batch open for the residual
            # explicit gather window so a concurrent same-shape arrival
            # can still pair (skipped entirely when traffic keeps the
            # queue non-empty — batching is already emerging naturally)
            rem_s = self.gather_window_s \
                - (time.perf_counter_ns() - p.opened_ns) / 1e9
            if rem_s > 0 and len(p.members) < self.max_batch:
                t0 = time.perf_counter_ns()
                time.sleep(rem_s)
                wait_ns = time.perf_counter_ns() - t0
        with self._lock:
            p.closed = True
            if self._pending.get(key) is p:
                del self._pending[key]
            members = list(p.members)
            active = self._active
        try:
            # reinstall the submitting thread's trace context when this
            # runs on the executor thread (no-op for tctx=None/inline)
            with obs_trace.use(tctx):
                # batcher occupancy at dispatch (&explain=analyze): how
                # many members shared this device submission and how
                # many query threads were concurrently inside the
                # backend when it closed (no-op event when untraced)
                obs_trace.event("batcher-dispatch", size=len(members),
                                active=active, priority=p.priority,
                                queued=queued)
                res = run_batch(members)
        except BaseException as e:  # noqa: BLE001 — fail all members
            self.stats.record(len(members), wait_ns, p.priority)
            p.future.set_exception(e)
            if not queued:
                raise
            return None
        self.stats.record(len(members), wait_ns, p.priority)
        p.future.set_result(res)
        if queued:
            return None
        with obs_trace.span("device-sync"):
            return res.get(0)
