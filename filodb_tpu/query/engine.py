"""Query execution engine (numpy oracle backend).

Materializes and evaluates LogicalPlans against a set of memstore shards.
This is the single-process analogue of the reference's ExecPlan pipeline
(query/exec/ExecPlan.scala:46, SelectRawPartitionsExec.scala:159,
PeriodicSamplesMapper.scala:61, AggrOverRangeVectors.scala:98,193,
BinaryJoinExec.scala:58, InstantVectorFunctionMapper, ScalarOperationMapper)
— re-shaped around dense [series, steps] grids instead of row iterators.

Every numeric here defines the oracle the TPU backend
(filodb_tpu.query.tpu) must match bit-for-bit modulo float tolerance.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.core.index import ColumnFilter
from filodb_tpu.core.memstore import TimeSeriesShard
from filodb_tpu.core.schemas import ColumnType
from filodb_tpu.memory import histogram as bh
from filodb_tpu.obs import trace as obs_trace
from filodb_tpu.memory.vectors import counter_correction
from filodb_tpu.query import logical as lp
from filodb_tpu.query import rangefn as rf
from filodb_tpu.query.model import (GridResult, QueryError, QueryLimits,
                                    QueryStats, RangeParams, RawSeries,
                                    ScalarResult, StaleRoutingError)

METRIC_LABELS = ("_metric_", "__name__")


def strip_metric(labels: Mapping[str, str]) -> Dict[str, str]:
    return {k: v for k, v in labels.items() if k not in METRIC_LABELS}


# ---------------------------------------------------------------------------
# Raw data selection (SelectRawPartitionsExec)
# ---------------------------------------------------------------------------

def select_raw_series(shards: Sequence[TimeSeriesShard],
                      filters: Sequence[ColumnFilter],
                      start_ms: int, end_ms: int,
                      column: Optional[str] = None,
                      stats: Optional[QueryStats] = None,
                      full: bool = False,
                      limits: Optional[QueryLimits] = None,
                      deadline=None) -> List[RawSeries]:
    """Gather raw samples for all matching series across shards
    (SelectRawPartitionsExec.scala:159 doExecute; schema resolved per
    partition like MultiSchemaPartitionsExec).

    ``full=True`` reads each matched partition's WHOLE series (cached chunk
    decode + buffer tail) and attaches store snapshot keys; the windowing
    path uses this so device tile caches hit across queries — the step grid
    itself restricts the evaluation to the query range."""
    with obs_trace.span("select-series", shards=len(shards)) as _sp:
        out = _select_raw_series(shards, filters, start_ms, end_ms,
                                 column, stats, full, limits, deadline)
        _sp.tag(series=len(out))
        return out


def _select_raw_series(shards, filters, start_ms, end_ms, column, stats,
                       full, limits, deadline) -> List[RawSeries]:
    out: List[RawSeries] = []
    for shard in shards:
        if deadline is not None:
            deadline.check("raw series selection")
        fetch_raw = getattr(shard, "fetch_raw", None)
        if fetch_raw is not None:       # RemoteShardGroup: peer dispatch
            try:
                got = fetch_raw(filters, start_ms, end_ms, column,
                                full=full)
            except StaleRoutingError:
                # NOT a degraded-mode drop: the peer refused because
                # our routing lags a handoff — the entry node must
                # re-resolve and retry, never serve the partial world
                raise
            except QueryError as e:
                # degraded mode: with allow_partial the lost shard group
                # drops out of the result and the response carries a
                # warning naming it; fail-fast (default) re-raises
                if not getattr(shard, "allow_partial", False) \
                        or stats is None:
                    raise
                desc = getattr(shard, "describe", None)
                who = desc() if desc is not None else \
                    f"node {getattr(shard, 'node_id', '?')}"
                stats.partial = True
                stats.warnings.append(
                    f"partial result: {who} unavailable ({e})")
                continue
            for s in got:
                if stats is not None:
                    stats.series_scanned += 1
                    # count the in-range samples, like the local branch —
                    # a full fetch ships the whole retention for caching
                    lo = int(np.searchsorted(s.ts, start_ms, side="left"))
                    hi = int(np.searchsorted(s.ts, end_ms, side="right"))
                    stats.samples_scanned += hi - lo
                    if limits is not None:
                        limits.check(stats)
            out.extend(got)
            continue
        for part in shard.lookup_partitions(filters, start_ms, end_ms):
            schema = part.schema
            col_name = column or schema.value_column
            try:
                ci = [c.name for c in schema.columns].index(col_name)
            except ValueError:
                raise QueryError(
                    f"schema {schema.name} has no column {col_name}")
            col = schema.columns[ci]
            if full:
                ts, vals, chunk_len = part.read_full(ci)
                snap = (shard.ref.dataset, shard.shard_num, part.part_id,
                        part.num_chunks, ci)
            else:
                ts, vals = part.read_range(start_ms, end_ms, ci)
                chunk_len, snap = -1, None
            les = None
            drops = None
            if col.col_type == ColumnType.HISTOGRAM:
                les = part._hist_scheme.les() if part._hist_scheme is not None \
                    else None
                if full and col.is_counter_like:
                    # taken after read_full's snapshot: rows appended in
                    # between may carry drop indices beyond ts.size
                    drops = part.hist_drop_rows(ci)
                    drops = drops[drops < ts.size]
            out.append(RawSeries(
                labels=dict(part.part_key.labels),
                ts=ts, values=vals,
                is_counter=col.is_counter_like,
                bucket_les=les,
                snapshot_key=snap,
                chunk_len=chunk_len if full else -1,
                hist_drop_rows=drops,
            ))
            if stats is not None:
                stats.series_scanned += 1
                if full:
                    lo = int(np.searchsorted(ts, start_ms, side="left"))
                    hi = int(np.searchsorted(ts, end_ms, side="right"))
                    stats.samples_scanned += hi - lo
                else:
                    stats.samples_scanned += int(ts.size)
                if limits is not None:
                    limits.check(stats)     # abort before materializing more
    return out


def select_span_series(shards: Sequence[TimeSeriesShard],
                       filters: Sequence[ColumnFilter],
                       start_ms: int, end_ms: int,
                       column: Optional[str] = None,
                       stats: Optional[QueryStats] = None,
                       limits: Optional[QueryLimits] = None,
                       node_id: str = "", ds: str = "",
                       deadline=None) -> List[RawSeries]:
    """Leaf-dispatch selection: SPAN-BOUNDED reads with node-scoped
    snapshot keys — the SerializedRangeVector analogue
    (core/query/RangeVector.scala:452). The wire payload scales with the
    query span (lookback is already folded into ``start_ms`` by the
    planner), never with retention. Each series carries
    ``snapshot_key = (node, ds, shard, part, num_chunks, col, span)`` and
    ``chunk_len`` = its immutable in-span prefix, so the entry node's
    device tile cache reuses tiles across identical re-fetches while
    write-buffer tail rows are spliced live."""
    with obs_trace.span("select-span", shards=len(shards)) as _sp:
        out = _select_span_series(shards, filters, start_ms, end_ms,
                                  column, stats, limits, node_id, ds,
                                  deadline)
        _sp.tag(series=len(out))
        return out


def _select_span_series(shards, filters, start_ms, end_ms, column,
                        stats, limits, node_id, ds,
                        deadline) -> List[RawSeries]:
    out: List[RawSeries] = []
    for shard in shards:
        if deadline is not None:
            deadline.check("span series selection")
        for part in shard.lookup_partitions(filters, start_ms, end_ms):
            schema = part.schema
            col_name = column or schema.value_column
            try:
                ci = [c.name for c in schema.columns].index(col_name)
            except ValueError:
                raise QueryError(
                    f"schema {schema.name} has no column {col_name}")
            col = schema.columns[ci]
            ts_all, val_all, full_chunk_len = part.read_full(ci)
            lo = int(np.searchsorted(ts_all, start_ms, side="left"))
            hi = int(np.searchsorted(ts_all, end_ms, side="right"))
            ts, vals = ts_all[lo:hi], val_all[lo:hi]
            chunk_len = int(np.clip(full_chunk_len - lo, 0, hi - lo))
            snap = (node_id, ds, shard.shard_num, part.part_id,
                    part.num_chunks, ci, int(start_ms), int(end_ms))
            les = None
            drops = None
            if col.col_type == ColumnType.HISTOGRAM:
                les = part._hist_scheme.les() \
                    if part._hist_scheme is not None else None
                if col.is_counter_like:
                    d = part.hist_drop_rows(ci)
                    d = d[(d >= lo) & (d < hi)] - lo
                    drops = d
            out.append(RawSeries(
                labels=dict(part.part_key.labels),
                ts=ts, values=vals,
                is_counter=col.is_counter_like,
                bucket_les=les,
                snapshot_key=snap,
                chunk_len=chunk_len,
                hist_drop_rows=drops,
            ))
            if stats is not None:
                stats.series_scanned += 1
                stats.samples_scanned += int(ts.size)
                if limits is not None:
                    limits.check(stats)
    return out


def clip_series(series: Sequence[RawSeries], start_ms: int, end_ms: int
                ) -> List[RawSeries]:
    """Restrict each series to samples in [start_ms, end_ms] (views, no
    copies). Used to hand the oracle / general device path only the span a
    window grid can touch, while tile caches keep the full snapshot."""
    out = []
    for s in series:
        lo = int(np.searchsorted(s.ts, start_ms, side="left"))
        hi = int(np.searchsorted(s.ts, end_ms, side="right"))
        if lo == 0 and hi == s.ts.size:
            out.append(s)
        else:
            dr = s.hist_drop_rows
            if dr is not None:
                dr = dr[(dr >= lo) & (dr < hi)] - lo
            out.append(RawSeries(s.labels, s.ts[lo:hi], s.values[lo:hi],
                                 s.is_counter, s.bucket_les,
                                 hist_drop_rows=dr))
    return out


# ---------------------------------------------------------------------------
# Periodic sampling / windowing (PeriodicSamplesMapper)
# ---------------------------------------------------------------------------

def periodic_samples(series: Sequence[RawSeries], params: RangeParams,
                     function: Optional[str], window_ms: int,
                     func_args: Sequence[float] = (),
                     offset_ms: int = 0) -> GridResult:
    """Apply a range function (or lookback last-sample) per series onto the
    step grid (exec/PeriodicSamplesMapper.scala:61; ChunkedWindowIterator
    :223 hot loop, vectorized)."""
    steps = params.steps
    wend = steps - offset_ms
    wstart = wend - window_ms
    func = function or "last_sample"
    s1 = func_args[0] if len(func_args) > 0 else None
    s2 = func_args[1] if len(func_args) > 1 else None

    keys: List[Dict[str, str]] = []
    rows: List[np.ndarray] = []
    hist_rows: List[np.ndarray] = []
    les = None
    any_hist = False
    for s in series:
        if s.values.ndim == 2:
            any_hist = True
            break

    if not any_hist:
        fn = rf.RANGE_FUNCTIONS.get(func)
        if fn is None:
            raise QueryError(f"unknown range function {func}")
        for s in series:
            keys.append(dict(s.labels))
            rows.append(fn(s.ts, s.values, wstart, wend,
                           scalar=s1, scalar2=s2))
        values = np.vstack(rows) if rows else np.zeros((0, steps.size))
        return GridResult(steps, keys, values)

    # histogram path: apply per bucket (HistogramRateFunctionBase,
    # RateFunctions.scala:249; SumOverTimeChunkedFunctionH)
    for s in series:
        keys.append(dict(s.labels))
        if s.values.ndim != 2:
            raise QueryError("mixed histogram/double inputs")
        les = s.bucket_les if s.bucket_les is not None else les
        hist_rows.append(_hist_window(s, func, wstart, wend))
    if hist_rows:
        nb = max(h.shape[1] for h in hist_rows)
        hist_rows = [h if h.shape[1] == nb else
                     np.pad(h, ((0, 0), (0, nb - h.shape[1]), (0, 0)),
                            constant_values=np.nan)
                     for h in hist_rows]
    hv = np.stack(hist_rows) if hist_rows else np.zeros((0, 0, steps.size))
    hv = np.transpose(hv, (0, 2, 1))  # [S, T, NB]
    return GridResult(steps, keys, np.full((len(keys), steps.size), np.nan),
                      hist_values=hv, bucket_les=les)


def _hist_window(s: RawSeries, func: str, wstart, wend) -> np.ndarray:
    """Evaluate a range function over a histogram series, per bucket.
    Returns [NB, T]."""
    ts = s.ts
    mat = s.values  # [n, nb]
    nb = mat.shape[1] if mat.size else 0
    if func in ("rate", "increase"):
        corrected = mat + bh.hist_counter_correction(
            mat, drop_rows=s.hist_drop_rows) if s.is_counter else mat
        out = np.empty((nb, wstart.size))
        lo, hi = rf.window_bounds(ts, wstart, wend)
        counts = hi - lo + 1
        lo_c = np.clip(lo, 0, max(ts.size - 1, 0))
        hi_c = np.clip(hi, 0, max(ts.size - 1, 0))
        for b in range(nb):
            if ts.size == 0:
                out[b] = np.nan
                continue
            out[b] = rf.extrapolated_rate(
                wstart, wend, counts,
                ts[lo_c], corrected[lo_c, b], ts[hi_c], corrected[hi_c, b],
                True, func == "rate")
        return out
    if func in ("sum_over_time", "rate_over_delta", "increase_over_delta"):
        out = np.empty((nb, wstart.size))
        for b in range(nb):
            out[b] = rf.RANGE_FUNCTIONS[
                "sum_over_time" if func != "rate_over_delta" else
                "rate_over_delta"](ts, mat[:, b], wstart, wend)
        return out
    if func == "last_sample":
        out = np.empty((nb, wstart.size))
        for b in range(nb):
            out[b] = rf.RANGE_FUNCTIONS["last_sample"](
                ts, mat[:, b], wstart, wend)
        return out
    raise QueryError(f"range function {func} unsupported for histograms")


# ---------------------------------------------------------------------------
# Aggregations across series (RowAggregator / AggregateMapReduce)
# ---------------------------------------------------------------------------

def _group_keys(keys: List[Dict[str, str]], by: Tuple[str, ...],
                without: Tuple[str, ...]):
    """Group index per series (AggregateMapReduce grouping,
    AggrOverRangeVectors.scala:98)."""
    gids: List[int] = []
    gkeys: List[Dict[str, str]] = []
    seen: Dict[Tuple, int] = {}
    for k in keys:
        k2 = strip_metric(k)
        if by:
            gk = {l: k2[l] for l in by if l in k2}
        elif without:
            gk = {l: v for l, v in k2.items() if l not in without}
        else:
            gk = {}
        key = tuple(sorted(gk.items()))
        gid = seen.setdefault(key, len(seen))
        if gid == len(gkeys):
            gkeys.append(gk)
        gids.append(gid)
    return np.array(gids, dtype=np.int64), gkeys


def aggregate(grid: GridResult, op: str, params: Tuple = (),
              by: Tuple[str, ...] = (), without: Tuple[str, ...] = ()
              ) -> GridResult:
    """Cross-series aggregation on the grid
    (exec/aggregator/*.scala map-reduce-present protocol)."""
    if grid.is_hist() and op == "sum":
        return _aggregate_hist_sum(grid, by, without)
    v = grid.values  # [S, T]
    steps = grid.steps
    if grid.num_series == 0:
        return GridResult(steps, [], np.zeros((0, steps.size)))
    gids, gkeys = _group_keys(grid.keys, tuple(by), tuple(without))
    ng = len(gkeys)
    T = steps.size
    present = ~np.isnan(v)
    vz = np.where(present, v, 0.0)

    def seg(arr):  # segment sum over groups
        out = np.zeros((ng, T))
        np.add.at(out, gids, arr)
        return out

    cnt = seg(present.astype(np.float64))
    none = cnt == 0
    with np.errstate(invalid="ignore", divide="ignore"):
        if op == "sum":
            out = seg(vz)
        elif op == "count":
            out = cnt
        elif op == "avg":
            out = seg(vz) / cnt
        elif op == "group":
            out = np.ones((ng, T))
        elif op in ("min", "max"):
            fill = np.inf if op == "min" else -np.inf
            vf = np.where(present, v, fill)
            out = np.full((ng, T), fill)
            ufunc = np.minimum if op == "min" else np.maximum
            ufunc.at(out, gids, vf)
            out = np.where(np.isinf(out), np.nan, out)
        elif op in ("stddev", "stdvar"):
            s = seg(vz)
            s2 = seg(vz * vz)
            mean = s / cnt
            var = np.maximum(s2 / cnt - mean * mean, 0.0)
            out = var if op == "stdvar" else np.sqrt(var)
        elif op in ("topk", "bottomk"):
            try:
                k = int(params[0])
            except (TypeError, ValueError, IndexError):
                raise QueryError(f"{op} expects a numeric k parameter")
            return _topk(grid, k, gids, gkeys,
                         bottom=(op == "bottomk"))
        elif op == "quantile":
            try:
                q = float(params[0])
            except (TypeError, ValueError, IndexError):
                raise QueryError("quantile expects a numeric parameter")
            out = np.full((ng, T), np.nan)
            for g in range(ng):
                sel = v[gids == g]  # [Sg, T]
                with np.errstate(all="ignore"):
                    out[g] = np.nanquantile(sel, min(max(q, 0), 1), axis=0) \
                        if 0 <= q <= 1 else (np.inf if q > 1 else -np.inf)
        elif op == "count_values":
            return _count_values(grid, str(params[0]), gids, gkeys)
        elif op == "absent":
            out = np.where(cnt == 0, 1.0, np.nan)
            none = np.zeros_like(none)
        else:
            raise QueryError(f"unknown aggregation op {op}")
    out = np.where(none, np.nan, out)
    return GridResult(steps, gkeys, out)


def _aggregate_hist_sum(grid: GridResult, by, without) -> GridResult:
    gids, gkeys = _group_keys(grid.keys, tuple(by), tuple(without))
    ng = len(gkeys)
    hv = grid.hist_values  # [S, T, NB]
    present = ~np.isnan(hv)
    out = np.zeros((ng,) + hv.shape[1:])
    np.add.at(out, gids, np.where(present, hv, 0.0))
    cnt = np.zeros((ng,) + hv.shape[1:])
    np.add.at(cnt, gids, present.astype(np.float64))
    out = np.where(cnt == 0, np.nan, out)
    return GridResult(grid.steps, gkeys,
                      np.full((ng, grid.steps.size), np.nan),
                      hist_values=out, bucket_les=grid.bucket_les)


def _topk(grid: GridResult, k: int, gids, gkeys, bottom: bool) -> GridResult:
    """topk/bottomk: per step, keep k best series per group; output is the
    union of selected series with NaN elsewhere (TopBottomK aggregator)."""
    v = grid.values
    S, T = v.shape
    out_rows: List[np.ndarray] = []
    out_keys: List[Dict[str, str]] = []
    for g in range(len(gkeys)):
        idx = np.where(gids == g)[0]
        sub = v[idx]  # [Sg, T]
        score = np.where(np.isnan(sub), -np.inf if not bottom else np.inf, sub)
        order = np.argsort(-score if not bottom else score, axis=0,
                           kind="stable")
        keep = np.zeros_like(sub, dtype=bool)
        kk = min(k, sub.shape[0])
        cols = np.arange(T)
        for r in range(kk):
            keep[order[r], cols] = True
        keep &= ~np.isnan(sub)
        for i, si in enumerate(idx):
            if keep[i].any():
                out_keys.append(dict(grid.keys[si]))
                out_rows.append(np.where(keep[i], sub[i], np.nan))
    values = np.vstack(out_rows) if out_rows else np.zeros((0, T))
    return GridResult(grid.steps, out_keys, values)


def _count_values(grid: GridResult, label: str, gids, gkeys) -> GridResult:
    v = grid.values
    T = grid.steps.size
    buckets: Dict[Tuple[int, str], np.ndarray] = {}
    for s in range(v.shape[0]):
        g = gids[s]
        for t in range(T):
            x = v[s, t]
            if np.isnan(x):
                continue
            key = (g, repr(float(x)) if x != int(x) else str(int(x)))
            row = buckets.setdefault(key, np.zeros(T))
            row[t] += 1
    keys_out: List[Dict[str, str]] = []
    rows = []
    for (g, val), row in sorted(buckets.items(), key=lambda kv: kv[0][1]):
        k = dict(gkeys[g])
        k[label] = val
        keys_out.append(k)
        rows.append(np.where(row == 0, np.nan, row))
    values = np.vstack(rows) if rows else np.zeros((0, T))
    return GridResult(grid.steps, keys_out, values)


# ---------------------------------------------------------------------------
# Binary operations (BinaryJoinExec, SetOperatorExec, ScalarOperationMapper)
# ---------------------------------------------------------------------------

_ARITH = {
    "+": np.add, "-": np.subtract, "*": np.multiply,
    "/": np.divide, "^": np.power,
}
_COMP = {
    "==": np.equal, "!=": np.not_equal, ">": np.greater,
    "<": np.less, ">=": np.greater_equal, "<=": np.less_equal,
}


def _apply_op(op: str, a, b, return_bool: bool):
    with np.errstate(all="ignore"):
        if op in _ARITH:
            return _ARITH[op](a, b)
        if op == "%":
            return np.fmod(a, b)
        if op == "atan2":
            return np.arctan2(a, b)
        if op in _COMP:
            m = _COMP[op](a, b)
            if return_bool:
                out = m.astype(np.float64)
                nan = np.isnan(a) | np.isnan(b)
                return np.where(nan, np.nan, out)
            return np.where(m, a, np.nan)
    raise QueryError(f"unknown binary op {op}")


def scalar_vector_op(grid: GridResult, scalar, op: str, scalar_is_lhs: bool,
                     return_bool: bool = False) -> GridResult:
    """(exec/RangeVectorTransformer.scala:201 ScalarOperationMapper).

    A FILTERING comparison (no ``bool``) always retains the VECTOR
    side's sample values regardless of operand order — ``10 < foo``
    keeps foo's values, not a broadcast 10. The generic ``_apply_op``
    filter keeps its left operand, which is only correct when the
    vector IS the left operand; pinned by the promql differential
    rail (test_pinned_scalar_lhs_comparison_filter)."""
    sv = scalar.values if isinstance(scalar, ScalarResult) else scalar
    a, b = (sv, grid.values) if scalar_is_lhs else (grid.values, sv)
    if op in _COMP and not return_bool:
        with np.errstate(all="ignore"):
            m = _COMP[op](a, b)
        out = np.where(m, grid.values, np.nan)
    else:
        out = _apply_op(op, a, b, return_bool)
    keys = [strip_metric(k) for k in grid.keys]
    return GridResult(grid.steps, keys, out)


def _join_key(labels: Mapping[str, str], on: Optional[Tuple[str, ...]],
              ignoring: Tuple[str, ...]) -> Tuple:
    l2 = strip_metric(labels)
    if on is not None:
        return tuple(sorted((k, v) for k, v in l2.items() if k in on))
    return tuple(sorted((k, v) for k, v in l2.items() if k not in ignoring))


def binary_join(lhs: GridResult, rhs: GridResult, op: str,
                cardinality: str = "one-to-one",
                on: Optional[Tuple[str, ...]] = None,
                ignoring: Tuple[str, ...] = (),
                include: Tuple[str, ...] = (),
                return_bool: bool = False) -> GridResult:
    """Vector-vector binary operation with label matching
    (exec/BinaryJoinExec.scala:58; set ops SetOperatorExec.scala:32)."""
    steps = lhs.steps
    if op in ("and", "or", "unless"):
        return _set_op(lhs, rhs, op, on, ignoring)

    # grouped joins: evaluate in-place with the ORIGINAL operand order —
    # swapping sides is wrong for non-commutative ops (-,/,^,%,atan2) —
    # output labels come from the "many" side (group_left: lhs is many,
    # group_right: rhs is many), include labels copied from the "one" side.
    if cardinality in ("many-to-one", "one-to-many"):
        many, one = ((lhs, rhs) if cardinality == "many-to-one"
                     else (rhs, lhs))
        omap: Dict[Tuple, int] = {}
        for j, k in enumerate(one.keys):
            key = _join_key(k, on, ignoring)
            if key in omap:
                raise QueryError(
                    "many-to-many join: duplicate series on 'one' side")
            omap[key] = j
        out_keys = []
        rows = []
        for i, k in enumerate(many.keys):
            key = _join_key(k, on, ignoring)
            j = omap.get(key)
            if j is None:
                continue
            if cardinality == "many-to-one":
                a, b = lhs.values[i], rhs.values[j]
            else:
                a, b = lhs.values[j], rhs.values[i]
            out = _apply_op(op, a, b, return_bool)
            labels = dict(strip_metric(k))
            for l in include:
                if l in one.keys[j]:
                    labels[l] = one.keys[j][l]
                else:
                    labels.pop(l, None)
            rows.append(out)
            out_keys.append(labels)
        values = np.vstack(rows) if rows else np.zeros((0, steps.size))
        return GridResult(steps, out_keys, values)

    rmap: Dict[Tuple, List[int]] = {}
    for j, k in enumerate(rhs.keys):
        rmap.setdefault(_join_key(k, on, ignoring), []).append(j)
    for key, js in rmap.items():
        if len(js) > 1:
            raise QueryError(
                "many-to-many join: duplicate series on right side")
    out_keys: List[Dict[str, str]] = []
    rows: List[np.ndarray] = []
    seen_left: Dict[Tuple, int] = {}
    for i, k in enumerate(lhs.keys):
        key = _join_key(k, on, ignoring)
        js = rmap.get(key)
        if not js:
            continue
        if key in seen_left:
            raise QueryError(
                "many-to-many join: duplicate series on left side")
        seen_left[key] = i
        j = js[0]
        a, b = lhs.values[i], rhs.values[j]
        out = _apply_op(op, a, b, return_bool)
        rows.append(out)
        out_keys.append(dict(strip_metric(k)))
    values = np.vstack(rows) if rows else np.zeros((0, steps.size))
    return GridResult(steps, out_keys, values)


def _set_op(lhs: GridResult, rhs: GridResult, op: str,
            on: Optional[Tuple[str, ...]], ignoring: Tuple[str, ...]
            ) -> GridResult:
    rkeys = {_join_key(k, on, ignoring): j for j, k in enumerate(rhs.keys)}
    steps = lhs.steps
    keys_out: List[Dict[str, str]] = []
    rows: List[np.ndarray] = []
    if op == "and":
        for i, k in enumerate(lhs.keys):
            j = rkeys.get(_join_key(k, on, ignoring))
            if j is None:
                continue
            mask = ~np.isnan(rhs.values[j])
            keys_out.append(dict(k))
            rows.append(np.where(mask, lhs.values[i], np.nan))
    elif op == "unless":
        for i, k in enumerate(lhs.keys):
            j = rkeys.get(_join_key(k, on, ignoring))
            row = lhs.values[i]
            if j is not None:
                row = np.where(np.isnan(rhs.values[j]), row, np.nan)
            keys_out.append(dict(k))
            rows.append(row)
    elif op == "or":
        lkeys = set()
        for i, k in enumerate(lhs.keys):
            lkeys.add(_join_key(k, on, ignoring))
            keys_out.append(dict(k))
            rows.append(lhs.values[i])
        for j, k in enumerate(rhs.keys):
            if _join_key(k, on, ignoring) not in lkeys:
                keys_out.append(dict(k))
                rows.append(rhs.values[j])
    values = np.vstack(rows) if rows else np.zeros((0, steps.size))
    return GridResult(steps, keys_out, values)


# ---------------------------------------------------------------------------
# Instant functions (rangefn/InstantFunction.scala)
# ---------------------------------------------------------------------------

_INSTANT_UNARY = {
    "abs": np.abs, "ceil": np.ceil, "floor": np.floor, "exp": np.exp,
    "ln": np.log, "log2": np.log2, "log10": np.log10, "sqrt": np.sqrt,
    "round": None, "sgn": np.sign,
    "acos": np.arccos, "asin": np.arcsin, "atan": np.arctan, "cos": np.cos,
    "cosh": np.cosh, "sin": np.sin, "sinh": np.sinh, "tan": np.tan,
    "tanh": np.tanh, "deg": np.degrees, "rad": np.radians,
}


def instant_function(grid: GridResult, func: str,
                     args: Sequence[float] = ()) -> GridResult:
    """(exec/RangeVectorTransformer.scala:62 InstantVectorFunctionMapper)."""
    keys = [strip_metric(k) for k in grid.keys]
    with np.errstate(all="ignore"):
        if func == "histogram_quantile":
            return histogram_quantile(grid, float(args[0]))
        if func == "histogram_bucket":
            return histogram_bucket(grid, float(args[0]))
        if func == "histogram_max_quantile":
            return histogram_quantile(grid, float(args[0]))
        if func in _INSTANT_UNARY:
            if func == "round":
                to_nearest = float(args[0]) if args else 1.0
                out = np.floor(grid.values / to_nearest + 0.5) * to_nearest
            else:
                out = _INSTANT_UNARY[func](grid.values)
            return GridResult(grid.steps, keys, out)
        if func == "clamp":
            out = np.clip(grid.values, float(args[0]), float(args[1]))
            return GridResult(grid.steps, keys, out)
        if func == "clamp_min":
            return GridResult(grid.steps, keys,
                              np.maximum(grid.values, float(args[0])))
        if func == "clamp_max":
            return GridResult(grid.steps, keys,
                              np.minimum(grid.values, float(args[0])))
        if func in ("days_in_month", "day_of_month", "day_of_week",
                    "day_of_year", "hour", "minute", "month", "year"):
            return _time_component(grid, func, keys)
    raise QueryError(f"unknown instant function {func}")


def _time_component(grid: GridResult, func: str, keys) -> GridResult:
    import datetime as dt
    v = grid.values
    out = np.full_like(v, np.nan)
    it = np.nditer(v, flags=["multi_index"])
    for x in it:
        if np.isnan(x):
            continue
        d = dt.datetime.fromtimestamp(float(x), dt.timezone.utc)
        out[it.multi_index] = {
            "days_in_month": ((d.replace(month=d.month % 12 + 1, day=1,
                                         year=d.year + d.month // 12)
                               - dt.timedelta(days=1)).day),
            "day_of_month": d.day,
            "day_of_week": (d.weekday() + 1) % 7,
            "day_of_year": d.timetuple().tm_yday,
            "hour": d.hour,
            "minute": d.minute,
            "month": d.month,
            "year": d.year,
        }[func]
    return GridResult(grid.steps, keys, out)


def histogram_quantile(grid: GridResult, q: float) -> GridResult:
    """histogram_quantile over native histogram columns — vectorized over
    [S, T] (InstantFunction.scala HistogramQuantileImpl; bucket math
    memory/format/vectors/Histogram.scala quantile). Non-histogram input
    falls back to the classic per-bucket `le`-series join
    (exec/HistogramQuantileMapper.scala)."""
    if not grid.is_hist():
        return _quantile_over_le_series(grid, q)
    hv = grid.hist_values  # [S, T, NB]
    les = np.asarray(grid.bucket_les, dtype=np.float64)
    S, T, NB = hv.shape
    out = np.full((S, T), np.nan)
    for s in range(S):
        for t in range(T):
            col = hv[s, t]
            if np.isnan(col[-1]):
                continue
            out[s, t] = bh.quantile(q, les, col)
    keys = [strip_metric(k) for k in grid.keys]
    return GridResult(grid.steps, keys, out)


def _quantile_over_le_series(grid: GridResult, q: float) -> GridResult:
    """histogram_quantile over classic per-bucket prom series: join series
    sharing all labels except `le` into one cumulative histogram per step
    (exec/HistogramQuantileMapper.scala — sorts bucket RVs by le, enforces
    monotonicity like Prometheus' ensureMonotonic, then bucket math)."""
    groups: Dict[Tuple, List[Tuple[float, int]]] = {}
    for i, k in enumerate(grid.keys):
        le_s = k.get("le")
        if le_s is None:
            continue        # non-bucket series are ignored (reference too)
        try:
            le = float(le_s.replace("+Inf", "inf")) \
                if isinstance(le_s, str) else float(le_s)
        except ValueError:
            continue
        base = tuple(sorted((kk, v) for kk, v in strip_metric(k).items()
                            if kk != "le"))
        groups.setdefault(base, []).append((le, i))
    if not groups:
        raise QueryError("histogram_quantile requires histogram input or "
                         "per-bucket series with an 'le' label")
    T = grid.steps.size
    out_keys: List[Dict[str, str]] = []
    rows: List[np.ndarray] = []
    for base, members in groups.items():
        members.sort(key=lambda m: m[0])
        les = np.array([m[0] for m in members])
        mat = grid.values[[m[1] for m in members]]   # [NB, T] cumulative
        vals = np.full(T, np.nan)
        for t in range(T):
            col = mat[:, t]
            present = ~np.isnan(col)     # a stale bucket series at this
            if not present.any():        # step doesn't poison the rest
                continue
            lc = les[present]
            if not np.isposinf(lc[-1]):
                continue    # no +Inf bucket sample: NaN (Prometheus)
            # Prometheus tolerates tiny non-monotonicity from float
            # noise / scrape skew: running max down the buckets
            vals[t] = bh.quantile(q, lc,
                                  np.maximum.accumulate(col[present]))
        out_keys.append(dict(base))
        rows.append(vals)
    values = np.vstack(rows) if rows else np.zeros((0, T))
    return GridResult(grid.steps, out_keys, values)


def histogram_bucket(grid: GridResult, le: float) -> GridResult:
    if not grid.is_hist():
        raise QueryError("histogram_bucket requires histogram input")
    les = np.asarray(grid.bucket_les, dtype=np.float64)
    idx = np.where(les == le)[0]
    keys = [strip_metric(k) for k in grid.keys]
    if idx.size == 0:
        return GridResult(grid.steps, keys,
                          np.full(grid.hist_values.shape[:2], np.nan))
    return GridResult(grid.steps, keys, grid.hist_values[:, :, idx[0]])


# ---------------------------------------------------------------------------
# Miscellaneous functions (MiscellaneousFunction.scala)
# ---------------------------------------------------------------------------

def label_replace(grid: GridResult, dst: str, repl: str, src: str,
                  regex: str) -> GridResult:
    try:
        pat = re.compile(regex)
    except re.error as e:
        raise QueryError(f"invalid regex: {e}")
    keys = []
    for k in grid.keys:
        k = dict(k)
        val = k.get(src, "")
        m = pat.fullmatch(val)
        if m:
            new = m.expand(_promql_template(repl))
            if new:
                k[dst] = new
            else:
                k.pop(dst, None)
        keys.append(k)
    return GridResult(grid.steps, keys, grid.values, grid.hist_values,
                      grid.bucket_les)


def _promql_template(repl: str) -> str:
    # PromQL uses $1; python re.expand uses \1
    return re.sub(r"\$(\d+)", r"\\\1", repl)


def label_join(grid: GridResult, dst: str, sep: str,
               srcs: Sequence[str]) -> GridResult:
    keys = []
    for k in grid.keys:
        k = dict(k)
        k[dst] = sep.join(k.get(s, "") for s in srcs)
        keys.append(k)
    return GridResult(grid.steps, keys, grid.values, grid.hist_values,
                      grid.bucket_les)


def sort_grid(grid: GridResult, descending: bool) -> GridResult:
    """sort()/sort_desc(): order series by value of last step
    (SortFunctionMapper :297)."""
    if grid.num_series == 0:
        return grid
    lastv = grid.values[:, -1]
    score = np.where(np.isnan(lastv), -np.inf if not descending else np.inf,
                     lastv)
    order = np.argsort(-score if descending else score, kind="stable")
    return GridResult(grid.steps, [grid.keys[i] for i in order],
                      grid.values[order])


def limit_grid(grid: GridResult, limit: int) -> GridResult:
    if limit <= 0 or grid.num_series <= limit:
        return grid
    return GridResult(grid.steps, grid.keys[:limit], grid.values[:limit],
                      None if grid.hist_values is None
                      else grid.hist_values[:limit], grid.bucket_les)


def absent_fn(grid: GridResult, filters: Sequence[ColumnFilter],
              steps: np.ndarray) -> GridResult:
    """absent(): 1 where no series has a value (AbsentFunctionMapper :420).
    Output labels come from equality filters (Prometheus semantics)."""
    if grid.num_series == 0:
        present = np.zeros(steps.size, dtype=bool)
    else:
        present = (~np.isnan(grid.values)).any(axis=0)
    out = np.where(present, np.nan, 1.0)
    labels = {f.label: f.value for f in filters
              if f.op == "eq" and f.label not in METRIC_LABELS}
    if present.all():
        return GridResult(steps, [], np.zeros((0, steps.size)))
    return GridResult(steps, [labels], out[None, :])


# ---------------------------------------------------------------------------
# Scalar plans
# ---------------------------------------------------------------------------

def eval_scalar(plan, engine) -> ScalarResult:
    if isinstance(plan, lp.ScalarFixedDoublePlan):
        steps = RangeParams(plan.start_ms, plan.step_ms, plan.end_ms).steps
        return ScalarResult(steps, np.full(steps.size, plan.value))
    if isinstance(plan, lp.ScalarTimeBasedPlan):
        steps = RangeParams(plan.start_ms, plan.step_ms, plan.end_ms).steps
        if plan.function == "time":
            return ScalarResult(steps, steps / 1000.0)
        raise QueryError(f"unknown scalar time function {plan.function}")
    if isinstance(plan, lp.ScalarVaryingDoublePlan):
        grid = engine.execute(plan.inner)
        # scalar(v): value when exactly one series, else NaN — per step
        if grid.num_series == 1:
            vals = grid.values[0]
        elif grid.num_series == 0:
            vals = np.full(grid.steps.size, np.nan)
        else:
            cnt = (~np.isnan(grid.values)).sum(axis=0)
            vals = np.where(cnt == 1, np.nansum(grid.values, axis=0), np.nan)
        return ScalarResult(grid.steps, vals)
    if isinstance(plan, lp.ScalarBinaryOperation):
        def side(x):
            if isinstance(x, (int, float)):
                return float(x)
            return eval_scalar(x, engine).values
        a, b = side(plan.lhs), side(plan.rhs)
        out = _apply_op(plan.op, a, b, return_bool=True) \
            if plan.op in _COMP else _apply_op(plan.op, a, b, False)
        steps = RangeParams(plan.start_ms, plan.step_ms, plan.end_ms).steps
        if np.isscalar(out) or out.ndim == 0:
            out = np.full(steps.size, float(out))
        return ScalarResult(steps, out)
    raise QueryError(f"not a scalar plan: {plan}")


# ---------------------------------------------------------------------------
# The engine: logical plan walker
# ---------------------------------------------------------------------------

class QueryEngine:
    """Evaluates LogicalPlans against shards (single-process oracle).

    The distributed path (filodb_tpu.parallel) re-uses these primitives with
    per-shard leaf evaluation + mesh reductions."""

    def __init__(self, shards: Sequence[TimeSeriesShard],
                 backend: Optional[object] = None,
                 limits: Optional[QueryLimits] = None):
        self.shards = list(shards)
        self.stats = QueryStats()
        self.backend = backend  # TPU backend hook (query/tpu.py)
        self.limits = limits    # per-query guardrails (None = off)

    # -- public ----------------------------------------------------------
    def execute(self, plan):
        if lp.is_scalar_plan(plan):
            return eval_scalar(plan, self)
        # metadata plans read local tag indexes only; cross-node metadata
        # is unioned at the HTTP layer (peer fan-out)
        local = [s for s in self.shards if not hasattr(s, "fetch_raw")]
        if isinstance(plan, lp.LabelValues):
            vals: set = set()
            for s in local:
                vals.update(s.index.label_values(
                    plan.label, plan.filters, plan.start_ms, plan.end_ms))
            return sorted(vals)
        if isinstance(plan, lp.LabelNames):
            names: set = set()
            for s in local:
                names.update(s.index.label_names(
                    plan.filters, plan.start_ms, plan.end_ms))
            return sorted(names)
        if isinstance(plan, lp.SeriesKeysByFilters):
            out = []
            for s in local:
                for pid in s.index.part_ids_from_filters(
                        plan.filters, plan.start_ms, plan.end_ms):
                    out.append(dict(s.index.labels_for(pid)))
            return out
        if isinstance(plan, lp.TsCardinalities):
            from filodb_tpu.core.cardinality import merge_records
            per = []
            for s in local:
                tracker = getattr(s, "card_tracker", None)
                if tracker is not None:
                    per.append(tracker.scan(plan.shard_key_prefix,
                                            plan.num_groups))
            return merge_records(per)
        return self._eval(plan)

    # -- vector evaluation ------------------------------------------------
    def _eval(self, plan) -> GridResult:
        if isinstance(plan, lp.PeriodicSeries):
            if plan.at_ms is not None:
                return self._at_pinned(plan.raw, plan.at_ms, None,
                                       plan.lookback_ms, (), plan.offset_ms,
                                       plan.start_ms, plan.step_ms,
                                       plan.end_ms)
            return self._periodic(plan.raw, plan.start_ms, plan.step_ms,
                                  plan.end_ms, None, plan.lookback_ms, (),
                                  plan.offset_ms)
        if isinstance(plan, lp.PeriodicSeriesWithWindowing):
            if plan.at_ms is not None:
                return self._at_pinned(plan.raw, plan.at_ms, plan.function,
                                       plan.window_ms, plan.func_args,
                                       plan.offset_ms, plan.start_ms,
                                       plan.step_ms, plan.end_ms)
            return self._periodic(plan.raw, plan.start_ms, plan.step_ms,
                                  plan.end_ms, plan.function, plan.window_ms,
                                  plan.func_args, plan.offset_ms)
        if isinstance(plan, lp.SubqueryWithWindowing):
            return self._subquery(plan)
        if isinstance(plan, lp.TopLevelSubquery):
            return self._eval(plan.inner)
        if isinstance(plan, lp.Aggregate):
            fused = self._try_fused_agg(plan)
            if fused is not None:
                return fused
            inner = self._eval(plan.inner)
            return aggregate(inner, plan.op, plan.params, tuple(plan.by),
                             tuple(plan.without))
        if isinstance(plan, lp.BinaryJoin):
            lhs = self._eval(plan.lhs)
            rhs = self._eval(plan.rhs)
            return binary_join(lhs, rhs, plan.op, plan.cardinality, plan.on,
                               plan.ignoring, plan.include, plan.return_bool)
        if isinstance(plan, lp.ScalarVectorBinaryOperation):
            grid = self._eval(plan.vector)
            scalar = eval_scalar(plan.scalar, self)
            return scalar_vector_op(grid, scalar, plan.op, plan.scalar_is_lhs,
                                    plan.return_bool)
        if isinstance(plan, lp.ApplyInstantFunction):
            grid = self._eval(plan.inner)
            args = [eval_scalar(a, self).values[0] if not isinstance(
                a, (int, float)) else a for a in plan.func_args]
            return instant_function(grid, plan.function, args)
        if isinstance(plan, lp.ApplyMiscellaneousFunction):
            grid = self._eval(plan.inner)
            if plan.function == "label_replace":
                return label_replace(grid, *plan.str_args)
            if plan.function == "label_join":
                dst, sep, *srcs = plan.str_args
                return label_join(grid, dst, sep, srcs)
            raise QueryError(f"unknown misc function {plan.function}")
        if isinstance(plan, lp.ApplySortFunction):
            return sort_grid(self._eval(plan.inner), plan.descending)
        if isinstance(plan, lp.ApplyLimitFunction):
            return limit_grid(self._eval(plan.inner), plan.limit)
        if isinstance(plan, lp.ApplyAbsentFunction):
            grid = self._eval(plan.inner)
            steps = RangeParams(plan.start_ms, plan.step_ms, plan.end_ms).steps
            return absent_fn(grid, plan.filters, steps)
        if isinstance(plan, lp.VectorPlan):
            sc = eval_scalar(plan.scalar, self)
            return GridResult(sc.steps, [{}], sc.values[None, :])
        if isinstance(plan, lp.RawSeriesPlan):
            # raw export (query endpoint with [range] at top level)
            series = select_raw_series(self.shards, plan.filters,
                                       plan.start_ms, plan.end_ms,
                                       plan.column, self.stats,
                                       limits=self.limits)
            return series
        raise QueryError(f"cannot execute plan {type(plan).__name__}")

    def _try_fused_agg(self, plan) -> Optional[GridResult]:
        """`sum/avg/count by (g) (rate/increase/delta(sel[w]))` fused
        end-to-end on device: grouping happens inside the Pallas
        group-sum kernel and the [S, T] per-series intermediate never
        exists (exec/AggrOverRangeVectors map-reduce, fused).

        None is returned only for plan SHAPES this path doesn't own;
        once the series are selected, any kernel ineligibility
        (irregular cadence, tail data, histograms, non-divisible grid)
        falls back to rangefn + aggregate() over the SAME selection —
        never a second fetch (remote shard groups pull raw series over
        the wire) or double-counted stats."""
        if self.backend is None or plan.op not in ("sum", "count", "avg"):
            return None
        if plan.params:
            return None
        inner = plan.inner
        if not isinstance(inner, lp.PeriodicSeriesWithWindowing):
            return None
        if inner.at_ms is not None or inner.func_args or \
                inner.function not in ("rate", "increase", "delta"):
            return None
        raw = inner.raw
        if not isinstance(raw, lp.RawSeriesPlan):
            return None
        fetch_start = inner.start_ms - inner.window_ms - inner.offset_ms
        fetch_end = (inner.end_ms - inner.offset_ms if inner.offset_ms
                     else inner.end_ms)
        series = select_raw_series(
            self.shards, raw.filters, fetch_start, fetch_end, raw.column,
            self.stats, full=True, limits=self.limits)
        params = RangeParams(inner.start_ms, inner.step_ms, inner.end_ms)
        res = None
        if series and not any(s.values.ndim == 2 for s in series):
            keys = [dict(s.labels) for s in series]
            gids, gkeys = _group_keys(keys, tuple(plan.by),
                                      tuple(plan.without))
            res = self.backend.fused_groupsum(
                series, inner.function, params.steps, inner.window_ms,
                inner.offset_ms, gids, len(gkeys))
        if res is not None:
            sums, cnts = res                       # [T, G]
            cnt = cnts.T.astype(np.float64)        # [G, T]
            with np.errstate(invalid="ignore", divide="ignore"):
                if plan.op == "sum":
                    out = sums.T.astype(np.float64)
                elif plan.op == "count":
                    out = cnt.copy()
                else:
                    out = sums.T.astype(np.float64) / cnt
            out = np.where(cnt == 0, np.nan, out)
            return GridResult(params.steps, gkeys, out)
        # general path over the already-selected series
        grid = None
        if self.backend is not None:
            grid = self.backend.periodic_samples(
                series, params, inner.function, inner.window_ms, (),
                inner.offset_ms)
        if grid is None:
            grid = periodic_samples(
                clip_series(series, fetch_start, fetch_end), params,
                inner.function, inner.window_ms, (), inner.offset_ms)
        return aggregate(grid, plan.op, (), tuple(plan.by),
                         tuple(plan.without))

    def _periodic(self, raw: lp.RawSeriesPlan, start_ms, step_ms, end_ms,
                  function, window_ms, func_args, offset_ms) -> GridResult:
        fetch_start = start_ms - window_ms - offset_ms
        fetch_end = end_ms - offset_ms if offset_ms else end_ms
        series = select_raw_series(
            self.shards, raw.filters, fetch_start, fetch_end, raw.column,
            self.stats, full=True, limits=self.limits)
        params = RangeParams(start_ms, step_ms, end_ms)
        if self.backend is not None and function is not None:
            out = self.backend.periodic_samples(
                series, params, function, window_ms, func_args, offset_ms)
            if out is not None:
                return out
        # oracle fallback: evaluate only over the span the grid can touch
        return periodic_samples(clip_series(series, fetch_start, fetch_end),
                                params, function, window_ms,
                                func_args, offset_ms)

    def _at_pinned(self, raw: lp.RawSeriesPlan, at_ms: int, function,
                   window_ms, func_args, offset_ms, start_ms, step_ms,
                   end_ms) -> GridResult:
        """`@` modifier: evaluate the selector once at the pinned instant
        (window ends at at_ms - offset) and broadcast that value across the
        whole step grid — Prometheus @-modifier semantics. `_periodic`
        derives fetch bounds from its grid, so pinning the grid to [at_ms]
        also fetches the right data range even when at_ms lies far outside
        [start, end]."""
        one = self._periodic(raw, at_ms, 0, at_ms, function, window_ms,
                             func_args, offset_ms)
        steps = RangeParams(start_ms, step_ms, end_ms).steps
        values = np.repeat(one.values, steps.size, axis=1) \
            if one.num_series else np.zeros((0, steps.size))
        hv = None
        if one.is_hist():
            hv = np.repeat(one.hist_values, steps.size, axis=1)
        return GridResult(steps, one.keys, values, hist_values=hv,
                          bucket_les=one.bucket_les)

    def _subquery(self, plan: lp.SubqueryWithWindowing) -> GridResult:
        """func(expr[w:s]): evaluate inner on the subquery grid, then window
        over the inner steps (SubqueryWithWindowing semantics). With @ the
        subquery grid is pinned to at_ms and every outer step carries the
        pinned value (LogicalPlan.scala:349, ast/SubqueryUtils)."""
        steps = RangeParams(plan.start_ms, plan.step_ms, plan.end_ms).steps
        if plan.at_ms is not None:
            pin_end = plan.at_ms
            inner_start = pin_end - plan.window_ms - plan.offset_ms
            sub = lp_replace_range(plan.inner, inner_start,
                                   plan.sub_step_ms,
                                   pin_end - plan.offset_ms)
            inner = self._eval(sub)
            wend = np.array([pin_end - plan.offset_ms], dtype=np.int64)
            wstart = wend - plan.window_ms
            one = self._subquery_windows(plan, inner,
                                         np.array([pin_end]), wstart, wend)
            values = np.repeat(one.values, steps.size, axis=1)
            return GridResult(steps, one.keys, values)
        # the offset shifts which inner times the outer windows read:
        # the inner grid must cover [start - offset - window, end - offset]
        inner_start = plan.start_ms - plan.window_ms - plan.offset_ms
        inner_end = (plan.end_ms - plan.offset_ms if plan.offset_ms
                     else plan.end_ms)
        sub = lp_replace_range(plan.inner, inner_start, plan.sub_step_ms,
                               inner_end)
        inner = self._eval(sub)
        wend = steps - plan.offset_ms
        wstart = wend - plan.window_ms
        return self._subquery_windows(plan, inner, steps, wstart, wend)

    def _subquery_windows(self, plan, inner, steps, wstart, wend
                          ) -> GridResult:
        fn = rf.RANGE_FUNCTIONS.get(plan.function)
        if fn is None:
            raise QueryError(f"unknown range function {plan.function}")
        s1 = plan.func_args[0] if len(plan.func_args) > 0 else None
        s2 = plan.func_args[1] if len(plan.func_args) > 1 else None
        rows = []
        for i in range(inner.num_series):
            m = ~np.isnan(inner.values[i])
            rows.append(fn(inner.steps[m], inner.values[i][m], wstart, wend,
                           scalar=s1, scalar2=s2))
        values = np.vstack(rows) if rows else np.zeros((0, steps.size))
        return GridResult(steps, [dict(k) for k in inner.keys], values)


def lp_replace_range(plan, start_ms: int, step_ms: int, end_ms: int):
    """Rewrite a plan's evaluation range (used for subqueries and the
    raw/downsample tier split)."""
    import dataclasses
    if isinstance(plan, (lp.PeriodicSeries, lp.PeriodicSeriesWithWindowing)):
        # raw fetch bounds mirror the parser: the window AND the offset
        # shift what data a step can touch (promql/parser.py selector
        # materialization)
        raw = dataclasses.replace(
            plan.raw,
            start_ms=start_ms - _plan_window(plan) - plan.offset_ms,
            end_ms=end_ms - plan.offset_ms if plan.offset_ms else end_ms)
        return dataclasses.replace(plan, raw=raw, start_ms=start_ms,
                                   step_ms=step_ms, end_ms=end_ms)
    if isinstance(plan, (lp.Aggregate, lp.ApplyInstantFunction,
                         lp.ApplyMiscellaneousFunction, lp.ApplySortFunction,
                         lp.ApplyLimitFunction, lp.ScalarVaryingDoublePlan,
                         lp.ApplyAbsentFunction)):
        changes = {"inner": lp_replace_range(plan.inner, start_ms, step_ms,
                                             end_ms)}
        if isinstance(plan, lp.ApplyAbsentFunction):
            changes.update(start_ms=start_ms, step_ms=step_ms, end_ms=end_ms)
        return dataclasses.replace(plan, **changes)
    if isinstance(plan, lp.BinaryJoin):
        return dataclasses.replace(
            plan,
            lhs=lp_replace_range(plan.lhs, start_ms, step_ms, end_ms),
            rhs=lp_replace_range(plan.rhs, start_ms, step_ms, end_ms))
    if isinstance(plan, lp.ScalarVectorBinaryOperation):
        return dataclasses.replace(
            plan,
            scalar=lp_replace_range(plan.scalar, start_ms, step_ms, end_ms),
            vector=lp_replace_range(plan.vector, start_ms, step_ms, end_ms))
    if isinstance(plan, lp.SubqueryWithWindowing):
        # rebase the subquery's OUTER grid only; its inner expression is
        # rebased by _subquery at eval time from these bounds. Without
        # this case a NESTED subquery kept its parse-time grid and the
        # enclosing subquery windowed over a truncated inner range —
        # found by the promql differential rail (pinned:
        # test_pinned_nested_subquery_rebase)
        return dataclasses.replace(plan, start_ms=start_ms,
                                   step_ms=step_ms, end_ms=end_ms)
    if isinstance(plan, (lp.ScalarTimeBasedPlan, lp.ScalarFixedDoublePlan)):
        return dataclasses.replace(plan, start_ms=start_ms, step_ms=step_ms,
                                   end_ms=end_ms)
    if isinstance(plan, lp.ScalarBinaryOperation):
        def _side(x):
            return x if isinstance(x, (int, float)) else \
                lp_replace_range(x, start_ms, step_ms, end_ms)
        return dataclasses.replace(plan, lhs=_side(plan.lhs),
                                   rhs=_side(plan.rhs), start_ms=start_ms,
                                   step_ms=step_ms, end_ms=end_ms)
    if isinstance(plan, lp.VectorPlan):
        return dataclasses.replace(
            plan, scalar=lp_replace_range(plan.scalar, start_ms, step_ms,
                                          end_ms))
    return plan


def _plan_window(plan) -> int:
    if isinstance(plan, lp.PeriodicSeriesWithWindowing):
        return plan.window_ms
    if isinstance(plan, lp.PeriodicSeries):
        return plan.lookback_ms
    return 0


# ---------------------------------------------------------------------------
# Results-cache split / stitch (query/resultcache.py's evaluation core)
#
# The incremental range-query cache stores per-step matrix extents; a
# sliding-window dashboard re-issue splits into the cached extent and
# (at most) a head + tail of uncovered steps, each evaluated through the
# NORMAL pipeline via an lp_replace_range-rebased plan — the same
# rewrite the plan cache and the raw/downsample tier split rely on, so a
# sub-range evaluation is exactly what a fresh parse at that range would
# compute. Step values are per-step functions of the underlying samples
# (windows are anchored on the step, not the grid bounds), so columns
# computed under different grids are bit-identical and stitch losslessly.
# ---------------------------------------------------------------------------

def uncovered_spans(start_ms: int, step_ms: int, end_ms: int,
                    cov_lo_ms: int, cov_hi_ms: int
                    ) -> List[Tuple[int, int]]:
    """Split a requested step range [start, end] against a covered
    sub-range [cov_lo, cov_hi] (all step-aligned, cov within request):
    the 0-2 contiguous spans that must be recomputed. An empty/invalid
    coverage yields the whole request."""
    if cov_lo_ms > cov_hi_ms:
        return [(start_ms, end_ms)]
    spans: List[Tuple[int, int]] = []
    if cov_lo_ms > start_ms:
        spans.append((start_ms, cov_lo_ms - step_ms))
    if cov_hi_ms < end_ms:
        spans.append((cov_hi_ms + step_ms, end_ms))
    return spans


def assemble_stitched(steps: np.ndarray, cached_steps: np.ndarray,
                      cached_keys: Sequence[Mapping[str, str]],
                      cached_values: np.ndarray,
                      span_grids: Sequence[GridResult]
                      ) -> Tuple[GridResult, List[Dict[str, str]]]:
    """Assemble the full request grid from cached step columns plus
    freshly computed span grids, matching series identity by label set.

    Series keep the CACHED extent's order — selection order is stable
    across evaluations of the same data, so a fresh full-range compute
    enumerates the same series in the same order and the stitched
    response is byte-identical to it. A cached series absent from a
    computed span keeps NaN there (the span evaluation fetched back
    through the lookback window, so absence means a fresh compute would
    find no samples for those steps either — Prometheus staleness).

    Returns (grid, churn): ``churn`` lists series present in a computed
    span but ABSENT from the cached extent. Stitching cannot place them
    (their values at the cached steps are unknown — e.g. a new series
    whose backfill may even invalidate aggregated cached columns), so
    the caller computes-through: a full-range fresh evaluation replaces
    the stitch when churn is non-empty."""
    T = int(steps.size)
    key_ix = {tuple(sorted(k.items())): i
              for i, k in enumerate(cached_keys)}
    values = np.full((len(cached_keys), T), np.nan)
    if cached_steps.size:
        pos = np.searchsorted(steps, cached_steps)
        values[:, pos] = cached_values
    churn: List[Dict[str, str]] = []
    out = GridResult(steps, [dict(k) for k in cached_keys], values)
    for g in span_grids:
        if g.is_hist():
            # histogram grids never enter the cache; a span turning
            # hist means the world changed under us — compute through
            churn.append({"__hist__": "1"})
            continue
        gpos = np.searchsorted(steps, g.steps)
        for i, k in enumerate(g.keys):
            j = key_ix.get(tuple(sorted(k.items())))
            if j is None:
                churn.append(dict(k))
                continue
            values[j][gpos] = g.values[i]
        out.absorb_degraded(g)
    return out, churn
