"""Incremental range-query results cache: step-aligned extent reuse,
ingest-watermark invalidation, tail-only recomputation.

Dashboard traffic is dominated by the SAME PromQL range query re-issued
every few seconds with a sliding time window; the plan cache (PR 3)
already skips re-parsing, but the computed per-step matrix was thrown
away and every refresh re-ran select -> decode -> device eval -> pack ->
encode over the whole range. This module is the Cortex/Thanos/Mimir
"query frontend" split-and-cache design folded into the serving node:

* Entries are **per-step matrix extents** — ``[num_series, num_steps]``
  float64 columns plus per-series label keys — stored in a
  byte-accounted LRU keyed on the plan cache's range-abstracted key
  ``(dataset, query text, step)`` plus **step alignment**
  (``start % step``): a request whose grid phase differs cannot reuse
  cached columns.

* On a hit, the requested ``[start, end]`` splits into the cached
  extent and (at most) a head + tail of uncovered steps; only those
  spans run through the normal pipeline (plan rebase -> batcher ->
  device), and :func:`filodb_tpu.query.engine.assemble_stitched` builds
  the response grid from cached columns + fresh span columns. Step
  values are per-step functions of the samples (windows anchor on the
  step, not the grid bounds), so stitched responses are byte-identical
  to a fresh full-range compute.

* **Freshness horizon**: steps newer than the shards' min ingest
  watermark (itself the MIN over per-partition last timestamps — the
  per-partition OOO guard means no known series can ever ingest
  at/below it) — or within ``hot_window_ms`` of the wall clock — are
  never served from (or admitted to) the cache; they may still receive
  samples. A watermark **regression** (stream replay, shard adoption/
  recovery — including a watermark appearing where an extent saw none)
  invalidates the overlapping extent, and a shard **backfill epoch**
  bump (a new/re-created series whose first rows land at/below the
  watermark, dirtying already-settled steps without moving the min)
  invalidates on lookup: the replayed/backfilled world may differ from
  the one the extent was computed against.

* **Dispatch scope is part of the key**: a ``dispatch=local`` /
  gRPC ``local_only`` evaluation (the pushdown loop-prevention hop)
  sees only this node's shards — its extents and a full fan-out
  query's extents live under distinct keys and never serve each other.

* **Series churn**: a computed span containing a series the cached
  extent has never seen cannot be stitched (its cached-step columns are
  unknown, and for aggregates its backfill could dirty neighbouring
  columns too) — the session computes-through with a full fresh
  evaluation and re-seeds the extent.

* **Degraded results are never admitted** (PR 1 partial-results guard):
  any ``partial`` flag or warning on the result or the engine's
  QueryStats skips the store, so a chaos-injected partial response can
  never poison later healthy queries.

Topology/schema invalidation rides the plan cache's listener hook
(:meth:`filodb_tpu.query.plancache.PlanCache.add_invalidation_listener`)
— any world change that clears cached plans clears cached results.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.lint.caches import cache_registry, event_source
from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.obs import trace as obs_trace
from filodb_tpu.query.model import GridResult
from filodb_tpu.query.plancache import range_abstracted_key

_CACHED_STEPS_HELP = ("Steps served from the results cache per hit "
                      "(full or partial)")
# per-series bookkeeping overhead charged against the byte budget on
# top of the value matrix (label dicts, key tuples, list slots)
_KEY_OVERHEAD = 128


def result_cacheable(plan) -> bool:
    """Plans whose extents may be cached: the plan cache's rebasable
    closure (lp_replace_range-rewritable, carries an evaluation grid)
    MINUS order-dependent nodes — ``sort()``/``sort_desc()`` order
    series by the range's LAST step and ``limit()`` truncates by
    position, so their output depends on the grid bounds rather than
    per-step data and extents must not be reused across ranges."""
    from filodb_tpu.query import logical as lp
    from filodb_tpu.query.plancache import _cacheable
    from filodb_tpu.query.planner import walk_plan_tree
    if not _cacheable(plan):
        return False
    found = [False]

    def visit(p):
        if isinstance(p, (lp.ApplySortFunction, lp.ApplyLimitFunction)):
            found[0] = True
            return True
        return False

    walk_plan_tree(plan, visit)
    return not found[0]


@event_source("dispatch-scope")
def dispatch_scope(engine) -> bool:
    """The engine's dispatch scope as a cache-key component: a
    ``dispatch=local`` / gRPC ``local_only`` evaluation (the pushdown
    loop-prevention hop) sees only this node's shards, so its extents
    and a full fan-out query's extents must never serve each other
    (the PR 5 review bug, now declared: graftlint requires the lookup
    hooks to read this function)."""
    return bool(getattr(engine, "local_dispatch", False))


@event_source("watermark")
def shards_watermark(shards: Sequence[object]) -> Optional[int]:
    """Freshness input: min ingest watermark over the engine's local
    shards that HAVE ingested, or None when none exposes one (pure
    remote dispatch / all-empty — only the hot window bounds staleness
    then, the Cortex frontend's max-freshness trade). Each shard's
    watermark is itself the min over its per-partition last timestamps
    (memstore), so no already-known series anywhere can ingest at or
    below the result; backfill by NEW series rides the shard's
    ingest_backfill_epoch instead (see :func:`shards_epoch`).
    Never-ingested shards (-1) constrain nothing; the moment one starts
    ingesting, the per-extent REGRESSION check (which also fires when a
    watermark appears where the extent recorded none) drops overlapping
    extents — so late backfill into a previously empty shard
    invalidates instead of serving stale. Remote shards behind a
    fan-out planner contribute when the planner stamped their group
    with a GOSSIPED watermark (the health-body watermark exchange,
    parallel/cluster.py peer_state_sink -> planner._stamp_peer_
    freshness) — fan-out extents then carry the same settled-time
    bound local ones do; unstamped groups stay invisible and only the
    hot window bounds their staleness, with the dispatch-scope key
    component fencing their scope."""
    wms = [getattr(s, "ingest_watermark_ms", None) for s in shards]
    wms = [w for w in wms if w is not None and w >= 0]
    if not wms:
        return None
    return int(min(wms))


@event_source("watermark")
def watermark_coverage(shards: Sequence[object]) -> int:
    """How many shards in the scope CONTRIBUTE a watermark (have
    ingested). Cached alongside the extent and checked on lookup: a
    never-ingested shard that starts ingesting can enter the min-set
    at exactly the old minimum — the min itself then never moves and
    the per-shard backfill epoch never bumps (an empty shard's first
    series has no watermark to land below), yet the extent's steps now
    miss that shard's series. A coverage CHANGE is that event made
    visible, generalizing the single-shard "watermark appearing"
    regression to mixed scopes (and, via the gossip-stamped
    ``ingest_watermark_coverage`` on remote groups, to fan-out
    scopes)."""
    total = 0
    for s in shards:
        cov = getattr(s, "ingest_watermark_coverage", None)
        if cov is not None:
            total += int(cov)
            continue
        wm = getattr(s, "ingest_watermark_ms", None)
        if wm is not None and wm >= 0:
            total += 1
    return total


@event_source("backfill-epoch")
def shards_epoch(shards: Sequence[object]) -> int:
    """Sum of the local shards' backfill epochs. A per-partition OOO
    guard cannot stop a NEW (or re-created/evicted-then-dropped) series
    from ingesting below the shard watermark; the shard bumps its
    epoch on any such entrance, and extents recorded under a different
    epoch are dropped on lookup (the backfilled steps were cached as
    settled). Monotone under bumps; a changed sum of any kind (shard
    replacement resets to 0) reads as invalidation."""
    return sum(int(getattr(s, "ingest_backfill_epoch", 0) or 0)
               for s in shards)


@event_source("integrity-quarantine")
def shards_quarantine(shards: Sequence[object]) -> int:
    """Unresolved quarantined-record count over the engine's local
    shards (storage-integrity rail, PR 16). A shard whose durable files
    quarantined records may be missing arbitrary samples — results
    computed over it are not wrong (the live memstore is intact) but
    extents CACHED from it could outlive a later repair/replay that
    restores the quarantined data, serving the lossy view long after
    the store healed. Any nonzero count makes the scope uncacheable
    and refuses existing extents until the quarantine is resolved
    (fsck repair + restart resets the count)."""
    return sum(int(getattr(s, "integrity_quarantined_records", 0) or 0)
               for s in shards)


def _pow2_spans(spans: List[Tuple[int, int]], start_ms: int,
                step_ms: int, grid_end: int) -> List[Tuple[int, int]]:
    """Widen uncovered spans to power-of-two step counts by extending
    them INTO covered territory (head spans grow toward the end, tail
    spans toward the start, both clamped to the request grid).

    Why: the device executors specialize on the step count — a sliding
    window whose raw tail length changes by one step per refresh would
    recompile the kernel on EVERY request (a ~100ms+ stall that dwarfs
    the cached win). Bucketed spans keep the shape set tiny (1, 2, 4,
    ... steps -> one compile each, then cache hits forever). The extra
    steps recompute values the extent already holds — bit-identical, so
    the stitch is unaffected; only the cached/computed step accounting
    reflects the overlap honestly."""
    out: List[Tuple[int, int]] = []
    for lo, hi in spans:
        n = (hi - lo) // step_ms + 1
        nb = 1
        while nb < n:
            nb <<= 1
        if lo == start_ms:              # head: extend toward the end
            out.append((lo, min(grid_end, lo + (nb - 1) * step_ms)))
        else:                           # tail: extend toward the start
            out.append((max(start_ms, hi - (nb - 1) * step_ms), hi))
    if len(out) == 2 and out[0][1] + step_ms >= out[1][0]:
        return [(start_ms, grid_end)]   # widened spans met: one pass
    return out


class CachedExtent:
    """One contiguous step-aligned extent of cached matrix columns.
    Immutable after construction (value array is frozen); lookups hand
    out column views, never copies of the whole matrix."""

    __slots__ = ("start_ms", "end_ms", "step_ms", "keys", "values",
                 "watermark_ms", "epoch", "coverage", "nbytes",
                 "encode_memo")

    def __init__(self, start_ms: int, end_ms: int, step_ms: int,
                 keys: List[Dict[str, str]], values: np.ndarray,
                 watermark_ms: Optional[int], epoch: int = 0,
                 coverage: int = 0):
        self.start_ms = int(start_ms)
        self.end_ms = int(end_ms)
        self.step_ms = int(step_ms)
        self.keys = keys
        values.setflags(write=False)
        self.values = values
        self.watermark_ms = watermark_ms
        self.epoch = int(epoch)     # shards' backfill-epoch sum at build
        self.coverage = int(coverage)   # shards contributing a watermark
        self.nbytes = int(values.nbytes) + _KEY_OVERHEAD * len(keys) + 256
        # (start_ms, end_ms) -> rendered JSON result rows: repeat FULL
        # hits splice pre-encoded bytes (prom_json.matrix_bytes
        # rows_memo). Dies with the extent, so it can never outlive the
        # values it renders; one rendered range at a time, and its text
        # bytes are CHARGED against the LRU budget via
        # ResultCache._memo_charge (rendered rows run ~3x the matrix).
        self.encode_memo: Dict[Tuple[int, int], str] = {}

    @property
    def steps(self) -> np.ndarray:
        return np.arange(self.start_ms, self.end_ms + 1, self.step_ms,
                         dtype=np.int64)


class _EncodeMemo:
    """Handle prom_json.matrix_bytes uses to reuse/store rendered row
    text for one (extent, range). Reads are lock-free (a racing clear
    just misses); stores go through the cache so the text bytes ride
    the byte budget."""

    __slots__ = ("cache", "cache_key", "ext", "range_key")

    def __init__(self, cache: "ResultCache", cache_key, ext, range_key):
        self.cache = cache
        self.cache_key = cache_key
        self.ext = ext
        self.range_key = range_key

    def get(self) -> Optional[str]:
        return self.ext.encode_memo.get(self.range_key)

    def put(self, text: str) -> None:
        self.cache._memo_charge(self.cache_key, self.ext,
                                self.range_key, text)


class RangeSession:
    """One range query's passage through the results cache.

    ``begin`` decides what must actually execute (``plans``: zero, one
    or two rebased sub-plans — or the full plan on a miss/bypass); the
    caller materializes + executes them through the normal pipeline and
    hands the grids to :meth:`finish`, which stitches, applies the
    degraded-result admission guard, rolls the extent forward, and
    returns the response result. ``state`` after finish is the
    disposition surfaced in response timings and span tags: off /
    bypass / uncacheable / miss / partial / hit / churn."""

    __slots__ = ("cache", "state", "plans", "key", "dataset", "query",
                 "start_ms", "step_ms", "end_ms", "full_plan",
                 "cached_steps", "computed_steps", "horizon_ms",
                 "watermark_ms", "epoch", "coverage", "_extent",
                 "_cov")

    def __init__(self, cache: "ResultCache", state: str, plans: List,
                 full_plan, key, dataset: str, query: str,
                 start_ms: int, step_ms: int, end_ms: int,
                 horizon_ms: int = -1,
                 watermark_ms: Optional[int] = None,
                 epoch: int = 0,
                 coverage: int = 0,
                 extent: Optional[CachedExtent] = None,
                 cov: Optional[Tuple[int, int]] = None,
                 cached_steps: int = 0, computed_steps: int = 0):
        self.cache = cache
        self.state = state
        self.plans = plans
        self.full_plan = full_plan
        self.key = key
        self.dataset = dataset
        self.query = query
        self.start_ms = start_ms
        self.step_ms = step_ms
        self.end_ms = end_ms
        self.horizon_ms = horizon_ms
        self.watermark_ms = watermark_ms
        self.epoch = epoch
        self.coverage = coverage
        self._extent = extent
        self._cov = cov
        self.cached_steps = cached_steps
        self.computed_steps = computed_steps

    def encode_memo(self):
        """Row-text memo handle for prom_json.matrix_bytes on a FULL
        hit — the rendered rows are a pure function of the immutable
        extent and the range — else None."""
        if self.state != "hit" or self._extent is None:
            return None
        return _EncodeMemo(self.cache, self.key, self._extent,
                           (self.start_ms, self.end_ms))

    # -- result assembly --------------------------------------------------
    def finish(self, engine, grids: Sequence) -> object:
        """Stitch/store and return the response result. ``grids`` holds
        the executed results of ``plans`` in order."""
        if self.state in ("off", "bypass", "uncacheable"):
            return grids[0] if grids else None
        if self.state == "miss":
            res = grids[0] if grids else None
            self.cache._record_miss(self.computed_steps)
            self._maybe_store(engine, res)
            return res
        # hit / partial: assemble from the extent + computed spans
        from filodb_tpu.query.engine import assemble_stitched
        ext = self._extent
        lo, hi = self._cov
        i0 = (lo - ext.start_ms) // ext.step_ms
        i1 = (hi - ext.start_ms) // ext.step_ms + 1
        steps = np.arange(self.start_ms, self.end_ms + 1, self.step_ms,
                          dtype=np.int64)
        if self.state == "hit":
            # full hit: the extent covers every requested step — serve
            # VIEWS straight off the frozen extent (no matrix copy, no
            # key rebuild) and skip the store (nothing to roll forward)
            grid = GridResult(steps, ext.keys, ext.values[:, i0:i1])
            self.cache._record_hit(full=True,
                                   cached_steps=self.cached_steps,
                                   computed_steps=0)
            obs_metrics.observe("filodb_resultcache_cached_steps",
                                _CACHED_STEPS_HELP,
                                float(self.cached_steps),
                                buckets=obs_metrics.STEPS_BUCKETS)
            return grid
        with obs_trace.span("resultcache-stitch", state=self.state,
                            cached_steps=self.cached_steps,
                            spans=len(grids)):
            grid, churn = assemble_stitched(
                steps, ext.steps[i0:i1], ext.keys,
                ext.values[:, i0:i1], grids)
        if churn:
            # compute-through: series the extent has never seen cannot
            # be stitched — evaluate the whole range fresh and re-seed
            self.state = "churn"
            self.computed_steps += self.cached_steps
            self.cached_steps = 0
            self.cache._record_churn(self.computed_steps)
            ex = engine.materialize(self.full_plan)
            res = ex.execute()
            self._maybe_store(engine, res)
            return res
        self.cache._record_hit(full=False,
                               cached_steps=self.cached_steps,
                               computed_steps=self.computed_steps)
        obs_metrics.observe("filodb_resultcache_cached_steps",
                            _CACHED_STEPS_HELP, float(self.cached_steps),
                            buckets=obs_metrics.STEPS_BUCKETS)
        self._maybe_store(engine, grid)
        return grid

    def _maybe_store(self, engine, res) -> None:
        """Admission guard + store: only clean (non-partial, warning-
        free, non-histogram) grid results enter the cache, trimmed to
        the freshness horizon."""
        if not isinstance(res, GridResult) or res.is_hist():
            return
        st = getattr(engine, "stats", None)
        degraded = (res.partial or bool(res.warnings)
                    or bool(getattr(st, "partial", False))
                    or bool(getattr(st, "warnings", ())))
        if degraded:
            self.cache._record_degraded_skip()
            return
        self.cache._store(self.key, res, self.start_ms, self.step_ms,
                          self.end_ms, self.horizon_ms,
                          self.watermark_ms, self.epoch, self.coverage)


@guarded_by("_lock", "_entries", "_bytes", "hits", "partial_hits",
            "misses", "stitches", "churn_recomputes", "bypassed",
            "uncacheable", "stores", "evictions", "degraded_skips",
            "invalidations", "watermark_invalidations",
            "backfill_invalidations", "integrity_refused",
            "cached_steps_served", "computed_steps_served",
            "stale_serves")
# inventory declaration (graftlint cache-invalidation-completeness):
# topology/schema events PUSH through the plan-cache listener chain to
# `invalidate`; watermark, backfill-epoch, dispatch-scope, and
# integrity-quarantine are PULL events — both serving entry points
# must keep reading their @event_source functions (shards_watermark/
# watermark_coverage, shards_epoch, dispatch_scope, shards_quarantine)
# or the lint gate fails. This is the declaration that would have
# caught the PR 5 dispatch-scope key miss and the PR 6
# watermark-coverage hole at review time.
@cache_registry("results",
                invalidated_by={"topology-epoch": "invalidate",
                                "schema": "invalidate"},
                validated_by={"watermark": ("begin", "stale_serve"),
                              "backfill-epoch": ("begin",
                                                 "stale_serve"),
                              "dispatch-scope": ("begin",
                                                 "stale_serve"),
                              "integrity-quarantine": ("begin",
                                                       "stale_serve")},
                keyed=("dataset", "query-text", "step", "grid-phase",
                       "dispatch-scope"))
class ResultCache:
    """Byte-accounted LRU of :class:`CachedExtent`, keyed
    ``(dataset, query, step, start % step, local_dispatch)``.

    Concurrency: HTTP handler threads look up and store concurrently
    while topology/schema events and watermark regressions invalidate;
    every access to the entry map and counters rides ``_lock``. Span
    evaluation happens strictly OUTSIDE the lock — lookups return
    immutable extent snapshots (frozen arrays), so a concurrent
    invalidation never mutates a grid mid-stitch."""

    def __init__(self, max_bytes: int = 64 << 20,
                 hot_window_ms: float = 10_000.0,
                 clock=time.time):
        self.max_bytes = int(max_bytes)
        self.hot_window_ms = float(hot_window_ms)
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, CachedExtent]" = OrderedDict()
        self._bytes = 0
        self.hits = 0               # every requested step from cache
        self.partial_hits = 0       # stitched: cached extent + spans
        self.misses = 0
        self.stitches = 0           # span evaluations stitched in
        self.churn_recomputes = 0   # compute-through on series churn
        self.bypassed = 0           # &cache=false
        self.uncacheable = 0
        self.stores = 0
        self.evictions = 0
        self.degraded_skips = 0     # partial/warning results refused
        self.invalidations = 0
        self.watermark_invalidations = 0
        self.backfill_invalidations = 0     # epoch-change drops
        self.integrity_refused = 0  # scope has unresolved quarantine
        self.cached_steps_served = 0
        self.computed_steps_served = 0
        self.stale_serves = 0       # brownout rung: served past horizon

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    # -- the serving entry points ----------------------------------------
    def begin(self, engine, dataset: str, query: str, plan,
              start_ms: int, step_ms: int, end_ms: int,
              bypass: bool = False) -> RangeSession:
        """Split one range request against the cache. Returns a session
        whose ``plans`` the caller must materialize + execute through
        the normal pipeline, then hand to ``session.finish``."""
        mk = RangeSession
        if not self.enabled:
            return mk(self, "off", [plan], plan, None, dataset, query,
                      start_ms, step_ms, end_ms)
        if bypass:
            with self._lock:
                self.bypassed += 1
            return mk(self, "bypass", [plan], plan, None, dataset,
                      query, start_ms, step_ms, end_ms)
        if step_ms <= 0 or not result_cacheable(plan):
            with self._lock:
                self.uncacheable += 1
            return mk(self, "uncacheable", [plan], plan, None, dataset,
                      query, start_ms, step_ms, end_ms)
        shards = getattr(engine, "shards", ())
        if shards_quarantine(shards) > 0:
            # unresolved quarantine in scope: the durable tier is
            # missing records — neither serve nor store extents from
            # this world (the query still runs, uncached)
            with self._lock:
                self.integrity_refused += 1
                self.uncacheable += 1
            return mk(self, "uncacheable", [plan], plan, None, dataset,
                      query, start_ms, step_ms, end_ms)
        wm = shards_watermark(shards)
        ep = shards_epoch(shards)
        cov_n = watermark_coverage(shards)
        now_ms = int(self._clock() * 1000)
        horizon = now_ms - int(self.hot_window_ms)
        if wm is not None:
            horizon = min(horizon, wm)
        # dispatch scope rides the key: a local-only hop (pushdown loop
        # prevention) evaluates a subset of the fan-out world — the two
        # must never share extents
        key = range_abstracted_key(dataset, query, step_ms) \
            + (int(start_ms) % int(step_ms), dispatch_scope(engine))
        n_steps = (end_ms - start_ms) // step_ms + 1
        # the grid's LAST step — coverage and span math run on the step
        # grid, not the raw end (which need not be step-aligned)
        grid_end = start_ms + (n_steps - 1) * step_ms
        ext = self._lookup(key, wm, ep, cov_n)
        # floor the horizon onto this request's step grid
        hz_hi = start_ms + ((horizon - start_ms) // step_ms) * step_ms \
            if horizon >= start_ms else start_ms - step_ms
        cov = None
        if ext is not None:
            lo = max(start_ms, ext.start_ms)
            hi = min(grid_end, ext.end_ms, hz_hi)
            if lo <= hi:
                cov = (lo, hi)
        if cov is None:
            return mk(self, "miss", [plan], plan, key, dataset, query,
                      start_ms, step_ms, end_ms, horizon_ms=horizon,
                      watermark_ms=wm, epoch=ep, coverage=cov_n,
                      computed_steps=n_steps)
        from filodb_tpu.query.engine import (lp_replace_range,
                                             uncovered_spans)
        spans = _pow2_spans(
            uncovered_spans(start_ms, step_ms, grid_end, cov[0],
                            cov[1]),
            start_ms, step_ms, grid_end)
        sub_plans = [lp_replace_range(plan, lo, step_ms, hi)
                     for lo, hi in spans]
        computed = sum((hi - lo) // step_ms + 1 for lo, hi in spans)
        return mk(self, "hit" if not spans else "partial", sub_plans,
                  plan, key, dataset, query, start_ms, step_ms, end_ms,
                  horizon_ms=horizon, watermark_ms=wm, epoch=ep,
                  coverage=cov_n, extent=ext, cov=cov,
                  cached_steps=n_steps - computed,
                  computed_steps=computed)

    def stale_serve(self, engine, dataset: str, query: str, plan,
                    start_ms: int, step_ms: int, end_ms: int):
        """Brownout rung (tenant QoS, query/qos.py): serve whatever
        overlapping extent exists, PAST the freshness horizon — the
        caller has decided a stale answer beats shedding the query.

        Unlike :meth:`begin`, the hot window and watermark horizon are
        ignored (stale is the point), but the correctness invalidators
        still apply: a watermark REGRESSION, backfill-epoch change, or
        coverage change means the extent may describe a world that
        never existed — stale must never mean WRONG, so those extents
        are dropped here exactly as on the normal path. The extent must
        cover the request's first step (a head-missing stitch has no
        cheap assembly); a short tail truncates and the caller stamps
        the result partial. Returns a GridResult (``partial`` set on
        truncation) or None; the result is never re-admitted — the
        caller's shed warning trips the degraded-admission guard."""
        if not self.enabled or step_ms <= 0 \
                or not result_cacheable(plan):
            return None
        shards = getattr(engine, "shards", ())
        if shards_quarantine(shards) > 0:
            # stale must never mean LOSSY: a quarantined scope refuses
            # its extents even on the brownout rung
            with self._lock:
                self.integrity_refused += 1
            return None
        key = range_abstracted_key(dataset, query, step_ms) \
            + (int(start_ms) % int(step_ms), dispatch_scope(engine))
        ext = self._lookup(key, shards_watermark(shards),
                           shards_epoch(shards),
                           watermark_coverage(shards))
        if ext is None:
            return None
        n_steps = (end_ms - start_ms) // step_ms + 1
        grid_end = start_ms + (n_steps - 1) * step_ms
        if ext.start_ms > start_ms or ext.end_ms < start_ms:
            return None
        hi = min(grid_end, ext.end_ms)
        i0 = (start_ms - ext.start_ms) // ext.step_ms
        i1 = (hi - ext.start_ms) // ext.step_ms + 1
        steps = np.arange(start_ms, hi + 1, step_ms, dtype=np.int64)
        grid = GridResult(steps, ext.keys, ext.values[:, i0:i1])
        grid.partial = hi < grid_end
        with self._lock:
            self.stale_serves += 1
        return grid

    def execute(self, engine, dataset: str, query: str, plan,
                start_ms: int, step_ms: int, end_ms: int,
                bypass: bool = False):
        """Convenience wrapper (the gRPC Exec path): begin -> run the
        sub-plans through engine.materialize -> finish. Returns
        (result, session)."""
        ses = self.begin(engine, dataset, query, plan, start_ms,
                         step_ms, end_ms, bypass=bypass)
        grids = [engine.materialize(p).execute() for p in ses.plans]
        return ses.finish(engine, grids), ses

    # -- internals --------------------------------------------------------
    def _lookup(self, key, wm: Optional[int], epoch: int,
                coverage: int = 0) -> Optional[CachedExtent]:
        with self._lock:
            ext = self._entries.get(key)
            if ext is None:
                return None
            if coverage != ext.coverage:
                # a shard entered (or left) the watermark min-set: a
                # previously-empty shard's first series can land at
                # exactly the old minimum — min and epochs unmoved —
                # yet dirty every cached step (the mixed-scope
                # generalization of "watermark appearing")
                self._bytes -= ext.nbytes
                del self._entries[key]
                self.watermark_invalidations += 1
                return None
            if wm is not None and (ext.watermark_ms is None
                                   or wm < ext.watermark_ms):
                # watermark regression: the stream replayed / the shard
                # was re-adopted below the extent's build point — the
                # overlapping extent may describe a world that no
                # longer exists. A watermark APPEARING where the extent
                # recorded none is the same event: the empty world the
                # extent was computed against has since ingested
                # (possibly backfill below every cached step)
                self._bytes -= ext.nbytes
                del self._entries[key]
                self.watermark_invalidations += 1
                return None
            if epoch != ext.epoch:
                # a series entered a shard below its watermark since
                # this extent was built: steps the extent holds as
                # settled may now have samples the cached columns miss
                self._bytes -= ext.nbytes
                del self._entries[key]
                self.backfill_invalidations += 1
                return None
            self._entries.move_to_end(key)
            return ext

    def _store(self, key, grid: GridResult, start_ms: int, step_ms: int,
               end_ms: int, horizon_ms: int,
               watermark_ms: Optional[int], epoch: int = 0,
               coverage: int = 0) -> None:
        if key is None:
            return
        steps = grid.steps
        if steps.size == 0:
            return
        hi = int(np.searchsorted(steps, horizon_ms, side="right"))
        if hi <= 0:
            return              # everything is hotter than the horizon
        values = np.array(grid.values[:, :hi])      # own the memory
        ext = CachedExtent(int(steps[0]), int(steps[hi - 1]), step_ms,
                           [dict(k) for k in grid.keys], values,
                           watermark_ms, epoch, coverage)
        if ext.nbytes > self.max_bytes:
            return              # larger than the whole budget
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = ext
            self._bytes += ext.nbytes
            self.stores += 1
            while self._bytes > self.max_bytes and self._entries:
                _, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                self.evictions += 1

    def _memo_charge(self, key, ext: CachedExtent, range_key,
                     text: str) -> None:
        """Admit rendered row text into an extent's encode memo,
        charging its bytes against the budget (one rendered range per
        extent — a new range replaces and refunds the old)."""
        with self._lock:
            if self._entries.get(key) is not ext:
                return          # extent replaced/evicted meanwhile
            if range_key in ext.encode_memo:
                return
            freed = sum(len(t) for t in ext.encode_memo.values())
            ext.encode_memo.clear()
            ext.encode_memo[range_key] = text
            delta = len(text) - freed
            ext.nbytes += delta
            self._bytes += delta
            while self._bytes > self.max_bytes and self._entries:
                _, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                self.evictions += 1

    # -- bookkeeping (called by sessions) ---------------------------------
    def _record_hit(self, full: bool, cached_steps: int,
                    computed_steps: int) -> None:
        with self._lock:
            if full:
                self.hits += 1
            else:
                self.partial_hits += 1
                self.stitches += 1
            self.cached_steps_served += cached_steps
            self.computed_steps_served += computed_steps

    def _record_miss(self, computed_steps: int) -> None:
        with self._lock:
            self.misses += 1
            self.computed_steps_served += computed_steps

    def _record_churn(self, computed_steps: int) -> None:
        with self._lock:
            self.churn_recomputes += 1
            self.computed_steps_served += computed_steps

    def _record_degraded_skip(self) -> None:
        with self._lock:
            self.degraded_skips += 1

    # -- invalidation / introspection -------------------------------------
    def invalidate(self, reason: str = "") -> None:
        """Drop every extent (topology/schema change — wired to the
        plan cache's invalidation listener)."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self.invalidations += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries), "bytes": self._bytes,
                "hits": self.hits, "partial_hits": self.partial_hits,
                "misses": self.misses, "stitches": self.stitches,
                "churn_recomputes": self.churn_recomputes,
                "bypassed": self.bypassed,
                "uncacheable": self.uncacheable,
                "stores": self.stores, "evictions": self.evictions,
                "degraded_skips": self.degraded_skips,
                "invalidations": self.invalidations,
                "watermark_invalidations":
                    self.watermark_invalidations,
                "backfill_invalidations":
                    self.backfill_invalidations,
                "cached_steps_served": self.cached_steps_served,
                "computed_steps_served": self.computed_steps_served,
                "stale_serves": self.stale_serves,
            }
