"""Windowed range functions — numpy oracle backend.

Re-implements the reference's RangeFunction registry semantics
(query/exec/rangefn/RangeFunction.scala:235, InternalRangeFunction.scala:10,
RateFunctions.scala:10-79, AggrOverTimeFunctions.scala) in vectorized form:

For a periodic query (start, step, end) each output step ``t`` evaluates a
function over the window ``[t - window, t]`` (both ends inclusive — the
reference default ``filodb.query.inclusive-range = true``,
filodb-defaults.conf:336; PeriodicSamplesMapper.scala:215).

Instead of iterating rows per window, we compute for every window its sample
index range ``[lo, hi]`` with searchsorted, then evaluate functions from
prefix sums / gathered endpoints.  This is O(samples + windows) and data
parallel — the formulation the TPU backend compiles (see
filodb_tpu.query.tpu).

All functions take timestamps in **milliseconds** and produce one value per
window; windows with insufficient samples yield NaN (Prometheus staleness
semantics).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional, Sequence

import numpy as np

from filodb_tpu.memory.vectors import counter_correction


def window_bounds(ts: np.ndarray, wstart: np.ndarray, wend: np.ndarray):
    """Per-window index ranges [lo, hi] (inclusive) into sorted ``ts``.

    Mirrors WindowedChunkIterator + binary search row ranges
    (core/store/ChunkSetInfo.scala:432; RangeFunction.scala:122)."""
    lo = np.searchsorted(ts, wstart, side="left")
    hi = np.searchsorted(ts, wend, side="right") - 1
    return lo, hi


def _prep(ts: np.ndarray, vals: np.ndarray, drop_nan: bool = True):
    """Drop NaN (stale) samples — the reference's iterators skip NaNs
    (shouldInclude in sliding iterators)."""
    if drop_nan and vals.ndim == 1:
        m = ~np.isnan(vals)
        if not m.all():
            return ts[m], vals[m]
    return ts, vals


def extrapolated_rate(wstart, wend, counts, first_ts, first_val, last_ts,
                      last_val, is_counter: bool, is_rate: bool):
    """Vectorized Prometheus extrapolation
    (rangefn/RateFunctions.scala:37-76 extrapolatedRate).  All array args are
    per-window; returns per-window result with NaN where counts < 2."""
    counts = counts.astype(np.float64)
    with np.errstate(invalid="ignore", divide="ignore"):
        duration_to_start = (first_ts - wstart).astype(np.float64) / 1000.0
        duration_to_end = (wend - last_ts).astype(np.float64) / 1000.0
        sampled_interval = (last_ts - first_ts).astype(np.float64) / 1000.0
        avg_duration = sampled_interval / (counts - 1.0)
        delta = last_val - first_val

        if is_counter:
            # extrapolate only to the counter zero point
            duration_to_zero = np.where(
                (delta > 0) & (first_val >= 0),
                sampled_interval * (first_val / np.where(delta == 0, np.nan,
                                                         delta)),
                np.inf)
            duration_to_start = np.minimum(duration_to_start,
                                           duration_to_zero)

        threshold = avg_duration * 1.1
        extrap = sampled_interval \
            + np.where(duration_to_start < threshold, duration_to_start,
                       avg_duration / 2.0) \
            + np.where(duration_to_end < threshold, duration_to_end,
                       avg_duration / 2.0)
        scaled = delta * (extrap / sampled_interval)
        if is_rate:
            scaled = scaled / (wend - wstart) * 1000.0
        return np.where(counts >= 2, scaled, np.nan)


class RangeFunctionError(ValueError):
    pass


def _rate_family(is_counter: bool, is_rate: bool, need_correction: bool):
    def f(ts, vals, wstart, wend, **kw):
        ts, vals = _prep(ts, vals)
        if ts.size == 0:
            return np.full(wstart.shape, np.nan)
        corrected = vals + counter_correction(vals) if need_correction else vals
        lo, hi = window_bounds(ts, wstart, wend)
        counts = hi - lo + 1
        valid = counts >= 1
        lo_c = np.clip(lo, 0, ts.size - 1)
        hi_c = np.clip(hi, 0, ts.size - 1)
        out = extrapolated_rate(
            wstart, wend, counts,
            ts[lo_c], corrected[lo_c], ts[hi_c], corrected[hi_c],
            is_counter, is_rate)
        return np.where(valid, out, np.nan)
    return f


def _sum_family(reducer: str):
    """Prefix-sum based over-time aggregations
    (AggrOverTimeFunctions.scala chunked Sum/Count/Avg/StdDev/StdVar)."""
    def f(ts, vals, wstart, wend, **kw):
        ts, vals = _prep(ts, vals)
        n = ts.size
        nw = wstart.shape[0]
        if n == 0:
            if reducer == "count":
                return np.zeros(nw) * np.nan
            return np.full(nw, np.nan)
        lo, hi = window_bounds(ts, wstart, wend)
        counts = (hi - lo + 1).astype(np.float64)
        empty = counts <= 0
        cs = np.concatenate([[0.0], np.cumsum(vals)])
        s = cs[np.clip(hi + 1, 0, n)] - cs[np.clip(lo, 0, n)]
        with np.errstate(invalid="ignore", divide="ignore"):
            if reducer == "sum":
                out = s
            elif reducer == "count":
                out = counts
            elif reducer == "avg":
                out = s / counts
            else:
                # shifted squares: prefix sums of (x-c)^2 with c = series
                # mean keep full precision when |mean| >> stddev (Prometheus
                # computes this with Welford; the shifted prefix form is
                # algebraically identical and windowable)
                finite = vals[np.isfinite(vals)]
                shift = finite.mean() if finite.size else 0.0
                d = vals - shift
                cs2 = np.concatenate([[0.0], np.cumsum(d * d)])
                s2 = cs2[np.clip(hi + 1, 0, n)] - cs2[np.clip(lo, 0, n)]
                mean = s / counts
                dm = mean - shift
                var = np.maximum(s2 / counts - dm * dm, 0.0)
                if reducer == "stdvar":
                    out = var
                elif reducer == "stddev":
                    out = np.sqrt(var)
                elif reducer == "zscore":
                    hi_c = np.clip(hi, 0, n - 1)
                    out = (vals[hi_c] - mean) / np.sqrt(var)
                else:
                    raise RangeFunctionError(reducer)
        return np.where(empty, np.nan, out)
    return f


def _minmax_family(op: str):
    def f(ts, vals, wstart, wend, **kw):
        ts, vals = _prep(ts, vals)
        n = ts.size
        nw = wstart.shape[0]
        out = np.full(nw, np.nan)
        if n == 0:
            return out
        lo, hi = window_bounds(ts, wstart, wend)
        fn = np.minimum if op == "min" else np.maximum
        # reduceat over [lo, hi+1) slices: interleave boundaries
        for i in range(nw):
            if hi[i] >= lo[i]:
                seg = vals[lo[i] : hi[i] + 1]
                out[i] = seg.min() if op == "min" else seg.max()
        return out
    return f


def _last_sample(ts, vals, wstart, wend, **kw):
    """Instant-vector lookback: latest sample in window, NaN if none
    (PeriodicSamplesMapper default LastSampleFunction)."""
    # Do NOT drop NaNs: a NaN (stale marker) sample makes the series stale.
    n = ts.size
    out = np.full(wstart.shape, np.nan)
    if n == 0:
        return out
    hi = np.searchsorted(ts, wend, side="right") - 1
    valid = hi >= np.searchsorted(ts, wstart, side="left")
    hi_c = np.clip(hi, 0, n - 1)
    got = vals[hi_c]
    return np.where(valid, got, np.nan)


def _timestamp_fn(ts, vals, wstart, wend, **kw):
    ts, vals = _prep(ts, vals)
    n = ts.size
    out = np.full(wstart.shape, np.nan)
    if n == 0:
        return out
    lo, hi = window_bounds(ts, wstart, wend)
    valid = hi >= lo
    hi_c = np.clip(hi, 0, n - 1)
    return np.where(valid, ts[hi_c] / 1000.0, np.nan)


def _changes(ts, vals, wstart, wend, **kw):
    ts, vals = _prep(ts, vals)
    n = ts.size
    nw = wstart.shape[0]
    if n == 0:
        return np.full(nw, np.nan)
    changed = np.concatenate([[0.0], (np.diff(vals) != 0).astype(np.float64)])
    cs = np.concatenate([[0.0], np.cumsum(changed)])
    lo, hi = window_bounds(ts, wstart, wend)
    # changes between consecutive samples strictly inside the window:
    # count changed[i] for lo+1 <= i <= hi
    out = cs[np.clip(hi + 1, 0, n)] - cs[np.clip(lo + 1, 0, n)]
    return np.where(hi >= lo, out, np.nan)


def _resets(ts, vals, wstart, wend, **kw):
    ts, vals = _prep(ts, vals)
    n = ts.size
    nw = wstart.shape[0]
    if n == 0:
        return np.full(nw, np.nan)
    reset = np.concatenate([[0.0], (np.diff(vals) < 0).astype(np.float64)])
    cs = np.concatenate([[0.0], np.cumsum(reset)])
    lo, hi = window_bounds(ts, wstart, wend)
    out = cs[np.clip(hi + 1, 0, n)] - cs[np.clip(lo + 1, 0, n)]
    return np.where(hi >= lo, out, np.nan)


def _deriv_predict(predict: bool):
    """deriv() / predict_linear(): least-squares slope over the window
    (rangefn Deriv/PredictLinear; matches Prometheus simple regression)."""
    def f(ts, vals, wstart, wend, scalar=None, **kw):
        ts, vals = _prep(ts, vals)
        n = ts.size
        nw = wstart.shape[0]
        out = np.full(nw, np.nan)
        if n == 0:
            return out
        lo, hi = window_bounds(ts, wstart, wend)
        for i in range(nw):
            if hi[i] - lo[i] + 1 < 2:
                continue
            t = ts[lo[i] : hi[i] + 1].astype(np.float64) / 1000.0
            v = vals[lo[i] : hi[i] + 1]
            t0 = t - t[0]  # numerical stability (Prometheus does the same)
            tm, vm = t0.mean(), v.mean()
            cov = ((t0 - tm) * (v - vm)).sum()
            var = ((t0 - tm) ** 2).sum()
            if var == 0:
                continue
            slope = cov / var
            if predict:
                intercept = vm - slope * tm
                horizon = float(scalar) + (wend[i] / 1000.0 - t[0])
                out[i] = slope * horizon + intercept
            else:
                out[i] = slope
        return out
    return f


def _quantile_over_time(ts, vals, wstart, wend, scalar=None, **kw):
    ts, vals = _prep(ts, vals)
    q = float(scalar)
    nw = wstart.shape[0]
    out = np.full(nw, np.nan)
    if ts.size == 0:
        return out
    lo, hi = window_bounds(ts, wstart, wend)
    for i in range(nw):
        if hi[i] >= lo[i]:
            seg = vals[lo[i] : hi[i] + 1]
            out[i] = np.quantile(seg, min(max(q, 0.0), 1.0)) \
                if 0 <= q <= 1 else (np.inf if q > 1 else -np.inf)
    return out


def _mad_over_time(ts, vals, wstart, wend, **kw):
    ts, vals = _prep(ts, vals)
    nw = wstart.shape[0]
    out = np.full(nw, np.nan)
    if ts.size == 0:
        return out
    lo, hi = window_bounds(ts, wstart, wend)
    for i in range(nw):
        if hi[i] >= lo[i]:
            seg = vals[lo[i] : hi[i] + 1]
            med = np.median(seg)
            out[i] = np.median(np.abs(seg - med))
    return out


def _holt_winters(ts, vals, wstart, wend, scalar=None, scalar2=None, **kw):
    """holt_winters(v, sf, tf) — inherently sequential smoothing; looped
    oracle (rangefn HoltWinters)."""
    ts, vals = _prep(ts, vals)
    sf, tf = float(scalar), float(scalar2)
    nw = wstart.shape[0]
    out = np.full(nw, np.nan)
    if ts.size == 0 or not (0 < sf < 1) or not (0 < tf < 1):
        return out
    lo, hi = window_bounds(ts, wstart, wend)
    for i in range(nw):
        n = hi[i] - lo[i] + 1
        if n < 2:
            continue
        seg = vals[lo[i] : hi[i] + 1]
        s = seg[0]
        b = seg[1] - seg[0]
        for x in seg[1:]:
            s_prev = s
            s = sf * x + (1 - sf) * (s + b)
            b = tf * (s - s_prev) + (1 - tf) * b
        out[i] = s
    return out


def _absent_over_time(ts, vals, wstart, wend, **kw):
    ts, vals = _prep(ts, vals)
    if ts.size == 0:
        return np.ones(wstart.shape)
    lo, hi = window_bounds(ts, wstart, wend)
    return np.where(hi >= lo, np.nan, 1.0)


def _present_over_time(ts, vals, wstart, wend, **kw):
    ts, vals = _prep(ts, vals)
    if ts.size == 0:
        return np.full(wstart.shape, np.nan)
    lo, hi = window_bounds(ts, wstart, wend)
    return np.where(hi >= lo, 1.0, np.nan)


def _last_over_time(ts, vals, wstart, wend, **kw):
    ts, vals = _prep(ts, vals)
    return _last_sample(ts, vals, wstart, wend)


def _first_over_time(ts, vals, wstart, wend, **kw):
    ts, vals = _prep(ts, vals)
    n = ts.size
    out = np.full(wstart.shape, np.nan)
    if n == 0:
        return out
    lo, hi = window_bounds(ts, wstart, wend)
    lo_c = np.clip(lo, 0, n - 1)
    return np.where(hi >= lo, vals[lo_c], np.nan)


def _rate_over_delta(ts, vals, wstart, wend, **kw):
    """rate for delta-temporality counters = sum_over_time / window_seconds
    (RateFunctions.scala:331 RateOverDeltaChunkedFunctionD)."""
    s = _sum_family("sum")(ts, vals, wstart, wend)
    return s / (wend - wstart) * 1000.0


def _increase_over_delta(ts, vals, wstart, wend, **kw):
    return _sum_family("sum")(ts, vals, wstart, wend)


def _irate_idelta(is_rate: bool):
    def f(ts, vals, wstart, wend, **kw):
        ts, vals = _prep(ts, vals)
        n = ts.size
        out = np.full(wstart.shape, np.nan)
        if n < 2:
            return out
        lo, hi = window_bounds(ts, wstart, wend)
        ok = (hi >= lo + 1)
        hi_c = np.clip(hi, 1, n - 1)
        prev = hi_c - 1
        dv = vals[hi_c] - vals[prev]
        if is_rate:
            # counter reset handling: if drop, use raw last value
            dv = np.where(dv < 0, vals[hi_c], dv)
            dt = (ts[hi_c] - ts[prev]) / 1000.0
            res = dv / np.where(dt == 0, np.nan, dt)
        else:
            res = dv
        return np.where(ok, res, np.nan)
    return f


# Registry: InternalRangeFunction name -> implementation
# (exec/InternalRangeFunction.scala:10; PromQL surface names in comments)
RANGE_FUNCTIONS: Dict[str, Callable] = {
    "rate": _rate_family(True, True, True),
    "increase": _rate_family(True, False, True),
    "delta": _rate_family(False, False, False),
    "irate": _irate_idelta(True),
    "idelta": _irate_idelta(False),
    "sum_over_time": _sum_family("sum"),
    "count_over_time": _sum_family("count"),
    "avg_over_time": _sum_family("avg"),
    "stddev_over_time": _sum_family("stddev"),
    "stdvar_over_time": _sum_family("stdvar"),
    "z_score": _sum_family("zscore"),
    "min_over_time": _minmax_family("min"),
    "max_over_time": _minmax_family("max"),
    "last_over_time": _last_over_time,
    "first_over_time": _first_over_time,
    "changes": _changes,
    "resets": _resets,
    "deriv": _deriv_predict(False),
    "predict_linear": _deriv_predict(True),
    "quantile_over_time": _quantile_over_time,
    "mad_over_time": _mad_over_time,
    "holt_winters": _holt_winters,
    "absent_over_time": _absent_over_time,
    "present_over_time": _present_over_time,
    "timestamp": _timestamp_fn,
    "rate_over_delta": _rate_over_delta,
    "increase_over_delta": _increase_over_delta,
    "last_sample": _last_sample,   # instant selector w/ lookback
}

# functions that interpret the value column as a monotonic counter
COUNTER_FUNCTIONS = frozenset({"rate", "increase", "irate", "resets"})

# functions whose semantics assume a gauge: applying them to a counter
# silently ignores resets (promlint warns — semant.py schema family)
GAUGE_FUNCTIONS = frozenset({"delta", "idelta", "deriv"})

# scalar-parameter arity per range function beyond the range-vector arg
# (promlint arity checking; the parser's plan builder indexes args
# positionally and would IndexError without this pre-check)
RANGE_FN_SCALAR_ARITY: Dict[str, int] = {
    "quantile_over_time": 1, "z_score": 0, "mad_over_time": 0,
    "predict_linear": 1, "holt_winters": 2,
}


def evaluate(func: str, ts: np.ndarray, vals: np.ndarray,
             start_ms: int, step_ms: int, end_ms: int, window_ms: int,
             scalar: Optional[float] = None,
             scalar2: Optional[float] = None) -> np.ndarray:
    """Evaluate one range function for one series over a periodic step grid.

    Output step timestamps are start_ms, start_ms+step, ..., <= end_ms; each
    step t evaluates over [t - window, t] (inclusive-range default)."""
    steps = np.arange(start_ms, end_ms + 1, step_ms, dtype=np.int64)
    wend = steps
    wstart = steps - window_ms
    fn = RANGE_FUNCTIONS.get(func)
    if fn is None:
        raise RangeFunctionError(f"unknown range function: {func}")
    return fn(np.asarray(ts, dtype=np.int64),
              np.asarray(vals, dtype=np.float64),
              wstart, wend, scalar=scalar, scalar2=scalar2)


def step_grid(start_ms: int, step_ms: int, end_ms: int) -> np.ndarray:
    return np.arange(start_ms, end_ms + 1, step_ms, dtype=np.int64)
