"""Query engine: LogicalPlan -> ExecPlan -> windowed range functions and
aggregations, with a numpy oracle backend and a JAX/TPU backend.

TPU-native analogue of the reference's ``query/`` module
(query/src/main/scala/filodb/query/*).  The central design change: instead of
row-at-a-time iterators (ChunkedWindowIterator hot loop,
query/exec/PeriodicSamplesMapper.scala:223), series are materialized into
dense ``[num_series, num_samples]`` tiles and every range function is a
vectorized computation over per-window index ranges — `searchsorted` +
cumulative-sum algebra — which maps directly onto the TPU VPU/MXU.
"""
