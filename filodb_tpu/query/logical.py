"""LogicalPlan AST (query/src/main/scala/filodb/query/LogicalPlan.scala:8).

Plans are built by the PromQL parser (filodb_tpu.promql) and materialized by
planners (filodb_tpu.query.planner) into executable plans.  Time fields are
milliseconds throughout.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple, Union

from filodb_tpu.core.index import ColumnFilter


@dataclass(frozen=True)
class RawSeriesPlan:
    """Select raw chunks/samples for series matching filters
    (LogicalPlan.scala:111 RawSeries)."""
    filters: Tuple[ColumnFilter, ...]
    start_ms: int          # data fetch range (already includes lookback)
    end_ms: int
    column: Optional[str] = None   # explicit value column (::col suffix)
    offset_ms: int = 0


@dataclass(frozen=True)
class PeriodicSeries:
    """Instant-vector selector evaluated on a step grid with lookback
    (LogicalPlan.scala:254)."""
    raw: RawSeriesPlan
    start_ms: int
    step_ms: int
    end_ms: int
    lookback_ms: int = 300_000   # Prometheus default staleness lookback
    offset_ms: int = 0
    at_ms: Optional[int] = None


@dataclass(frozen=True)
class PeriodicSeriesWithWindowing:
    """range-function(selector[window]) (LogicalPlan.scala:375)."""
    raw: RawSeriesPlan
    function: str                # range function name (rangefn registry key)
    window_ms: int
    start_ms: int
    step_ms: int
    end_ms: int
    func_args: Tuple[float, ...] = ()
    offset_ms: int = 0
    at_ms: Optional[int] = None


@dataclass(frozen=True)
class SubqueryWithWindowing:
    """range-function(<expr>[w:s]) (LogicalPlan.scala:307)."""
    inner: "LogicalPlan"
    function: str
    window_ms: int
    sub_step_ms: int
    start_ms: int
    step_ms: int
    end_ms: int
    func_args: Tuple[float, ...] = ()
    offset_ms: int = 0
    # @-pinned evaluation time (LogicalPlan.scala:349): the subquery grid
    # ends at at_ms and every outer step carries the same pinned value
    at_ms: Optional[int] = None


@dataclass(frozen=True)
class TopLevelSubquery:
    """<expr>[w:s] as the outermost expression (LogicalPlan.scala:349)."""
    inner: "LogicalPlan"
    start_ms: int
    step_ms: int
    end_ms: int
    original_lookback_ms: int = 0
    offset_ms: int = 0


@dataclass(frozen=True)
class Aggregate:
    """sum/avg/min/max/count/topk/... by (labels) (LogicalPlan.scala:429)."""
    op: str
    inner: "LogicalPlan"
    params: Tuple = ()                      # k for topk, q for quantile, ...
    by: Tuple[str, ...] = ()
    without: Tuple[str, ...] = ()


@dataclass(frozen=True)
class BinaryJoin:
    """vector-vector binary operation (LogicalPlan.scala:453)."""
    lhs: "LogicalPlan"
    op: str
    rhs: "LogicalPlan"
    cardinality: str = "one-to-one"   # one-to-one | many-to-one | one-to-many
    on: Optional[Tuple[str, ...]] = None
    ignoring: Tuple[str, ...] = ()
    include: Tuple[str, ...] = ()     # group_left/right(include)
    return_bool: bool = False


@dataclass(frozen=True)
class ScalarVectorBinaryOperation:
    """scalar op vector / vector op scalar (LogicalPlan.scala)."""
    op: str
    scalar: "LogicalPlan"     # ScalarPlan
    vector: "LogicalPlan"
    scalar_is_lhs: bool
    return_bool: bool = False


@dataclass(frozen=True)
class ApplyInstantFunction:
    inner: "LogicalPlan"
    function: str
    func_args: Tuple["LogicalPlan", ...] = ()


@dataclass(frozen=True)
class ApplyMiscellaneousFunction:
    inner: "LogicalPlan"
    function: str            # label_replace | label_join | ...
    str_args: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ApplySortFunction:
    inner: "LogicalPlan"
    descending: bool = False


@dataclass(frozen=True)
class ApplyLimitFunction:
    inner: "LogicalPlan"
    limit: int = 0


@dataclass(frozen=True)
class ApplyAbsentFunction:
    inner: "LogicalPlan"
    filters: Tuple[ColumnFilter, ...]
    start_ms: int = 0
    step_ms: int = 0
    end_ms: int = 0


@dataclass(frozen=True)
class ScalarTimeBasedPlan:
    """time(), hour(), ... evaluated on the step grid (ScalarPlan family)."""
    function: str
    start_ms: int
    step_ms: int
    end_ms: int


@dataclass(frozen=True)
class ScalarFixedDoublePlan:
    value: float
    start_ms: int
    step_ms: int
    end_ms: int


@dataclass(frozen=True)
class ScalarVaryingDoublePlan:
    """scalar(vector-expr) (ScalarVaryingDoublePlan)."""
    inner: "LogicalPlan"
    function: str = "scalar"


@dataclass(frozen=True)
class ScalarBinaryOperation:
    op: str
    lhs: Union[float, "LogicalPlan"]
    rhs: Union[float, "LogicalPlan"]
    start_ms: int = 0
    step_ms: int = 0
    end_ms: int = 0


@dataclass(frozen=True)
class VectorPlan:
    """vector(scalar) (VectorPlan)."""
    scalar: "LogicalPlan"


# --- metadata plans (LogicalPlan.scala metadata section) -------------------

@dataclass(frozen=True)
class LabelValues:
    label: str
    filters: Tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int


@dataclass(frozen=True)
class LabelNames:
    filters: Tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int


@dataclass(frozen=True)
class SeriesKeysByFilters:
    filters: Tuple[ColumnFilter, ...]
    start_ms: int
    end_ms: int


@dataclass(frozen=True)
class TsCardinalities:
    shard_key_prefix: Tuple[str, ...]
    num_groups: int = 2


LogicalPlan = Union[
    RawSeriesPlan, PeriodicSeries, PeriodicSeriesWithWindowing,
    SubqueryWithWindowing, TopLevelSubquery, Aggregate, BinaryJoin,
    ScalarVectorBinaryOperation, ApplyInstantFunction,
    ApplyMiscellaneousFunction, ApplySortFunction, ApplyLimitFunction,
    ApplyAbsentFunction, ScalarTimeBasedPlan, ScalarFixedDoublePlan,
    ScalarVaryingDoublePlan, ScalarBinaryOperation, VectorPlan,
    LabelValues, LabelNames, SeriesKeysByFilters, TsCardinalities,
]


def is_scalar_plan(plan) -> bool:
    return isinstance(plan, (ScalarTimeBasedPlan, ScalarFixedDoublePlan,
                             ScalarVaryingDoublePlan, ScalarBinaryOperation))


def is_metadata_plan(plan) -> bool:
    return isinstance(plan, (LabelValues, LabelNames, SeriesKeysByFilters,
                             TsCardinalities))
