"""Pallas TPU kernels for the windowed query hot loop.

The reference's inner loop (rangefn/RangeFunction.scala:122 addChunks:
per-chunk binary search + accumulate per window) becomes one fused kernel
over dense series tiles. XLA-level formulations are all bottlenecked on
TPU: vmapped searchsorted serializes, per-element gathers cost ~40ns, f64
scatters ~100ns. This kernel instead computes, per (series row, window):

  * ``started[t,i] = ts_i <= wend_t`` and ``after[t,i] = ts_i >= wstart_t``
    — with sorted rows these are prefix/suffix masks, so the FIRST sample
    >= wstart and LAST sample <= wend are mask XOR-shifts (no search);
  * window sample counts as mask reductions;
  * boundary timestamps/values as one-hot masked reductions (each has
    exactly ONE nonzero term, so f32/int32 accumulation is exact).

f64 payloads (Prometheus semantics) are carried as THREE f32 channels
(24+24+5 mantissa bits >= 53): split3() is exact, each channel extraction
is exact, and the f64 recombine outside the kernel is exact.

Timestamps enter as int32 offsets relative to the first window start —
callers must guard that the whole query span fits in int31 (~24.8 days).
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# int32 sentinel for padded samples: beyond any valid relative timestamp
TR_PAD = np.int32(2**31 - 1)

# tile sizes: BS series rows x TT windows per program (TPU block tiling
# requires multiples of (8, 128) on the trailing dims); the kernel loops
# over _TC-window chunks internally so mask temporaries stay [BS, TC, N]
_BS = 8
_TT = 128
_TC = 32


def split3(v: jnp.ndarray) -> jnp.ndarray:
    """Exactly split f64 [S, N] into three stacked f32 channels [S, 3, N]:
    v == h + m + l with no rounding (53 <= 24+24+24 mantissa bits)."""
    h = v.astype(jnp.float32)
    r = v - h.astype(jnp.float64)
    m = r.astype(jnp.float32)
    l = (r - m.astype(jnp.float64)).astype(jnp.float32)
    return jnp.stack([h, m, l], axis=1)


def combine3(c: jnp.ndarray) -> jnp.ndarray:
    """[..., 3, T] f32 channels -> f64 (exact)."""
    return (c[..., 0, :].astype(jnp.float64)
            + c[..., 1, :].astype(jnp.float64)
            + c[..., 2, :].astype(jnp.float64))


# ---------------------------------------------------------------------------
# Fused counter group-sum kernel: the north-star `sum by (g) (rate(c[w]))`
# as ONE pass over the stride-permuted tiles. XLA's best arrangement of
# the same computation (slices -> epilogue -> one-hot matmul) pays ~2.5x
# the HBM traffic materializing the [T, S] rate intermediate and
# re-reading it on the MXU; here the 4 boundary row-blocks per step-tile
# are DMA'd HBM->VMEM (double-buffered), the f32 extrapolation epilogue
# (rangefn/RateFunctions.scala:23-79 semantics) runs in VMEM, and only
# the [T, G] group sums + counts ever leave the chip. Values ride the
# exact 3xf32 split (53 <= 24*3 mantissa bits), so boundary deltas keep
# f64 precision without f64 ALU ops.
# ---------------------------------------------------------------------------

_GS_TT = 128           # query steps per tile (sublane dim of compute)
_GS_SS = 512           # series per tile (lane dim)
_GS_AL = 8             # sublane alignment Mosaic requires of HBM slices


def _groupsum_kernel(func: str, st: int, n_ttiles: int,
                     params_ref, v_ref, oh_ref,
                     sum_ref, cnt_ref, v_scr, sems):
    """Grid: (n_s,). params (SMEM, i32):
    [kc0, kp0, kl0, kn0, w0e_rel, window, step, counts_base, T].
    """
    si = pl.program_id(0)
    kstarts = [params_ref[0], params_ref[1], params_ref[2], params_ref[3]]
    w0e_rel = params_ref[4]
    window = params_ref[5]
    step = params_ref[6]
    counts_base = params_ref[7]
    T = params_ref[8]

    def fam_g(f, ti):
        """(aligned DMA start, in-block row offset) for family f, tile ti.
        HBM slices on the tiled G dim must start at a sublane-tile
        multiple, so the DMA reads _GS_AL extra rows and the compute
        phase shifts by `off` inside VMEM."""
        kf = kstarts[f]
        g = jax.lax.div(kf, jnp.int32(st)) + ti * _GS_TT
        g8 = pl.multiple_of((g // _GS_AL) * _GS_AL, _GS_AL)
        return g8, g - g8

    def dmas(slot, ti):
        out = []
        for f in range(4):
            kf = kstarts[f]
            r = jax.lax.rem(kf, jnp.int32(st))
            # the permuted G axis is padded past every tail tile
            # (t_perm_tiled), so the block stays in bounds; dead rows
            # are masked out of every contribution below via `live`.
            # ONE copy per family: timestamps (bitcast f32) + h/m/l
            # value planes ride a single CONTIGUOUS HBM read —
            # consecutive G rows of a (s-tile, residue) plane are
            # adjacent in memory.
            g8, _ = fam_g(f, ti)
            out.append(pltpu.make_async_copy(
                v_ref.at[si, r, pl.ds(g8, _GS_TT + _GS_AL), :],
                v_scr.at[slot, f], sems.at[slot, f]))
        return out

    @pl.when(si == 0)
    def _():
        sum_ref[:] = jnp.zeros_like(sum_ref)
        cnt_ref[:] = jnp.zeros_like(cnt_ref)

    for d in dmas(0, 0):
        d.start()

    def t_loop(ti, _):
        slot = jax.lax.rem(ti, 2)
        nxt = jax.lax.rem(ti + 1, 2)

        @pl.when(ti + 1 < n_ttiles)
        def _():
            for d in dmas(nxt, ti + 1):
                d.start()
        for d in dmas(slot, ti):
            d.wait()

        gt = ti * _GS_TT + jax.lax.broadcasted_iota(
            jnp.int32, (_GS_TT, 1), 0)                     # [TT, 1]
        live = gt < T
        wend_r = w0e_rel + gt * step
        wstart_r = wend_r - window
        offs = [fam_g(f, ti)[1] for f in range(4)]

        def shifted(full, f):
            """Drop the first `offs[f]` alignment rows of a loaded
            [TT+AL, SS] block -> [TT, SS] via dynamic sublane rotate
            (plain dynamic_slice on vectors has no Mosaic lowering, and
            NEGATIVE dynamic roll shifts mis-lower — rotate left by
            `len - off` instead)."""
            return pltpu.roll(full, shift=(_GS_TT + _GS_AL) - offs[f],
                              axis=0)[:_GS_TT]

        vs = [shifted(v_scr[slot, f], f) for f in range(4)]

        def tsch(f):
            return vs[f][:, :_GS_SS]

        ts_kc = tsch(0)
        ts_kp = tsch(1)
        ts_kcl = tsch(2)
        ts_kn = tsch(3)
        over = ts_kc > wend_r
        under = ts_kcl < wstart_r
        counts = (counts_base - over.astype(jnp.int32)
                  - under.astype(jnp.int32))
        use1 = ~over                                       # ts_kc <= wend
        useb = ~under
        t2 = jnp.where(use1, ts_kc, ts_kp)
        t1 = jnp.where(useb, ts_kcl, ts_kn)

        def vch(f, c):
            """h/m/l plane c of family f (packed after the ts plane)."""
            return jax.lax.bitcast_convert_type(
                vs[f][:, (c + 1) * _GS_SS:(c + 2) * _GS_SS], jnp.float32)

        h2 = jnp.where(use1, vch(0, 0), vch(1, 0))
        m2 = jnp.where(use1, vch(0, 1), vch(1, 1))
        l2 = jnp.where(use1, vch(0, 2), vch(1, 2))
        h1 = jnp.where(useb, vch(2, 0), vch(3, 0))
        m1 = jnp.where(useb, vch(2, 1), vch(3, 1))
        l1 = jnp.where(useb, vch(2, 2), vch(3, 2))
        # exact-split delta: each per-channel difference is (near-)exact,
        # and the sum telescopes to the f64 difference (see split3)
        delta = (h2 - h1) + (m2 - m1) + (l2 - l1)
        sampled = (t2 - t1).astype(jnp.float32) * 1e-3
        dstart = (t1 - wstart_r).astype(jnp.float32) * 1e-3
        dend = (wend_r - t2).astype(jnp.float32) * 1e-3
        counts_f = counts.astype(jnp.float32)
        avg = sampled / (counts_f - 1.0)
        if func != "delta":
            v1f = h1 + (m1 + l1)
            dzero = jnp.where(
                (delta > 0) & (v1f >= 0),
                sampled * (v1f / jnp.where(delta == 0, jnp.nan, delta)),
                jnp.inf)
            dstart = jnp.minimum(dstart, dzero)
        th = avg * 1.1
        extrap = sampled \
            + jnp.where(dstart < th, dstart, avg * 0.5) \
            + jnp.where(dend < th, dend, avg * 0.5)
        factor = extrap / sampled
        if func == "rate":
            factor = factor / (window.astype(jnp.float32) * 1e-3)
        out = delta * factor
        ok = live & (counts >= 2) & ~jnp.isnan(out)
        local = jnp.where(ok, out, jnp.float32(0.0))
        okf = jnp.where(ok, jnp.float32(1.0), jnp.float32(0.0))
        oh = oh_ref[:]
        sl = pl.ds(ti * _GS_TT, _GS_TT)
        # HIGHEST: the MXU's default bf16 input truncation would round
        # every rate to 8 mantissa bits (bf16(0.1) = 0.10009765625)
        sum_ref[sl, :] += jnp.dot(local, oh,
                                  preferred_element_type=jnp.float32,
                                  precision=jax.lax.Precision.HIGHEST)
        cnt_ref[sl, :] += jnp.dot(okf, oh,
                                  preferred_element_type=jnp.float32,
                                  precision=jax.lax.Precision.HIGHEST)

    jax.lax.fori_loop(0, n_ttiles, t_loop, None)


def counter_groupsum(func: str, st: int, v_p, onehot,
                     kc0: int, kl0: int, w0e_rel: int, window: int,
                     step: int, nsteps: int,
                     interpret: bool = False):
    """sum by(group) of rate/increase/delta over stride-permuted dense
    tiles -> (sums f32 [T, G], counts f32 [T, G]; sum is only meaningful
    where count > 0).

    v_p: the packed kernel channel [n_s, st, G_perm, 4*_GS_SS] i32 —
    plane 0 = int32 relative timestamps, planes 1-3 = the exact 3xf32
    split BITCAST to i32 (int lanes are inert in data movement; i32
    timestamps bitcast to f32 would be flush-to-zero denormals) of the
    (counter-corrected) value channel
    (AlignedTiles.t_perm_split_tiled). onehot: [n_s * _GS_SS, G] f32
    group membership (pad series with all-zero one-hot rows).
    Preconditions (the tilestore dispatcher checks them): regular grid
    step == st*dt entirely interior to the tile, dense tiles, span fits
    int32 ms."""
    n_s = v_p.shape[0]
    G = onehot.shape[1]
    assert onehot.shape[0] == n_s * _GS_SS, (onehot.shape, n_s)
    T_pad = -(-nsteps // _GS_TT) * _GS_TT
    n_ttiles = T_pad // _GS_TT
    params = jnp.asarray(
        jnp.stack([jnp.asarray(v, jnp.int32) for v in (
            kc0, kc0 - 1, kl0, kl0 + 1, w0e_rel, window, step,
            kc0 + 1 - kl0, nsteps)]))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_s,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((_GS_SS, G), lambda si, p: (si, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((T_pad, G), lambda si, p: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((T_pad, G), lambda si, p: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, 4, _GS_TT + _GS_AL, 4 * _GS_SS), jnp.int32),
            pltpu.SemaphoreType.DMA((2, 4)),
        ],
    )
    with jax.enable_x64(False):
        sums, cnts = pl.pallas_call(
            functools.partial(_groupsum_kernel, func, st, n_ttiles),
            grid_spec=grid_spec,
            out_shape=(
                jax.ShapeDtypeStruct((T_pad, G), jnp.float32),
                jax.ShapeDtypeStruct((T_pad, G), jnp.float32),
            ),
            interpret=interpret,
        )(params, v_p, onehot)
    return sums[:nsteps], cnts[:nsteps]


def _extract_kernel(nchan: int, params_ref, tr_ref, pay_ref,
                    cnt_ref, tlo_ref, thi_ref, plo_ref, phi_ref):
    """One (series-tile, window-tile) program."""
    j = pl.program_id(1)
    step = params_ref[0, 0]
    window = params_ref[0, 1]
    tr = tr_ref[:]                                        # [BS, N] i32
    trb = tr[:, None, :]                                  # [BS, 1, N]
    # neighbor timestamps (computed once, 2D int32 — Mosaic cannot
    # concatenate i1 vectors, so shift masks are derived by comparison)
    tr_next = jnp.concatenate(
        [tr[:, 1:], jnp.full_like(tr[:, :1], TR_PAD)], axis=1)
    tr_prev = jnp.concatenate(
        [jnp.full_like(tr[:, :1], jnp.int32(-2**31)), tr[:, :-1]], axis=1)
    trn = tr_next[:, None, :]
    trp = tr_prev[:, None, :]
    for sub in range(_TT // _TC):
        t_idx = jax.lax.broadcasted_iota(jnp.int32, (1, _TC, 1), 1)
        wstart = (j * _TT + sub * _TC + t_idx) * step     # [1, TC, 1]
        wend = wstart + window
        started = trb <= wend                             # [BS, TC, N]
        after = trb >= wstart
        inwin = started & after
        sl_t = slice(sub * _TC, (sub + 1) * _TC)
        cnt_ref[:, sl_t] = jnp.where(inwin, jnp.int32(1),
                                     jnp.int32(0)).sum(
            axis=2, dtype=jnp.int32)
        # last in-window sample: started is prefix-true (rows sorted),
        # so the transition is where the NEXT sample is past wend
        oh_hi = started & (trn > wend) & after
        # first in-window sample: after is suffix-true; transition where
        # the PREVIOUS sample is before wstart
        oh_lo = after & (trp < wstart) & started
        tlo_ref[:, sl_t] = jnp.where(oh_lo, trb, jnp.int32(0)).sum(
            axis=2, dtype=jnp.int32)
        thi_ref[:, sl_t] = jnp.where(oh_hi, trb, jnp.int32(0)).sum(
            axis=2, dtype=jnp.int32)
        for c in range(nchan):
            v = pay_ref[:, c, :][:, None, :]              # [BS, 1, N]
            plo_ref[:, c, sl_t] = jnp.where(oh_lo, v, jnp.float32(0)).sum(
                axis=2, dtype=jnp.float32)
            phi_ref[:, c, sl_t] = jnp.where(oh_hi, v, jnp.float32(0)).sum(
                axis=2, dtype=jnp.float32)


def window_extract(tr: jnp.ndarray, pay: jnp.ndarray,
                   step, window, nsteps: int,
                   interpret: bool = False
                   ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                              jnp.ndarray, jnp.ndarray]:
    """Run the boundary-extract kernel.

    tr:  [S, N] int32 sample times relative to the FIRST window start
         (pad = TR_PAD). S must be a multiple of the row tile.
    pay: [S, C, N] f32 payload channels to extract at window boundaries.
    Windows: wstart_t = t*step (relative), wend_t = wstart_t + window.

    Returns (counts i32 [S,T], t_lo i32, t_hi i32,
             pay_at_lo f32 [S,C,T], pay_at_hi f32 [S,C,T]) — entries only
    meaningful where counts >= 1."""
    S, C, N = pay.shape
    T_pad = -(-nsteps // _TT) * _TT
    S_pad = -(-S // _BS) * _BS
    if S_pad != S:
        tr = jnp.pad(tr, ((0, S_pad - S), (0, 0)),
                     constant_values=TR_PAD)
        pay = jnp.pad(pay, ((0, S_pad - S), (0, 0), (0, 0)))
    params = jnp.array([[step, window]], dtype=jnp.int32)
    grid = (S_pad // _BS, T_pad // _TT)
    out_shapes = (
        jax.ShapeDtypeStruct((S_pad, T_pad), jnp.int32),
        jax.ShapeDtypeStruct((S_pad, T_pad), jnp.int32),
        jax.ShapeDtypeStruct((S_pad, T_pad), jnp.int32),
        jax.ShapeDtypeStruct((S_pad, C, T_pad), jnp.float32),
        jax.ShapeDtypeStruct((S_pad, C, T_pad), jnp.float32),
    )
    st_spec = pl.BlockSpec((_BS, _TT), lambda i, j: (i, j),
                           memory_space=pltpu.VMEM)
    st3_spec = pl.BlockSpec((_BS, C, _TT), lambda i, j: (i, 0, j),
                            memory_space=pltpu.VMEM)
    # trace the kernel in 32-bit mode: under jax_enable_x64, index-map and
    # literal constants become i64, which Mosaic cannot legalize
    with jax.enable_x64(False):
        outs = pl.pallas_call(
            functools.partial(_extract_kernel, C),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 2), lambda i, j: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((_BS, N), lambda i, j: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((_BS, C, N), lambda i, j: (i, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=(st_spec, st_spec, st_spec, st3_spec, st3_spec),
            out_shape=out_shapes,
            interpret=interpret,
        )(params, tr, pay)
    cnt, tlo, thi, plo, phi = outs
    return (cnt[:S, :nsteps], tlo[:S, :nsteps], thi[:S, :nsteps],
            plo[:S, :, :nsteps], phi[:S, :, :nsteps])
