"""Pallas TPU kernels for the windowed query hot loop.

The reference's inner loop (rangefn/RangeFunction.scala:122 addChunks:
per-chunk binary search + accumulate per window) becomes one fused kernel
over dense series tiles. XLA-level formulations are all bottlenecked on
TPU: vmapped searchsorted serializes, per-element gathers cost ~40ns, f64
scatters ~100ns. This kernel instead computes, per (series row, window):

  * ``started[t,i] = ts_i <= wend_t`` and ``after[t,i] = ts_i >= wstart_t``
    — with sorted rows these are prefix/suffix masks, so the FIRST sample
    >= wstart and LAST sample <= wend are mask XOR-shifts (no search);
  * window sample counts as mask reductions;
  * boundary timestamps/values as one-hot masked reductions (each has
    exactly ONE nonzero term, so f32/int32 accumulation is exact).

f64 payloads (Prometheus semantics) are carried as THREE f32 channels
(24+24+5 mantissa bits >= 53): split3() is exact, each channel extraction
is exact, and the f64 recombine outside the kernel is exact.

Timestamps enter as int32 offsets relative to the first window start —
callers must guard that the whole query span fits in int31 (~24.8 days).
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# int32 sentinel for padded samples: beyond any valid relative timestamp
TR_PAD = np.int32(2**31 - 1)

# tile sizes: BS series rows x TT windows per program (TPU block tiling
# requires multiples of (8, 128) on the trailing dims); the kernel loops
# over _TC-window chunks internally so mask temporaries stay [BS, TC, N]
_BS = 8
_TT = 128
_TC = 32


def split3(v: jnp.ndarray) -> jnp.ndarray:
    """Exactly split f64 [S, N] into three stacked f32 channels [S, 3, N]:
    v == h + m + l with no rounding (53 <= 24+24+24 mantissa bits)."""
    h = v.astype(jnp.float32)
    r = v - h.astype(jnp.float64)
    m = r.astype(jnp.float32)
    l = (r - m.astype(jnp.float64)).astype(jnp.float32)
    return jnp.stack([h, m, l], axis=1)


def combine3(c: jnp.ndarray) -> jnp.ndarray:
    """[..., 3, T] f32 channels -> f64 (exact)."""
    return (c[..., 0, :].astype(jnp.float64)
            + c[..., 1, :].astype(jnp.float64)
            + c[..., 2, :].astype(jnp.float64))


def _extract_kernel(nchan: int, params_ref, tr_ref, pay_ref,
                    cnt_ref, tlo_ref, thi_ref, plo_ref, phi_ref):
    """One (series-tile, window-tile) program."""
    j = pl.program_id(1)
    step = params_ref[0, 0]
    window = params_ref[0, 1]
    tr = tr_ref[:]                                        # [BS, N] i32
    trb = tr[:, None, :]                                  # [BS, 1, N]
    # neighbor timestamps (computed once, 2D int32 — Mosaic cannot
    # concatenate i1 vectors, so shift masks are derived by comparison)
    tr_next = jnp.concatenate(
        [tr[:, 1:], jnp.full_like(tr[:, :1], TR_PAD)], axis=1)
    tr_prev = jnp.concatenate(
        [jnp.full_like(tr[:, :1], jnp.int32(-2**31)), tr[:, :-1]], axis=1)
    trn = tr_next[:, None, :]
    trp = tr_prev[:, None, :]
    for sub in range(_TT // _TC):
        t_idx = jax.lax.broadcasted_iota(jnp.int32, (1, _TC, 1), 1)
        wstart = (j * _TT + sub * _TC + t_idx) * step     # [1, TC, 1]
        wend = wstart + window
        started = trb <= wend                             # [BS, TC, N]
        after = trb >= wstart
        inwin = started & after
        sl_t = slice(sub * _TC, (sub + 1) * _TC)
        cnt_ref[:, sl_t] = jnp.where(inwin, jnp.int32(1),
                                     jnp.int32(0)).sum(
            axis=2, dtype=jnp.int32)
        # last in-window sample: started is prefix-true (rows sorted),
        # so the transition is where the NEXT sample is past wend
        oh_hi = started & (trn > wend) & after
        # first in-window sample: after is suffix-true; transition where
        # the PREVIOUS sample is before wstart
        oh_lo = after & (trp < wstart) & started
        tlo_ref[:, sl_t] = jnp.where(oh_lo, trb, jnp.int32(0)).sum(
            axis=2, dtype=jnp.int32)
        thi_ref[:, sl_t] = jnp.where(oh_hi, trb, jnp.int32(0)).sum(
            axis=2, dtype=jnp.int32)
        for c in range(nchan):
            v = pay_ref[:, c, :][:, None, :]              # [BS, 1, N]
            plo_ref[:, c, sl_t] = jnp.where(oh_lo, v, jnp.float32(0)).sum(
                axis=2, dtype=jnp.float32)
            phi_ref[:, c, sl_t] = jnp.where(oh_hi, v, jnp.float32(0)).sum(
                axis=2, dtype=jnp.float32)


def window_extract(tr: jnp.ndarray, pay: jnp.ndarray,
                   step, window, nsteps: int,
                   interpret: bool = False
                   ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                              jnp.ndarray, jnp.ndarray]:
    """Run the boundary-extract kernel.

    tr:  [S, N] int32 sample times relative to the FIRST window start
         (pad = TR_PAD). S must be a multiple of the row tile.
    pay: [S, C, N] f32 payload channels to extract at window boundaries.
    Windows: wstart_t = t*step (relative), wend_t = wstart_t + window.

    Returns (counts i32 [S,T], t_lo i32, t_hi i32,
             pay_at_lo f32 [S,C,T], pay_at_hi f32 [S,C,T]) — entries only
    meaningful where counts >= 1."""
    S, C, N = pay.shape
    T_pad = -(-nsteps // _TT) * _TT
    S_pad = -(-S // _BS) * _BS
    if S_pad != S:
        tr = jnp.pad(tr, ((0, S_pad - S), (0, 0)),
                     constant_values=TR_PAD)
        pay = jnp.pad(pay, ((0, S_pad - S), (0, 0), (0, 0)))
    params = jnp.array([[step, window]], dtype=jnp.int32)
    grid = (S_pad // _BS, T_pad // _TT)
    out_shapes = (
        jax.ShapeDtypeStruct((S_pad, T_pad), jnp.int32),
        jax.ShapeDtypeStruct((S_pad, T_pad), jnp.int32),
        jax.ShapeDtypeStruct((S_pad, T_pad), jnp.int32),
        jax.ShapeDtypeStruct((S_pad, C, T_pad), jnp.float32),
        jax.ShapeDtypeStruct((S_pad, C, T_pad), jnp.float32),
    )
    st_spec = pl.BlockSpec((_BS, _TT), lambda i, j: (i, j),
                           memory_space=pltpu.VMEM)
    st3_spec = pl.BlockSpec((_BS, C, _TT), lambda i, j: (i, 0, j),
                            memory_space=pltpu.VMEM)
    # trace the kernel in 32-bit mode: under jax_enable_x64, index-map and
    # literal constants become i64, which Mosaic cannot legalize
    with jax.enable_x64(False):
        outs = pl.pallas_call(
            functools.partial(_extract_kernel, C),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 2), lambda i, j: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((_BS, N), lambda i, j: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((_BS, C, N), lambda i, j: (i, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=(st_spec, st_spec, st_spec, st3_spec, st3_spec),
            out_shape=out_shapes,
            interpret=interpret,
        )(params, tr, pay)
    cnt, tlo, thi, plo, phi = outs
    return (cnt[:S, :nsteps], tlo[:S, :nsteps], thi[:S, :nsteps],
            plo[:S, :, :nsteps], phi[:S, :, :nsteps])
