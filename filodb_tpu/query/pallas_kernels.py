"""Pallas TPU kernels for the windowed query hot loop.

The reference's inner loop (rangefn/RangeFunction.scala:122 addChunks:
per-chunk binary search + accumulate per window) becomes one fused kernel
over dense series tiles. XLA-level formulations are all bottlenecked on
TPU: vmapped searchsorted serializes, per-element gathers cost ~40ns, f64
scatters ~100ns. This kernel instead computes, per (series row, window):

  * ``started[t,i] = ts_i <= wend_t`` and ``after[t,i] = ts_i >= wstart_t``
    — with sorted rows these are prefix/suffix masks, so the FIRST sample
    >= wstart and LAST sample <= wend are mask XOR-shifts (no search);
  * window sample counts as mask reductions;
  * boundary timestamps/values as one-hot masked reductions (each has
    exactly ONE nonzero term, so f32/int32 accumulation is exact).

f64 payloads (Prometheus semantics) are carried as THREE f32 channels
(24+24+5 mantissa bits >= 53): split3() is exact, each channel extraction
is exact, and the f64 recombine outside the kernel is exact.

Timestamps enter as int32 offsets relative to the first window start —
callers must guard that the whole query span fits in int31 (~24.8 days).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from filodb_tpu.lint.contracts import ANY, SEM, SMEM, Block, kernel_contract
from filodb_tpu.lint.numerics import precision

# jax dropped / moved the top-level enable_x64 context manager across
# versions; resolve whichever this install provides
if hasattr(jax, "enable_x64"):
    _enable_x64 = jax.enable_x64
else:                                                   # jax <= 0.4.x
    from jax.experimental import enable_x64 as _enable_x64

# int32 sentinel for padded samples: beyond any valid relative timestamp
TR_PAD = np.int32(2**31 - 1)

# tile sizes: BS series rows x TT windows per program (TPU block tiling
# requires multiples of (8, 128) on the trailing dims); the kernel loops
# over _TC-window chunks internally so mask temporaries stay [BS, TC, N]
_BS = 8
_TT = 128
_TC = 32


def split3(v: jnp.ndarray) -> jnp.ndarray:
    """Exactly split f64 [S, N] into three stacked f32 channels [S, 3, N]:
    v == h + m + l with no rounding (53 <= 24+24+24 mantissa bits)."""
    h = v.astype(jnp.float32)
    r = v - h.astype(jnp.float64)
    m = r.astype(jnp.float32)
    l = (r - m.astype(jnp.float64)).astype(jnp.float32)
    return jnp.stack([h, m, l], axis=1)


def combine3(c: jnp.ndarray) -> jnp.ndarray:
    """[..., 3, T] f32 channels -> f64 (exact)."""
    return (c[..., 0, :].astype(jnp.float64)
            + c[..., 1, :].astype(jnp.float64)
            + c[..., 2, :].astype(jnp.float64))


# ---------------------------------------------------------------------------
# Fused counter group-sum kernel: the north-star `sum by (g) (rate(c[w]))`
# as ONE pass over the stride-permuted tiles. XLA's best arrangement of
# the same computation (slices -> epilogue -> one-hot matmul) pays ~2.5x
# the HBM traffic materializing the [T, S] rate intermediate and
# re-reading it on the MXU; here the boundary row-blocks per step-tile
# are DMA'd HBM->VMEM (double-buffered, prefetched across the sequential
# program grid), the f32 extrapolation epilogue
# (rangefn/RateFunctions.scala:23-79 semantics) runs in VMEM, and only
# the [T, G] group sums + counts ever leave the chip.
#
# Values ride a per-series 2xint32 FIXED-POINT channel: at pack time each
# series is rebased to its in-tile midpoint and scaled by a per-series
# power of two so the full in-tile value range spans 61 bits split as
# hi*2^31 + lo. Boundary deltas are computed as exact int32 subtractions
# (dh, dl) and only the final f32 recombine dh*2^(31-s) + dl*2^-s rounds
# — relative to the DELTA, not the absolute counter value — so the error
# is 2^-23|delta| + span*2^-53: the same noise floor as the reference's
# f64 path (RateFunctions.scala computes v2-v1 in f64), at 8 bytes per
# value instead of 16 and with native i32 VPU ops instead of f64
# emulation.
#
# Traffic shape: the dispatcher only takes grids where the window is a
# whole number of steps ((kc0-kl0) % st == 0), which puts the
# window-end family (kc0) and window-start family (kl0) in the SAME
# stride-residue plane, dspan = (kc0-kl0)/st rows apart — one merged DMA
# of TT+dspan rows serves both, and all views are STATIC slices of one
# rolled block. The jitter fallback families (kc0-1 / kl0+1) are elided
# entirely (hi_mode/lo_mode) when the query grid's phase relative to the
# scrape ticks clears the tile's max jitter: then "is the boundary
# sample inside the window" has the same answer for every series and
# every step, statically.
# ---------------------------------------------------------------------------

_GS_TT = 256           # query steps per tile (sublane dim of compute):
#                        256 halves the sequential-grid iteration count
#                        vs 128 — the loop is scalar-core/DMA-issue
#                        bound, so fewer, larger tiles win
_GS_TT_WIDE = 512      # widened step tile: picked per query by
#                        _gs_pipeline when the [T, G] accumulators +
#                        DMA scratch still fit the VMEM budget — halves
#                        the sequential grid again for long ranges
_GS_NBUF_MAX = 3       # deepest DMA pipeline: triple-buffered scratch
#                        keeps the DMA engine (nbuf-1) tiles ahead, so
#                        the HBM read of tile g+2 overlaps tile g's
#                        compute ACROSS sequential-program boundaries

_GS_SS = 512           # series per tile (lane dim)
_GS_AL = 8             # sublane alignment Mosaic requires of HBM slices

# boundary-family modes (static per compiled kernel)
GS_BOTH = 0            # jitter straddles the grid phase: select per element
GS_CUR = 1             # the nominal slot is always inside the window
GS_ALT = 2             # the nominal slot is always outside: use kc0-1/kl0+1

_GS_DSPAN_MAX = 48     # dispatcher cap on window/step (merged-stream rows)

import os as _os  # noqa: E402
# dev-only ablation knob (noroll/noepi/nodot/lowdot). DELIBERATELY only
# honored in interpret/debug mode: every ablation produces WRONG numbers
# by design (they exist to isolate kernel-stage costs in benchmarks),
# so a stray env var must never corrupt compiled production results.
_GS_ABLATE = frozenset(
    x for x in (_os.environ.get("GS_ABLATE") or "").split(",") if x)
_GS_ABLATE_WARNED = False


def _gs_ablate_active(interpret: bool) -> frozenset:
    """Effective ablation set for one kernel build; logs LOUDLY when any
    ablation is active and when a compiled-mode run ignores the knob."""
    global _GS_ABLATE_WARNED
    if not _GS_ABLATE:
        return _GS_ABLATE
    import logging
    log = logging.getLogger(__name__)
    if not interpret:
        if not _GS_ABLATE_WARNED:
            _GS_ABLATE_WARNED = True
            log.warning(
                "GS_ABLATE=%s ignored: ablations only apply in "
                "interpret/debug mode (results would be wrong)",
                ",".join(sorted(_GS_ABLATE)))
        return frozenset()
    log.warning("GS_ABLATE active (%s): group-sum kernel results are "
                "INTENTIONALLY wrong (benchmark ablation mode)",
                ",".join(sorted(_GS_ABLATE)))
    return _GS_ABLATE


def _gs_mlen(st: int, dspan: int, tt: int = _GS_TT) -> int:
    lead = 1 if st == 1 else 0
    return tt + _GS_AL + (-(-(dspan + lead) // _GS_AL)) * _GS_AL


def _gs_nstreams(st: int, hi_mode: int, lo_mode: int) -> int:
    return 1 + (1 if hi_mode != GS_CUR and st != 1 else 0) \
        + (1 if lo_mode != GS_CUR and st != 1 else 0)


def _gs_pipeline(st: int, dspan: int, hi_mode: int, lo_mode: int,
                 nsteps: int, G: int,
                 vmem_budget: int = 14 << 20) -> Optional[Tuple[int, int]]:
    """(tt, nbuf) for one kernel build, or None when no configuration
    fits the VMEM budget: prefer the WIDER step tile (fewer sequential
    grid iterations — the loop is scalar-core/DMA-issue bound), then
    the DEEPER DMA pipeline (prefetch distance nbuf-1 overlaps HBM
    reads with compute across program boundaries). The budget covers
    accumulators + scratch + onehot/base input blocks — the full
    on-chip footprint, so an inadmissible query falls back on the host
    instead of exploding at Mosaic compile time."""
    nstreams = _gs_nstreams(st, hi_mode, lo_mode)
    fixed = _GS_SS * G * 4 + 8 * _GS_SS * 4          # onehot + base
    for tt in (_GS_TT_WIDE, _GS_TT):
        if tt != _GS_TT and nsteps <= _GS_TT:
            continue                                 # nothing to widen
        t_pad = -(-nsteps // tt) * tt
        accum = 2 * t_pad * G * 4
        mlen = _gs_mlen(st, dspan, tt)
        for nbuf in range(_GS_NBUF_MAX, 1, -1):
            scratch = nbuf * nstreams * mlen * 3 * _GS_SS * 4
            if accum + scratch + fixed <= vmem_budget:
                return tt, nbuf
    return None


def _groupsum_kernel(func: str, st: int, dspan: int, hi_mode: int,
                     lo_mode: int, exact_branch: bool, n_ttiles: int,
                     mlen: int, tt: int, nbuf: int, ablate: frozenset,
                     params_ref, v_ref, base_ref, oh_ref,
                     sum_ref, cnt_ref, v_scr, sems):
    """Grid: (n_s,) sequential. params (SMEM, i32):
    [kl0, w0e_rel, window, step, T]."""
    si = pl.program_id(0)
    n_s = pl.num_programs(0)
    kl0 = params_ref[0]
    w0e_rel = params_ref[1]
    window = params_ref[2]
    step = params_ref[3]
    T = params_ref[4]
    kc0 = kl0 + dspan * st
    lead = 1 if st == 1 else 0
    # st == 1 puts every slot in the single residue plane, so the
    # fallback families live INSIDE the merged block (lead covers kc0-1
    # when dspan == 0); otherwise they are their own streams.
    need1 = hi_mode != GS_CUR and st != 1
    need3 = lo_mode != GS_CUR and st != 1
    idx1 = 1
    idx3 = 1 + (1 if need1 else 0)
    i_kl = lead
    i_kc = lead + dspan
    i_f1 = dspan + lead - 1          # st == 1 only (kc0 - 1)
    i_f3 = lead + 1                  # st == 1 only (kl0 + 1)

    def dmas(si_, slot, ti):
        out = []
        g_m = jax.lax.div(kl0, jnp.int32(st)) + ti * tt - lead
        g8m = pl.multiple_of((g_m // _GS_AL) * _GS_AL, _GS_AL)
        # the permuted G axis is padded past every tail tile
        # (t_perm_tiled), so blocks stay in bounds; dead rows are masked
        # out via `live`. ONE copy per stream: ts + hi + lo planes ride
        # a single contiguous HBM read (consecutive G rows of a
        # (s-tile, residue) plane are adjacent in memory).
        out.append(pltpu.make_async_copy(
            v_ref.at[si_, jax.lax.rem(kl0, jnp.int32(st)),
                     pl.ds(g8m, mlen), :],
            v_scr.at[slot, 0], sems.at[slot, 0]))
        for need, idx, kf in ((need1, idx1, kc0 - 1),
                              (need3, idx3, kl0 + 1)):
            if not need:
                continue
            g = jax.lax.div(kf, jnp.int32(st)) + ti * tt
            g8 = pl.multiple_of((g // _GS_AL) * _GS_AL, _GS_AL)
            out.append(pltpu.make_async_copy(
                v_ref.at[si_, jax.lax.rem(kf, jnp.int32(st)),
                         pl.ds(g8, tt + _GS_AL), :],
                v_scr.at[slot, idx, pl.ds(0, tt + _GS_AL)],
                sems.at[slot, idx]))
        return out

    @pl.when(si == 0)
    def _():
        sum_ref[:] = jnp.zeros_like(sum_ref)
        cnt_ref[:] = jnp.zeros_like(cnt_ref)
        # pipeline warm-up: fill nbuf-1 scratch slots ahead (global
        # tiles 0..nbuf-2, crossing program boundaries for tiny grids)
        for g in range(nbuf - 1):

            @pl.when(jnp.int32(g) < n_s * n_ttiles)
            def _(g=g):
                for d in dmas(jnp.int32(g // n_ttiles), g % nbuf,
                              jnp.int32(g % n_ttiles)):
                    d.start()

    def t_loop(ti, _):
        gti = si * n_ttiles + ti
        slot = jax.lax.rem(gti, nbuf)

        # keep the DMA engine nbuf-1 tiles AHEAD — prefetching across
        # sequential-program boundaries, so the HBM read of tile
        # g+nbuf-1 overlaps tile g's compute and the engine never
        # idles between grid programs
        gn = gti + (nbuf - 1)

        @pl.when(gn < n_s * n_ttiles)
        def _():
            for d in dmas(jax.lax.div(gn, jnp.int32(n_ttiles)),
                          jax.lax.rem(gn, jnp.int32(nbuf)),
                          jax.lax.rem(gn, jnp.int32(n_ttiles))):
                d.start()
        for d in dmas(si, slot, ti):
            d.wait()

        gt = ti * tt + jax.lax.broadcasted_iota(
            jnp.int32, (tt, 1), 0)                         # [TT, 1]
        live = gt < T
        wend_r = w0e_rel + gt * step
        wstart_r = wend_r - window

        g_m = jax.lax.div(kl0, jnp.int32(st)) + ti * tt - lead
        g8m = pl.multiple_of((g_m // _GS_AL) * _GS_AL, _GS_AL)
        offm = g_m - g8m
        # ONE dynamic roll; every family view is a STATIC slice of it
        # (plain dynamic_slice on vectors has no Mosaic lowering, and
        # NEGATIVE dynamic roll shifts mis-lower — rotate left by
        # `len - off` instead). Row i of R is permuted-G row g_m + i.
        if "noroll" in ablate:
            R = v_scr[slot, 0]
        else:
            R = pltpu.roll(v_scr[slot, 0], shift=mlen - offm, axis=0)

        def view(row0):
            return R[row0:row0 + tt]

        def fam_view(idx, kf):
            full = v_scr[slot, idx, :tt + _GS_AL]
            if "noroll" in ablate:
                return full[:tt]
            g = jax.lax.div(kf, jnp.int32(st)) + ti * tt
            off = g - pl.multiple_of((g // _GS_AL) * _GS_AL, _GS_AL)
            return pltpu.roll(full, shift=(tt + _GS_AL) - off,
                              axis=0)[:tt]

        def planes(v):
            return (v[:, :_GS_SS], v[:, _GS_SS:2 * _GS_SS],
                    v[:, 2 * _GS_SS:3 * _GS_SS])

        ts_kc, hi_kc, lo_kc = planes(view(i_kc))
        ts_kl, hi_kl, lo_kl = planes(view(i_kl))
        if hi_mode != GS_CUR:
            ts_kp, hi_kp, lo_kp = planes(
                view(i_f1) if st == 1 else fam_view(idx1, kc0 - 1))
        if lo_mode != GS_CUR:
            ts_kn, hi_kn, lo_kn = planes(
                view(i_f3) if st == 1 else fam_view(idx3, kl0 + 1))

        if hi_mode == GS_BOTH:
            over = ts_kc > wend_r
            overc = over.astype(jnp.int32)
            t2 = jnp.where(over, ts_kp, ts_kc)
            h2 = jnp.where(over, hi_kp, hi_kc)
            l2 = jnp.where(over, lo_kp, lo_kc)
        elif hi_mode == GS_CUR:
            overc = jnp.int32(0)
            t2, h2, l2 = ts_kc, hi_kc, lo_kc
        else:
            overc = jnp.int32(1)
            t2, h2, l2 = ts_kp, hi_kp, lo_kp
        if lo_mode == GS_BOTH:
            under = ts_kl < wstart_r
            underc = under.astype(jnp.int32)
            t1 = jnp.where(under, ts_kn, ts_kl)
            h1 = jnp.where(under, hi_kn, hi_kl)
            l1 = jnp.where(under, lo_kn, lo_kl)
        elif lo_mode == GS_CUR:
            underc = jnp.int32(0)
            t1, h1, l1 = ts_kl, hi_kl, lo_kl
        else:
            underc = jnp.int32(1)
            t1, h1, l1 = ts_kn, hi_kn, lo_kn

        counts = (dspan * st + 1) - overc - underc
        # exact integer boundary deltas; the f32 recombine rounds
        # relative to the delta (see module comment)
        dh = (h2 - h1).astype(jnp.float32)
        dl = (l2 - l1).astype(jnp.float32)
        c1 = base_ref[1:2, :]                              # 2^(31-s)
        c2 = base_ref[2:3, :]                              # 2^-s
        delta = dh * c1 + dl * c2
        sampled_i = t2 - t1
        dstart_i = t1 - wstart_r
        dend_i = wend_r - t2
        sampled = sampled_i.astype(jnp.float32) * 1e-3
        dstart = dstart_i.astype(jnp.float32) * 1e-3
        dend = dend_i.astype(jnp.float32) * 1e-3
        counts_f = counts.astype(jnp.float32)
        avg = sampled / (counts_f - 1.0)
        th = avg * 1.1
        # the "gap < 1.1 * avg interval" extrapolation branches: every
        # input is integer ms, so when 10*counts*window can't overflow
        # i32 the branch is decided EXACTLY as 10*(cnt-1)*gap <=
        # 11*sampled (<=, not <: f64 rounds 1.1 upward, so the
        # reference's f64 compare takes the extrapolate side on exact
        # ties — knife-edge windows otherwise flip between the f32
        # kernel and the f64 oracle)
        if exact_branch:
            cm1 = counts - 1
            s11 = 11 * sampled_i
            use_ds = (10 * cm1) * dstart_i <= s11
            use_de = (10 * cm1) * dend_i <= s11
        else:
            use_ds = dstart < th
            use_de = dend < th
        if func != "delta":
            v1f = (h1.astype(jnp.float32) * c1
                   + l1.astype(jnp.float32) * c2) + base_ref[0:1, :]
            dzero = jnp.where(
                (delta > 0) & (v1f >= 0),
                sampled * (v1f / jnp.where(delta == 0, jnp.nan, delta)),
                jnp.inf)
            zlt = dzero < dstart
            dstart = jnp.where(zlt, dzero, dstart)
            # boolean select via mask algebra (Mosaic has no i1 select)
            use_ds = (zlt & (dzero < th)) | (~zlt & use_ds)
        extrap = sampled \
            + jnp.where(use_ds, dstart, avg * 0.5) \
            + jnp.where(use_de, dend, avg * 0.5)
        factor = extrap / sampled
        if func == "rate":
            factor = factor / (window.astype(jnp.float32) * 1e-3)
        if "noepi" in ablate:
            out = delta
        else:
            out = delta * factor
        ok = live & (counts >= 2) & ~jnp.isnan(out)
        local = jnp.where(ok, out, jnp.float32(0.0))
        okf = jnp.where(ok, jnp.float32(1.0), jnp.float32(0.0))
        oh = oh_ref[:]
        sl = pl.ds(ti * tt, tt)
        if "nodot" in ablate:
            sum_ref[sl, :] += local[:, :16]
            cnt_ref[sl, :] += okf[:, :16]
            return
        # HIGHEST: the MXU's default bf16 input truncation would round
        # every rate to 8 mantissa bits (bf16(0.1) = 0.10009765625)
        prec = (jax.lax.Precision.DEFAULT if "lowdot" in ablate
                else jax.lax.Precision.HIGHEST)
        sum_ref[sl, :] += jnp.dot(local, oh,
                                  preferred_element_type=jnp.float32,
                                  precision=prec)
        cnt_ref[sl, :] += jnp.dot(okf, oh,
                                  preferred_element_type=jnp.float32,
                                  precision=prec)

    jax.lax.fori_loop(0, n_ttiles, t_loop, None)


def _groupsum_example():
    """Abstract inputs for jax.eval_shape: st=1 / dspan=0 / both modes
    GS_CUR is the single-stream configuration (mlen = 272)."""
    g_perm = 512
    args = ("rate", 1, 0, GS_CUR, GS_CUR,
            jax.ShapeDtypeStruct((1, 1, g_perm, 3 * _GS_SS), jnp.int32),
            jax.ShapeDtypeStruct((1, 8, _GS_SS), jnp.float32),
            jax.ShapeDtypeStruct((_GS_SS, 16), jnp.float32),
            1, 5_000, 5_000, 1_000, 256)
    return args, {}


def _groupsum_expect(out):
    want = ((256, 16), jnp.float32)
    for o in out:
        if tuple(o.shape) != want[0] or o.dtype != want[1]:
            return f"output {o.shape}/{o.dtype} != {want}"
    return None


# Worst-case on-chip footprint the tilestore dispatcher may admit (its
# own cap is 14 MB): three DMA streams at the _GS_DSPAN_MAX merged
# length, modest group count. The dispatcher trades streams against
# [T, G] accumulator size; this declaration pins the largest shape on
# the stream-heavy side of that frontier.
@precision(
    "groupsum-recombine-f32", bits=61, rel_ulps=4,
    reason="boundary deltas are exact int32 subtractions of the "
           "fixed-point hi/lo planes; the f32 recombine "
           "dh*2^(31-s) + dl*2^-s rounds relative to the delta (wide "
           "deltas also round dl itself into f32), bounded by a few "
           "f32 ulps plus the span*2^-59 quantization floor — "
           "certified against the direct f64 delta over full-span "
           "boundary pairs; branch decisions stay in integer space "
           "(exact_branch), which mixed-dtype-comparison polices")
@kernel_contract(
    "counter_groupsum", kind="pallas",
    grid=(8,),
    blocks=(
        Block("params", (5,), "int32", space=SMEM, tiled=False),
        Block("v_p", (8, 2, 4096, 3 * _GS_SS), "int32", space=ANY),
        Block("base", (1, 8, _GS_SS), "float32",
              array_shape=(8, 8, _GS_SS),
              index_map=lambda si: (si, 0, 0)),
        Block("onehot", (_GS_SS, 256), "float32",
              array_shape=(8 * _GS_SS, 256),
              index_map=lambda si: (si, 0)),
    ),
    scratch=(
        # worst-case ADMISSIBLE DMA scratch on the (step-tile width,
        # pipeline depth) frontier _gs_pipeline walks: 2 slots x 3
        # streams x mlen(st=2, dspan=48, tt=256)=312 rows x 3 planes
        # (wider tiles / deeper pipelines are only chosen in cheaper
        # stream configurations — the chooser keeps the total <= 14MB)
        Block("v_scr", (2, 3, 312, 3 * _GS_SS), "int32"),
        Block("sems", (2, 3), "int32", space=SEM),
    ),
    outputs=(
        Block("sums", (256, 256), "float32",
              array_shape=(256, 256), index_map=lambda si: (0, 0)),
        Block("cnts", (256, 256), "float32",
              array_shape=(256, 256), index_map=lambda si: (0, 0)),
    ),
    vmem_budget=14 << 20,
    rel_time_bits=31,
    span_guard="filodb_tpu.query.tilestore:_slide_eligible",
    example=_groupsum_example, expect=_groupsum_expect,
    notes="dispatched only via tilestore.groupsum_counters, which "
          "re-derives this footprint per query and falls back to the "
          "general path above 14 MB")
def counter_groupsum(func: str, st: int, dspan: int, hi_mode: int,
                     lo_mode: int, v_p, base, onehot,
                     kl0, w0e_rel, window: int, step: int, nsteps: int,
                     interpret: bool = False,
                     exact_branch: Optional[bool] = None):
    """sum by(group) of rate/increase/delta over stride-permuted dense
    tiles -> (sums f32 [T, G], counts f32 [T, G]; sum is only meaningful
    where count > 0).

    v_p: the packed kernel channel [n_s, st, G_perm, 3*_GS_SS] i32 —
    plane 0 = int32 relative timestamps, planes 1-2 = the per-series
    fixed-point hi/lo split of the (counter-corrected) value channel
    (AlignedTiles.t_perm_fixed_tiled). base: [n_s, 8, _GS_SS] f32 — row
    0 = per-series rebase midpoint (f32), row 1 = 2^(31-s), row 2 =
    2^-s (AlignedTiles.t_fixed_base). onehot: [n_s * _GS_SS, G] f32
    group membership (pad series with all-zero one-hot rows).

    Static dispatch contract (the tilestore dispatcher checks it):
    regular grid with step == st*dt entirely interior to the tile,
    dense tiles, span fits int32 ms, kc0 - kl0 == dspan * st with
    kc0/kl0 the per-query boundary slots, and hi_mode/lo_mode sound for
    the tile's jitter bound (GS_CUR/GS_ALT only when the grid phase
    clears the max |ts - tick|)."""
    n_s = v_p.shape[0]
    G = onehot.shape[1]
    assert onehot.shape[0] == n_s * _GS_SS, (onehot.shape, n_s)
    # step-tile width + DMA pipeline depth for this query shape: widen
    # to _GS_TT_WIDE / deepen to triple-buffering whenever the on-chip
    # footprint allows (callers pre-check _gs_pipeline; this assert is
    # the contract)
    pipe = _gs_pipeline(st, dspan, hi_mode, lo_mode, nsteps, G)
    assert pipe is not None, "caller must gate on _gs_pipeline"
    tt, nbuf = pipe
    T_pad = -(-nsteps // tt) * tt
    n_ttiles = T_pad // tt
    mlen = _gs_mlen(st, dspan, tt)
    if exact_branch is None:
        # integer extrapolation-branch products must fit i32
        exact_branch = 11 * int(window) * (dspan * st + 1) < 2 ** 31
    need1 = hi_mode != GS_CUR and st != 1
    need3 = lo_mode != GS_CUR and st != 1
    nstreams = 1 + (1 if need1 else 0) + (1 if need3 else 0)
    params = jnp.asarray(
        jnp.stack([jnp.asarray(v, jnp.int32) for v in (
            kl0, w0e_rel, window, step, nsteps)]))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_s,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((1, 8, _GS_SS), lambda si, p: (si, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_GS_SS, G), lambda si, p: (si, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((T_pad, G), lambda si, p: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((T_pad, G), lambda si, p: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((nbuf, nstreams, mlen, 3 * _GS_SS), jnp.int32),
            pltpu.SemaphoreType.DMA((nbuf, nstreams)),
        ],
    )

    def body(params, v_p, base, onehot, *, _k=functools.partial(
            _groupsum_kernel, func, st, dspan, hi_mode, lo_mode,
            bool(exact_branch), n_ttiles, mlen, tt, nbuf,
            _gs_ablate_active(interpret))):
        def kern(params_ref, v_ref, base_ref, oh_ref,
                 sum_ref, cnt_ref, v_scr, sems):
            _k(params_ref, v_ref, base_ref[0], oh_ref,
               sum_ref, cnt_ref, v_scr, sems)
        return pl.pallas_call(
            kern,
            grid_spec=grid_spec,
            out_shape=(
                jax.ShapeDtypeStruct((T_pad, G), jnp.float32),
                jax.ShapeDtypeStruct((T_pad, G), jnp.float32),
            ),
            interpret=interpret,
        )(params, v_p, base, onehot)

    with _enable_x64(False):
        sums, cnts = body(params, v_p, base, onehot)
    return sums[:nsteps], cnts[:nsteps]


def _extract_kernel(nchan: int, params_ref, tr_ref, pay_ref,
                    cnt_ref, tlo_ref, thi_ref, plo_ref, phi_ref):
    """One (series-tile, window-tile) program."""
    j = pl.program_id(1)
    step = params_ref[0, 0]
    window = params_ref[0, 1]
    tr = tr_ref[:]                                        # [BS, N] i32
    trb = tr[:, None, :]                                  # [BS, 1, N]
    # neighbor timestamps (computed once, 2D int32 — Mosaic cannot
    # concatenate i1 vectors, so shift masks are derived by comparison)
    tr_next = jnp.concatenate(
        [tr[:, 1:], jnp.full_like(tr[:, :1], TR_PAD)], axis=1)
    tr_prev = jnp.concatenate(
        [jnp.full_like(tr[:, :1], jnp.int32(-2**31)), tr[:, :-1]], axis=1)
    trn = tr_next[:, None, :]
    trp = tr_prev[:, None, :]
    for sub in range(_TT // _TC):
        t_idx = jax.lax.broadcasted_iota(jnp.int32, (1, _TC, 1), 1)
        wstart = (j * _TT + sub * _TC + t_idx) * step     # [1, TC, 1]
        wend = wstart + window
        started = trb <= wend                             # [BS, TC, N]
        after = trb >= wstart
        inwin = started & after
        sl_t = slice(sub * _TC, (sub + 1) * _TC)
        cnt_ref[:, sl_t] = jnp.where(inwin, jnp.int32(1),
                                     jnp.int32(0)).sum(
            axis=2, dtype=jnp.int32)
        # last in-window sample: started is prefix-true (rows sorted),
        # so the transition is where the NEXT sample is past wend
        oh_hi = started & (trn > wend) & after
        # first in-window sample: after is suffix-true; transition where
        # the PREVIOUS sample is before wstart
        oh_lo = after & (trp < wstart) & started
        tlo_ref[:, sl_t] = jnp.where(oh_lo, trb, jnp.int32(0)).sum(
            axis=2, dtype=jnp.int32)
        thi_ref[:, sl_t] = jnp.where(oh_hi, trb, jnp.int32(0)).sum(
            axis=2, dtype=jnp.int32)
        for c in range(nchan):
            v = pay_ref[:, c, :][:, None, :]              # [BS, 1, N]
            plo_ref[:, c, sl_t] = jnp.where(oh_lo, v, jnp.float32(0)).sum(
                axis=2, dtype=jnp.float32)
            phi_ref[:, c, sl_t] = jnp.where(oh_hi, v, jnp.float32(0)).sum(
                axis=2, dtype=jnp.float32)


def _extract_example():
    args = (jax.ShapeDtypeStruct((8, 2048), jnp.int32),
            jax.ShapeDtypeStruct((8, 3, 2048), jnp.float32))
    return args, {"step": 1_000, "window": 5_000, "nsteps": 128}


def _extract_expect(out):
    want = [((8, 128), jnp.int32)] * 3 + [((8, 3, 128), jnp.float32)] * 2
    got = [(tuple(o.shape), o.dtype) for o in out]
    if got != want:
        return f"outputs {got} != {want}"
    return None


# Representative worst case: N = 2048 samples per row block. The [BS,
# TC, N] mask temporaries dominate the footprint — they are compute
# intermediates, declared here as scratch so the budget covers them.
@kernel_contract(
    "window_extract", kind="pallas",
    grid=(4, 2),
    blocks=(
        Block("params", (1, 2), "int32", space=SMEM, tiled=False),
        Block("tr", (_BS, 2048), "int32",
              array_shape=(32, 2048), index_map=lambda i, j: (i, 0)),
        # C=3 payload channels sit mid-block: Mosaic pads the sublane
        # dim, so the (8,128) check is waived for this block
        Block("pay", (_BS, 3, 2048), "float32", tiled=False,
              array_shape=(32, 3, 2048),
              index_map=lambda i, j: (i, 0, 0)),
    ),
    scratch=(
        Block("mask_started", (_BS, _TC, 2048), "int32"),
        Block("mask_after", (_BS, _TC, 2048), "int32"),
        Block("onehot_edges", (_BS, _TC, 2048), "int32"),
    ),
    outputs=(
        Block("cnt", (_BS, _TT), "int32",
              array_shape=(32, 256), index_map=lambda i, j: (i, j)),
        Block("t_lo", (_BS, _TT), "int32",
              array_shape=(32, 256), index_map=lambda i, j: (i, j)),
        Block("t_hi", (_BS, _TT), "int32",
              array_shape=(32, 256), index_map=lambda i, j: (i, j)),
        Block("pay_lo", (_BS, 3, _TT), "float32", tiled=False,
              array_shape=(32, 3, 256),
              index_map=lambda i, j: (i, 0, j)),
        Block("pay_hi", (_BS, 3, _TT), "float32", tiled=False,
              array_shape=(32, 3, 256),
              index_map=lambda i, j: (i, 0, j)),
    ),
    vmem_budget=8 << 20,
    rel_time_bits=31,
    span_guard="filodb_tpu.query.tpu:_window_endpoint_pallas",
    example=_extract_example, expect=_extract_expect,
    notes="rate-family boundary extraction for irregular series; "
          "timestamps are int32 offsets from the first window start "
          "(TR_PAD sentinel for padding)")
def window_extract(tr: jnp.ndarray, pay: jnp.ndarray,
                   step, window, nsteps: int,
                   interpret: bool = False
                   ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                              jnp.ndarray, jnp.ndarray]:
    """Run the boundary-extract kernel.

    tr:  [S, N] int32 sample times relative to the FIRST window start
         (pad = TR_PAD). S must be a multiple of the row tile.
    pay: [S, C, N] f32 payload channels to extract at window boundaries.
    Windows: wstart_t = t*step (relative), wend_t = wstart_t + window.

    Returns (counts i32 [S,T], t_lo i32, t_hi i32,
             pay_at_lo f32 [S,C,T], pay_at_hi f32 [S,C,T]) — entries only
    meaningful where counts >= 1."""
    S, C, N = pay.shape
    T_pad = -(-nsteps // _TT) * _TT
    S_pad = -(-S // _BS) * _BS
    if S_pad != S:
        tr = jnp.pad(tr, ((0, S_pad - S), (0, 0)),
                     constant_values=TR_PAD)
        pay = jnp.pad(pay, ((0, S_pad - S), (0, 0), (0, 0)))
    params = jnp.array([[step, window]], dtype=jnp.int32)
    grid = (S_pad // _BS, T_pad // _TT)
    out_shapes = (
        jax.ShapeDtypeStruct((S_pad, T_pad), jnp.int32),
        jax.ShapeDtypeStruct((S_pad, T_pad), jnp.int32),
        jax.ShapeDtypeStruct((S_pad, T_pad), jnp.int32),
        jax.ShapeDtypeStruct((S_pad, C, T_pad), jnp.float32),
        jax.ShapeDtypeStruct((S_pad, C, T_pad), jnp.float32),
    )
    st_spec = pl.BlockSpec((_BS, _TT), lambda i, j: (i, j),
                           memory_space=pltpu.VMEM)
    st3_spec = pl.BlockSpec((_BS, C, _TT), lambda i, j: (i, 0, j),
                            memory_space=pltpu.VMEM)
    # trace the kernel in 32-bit mode: under jax_enable_x64, index-map and
    # literal constants become i64, which Mosaic cannot legalize
    with _enable_x64(False):
        outs = pl.pallas_call(
            functools.partial(_extract_kernel, C),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 2), lambda i, j: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((_BS, N), lambda i, j: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((_BS, C, N), lambda i, j: (i, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=(st_spec, st_spec, st_spec, st3_spec, st3_spec),
            out_shape=out_shapes,
            interpret=interpret,
        )(params, tr, pay)
    cnt, tlo, thi, plo, phi = outs
    return (cnt[:S, :nsteps], tlo[:S, :nsteps], thi[:S, :nsteps],
            plo[:S, :, :nsteps], phi[:S, :, :nsteps])
