"""Structural LogicalPlan tree serialization for the Exec data plane.

The reference ships whole ExecPlan trees over gRPC as protobuf messages
(grpc/src/main/protobuf/exec_plan.proto,
coordinator/.../ProtoConverters.scala) so remote dispatch never depends
on a printable query text. This is the same capability for this
framework's LogicalPlan dataclasses: a type-tagged structural codec —
every frozen-dataclass plan node, ColumnFilter, tuple and primitive
round-trips; no PromQL printer in the loop. Pushdown/federation prefer
this wire and fall back to the printed-PromQL form only for peers that
predate it.

Wire form: JSON-compatible nested dicts ({"__p__": type_tag, ...fields})
carried inside the gRPC ExecRequest / HTTP exec body.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

from filodb_tpu.core.index import ColumnFilter
from filodb_tpu.query import logical as lp

# every plan node type, by stable tag (class name)
_PLAN_TYPES = {
    name: obj for name, obj in vars(lp).items()
    if dataclasses.is_dataclass(obj)
}
_PLAN_TYPES["ColumnFilter"] = ColumnFilter


def _enc(v: Any):
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        tag = type(v).__name__
        if tag not in _PLAN_TYPES:
            raise ValueError(f"unserializable plan node {tag}")
        out = {"__p__": tag}
        for f in dataclasses.fields(v):
            out[f.name] = _enc(getattr(v, f.name))
        return out
    if isinstance(v, (list, tuple)):
        return {"__t__": [_enc(x) for x in v]}
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    raise ValueError(f"unserializable plan value {type(v).__name__}")


def _dec(v: Any):
    if isinstance(v, dict) and "__p__" in v:
        cls = _PLAN_TYPES.get(v["__p__"])
        if cls is None:
            raise ValueError(f"unknown plan node {v['__p__']}")
        kwargs = {k: _dec(x) for k, x in v.items() if k != "__p__"}
        return cls(**kwargs)
    if isinstance(v, dict) and "__t__" in v:
        return tuple(_dec(x) for x in v["__t__"])
    return v


def plan_to_wire(plan) -> bytes:
    """LogicalPlan tree -> canonical JSON bytes."""
    return json.dumps(_enc(plan), separators=(",", ":"),
                      sort_keys=True).encode()


def plan_from_wire(buf: bytes):
    """Inverse of plan_to_wire."""
    return _dec(json.loads(buf))
