"""LogicalPlan → PromQL string round-trip.

(coordinator/queryplanner/LogicalPlanParser.scala — the reference prints
plans back to PromQL so whole queries can be forwarded to remote clusters
via PromQlRemoteExec.) Returns None for shapes with no faithful PromQL
rendering; callers fall back to leaf dispatch.
"""

from __future__ import annotations

from typing import Optional

import re

from filodb_tpu.query import logical as lp

_METRIC_LABELS = ("_metric_", "__name__")
_IDENT = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _num(x) -> str:
    """Full-precision numeric literal (repr round-trips f64 exactly;
    %g's 6 digits would silently shift @ instants / thresholds)."""
    f = float(x)
    return str(int(f)) if f.is_integer() else repr(f)


def _dur(ms: int) -> str:
    if ms % 3_600_000 == 0:
        return f"{ms // 3_600_000}h"
    if ms % 60_000 == 0:
        return f"{ms // 60_000}m"
    if ms % 1000 == 0:
        return f"{ms // 1000}s"
    return f"{ms}ms"


_OPS = {"eq": "=", "neq": "!=", "re": "=~", "nre": "!~"}


def _q(s: str) -> str:
    """Quote a PromQL string literal (escape backslashes + quotes so the
    peer's parser reads back the identical value)."""
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def _selector(raw: lp.RawSeriesPlan, window_ms: Optional[int],
              offset_ms: int, at_ms: Optional[int]) -> Optional[str]:
    metric = ""
    matchers = []
    for f in raw.filters:
        if f.label in _METRIC_LABELS and f.op == "eq" and not metric \
                and _IDENT.match(f.value):
            metric = f.value
            continue
        op = _OPS.get(f.op)
        if op is None:
            return None     # in/prefix filters have no PromQL spelling
        matchers.append(f"{f.label}{op}{_q(f.value)}")
    s = metric
    if matchers or not metric:
        s += "{" + ",".join(matchers) + "}"
    if raw.column:
        s += f"::{raw.column}"
    if window_ms is not None:
        s += f"[{_dur(window_ms)}]"
    if offset_ms:
        s += f" offset {_dur(offset_ms)}"
    if at_ms is not None:
        s += f" @ {_num(at_ms / 1000)}"
    return s


def plan_to_promql(plan) -> Optional[str]:
    """PromQL text for a plan, or None when not expressible (never
    raises — unprintable shapes fall back to leaf dispatch)."""
    try:
        return _print(plan)
    except (TypeError, ValueError):
        return None


def _print(plan) -> Optional[str]:
    if isinstance(plan, lp.PeriodicSeries):
        return _selector(plan.raw, None, plan.offset_ms, plan.at_ms)
    if isinstance(plan, lp.PeriodicSeriesWithWindowing):
        inner = _selector(plan.raw, plan.window_ms, plan.offset_ms,
                          plan.at_ms)
        if inner is None:
            return None
        # predict_linear/holt_winters take their scalars AFTER the range
        # vector (the parser's RANGE_FN_SCALAR_AFTER table); the rest
        # (quantile_over_time) take them before
        from filodb_tpu.promql.parser import RANGE_FN_SCALAR_AFTER
        if plan.function in RANGE_FN_SCALAR_AFTER:
            args = "".join(f", {_num(a)}" for a in plan.func_args)
            return f"{plan.function}({inner}{args})"
        args = "".join(f"{_num(a)}, " for a in plan.func_args)
        return f"{plan.function}({args}{inner})"
    if isinstance(plan, lp.Aggregate):
        inner = _print(plan.inner)
        if inner is None:
            return None
        mod = ""
        if plan.by:
            mod = f" by ({', '.join(plan.by)})"
        elif plan.without:
            mod = f" without ({', '.join(plan.without)})"
        params = "".join(
            (f"{_q(p)}, " if isinstance(p, str) else f"{_num(p)}, ")
            for p in plan.params)
        return f"{plan.op}({params}{inner}){mod}"
    if isinstance(plan, lp.BinaryJoin):
        lhs = _print(plan.lhs)
        rhs = _print(plan.rhs)
        if lhs is None or rhs is None:
            return None
        op = plan.op + (" bool" if plan.return_bool else "")
        mod = ""
        if plan.on is not None:
            mod = f" on ({', '.join(plan.on)})"
        elif plan.ignoring:
            mod = f" ignoring ({', '.join(plan.ignoring)})"
        # always parenthesize the include list: a bare group_left followed
        # by the parenthesized rhs would parse the parens as labels
        if plan.cardinality == "many-to-one":
            mod += f" group_left({', '.join(plan.include)})"
        elif plan.cardinality == "one-to-many":
            mod += f" group_right({', '.join(plan.include)})"
        return f"({lhs}) {op}{mod} ({rhs})"
    if isinstance(plan, lp.ScalarVectorBinaryOperation):
        sc = _print(plan.scalar)
        vec = _print(plan.vector)
        if sc is None or vec is None:
            return None
        op = plan.op + (" bool" if plan.return_bool else "")
        return f"({sc}) {op} ({vec})" if plan.scalar_is_lhs \
            else f"({vec}) {op} ({sc})"
    if isinstance(plan, lp.ApplyInstantFunction):
        inner = _print(plan.inner)
        if inner is None:
            return None
        args = []
        for a in plan.func_args:
            s = _print(a) if not isinstance(a, (int, float)) \
                else _num(a)
            if s is None:
                return None
            args.append(s)
        # the parser puts scalars BEFORE the vector only for the
        # histogram_quantile family; clamp/round take them after
        from filodb_tpu.promql.parser import INSTANT_FN_SCALAR_FIRST
        if plan.function in INSTANT_FN_SCALAR_FIRST:
            joined = "".join(f"{a}, " for a in args)
            return f"{plan.function}({joined}{inner})"
        joined = "".join(f", {a}" for a in args)
        return f"{plan.function}({inner}{joined})"
    if isinstance(plan, lp.ApplyMiscellaneousFunction):
        inner = _print(plan.inner)
        if inner is None:
            return None
        args = "".join(f", {_q(a)}" for a in plan.str_args)
        return f"{plan.function}({inner}{args})"
    if isinstance(plan, lp.ApplySortFunction):
        inner = _print(plan.inner)
        return None if inner is None else \
            (f"sort_desc({inner})" if plan.descending else f"sort({inner})")
    if isinstance(plan, lp.ScalarFixedDoublePlan):
        return _num(plan.value)
    if isinstance(plan, lp.ScalarTimeBasedPlan):
        return f"{plan.function}()"
    if isinstance(plan, lp.ScalarVaryingDoublePlan):
        inner = _print(plan.inner)
        return None if inner is None else f"scalar({inner})"
    if isinstance(plan, lp.VectorPlan):
        inner = _print(plan.scalar)
        return None if inner is None else f"vector({inner})"
    return None
