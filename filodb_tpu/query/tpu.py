"""TPU/JAX backend for the windowed query hot loop.

This replaces the reference's per-row iterator hot loop
(query/exec/PeriodicSamplesMapper.scala:223 ChunkedWindowIterator;
rangefn/RangeFunction.scala:122 addChunks binary-search + accumulate) with a
single fused XLA computation over dense series tiles:

  1. Series are packed host-side into padded ``[S, N]`` tiles (timestamps
     int64, values float64; NaN stale markers dropped during packing).
  2. Per-window index ranges come from a vmapped ``searchsorted`` — the
     device-wide analogue of the reference's per-chunk binary search.
  3. Endpoint functions (rate family, last/first) and prefix-sum functions
     (sum/avg/count/stddev/changes/resets) are computed from cumulative sums
     — O(samples + windows), no per-window gather.
  4. Order-statistic functions (min/max/quantile) gather a bounded window
     tile ``[S, T, W]`` and reduce over the W axis.

Counter correction (reset detection) is a device-side cumsum of drops —
the vectorized equivalent of CorrectingDoubleVectorReader
(memory/format/vectors/DoubleVector.scala:301) with cross-chunk carryover
folded in for free (tiles are whole series, not chunks).

Shapes are bucketized (pow2 padding of S and N) so XLA compiles a small
number of kernels that get reused across queries.
"""

from __future__ import annotations

import functools
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

# Prometheus semantics require f64 values and i64 millisecond timestamps;
# XLA supports both on TPU (f64 via emulation on the scalar/vector units).
# Must be enabled before any kernel is traced.
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from filodb_tpu.lint.caches import cache_registry
from filodb_tpu.lint.capacity import capacity
from filodb_tpu.lint.contracts import kernel_contract
from filodb_tpu.lint.numerics import precision
from filodb_tpu.lint.hotpath import hot_path
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.obs import devprof
from filodb_tpu.obs import metrics as obs_metrics
from filodb_tpu.obs import trace as obs_trace
from filodb_tpu.query.model import GridResult, RangeParams, RawSeries

_DEV_HELP = ("Wall seconds per device dispatch (kernel submission + "
             "device compute + the batch's one host sync)")


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _tile_example(extra=(), nsteps=16, S=8, N=64):
    """Shared [S, N] tile example for the windowed-kernel contracts."""
    args = (*extra,
            _sds((S, N), jnp.int64), _sds((S, N), jnp.float64),
            _sds((S,), jnp.int32),
            _sds((), jnp.int64), _sds((), jnp.int64),
            _sds((), jnp.int64), nsteps, _sds((), jnp.float64))
    return args, {}


def _grid_expect(S, T):
    def expect(out):
        if tuple(out.shape) != (S, T) or str(out.dtype) != "float64":
            return f"output {out.shape}/{out.dtype} != ({S}, {T}) f64"
        return None
    return expect

# sentinel timestamp for padding: larger than any real ms timestamp
_TS_PAD = np.int64(1) << 60

# functions implemented on device; everything else falls back to the oracle
DEVICE_FUNCS = frozenset({
    "rate", "increase", "delta", "irate", "idelta",
    "sum_over_time", "count_over_time", "avg_over_time",
    "stddev_over_time", "stdvar_over_time", "z_score",
    "min_over_time", "max_over_time", "last_sample", "last_over_time",
    "first_over_time", "changes", "resets", "timestamp",
    "rate_over_delta", "increase_over_delta", "quantile_over_time",
    "present_over_time", "absent_over_time",
})

_ENDPOINT_RATE = {"rate": (True, True), "increase": (True, False),
                  "delta": (False, False)}


def _next_pow2(n: int, lo: int = 8) -> int:
    p = lo
    while p < n:
        p <<= 1
    return p


def clean_rows(series: Sequence[RawSeries], drop_nan: bool
               ) -> Tuple[List[Tuple[np.ndarray, np.ndarray]], int]:
    """Per-series NaN-drop (stale markers) shared by all packers.

    Dropping NaNs means device code needn't mask them — matches the
    oracle's _prep. The instant-selector path (last_sample) keeps NaNs: a
    stale marker must make the step stale. Returns (rows, max_len)."""
    cleaned: List[Tuple[np.ndarray, np.ndarray]] = []
    maxlen = 1
    for s in series:
        if drop_nan:
            m = ~np.isnan(s.values)
            ts, vals = s.ts[m], s.values[m]
        else:
            ts, vals = s.ts, s.values
        cleaned.append((ts, vals))
        maxlen = max(maxlen, ts.size)
    return cleaned, maxlen


def pack_series(series: Sequence[RawSeries], drop_nan: bool = True
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack ragged raw series into padded [S, N] tiles (host side).
    Returns (ts_pad i64, vals f64, lens i32)."""
    cleaned, maxlen = clean_rows(series, drop_nan)
    N = _next_pow2(maxlen)
    S = len(series)
    ts_pad = np.full((S, N), _TS_PAD, dtype=np.int64)
    vals_pad = np.zeros((S, N), dtype=np.float64)
    lens = np.zeros(S, dtype=np.int32)
    for i, (ts, vals) in enumerate(cleaned):
        n = ts.size
        ts_pad[i, :n] = ts
        vals_pad[i, :n] = vals
        lens[i] = n
    return ts_pad, vals_pad, lens


def _abstract(a):
    """Array -> ShapeDtypeStruct for lazy cost probes (0-d scalars and
    plain Python values stay concrete — tiny, and statics must be)."""
    if getattr(a, "ndim", 0) > 0:
        return _sds(tuple(a.shape), a.dtype)
    return a


def _lower_probe(jfn, *largs):
    """() -> Compiled over an abstract call signature: the on-demand
    cost-analysis probe for kernels that compile inside their own
    ``jax.jit`` cache (we cannot reach that executable, so analyze
    pays one equivalent compile per executable, once)."""
    def probe():
        return jfn.lower(*largs).compile()
    return probe


def _pad_series_rows(ts: np.ndarray, vals: np.ndarray, lens: np.ndarray,
                     s_bucket: int):
    """Pad the series axis to a pow2 bucket (executable reuse): pad rows
    are all-sentinel/empty, produce all-NaN outputs, and are sliced off
    by the caller."""
    S, N = ts.shape
    ts2 = np.full((s_bucket, N), _TS_PAD, dtype=np.int64)
    vals2 = np.zeros((s_bucket, N), dtype=np.float64)
    lens2 = np.zeros(s_bucket, dtype=np.int32)
    ts2[:S] = ts
    vals2[:S] = vals
    lens2[:S] = lens
    return ts2, vals2, lens2


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------

def _colify(x):
    """Grid scalars may arrive per-row ([S] vectors) when the
    micro-batcher stacks queries with different windows along the
    series axis; reshape to a broadcastable [S, 1] column (scalars
    pass through — rank is static under trace)."""
    return x[:, None] if getattr(x, "ndim", 0) == 1 else x


def _grid(w0s, w0e, step, nsteps):
    """Reconstruct the uniform window grid on device: [T] for scalar
    inputs, [S, T] for per-row ([S]) inputs (micro-batched stacking)."""
    t = jnp.arange(nsteps, dtype=jnp.int64)
    return _colify(w0s) + t * _colify(step), \
        _colify(w0e) + t * _colify(step)


def _bounds(ts, w0s, w0e, step, nsteps):
    """[S, T] window index bounds for a UNIFORM step grid.

    Replaces per-window binary search (the reference's addChunks
    searchsorted, rangefn/RangeFunction.scala:122) with arithmetic window
    assignment + a scatter-add histogram + cumsum — O(S·(N+T)) and ~20x
    faster on TPU than a vmapped searchsorted (which XLA serializes).

    lo[s,t] = #{i: ts[s,i] <  wstart[t]}   (searchsorted side='left')
    hi[s,t] = #{i: ts[s,i] <= wend[t]} - 1 (searchsorted side='right' - 1)

    Each sample's first out-of-reach / first covering window index is a
    closed form in (w0, step); per-row histograms of those indices cumsum
    into the counts above. Pad samples (ts=_TS_PAD) land in the dropped
    overflow bucket."""
    S, N = ts.shape
    step = jnp.maximum(_colify(step), 1)
    w0s = _colify(w0s)
    w0e = _colify(w0e)
    rows = jnp.arange(S)[:, None]
    b_lo = jnp.clip((ts - w0s) // step + 1, 0, nsteps).astype(jnp.int32)
    b_hi = jnp.clip(-((w0e - ts) // step), 0, nsteps).astype(jnp.int32)
    hist_lo = jnp.zeros((S, nsteps + 1), jnp.int32).at[rows, b_lo].add(
        1, mode="drop")
    hist_hi = jnp.zeros((S, nsteps + 1), jnp.int32).at[rows, b_hi].add(
        1, mode="drop")
    lo = jnp.cumsum(hist_lo, axis=1)[:, :nsteps]
    hi = jnp.cumsum(hist_hi, axis=1)[:, :nsteps] - 1
    return lo, hi


def _take(arr, idx):
    return jnp.take_along_axis(arr, idx, axis=1)


def _prefix(x):
    """[S, N] -> [S, N+1] exclusive prefix sums."""
    return jnp.concatenate(
        [jnp.zeros((x.shape[0], 1), x.dtype), jnp.cumsum(x, axis=1)], axis=1)


def _correction(vals, lens):
    """Counter-reset correction per sample: cumsum of drop magnitudes."""
    idx = jnp.arange(vals.shape[1])
    valid = idx[None, :] < lens[:, None]
    prev = jnp.concatenate([vals[:, :1], vals[:, :-1]], axis=1)
    dropped = (vals < prev) & valid & (idx[None, :] > 0)
    drops = jnp.where(dropped, prev, 0.0)
    return jnp.cumsum(drops, axis=1)


@precision(
    "extrapolated-rate-f64", bits=53, rel_ulps=4,
    reason="the shared f64 extrapolation formula every exact counter "
           "path funnels through; certified within a few f64 ulps of "
           "the pure-Python reference (promql/refeval._extrapolated) "
           "— the two arms of the differential rail agree at the "
           "formula level, not just end to end")
def _extrapolated_rate(wstart, wend, counts, t1, v1, t2, v2, is_counter,
                       is_rate):
    """(rangefn/RateFunctions.scala:37 extrapolatedRate, on device.)
    Shape-agnostic: callers broadcast wstart/wend against their tile
    orientation ([S, T] row-major or [T, S] slot-major)."""
    counts = counts.astype(jnp.float64)
    dstart = (t1 - wstart).astype(jnp.float64) / 1000.0
    dend = (wend - t2).astype(jnp.float64) / 1000.0
    sampled = (t2 - t1).astype(jnp.float64) / 1000.0
    avg_dur = sampled / (counts - 1.0)
    delta = v2 - v1
    if is_counter:
        dzero = jnp.where((delta > 0) & (v1 >= 0),
                          sampled * (v1 / jnp.where(delta == 0, jnp.nan,
                                                    delta)),
                          jnp.inf)
        dstart = jnp.minimum(dstart, dzero)
    thresh = avg_dur * 1.1
    extrap = sampled \
        + jnp.where(dstart < thresh, dstart, avg_dur / 2.0) \
        + jnp.where(dend < thresh, dend, avg_dur / 2.0)
    scaled = delta * (extrap / sampled)
    if is_rate:
        scaled = scaled / (wend - wstart) * 1000.0
    return jnp.where(counts >= 2, scaled, jnp.nan)


@kernel_contract(
    "window_endpoint", kind="jit",
    example=lambda: _tile_example(extra=("rate",)),
    expect=_grid_expect(8, 16),
    notes="endpoint + prefix-sum family over [S, N] i64/f64 tiles; "
          "uniform window grid, output [S, T] f64")
@functools.partial(jax.jit, static_argnames=("func", "nsteps"))
def _window_endpoint(func: str, ts, vals, lens, w0s, w0e,
                     step, nsteps, scalar):
    """Endpoint + prefix-sum family, one fused kernel.

    The window grid is uniform: wstart[t] = w0s + t*step,
    wend[t] = w0e + t*step (scalars traced, nsteps static). Grid args
    may instead be [S] vectors — per-ROW grids, used by the
    micro-batcher to stack queries with different windows along the
    series axis; every op below is row-local, so a stacked row's output
    is bit-for-bit the single-query output."""
    S, N = ts.shape
    wstart, wend = _grid(w0s, w0e, step, nsteps)
    ws2 = wstart if wstart.ndim == 2 else wstart[None, :]
    we2 = wend if wend.ndim == 2 else wend[None, :]
    lo, hi = _bounds(ts, w0s, w0e, step, nsteps)
    counts = hi - lo + 1
    has = counts >= 1
    lo_c = jnp.clip(lo, 0, N - 1)
    hi_c = jnp.clip(hi, 0, N - 1)
    nan = jnp.nan

    if func in _ENDPOINT_RATE:
        counter, is_rate = _ENDPOINT_RATE[func]
        v = vals + _correction(vals, lens) if counter else vals
        out = _extrapolated_rate(ws2, we2, counts,
                                 _take(ts, lo_c), _take(v, lo_c),
                                 _take(ts, hi_c), _take(v, hi_c),
                                 counter, is_rate)
        return jnp.where(has, out, nan)

    if func in ("irate", "idelta"):
        ok = counts >= 2
        hi2 = jnp.clip(hi, 1, N - 1)
        v2 = _take(vals, hi2)
        v1 = _take(vals, hi2 - 1)
        dv = v2 - v1
        if func == "irate":
            dv = jnp.where(dv < 0, v2, dv)
            dt = (_take(ts, hi2) - _take(ts, hi2 - 1)).astype(jnp.float64) \
                / 1000.0
            res = dv / jnp.where(dt == 0, jnp.nan, dt)
        else:
            res = dv
        return jnp.where(ok, res, nan)

    if func in ("last_sample", "last_over_time"):
        return jnp.where(has, _take(vals, hi_c), nan)
    if func == "first_over_time":
        return jnp.where(has, _take(vals, lo_c), nan)
    if func == "timestamp":
        return jnp.where(has, _take(ts, hi_c).astype(jnp.float64) / 1000.0,
                         nan)
    if func == "present_over_time":
        return jnp.where(has, 1.0, nan)
    if func == "absent_over_time":
        return jnp.where(has, nan, 1.0)

    if func in ("changes", "resets"):
        prev = jnp.concatenate([vals[:, :1], vals[:, :-1]], axis=1)
        idx = jnp.arange(N)
        valid = (idx[None, :] < lens[:, None]) & (idx[None, :] > 0)
        if func == "changes":
            ev = (vals != prev) & valid
        else:
            ev = (vals < prev) & valid
        cs = _prefix(ev.astype(jnp.float64))
        lo1 = jnp.clip(lo + 1, 0, N)
        out = _take(cs, jnp.clip(hi + 1, 0, N)) - _take(cs, lo1)
        return jnp.where(has, out, nan)

    # prefix-sum family
    cs = _prefix(vals)
    s = _take(cs, jnp.clip(hi + 1, 0, N)) - _take(cs, jnp.clip(lo, 0, N))
    cnt = counts.astype(jnp.float64)
    if func in ("sum_over_time", "increase_over_delta"):
        out = s
    elif func == "rate_over_delta":
        out = s / (we2 - ws2) * 1000.0
    elif func == "count_over_time":
        out = cnt
    elif func == "avg_over_time":
        out = s / cnt
    else:
        cs2 = _prefix(vals * vals)
        s2 = _take(cs2, jnp.clip(hi + 1, 0, N)) - _take(cs2,
                                                        jnp.clip(lo, 0, N))
        mean = s / cnt
        var = jnp.maximum(s2 / cnt - mean * mean, 0.0)
        if func == "stdvar_over_time":
            out = var
        elif func == "stddev_over_time":
            out = jnp.sqrt(var)
        elif func == "z_score":
            out = (_take(vals, hi_c) - mean) / jnp.sqrt(var)
        else:
            raise ValueError(f"unhandled device func {func}")
    return jnp.where(has, out, nan)


@kernel_contract(
    "window_gather", kind="jit",
    example=lambda: _tile_example(extra=("min_over_time", 8)),
    expect=_grid_expect(8, 16),
    notes="order-statistic family: [S, T, W] bounded gather, W static; "
          "the [S*T*W] intermediate is XLA-managed HBM, not VMEM")
@functools.partial(jax.jit, static_argnames=("func", "w_bound", "nsteps"))
def _window_gather(func: str, w_bound: int, ts, vals, lens, w0s, w0e,
                   step, nsteps, scalar):
    """Order-statistic family: gather [S, T, W] window tiles, reduce over W.
    W (max samples per window) is a static bound."""
    S, N = ts.shape
    lo, hi = _bounds(ts, w0s, w0e, step, nsteps)   # [S, T]
    has = hi >= lo
    offs = jnp.arange(w_bound)                  # [W]
    gidx = lo[:, :, None] + offs[None, None, :]  # [S, T, W]
    in_win = (gidx <= hi[:, :, None]) & (gidx < lens[:, None, None])
    gidx_c = jnp.clip(gidx, 0, N - 1)
    g = jnp.take_along_axis(vals, gidx_c.reshape(S, -1), axis=1).reshape(
        gidx.shape)
    if func == "min_over_time":
        out = jnp.min(jnp.where(in_win, g, jnp.inf), axis=2)
        out = jnp.where(jnp.isinf(out), jnp.nan, out)
    elif func == "max_over_time":
        out = jnp.max(jnp.where(in_win, g, -jnp.inf), axis=2)
        out = jnp.where(jnp.isinf(out), jnp.nan, out)
    elif func == "quantile_over_time":
        q = jnp.clip(scalar, 0.0, 1.0)
        big = jnp.where(in_win, g, jnp.inf)
        srt = jnp.sort(big, axis=2)              # valid values first
        cnt = in_win.sum(axis=2)                 # [S, T]
        rank = q * (cnt - 1).astype(jnp.float64)
        lo_r = jnp.floor(rank).astype(jnp.int32)
        hi_r = jnp.ceil(rank).astype(jnp.int32)
        frac = rank - lo_r
        v_lo = jnp.take_along_axis(srt, jnp.clip(lo_r, 0, w_bound - 1)[..., None],
                                   axis=2)[..., 0]
        v_hi = jnp.take_along_axis(srt, jnp.clip(hi_r, 0, w_bound - 1)[..., None],
                                   axis=2)[..., 0]
        out = v_lo + (v_hi - v_lo) * frac
        out = jnp.where(cnt > 0, out, jnp.nan)
        out = jnp.where(scalar > 1, jnp.inf, out)
        out = jnp.where(scalar < 0, -jnp.inf, out)
    else:
        raise ValueError(f"unhandled gather func {func}")
    return jnp.where(has, out, jnp.nan)


_GATHER_FUNCS = frozenset({"min_over_time", "max_over_time",
                           "quantile_over_time"})

# rate family served by the Pallas boundary-extract kernel when series
# are irregular (the aligned tilestore path handles regular cadence)
_PALLAS_FUNCS = frozenset({"rate", "increase", "delta"})


@kernel_contract(
    "pallas_rate", kind="jit",
    example=lambda: (
        ("rate", 128, False,
         _sds((8, 128), jnp.int64), _sds((8, 128), jnp.float64),
         _sds((8,), jnp.int32), _sds((), jnp.int64),
         _sds((), jnp.int64), _sds((), jnp.int64)), {}),
    expect=_grid_expect(8, 128),
    rel_time_bits=31, span_guard="_window_endpoint_pallas",
    notes="irregular-cadence rate family: counter correction + exact "
          "f64->3xf32 split feeding the Pallas boundary-extract kernel; "
          "timestamps rebased to w0s must fit int31 ms")
@functools.partial(jax.jit, static_argnames=("func", "nsteps", "interpret"))
def _pallas_rate_impl(func, nsteps, interpret, ts, vals, lens, w0s, w0e,
                      step):
    from filodb_tpu.query import pallas_kernels as pk

    S, N = ts.shape
    idx = jnp.arange(N)[None, :]
    in_len = idx < lens[:, None]
    is_counter = func != "delta"
    v = vals + _correction(vals, lens) if is_counter else vals
    tr = jnp.where(in_len, ts - w0s, pk.TR_PAD).astype(jnp.int32)
    pay = pk.split3(jnp.where(in_len, v, 0.0)).astype(jnp.float32)
    window = (w0e - w0s).astype(jnp.int32)
    cnt, tlo, thi, plo, phi = pk.window_extract(
        tr, pay, step.astype(jnp.int32), window, nsteps,
        interpret=interpret)
    t = jnp.arange(nsteps, dtype=jnp.int64)
    wstart = w0s + t * step
    wend = w0e + t * step
    t1 = tlo.astype(jnp.int64) + w0s
    t2 = thi.astype(jnp.int64) + w0s
    v1 = pk.combine3(plo)
    v2 = pk.combine3(phi)
    out = _extrapolated_rate(wstart[None, :], wend[None, :], cnt, t1, v1, t2, v2,
                             is_counter, func == "rate")
    return jnp.where(cnt >= 1, out, jnp.nan)


def _window_endpoint_pallas(func, ts, vals, lens, w0s, w0e, step, nsteps):
    """Pallas boundary-extract path for rate/increase/delta. Returns None
    when preconditions fail (range exceeds int32, or no compiled-TPU
    backend and the problem is too big for interpret mode)."""
    mask = np.arange(ts.shape[1])[None, :] < lens[:, None]
    if not mask.any():
        return None
    t_min, t_max = int(ts[mask].min()), int(ts[mask].max())
    span_ok = (abs(t_min - int(w0s)) < 2**31 - 2
               and abs(t_max - int(w0s)) < 2**31 - 2
               and int(w0e - w0s) + (nsteps - 1) * int(step) < 2**31 - 2)
    if not span_ok:
        return None
    on_tpu = jax.default_backend() not in ("cpu",)
    if not on_tpu and not PALLAS_RATE_INTERPRET:
        return None     # CPU serving: endpoint kernel (see flag above)
    if not on_tpu and ts.size > 262_144:
        return None     # interpret mode is for small (test) shapes only
    return _pallas_rate_impl(func, nsteps, not on_tpu,
                             jnp.asarray(ts), jnp.asarray(vals),
                             jnp.asarray(lens), jnp.asarray(w0s),
                             jnp.asarray(w0e), jnp.asarray(step))


# tests set this to exercise the fused group-sum kernel in interpret
# mode on the CPU test mesh; production CPU nodes leave it off
FUSED_GROUPSUM_INTERPRET = False

# tests set this to exercise the Pallas boundary-extract rate path in
# interpret mode on CPU; production CPU nodes leave it off — interpret
# mode re-jits per (shape, nsteps) at ~0.5-1s a piece, and with live
# ingest moving the write-buffer tail every flush changes the tail
# step count, so a serving node would hit a fresh compile every few
# seconds. The endpoint kernel is bit-for-bit identical for the rate
# family (pinned by test_batcher), so CPU serving loses nothing.
PALLAS_RATE_INTERPRET = False


class _TileEntry:
    """One tile-cache entry: device tiles over an immutable prefix,
    plus the coverage bound that makes stale serves correct."""

    __slots__ = ("tiles", "idx", "prefix_has_nan", "refs", "cov_min_ms",
                 "ident_key")

    def __init__(self, tiles, idx, prefix_has_nan, refs, cov_min_ms,
                 ident_key=None):
        self.tiles = tiles
        self.idx = idx
        self.prefix_has_nan = prefix_has_nan
        self.refs = refs
        self.cov_min_ms = cov_min_ms    # first ms NOT in tiles; None=all
        self.ident_key = ident_key


class _PackedMember:
    """One query's packed tile + grid scalars inside a packed batch."""

    __slots__ = ("ts", "vals", "lens", "w0s", "w0e", "step", "nsteps",
                 "w_bound")

    def __init__(self, ts, vals, lens, w0s, w0e, step, nsteps, w_bound):
        self.ts = ts
        self.vals = vals
        self.lens = lens
        self.w0s = w0s
        self.w0e = w0e
        self.step = step
        self.nsteps = nsteps
        self.w_bound = w_bound


# cache inventory: the tile cache is immune to world events BY KEY —
# snapshot keys embed (dataset, shard, part_id, num_chunks), so a flush
# that publishes chunks changes the key instead of invalidating (the
# stale-ident serve is coverage-bounded by cov_min_ms). The executable
# set keys on pure kernel shape (world-independent by construction).
@cache_registry("device-tile",
                keyed=("selection-snapshot", "chunk-set"))
@cache_registry("packed-executable", keyed=("kernel", "shape-bucket"))
class TpuBackend:
    """Pluggable device backend for QueryEngine (the ``--exec-backend=tpu``
    boundary from BASELINE.json).

    ``batcher`` (query/batcher.py MicroBatcher, on by default) is the
    serving fast path's admission layer: concurrent queries resolving to
    the same bucketed kernel shape share one device dispatch — along the
    grid axis for the aligned tilestore evaluators, along the series
    axis (with per-query segment offsets) for the general packed path.
    Pass ``batcher=None``/``MicroBatcher(enabled=False)`` to always take
    the single-query kernel paths."""

    def __init__(self, device: Optional[object] = None,
                 batcher: Optional[object] = "default",
                 mesh_eval: Optional[object] = None):
        self.device = device
        # multi-chip serving (parallel/shardstore.ShardedTileEvaluator):
        # when set, eligible aligned-tile dispatches run the SAME
        # evaluator bodies sharded over the ('shard','time') mesh from
        # device-resident tiles — bit-for-bit the single-device values
        self.mesh_eval = mesh_eval
        self.mesh_dispatches = 0    # observability: sharded dispatches
        self._tile_cache: Dict = {}
        # guards cache get/insert/evict against concurrent HTTP query
        # threads (non-atomic FIFO evict could KeyError, inserts overshoot)
        self._tile_lock = threading.Lock()
        # selection identity (snapshot keys minus chunk counts) -> the
        # latest cache key: lets a post-flush query serve the previous
        # snapshot's tiles while the rebuild runs in the background
        self._tile_ident: Dict = {}
        self._tile_refreshing: set = set()
        self.tile_builds = 0    # observability: device tile (re)builds
        self.tile_hits = 0      # observability: cache hits
        self.fused_aggs = 0     # observability: fused group-sum queries
        if batcher == "default":
            from filodb_tpu.query.batcher import MicroBatcher
            batcher = MicroBatcher()
        self.batcher = batcher
        # executable-reuse observability for the packed kernel family:
        # a (kernel, func, S/N/T-bucket) combination seen before means
        # the jit cache serves it without a retrace
        self._exec_lock = threading.Lock()
        self._exec_keys: set = set()
        self.exec_cache_hits = 0
        self.exec_cache_misses = 0

    def _count_exec(self, key: Tuple, probe=None) -> None:
        """Executable reuse accounting + compile/cost profiling
        (obs/devprof.py). ``probe`` is a ``() -> Compiled`` lazy cost
        probe over the abstract call signature: registered on the key's
        FIRST sight only, compiled on demand by the first
        ``&explain=analyze`` touching the executable (serving
        dispatches never pay it)."""
        with self._exec_lock:
            first = key not in self._exec_keys
            if first:
                self._exec_keys.add(key)
                self.exec_cache_misses += 1
            else:
                self.exec_cache_hits += 1
        devprof.note_dispatch("packed", key, first,
                              probe=probe if first else None)

    def executable_cache_stats(self) -> Dict[str, int]:
        """Packed-kernel + tilestore executable-reuse counters (the
        compile-cache hit/miss surface in /metrics)."""
        from filodb_tpu.query import tilestore as tst
        ts_stats = tst.executable_cache_stats()
        with self._exec_lock:
            return {"hits": self.exec_cache_hits + ts_stats["hits"],
                    "misses": self.exec_cache_misses + ts_stats["misses"],
                    "entries": len(self._exec_keys) + ts_stats["entries"]}

    def periodic_samples(self, series: Sequence[RawSeries],
                         params: RangeParams, function: str, window_ms: int,
                         func_args: Sequence[float] = (),
                         offset_ms: int = 0) -> Optional[GridResult]:
        """Returns None to signal fallback to the numpy oracle (histograms,
        unsupported functions)."""
        func = function or "last_sample"
        if func not in DEVICE_FUNCS or not series:
            return None
        if any(s.values.ndim != 1 for s in series):
            return None
        steps = params.steps
        nsteps = steps.size
        keys = [dict(s.labels) for s in series]
        if nsteps == 0:
            return GridResult(steps, keys,
                              np.empty((len(series), 0), dtype=np.float64))
        if self.batcher is not None:
            self.batcher.enter()
        try:
            with obs_trace.span("device-eval", func=func,
                                series=len(series)) as _sp:
                aligned = self._try_aligned(series, func, steps,
                                            params.step_ms, window_ms,
                                            offset_ms, func_args)
                if aligned is not None:
                    _sp.tag(path="aligned")
                    return GridResult(steps, keys, aligned)
                _sp.tag(path="packed")
                out = self._general(series, func, steps, params.step_ms,
                                    window_ms, offset_ms, func_args)
        finally:
            if self.batcher is not None:
                self.batcher.exit()
        return GridResult(steps, keys, out)

    def _general(self, series, func: str, steps: np.ndarray, step_ms: int,
                 window_ms: int, offset_ms: int, func_args) -> np.ndarray:
        """General packed path (any cadence): fused window kernels over
        padded [S, N] tiles. ``steps`` may be any contiguous slice of a
        uniform grid. Host-side packing happens here, on the calling
        worker thread — under the micro-batcher it overlaps device
        compute of the previous batch."""
        from filodb_tpu.query.engine import clip_series

        nsteps = steps.size
        w0e = np.int64(steps[0] - offset_ms)
        w0s = np.int64(w0e - window_ms)
        step = np.int64(step_ms if nsteps > 1 else 1)
        # pack only the span the grid can touch — series may carry the whole
        # retention (select full=True for tile caching)
        series = clip_series(series, int(w0s),
                             int(steps[-1] - offset_ms))
        with obs_trace.span("pack", series=len(series)):
            ts, vals, lens = pack_series(series,
                                         drop_nan=(func != "last_sample"))
        scalar = float(func_args[0]) if func_args else 0.0
        w_bound = self._window_sample_bound(series, window_ms, ts.shape[1]) \
            if func in _GATHER_FUNCS else 0
        t_bucket = _next_pow2(nsteps, 8)
        b = self.batcher
        if b is not None and b.enabled:
            # concurrent queries sharing (func, N, T-bucket) stack along
            # the series axis and run as ONE kernel dispatch
            key = ("packed", func, ts.shape[1], t_bucket,
                   func != "last_sample", scalar)
            member = _PackedMember(ts, vals, lens, int(w0s), int(w0e),
                                   int(step), nsteps, w_bound)
            return b.submit(key, member, functools.partial(
                self._packed_run, func, t_bucket, scalar))
        with obs_metrics.timed("filodb_device_execute_seconds",
                               _DEV_HELP), \
                obs_trace.span("device-dispatch", path="packed"):
            return self._packed_single(func, ts, vals, lens, w0s, w0e,
                                       step, nsteps, t_bucket, scalar,
                                       w_bound)

    @hot_path
    def _packed_single(self, func, ts, vals, lens, w0s, w0e, step, nsteps,
                       t_bucket, scalar, w_bound) -> np.ndarray:
        """Single-query packed dispatch with pow2 shape bucketing: S and
        the step count pad to buckets so repeat queries of nearby shapes
        reuse compiled executables instead of retracing."""
        S, N = ts.shape
        s_bucket = _next_pow2(S, 8)
        if s_bucket != S:
            ts, vals, lens = _pad_series_rows(ts, vals, lens, s_bucket)
        if func in _GATHER_FUNCS:
            self._count_exec(
                ("gather", func, s_bucket, N, t_bucket, w_bound),
                probe=_lower_probe(_window_gather, func, w_bound,
                                   _abstract(ts), _abstract(vals),
                                   _abstract(lens), w0s, w0e, step,
                                   t_bucket, scalar))
            out = _window_gather(func, w_bound, ts, vals, lens,
                                 w0s, w0e, step, t_bucket, scalar)
        else:
            if func in _PALLAS_FUNCS:
                # the Pallas boundary-extract path keeps the exact step
                # count (its grid layout is nsteps-derived); bit-for-bit
                # with _window_endpoint — pinned by test_batcher
                out = _window_endpoint_pallas(func, ts, vals, lens, w0s,
                                              w0e, step, nsteps)
                if out is not None:
                    self._count_exec(("pallas", func, s_bucket, N, nsteps))
                    # graftlint: disable=host-transfer-in-hot-loop (single-query path: designed sync point at kernel egress)
                    return np.asarray(out)[:S]
            self._count_exec(
                ("endpoint", func, s_bucket, N, t_bucket),
                probe=_lower_probe(_window_endpoint, func,
                                   _abstract(ts), _abstract(vals),
                                   _abstract(lens), w0s, w0e, step,
                                   t_bucket, scalar))
            out = _window_endpoint(func, ts, vals, lens,
                                   w0s, w0e, step, t_bucket, scalar)
        # graftlint: disable=host-transfer-in-hot-loop (single-query path: designed sync point at kernel egress)
        return np.asarray(out)[:S, :nsteps]

    def _packed_run(self, func: str, t_bucket: int, scalar: float,
                    members) -> object:
        """Execute one packed batch: stack member tiles along the series
        axis, dispatch ONE kernel with per-row window vectors, split by
        per-query segment offsets. A batch of one takes the single-query
        path (bit-for-bit identical; the parity test pins it)."""
        from filodb_tpu.query.batcher import SplitResult

        with obs_metrics.timed("filodb_device_execute_seconds",
                               _DEV_HELP), \
                obs_trace.span("device-dispatch", path="packed",
                               batch=len(members)):
            return self._packed_run_inner(func, t_bucket, scalar,
                                          members, SplitResult)

    def _packed_run_inner(self, func: str, t_bucket: int, scalar: float,
                          members, SplitResult) -> object:
        if len(members) == 1:
            m = members[0]
            out = self._packed_single(func, m.ts, m.vals, m.lens,
                                      np.int64(m.w0s), np.int64(m.w0e),
                                      np.int64(m.step), m.nsteps, t_bucket,
                                      scalar, m.w_bound)
            return SplitResult(out, 1, split=lambda h, i: h)
        offs = np.cumsum([0] + [m.ts.shape[0] for m in members])
        s_total = int(offs[-1])
        s_bucket = _next_pow2(s_total, 8)
        N = members[0].ts.shape[1]
        ts = np.full((s_bucket, N), _TS_PAD, dtype=np.int64)
        vals = np.zeros((s_bucket, N), dtype=np.float64)
        lens = np.zeros(s_bucket, dtype=np.int32)
        w0s_v = np.zeros(s_bucket, dtype=np.int64)
        w0e_v = np.ones(s_bucket, dtype=np.int64)
        step_v = np.ones(s_bucket, dtype=np.int64)
        for m, o in zip(members, offs):
            sl = slice(int(o), int(o) + m.ts.shape[0])
            ts[sl] = m.ts
            vals[sl] = m.vals
            lens[sl] = m.lens
            w0s_v[sl] = m.w0s
            w0e_v[sl] = m.w0e
            step_v[sl] = m.step
        if func in _GATHER_FUNCS:
            w_bound = max(m.w_bound for m in members)
            self._count_exec(
                ("gather-b", func, s_bucket, N, t_bucket, w_bound),
                probe=_lower_probe(_window_gather, func, w_bound,
                                   _abstract(ts), _abstract(vals),
                                   _abstract(lens), _abstract(w0s_v),
                                   _abstract(w0e_v), _abstract(step_v),
                                   t_bucket, scalar))
            dev = _window_gather(func, w_bound, ts, vals, lens,
                                 jnp.asarray(w0s_v), jnp.asarray(w0e_v),
                                 jnp.asarray(step_v), t_bucket, scalar)
        else:
            # rate-family members ride _window_endpoint here (the Pallas
            # boundary-extract kernel takes scalar grids); exact f64 on
            # both paths — bit-for-bit, pinned by the parity test
            self._count_exec(
                ("endpoint-b", func, s_bucket, N, t_bucket),
                probe=_lower_probe(_window_endpoint, func,
                                   _abstract(ts), _abstract(vals),
                                   _abstract(lens), _abstract(w0s_v),
                                   _abstract(w0e_v), _abstract(step_v),
                                   t_bucket, scalar))
            dev = _window_endpoint(func, ts, vals, lens,
                                   jnp.asarray(w0s_v), jnp.asarray(w0e_v),
                                   jnp.asarray(step_v), t_bucket, scalar)
        sizes = [m.ts.shape[0] for m in members]
        nst = [m.nsteps for m in members]

        def split(host: np.ndarray, i: int) -> np.ndarray:
            o = int(offs[i])
            return host[o:o + sizes[i], :nst[i]]

        return SplitResult(dev, len(members), split=split)

    _TILE_CACHE_MAX = 16

    @staticmethod
    def _prefix_len(s) -> int:
        return s.chunk_len if s.chunk_len >= 0 else s.ts.size

    def _build_tile_entry(self, series, use_snap: bool):
        """Build one tile-cache entry over the series' immutable chunk
        prefixes. ``cov_min_ms`` records the first timestamp NOT covered
        by the tiles (None = full coverage): consumers must route steps
        whose windows reach past it through the packed path — this is
        what makes serving a STALE entry correct while a flush's rebuild
        runs in the background."""
        from filodb_tpu.query import tilestore as tst

        prefix = [
            RawSeries(s.labels, s.ts[:self._prefix_len(s)],
                      s.values[:self._prefix_len(s)], s.is_counter,
                      s.bucket_les)
            for s in series
        ]
        cov_min = None
        for s in series:
            cl = self._prefix_len(s)
            if cl < s.ts.size:
                tm = int(s.ts[cl])
                cov_min = tm if cov_min is None else min(cov_min, tm)
        tiles, idx = tst.build_aligned_tiles(prefix)
        self.tile_builds += 1
        prefix_has_nan = any(np.isnan(p.values).any() for p in prefix)
        return _TileEntry(tiles, idx, prefix_has_nan,
                          None if use_snap else list(series), cov_min)

    @capacity(
        "device-tile-cache", bytes_per_sample=17.0,
        reason="each tile-cache entry retains one AlignedTiles cohort "
               "(valid bool + ts f64 + vals f64 = 17 B per slot) over "
               "the selection's immutable chunk prefix, FIFO-capped "
               "at _TILE_CACHE_MAX entries; warm channel caches on "
               "the retained cohort are priced by the tilestore claim")
    def _insert_tile_entry(self, key, ident, entry) -> None:
        with self._tile_lock:
            while len(self._tile_cache) >= self._TILE_CACHE_MAX:
                old_key = next(iter(self._tile_cache))
                old = self._tile_cache.pop(old_key)
                if old is not None and \
                        self._tile_ident.get(old.ident_key) == old_key:
                    self._tile_ident.pop(old.ident_key, None)
            entry.ident_key = ident
            self._tile_cache[key] = entry
            if ident is not None:
                self._tile_ident[ident] = key

    def _tile_entry(self, series):
        """Cache of (tiles, idx) built over each series' IMMUTABLE chunk
        prefix. Keyed by store snapshot keys when the selection carries them
        (dataset, shard, part_id, num_chunks — pinned content, so the cache
        hits across queries until a flush publishes new chunks); falls back
        to object identity (holding refs so ids can't be recycled) for
        ad-hoc series. Bounded FIFO.

        A flush changes num_chunks and would historically stall the next
        query ~tens of ms rebuilding tiles. Now the PREVIOUS snapshot's
        entry for the same selection identity (same partitions/column,
        num_chunks abstracted) keeps serving — its ``cov_min_ms`` bounds
        the device steps, the packed path covers the rest — while the
        rebuild runs on the batcher's device-executor thread; queries
        swap to the fresh tiles when it lands.

        Known tradeoff: the key covers the whole selection, so overlapping
        selections duplicate tiles and >_TILE_CACHE_MAX distinct selectors
        thrash; per-partition tiles would compose but conflict with cohort
        (shared-cadence) packing, which is what makes the kernels fast."""
        use_snap = all(s.snapshot_key is not None for s in series)
        if use_snap:
            key = tuple(s.snapshot_key for s in series)
            # snapshot key minus the chunk-count field: stable across
            # flushes for the same partitions + column selection
            ident = tuple(s.snapshot_key[:3] + s.snapshot_key[4:]
                          for s in series)
        else:
            key = tuple(id(s) for s in series)
            ident = None
        with self._tile_lock:
            entry = self._tile_cache.get(key)
            stale = None
            if entry is None and ident is not None:
                old_key = self._tile_ident.get(ident)
                if old_key is not None:
                    stale = self._tile_cache.get(old_key)
        if entry is not None:
            self.tile_hits += 1
            return entry
        if stale is not None and self.batcher is not None:
            # stale-but-correct serve + background refresh (once per key)
            self.tile_hits += 1
            with self._tile_lock:
                if key in self._tile_refreshing:
                    return stale
                self._tile_refreshing.add(key)
            held = list(series)     # pin arrays until the rebuild lands

            @thread_root("tile-refresh")
            def refresh():
                try:
                    fresh = self._build_tile_entry(held, use_snap)
                    me = self.mesh_eval
                    if me is not None and stale.tiles is not None:
                        # cross-flush hand-over of the mesh placement:
                        # the donated append reuses the resident HBM
                        # buffers in place (zero-copy) when the new
                        # tiles extend the old cohort
                        me.refresh(stale.tiles, fresh.tiles)
                    self._insert_tile_entry(key, ident, fresh)
                finally:
                    with self._tile_lock:
                        self._tile_refreshing.discard(key)
            # background class: a tile rebuild improves FUTURE queries
            # and must never delay a queued interactive dispatch
            from filodb_tpu.query import qos as _qos
            self.batcher.executor.submit(
                refresh, priority=_qos.PRIORITY_BACKGROUND)
            return stale
        entry = self._build_tile_entry(series, use_snap)
        self._insert_tile_entry(key, ident, entry)
        return entry

    def _try_aligned(self, series, func: str, steps: np.ndarray,
                     step_ms: int, window_ms: int, offset_ms: int,
                     func_args) -> Optional[np.ndarray]:
        """Aligned-tile fast path (tilestore): regular-cadence series are
        served with shared-column takes over cached device tiles.

        Tiles cover only published (immutable) chunks; steps whose window
        reaches into any series' write-buffer tail are computed via the
        general packed path over the live data and spliced onto the device
        columns — so ingest never invalidates the device store, flushes do
        (SURVEY §7: 'recent samples answered from a host-side tail scan
        merged at present stage')."""
        from filodb_tpu.query import tilestore as tst

        if func not in tst.ALIGNED_FUNCS:
            return None
        entry = self._tile_entry(series)
        tiles, idx = entry.tiles, entry.idx
        if func == "last_sample":
            # stale markers must stay visible to the step; the immutable
            # prefix's flag is cached with the tiles, only tails re-scan
            if entry.prefix_has_nan or any(
                    np.isnan(s.values[self._prefix_len(s):]).any()
                    for s in series):
                return None
        if tiles is None or len(idx) != len(series):
            return None     # partial alignment: keep one result path
        # windows ending before the earliest sample the tiles don't
        # cover see only tiles: the tail of the CURRENT series, clipped
        # further by the entry's build-time coverage when a stale entry
        # is serving across a flush (the rebuild lands in background)
        tail_min = entry.cov_min_ms
        for s in series:
            cl = self._prefix_len(s)
            if cl < s.ts.size:
                tm = int(s.ts[cl])
                tail_min = tm if tail_min is None else min(tail_min, tm)
        wends = steps - offset_ms
        t_dev = (steps.size if tail_min is None
                 else int(np.searchsorted(wends, tail_min, side="left")))
        if t_dev == 0:
            return None     # every window touches live data
        res = self._aligned_dispatch(tiles, func, steps[:t_dev],
                                     window_ms, offset_ms, func_args)
        if len(idx) != res.shape[0]:
            return None
        # restore original series order (build may drop/reorder rows)
        full = np.empty((len(series), steps.size), dtype=np.float64)
        dev = np.empty((len(series), t_dev), dtype=np.float64)
        dev[np.asarray(idx)] = res
        full[:, :t_dev] = dev
        if t_dev < steps.size:
            full[:, t_dev:] = self._general(series, func, steps[t_dev:],
                                            step_ms, window_ms, offset_ms,
                                            func_args)
        return full

    @hot_path
    def _aligned_dispatch(self, tiles, func: str, steps: np.ndarray,
                          window_ms: int, offset_ms: int,
                          func_args) -> np.ndarray:
        """Aligned-tile kernel dispatch -> [S, T] numpy.

        With the micro-batcher on, concurrent queries over the SAME
        cached tiles that share (func, step count, step, window) — the
        dashboard-refresh shape, differing only in grid position — run
        as ONE vmapped device dispatch along the grid axis. A lone
        query (or batcher off) takes the scalar evaluator exactly as
        before; the vmapped families are bit-for-bit the scalar ones
        (test_batcher pins it)."""
        from filodb_tpu.query import tilestore as tst

        counters = func in ("rate", "increase", "delta")
        b = self.batcher
        nsteps = steps.size
        if counters and nsteps >= 1:
            family = tst.counters_batch_family(tiles, func, steps,
                                               window_ms, offset_ms)
        else:
            family = None
        mesh_st = None
        if not func_args and nsteps >= 1:
            mesh_st = self._mesh_sharded(tiles, func, steps, window_ms,
                                         offset_ms, family)
        if b is not None and b.enabled and not func_args and nsteps >= 1:
            w0e = int(steps[0] - offset_ms)
            w0s = w0e - window_ms
            step = int(steps[1] - steps[0]) if nsteps > 1 else 1
            # id(tiles) is safe as a key component: members hold a
            # reference to the tiles object, so the id cannot be
            # recycled while the batch is open
            key = ("aligned", id(tiles), func, nsteps, step, window_ms,
                   family, mesh_st is not None)
            return b.submit(
                key, (w0s, w0e, steps, tiles),
                functools.partial(self._aligned_run, tiles, func,
                                  family, nsteps, step, window_ms,
                                  offset_ms, mesh_st),
                # ONE thread owns sharded submissions: a mesh program
                # already spans every device, so inline execution on N
                # query threads would only oversubscribe it
                use_executor=True if mesh_st is not None else None)
        with obs_metrics.timed("filodb_device_execute_seconds",
                               _DEV_HELP), \
                obs_trace.span("device-dispatch",
                               path="mesh-aligned" if mesh_st is not None
                               else "aligned"):
            if counters:
                if mesh_st is not None:
                    self.mesh_dispatches += 1
                    # graftlint: disable=host-transfer-in-hot-loop (single-query path: designed sync point at kernel egress)
                    return np.asarray(mesh_st.eval_counters(
                        func, steps, window_ms, offset_ms)).T
                # counter family rides the slot-major f32-hybrid fast
                # path: int32 timestamps + exact f64 boundary deltas,
                # f32 extrapolation epilogue (~3e-7 relative vs the f64
                # oracle; grids wider than int32 ms take the exact
                # path) — test_tilestore pins parity + the exact
                # fallback
                # graftlint: disable=host-transfer-in-hot-loop (single-query path: designed sync point at kernel egress)
                return np.asarray(tst.evaluate_counters_t(
                    tiles, func, steps, window_ms, offset_ms).T)
            if mesh_st is not None:
                self.mesh_dispatches += 1
                # graftlint: disable=host-transfer-in-hot-loop (single-query path: designed sync point at kernel egress)
                return np.asarray(mesh_st.eval_aligned(
                    tiles, func, steps, window_ms, offset_ms))
            # graftlint: disable=host-transfer-in-hot-loop (single-query path: designed sync point at kernel egress)
            return np.asarray(tst.evaluate_aligned(
                tiles, func, steps, window_ms, offset_ms, func_args))

    def _mesh_sharded(self, tiles, func: str, steps, window_ms: int,
                      offset_ms: int, family):
        """The device-resident sharded placement serving this dispatch,
        or None for the single-device path. Counter families route only
        when the single-device dispatcher would pick the f32-hybrid
        slide/fast evaluator (identical values), so mesh-on vs mesh-off
        responses stay byte-identical; the exact-f64 wide-grid family
        keeps the single-device path."""
        me = self.mesh_eval
        if me is None or tiles is None:
            return None
        if family is not None and family[0] not in ("slide", "fast"):
            return None
        st = me.place(tiles)
        if st is None:
            return None
        if family is not None and not st.query_fits(
                np.asarray(steps), window_ms, offset_ms):
            return None
        return st

    def _aligned_run(self, tiles, func: str, family, nsteps: int,
                     step: int, window_ms: int, offset_ms: int,
                     mesh_st, members) -> object:
        """Execute one aligned batch: B=1 takes the scalar evaluator,
        B>=2 one vmapped dispatch computing every member's grid (the
        mesh-sharded twins of both when ``mesh_st`` serves)."""
        from filodb_tpu.query import tilestore as tst
        from filodb_tpu.query.batcher import SplitResult

        with obs_metrics.timed("filodb_device_execute_seconds",
                               _DEV_HELP), \
                obs_trace.span("device-dispatch",
                               path="mesh-aligned" if mesh_st is not None
                               else "aligned",
                               batch=len(members)):
            return self._aligned_run_inner(tst, SplitResult, tiles,
                                           func, family, nsteps, step,
                                           window_ms, offset_ms, mesh_st,
                                           members)

    def _aligned_run_inner(self, tst, SplitResult, tiles, func: str,
                           family, nsteps: int, step: int,
                           window_ms: int, offset_ms: int, mesh_st,
                           members) -> object:
        counters = func in ("rate", "increase", "delta")
        if mesh_st is not None:
            self.mesh_dispatches += len(members)
        if len(members) == 1:
            steps0 = members[0][2]
            if counters:
                if mesh_st is not None:
                    dev = mesh_st.eval_counters(func, steps0, window_ms,
                                                offset_ms)
                else:
                    dev = tst.evaluate_counters_t(tiles, func, steps0,
                                                  window_ms, offset_ms)
                return SplitResult(dev, 1, split=lambda h, i: h.T)
            if mesh_st is not None:
                dev = mesh_st.eval_aligned(tiles, func, steps0,
                                           window_ms, offset_ms)
            else:
                dev = tst.evaluate_aligned(tiles, func, steps0, window_ms,
                                           offset_ms, ())
            return SplitResult(dev, 1, split=lambda h, i: h)
        w0s_list = [m[0] for m in members]
        w0e_list = [m[1] for m in members]
        if counters:
            if mesh_st is not None:
                # the mesh-shaped batch: ONE sharded program computes
                # every member's grid from the resident tiles
                dev = mesh_st.eval_counters_batch(func, nsteps, step,
                                                  w0s_list, w0e_list)
            else:
                dev = tst.evaluate_counters_t_batch(
                    tiles, func, family, nsteps, step, w0s_list,
                    w0e_list)
            # [B_pad, T, S] -> member i's [S, T]
            return SplitResult(dev, len(members),
                               split=lambda h, i: h[i].T)
        if mesh_st is not None:
            dev = mesh_st.eval_aligned_batch(tiles, func, nsteps, step,
                                             w0s_list, w0e_list)
        else:
            dev = tst.evaluate_aligned_batch(
                tiles, func, nsteps, step, w0s_list, w0e_list)
        return SplitResult(dev, len(members), split=lambda h, i: h[i])

    def fused_groupsum(self, series, func: str, steps: np.ndarray,
                       window_ms: int, offset_ms: int,
                       gids: np.ndarray, G: int):
        """`sum/avg/count by (g)` of rate/increase/delta fused on device:
        the Pallas group-sum kernel consumes the cached aligned tiles and
        only [T, G] group sums + counts leave the chip — the [S, T] rate
        intermediate is never materialized (the reference pays this as
        per-shard AggrOverRangeVectors map-reduce over row iterators,
        exec/aggregator/*.scala). Returns (sums, cnts) as [T, G] numpy
        or None when ineligible (caller falls back to the general
        rangefn + aggregate path)."""
        from filodb_tpu.query import tilestore as tst

        if func not in ("rate", "increase", "delta") or not len(series):
            return None
        import jax
        on_cpu = jax.default_backend() == "cpu"
        if on_cpu and not FUSED_GROUPSUM_INTERPRET \
                and self.mesh_eval is None:
            # interpret-mode Pallas re-traces per tile shape — with live
            # ingest growing the tiles that is seconds per query; CPU
            # nodes take the vectorized-numpy path instead (tests flip
            # the flag to exercise the kernel in interpret mode; the
            # mesh-sharded grouped collective below is XLA, not Pallas,
            # so it serves on any backend)
            return None
        entry = self._tile_entry(series)
        tiles, idx = entry.tiles, entry.idx
        if tiles is None or len(idx) != len(series):
            return None
        # every window must resolve on the tiles' covered prefix: fused
        # results can't splice a host-side tail scan per group (a stale
        # entry serving across a flush covers less than the current
        # chunk prefix — cov_min_ms is the binding bound)
        if entry.cov_min_ms is not None and steps.size and \
                int(steps[-1] - offset_ms) >= entry.cov_min_ms:
            return None
        for s in series:
            cl = self._prefix_len(s)
            if cl < s.ts.size and steps.size and \
                    int(steps[-1] - offset_ms) >= int(s.ts[cl]):
                return None
        gvec = np.asarray(gids)[np.asarray(idx)]
        # mesh-resident grouped collective first: the one-hot matmul +
        # psum runs off the device-resident sharded tiles (no per-query
        # pack), honoring the same fast-family eligibility as the
        # per-series sharded path
        if self.mesh_eval is not None and steps.size >= 1:
            mesh_st = self._mesh_sharded(
                tiles, func, steps, window_ms, offset_ms,
                tst.counters_batch_family(tiles, func, steps, window_ms,
                                          offset_ms))
            if mesh_st is not None:
                self.fused_aggs += 1
                self.mesh_dispatches += 1
                return mesh_st.eval_grouped_pair(func, steps, window_ms,
                                                 gvec, G, offset_ms)
        if on_cpu and not FUSED_GROUPSUM_INTERPRET:
            return None
        onehot = np.zeros((len(series), G), np.float32)
        onehot[np.arange(len(series)), gvec] = 1.0
        res = tst.groupsum_counters(
            tiles, func, steps, window_ms, onehot, offset_ms,
            interpret=on_cpu)
        if res is None:
            return None
        self.fused_aggs += 1
        return np.asarray(res[0]), np.asarray(res[1])

    @staticmethod
    def _window_sample_bound(series, window_ms: int, n_cap: int) -> int:
        """Static upper bound on samples per window: window / min-interval."""
        min_dt = None
        for s in series:
            if s.ts.size >= 2:
                d = np.diff(s.ts).min()
                if d > 0:
                    min_dt = d if min_dt is None else min(min_dt, d)
        if min_dt is None or min_dt <= 0:
            return n_cap
        bound = int(window_ms // int(min_dt)) + 2
        return min(_next_pow2(bound, 4), max(n_cap, 4))
