"""Device-resident aligned tile store: the TPU-native in-memory chunk store.

FiloDB keeps hot chunks in off-heap memory and scans them per query
(core/memstore/TimeSeriesShard.scala, store/ChunkSetInfo.scala:432
WindowedChunkIterator). The TPU equivalent keeps each series as a row in a
**cadence-aligned device tile**: slot ``i`` nominally holds the sample
scraped at time ``i*dt`` (epoch-aligned, like DeltaDeltaVector's const
variant for regular timestamps — memory/format/vectors/DeltaDeltaVector.scala).

Because slots are global, every window boundary maps to the SAME slot
column for all series (+/-1 for scrape jitter), so the windowed hot loop
needs **no per-row gathers** — only shared-column takes, which are ~free
on TPU (vs ~40ns/element for per-row dynamic gathers). Gaps and jitter are
handled exactly:

  * pack time (once per tile publication, amortized over queries):
    validity mask, true timestamps, counter-reset correction, forward/
    backward fills (value+ts at last/first valid slot), inclusive prefix
    sums of any per-sample channel;
  * query time: boundary slots ``K_lo/K_hi`` from closed-form arithmetic,
    2-candidate jitter resolution (a slot's sample can straddle the window
    edge by < dt/2), prefix-difference window sums with edge-slot
    adjustments.

Series whose timestamps don't fit a shared cadence grid (collisions,
irregular scrape) fall back to the general packed path in tpu.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from filodb_tpu.lint.capacity import capacity
from filodb_tpu.lint.contracts import kernel_contract
from filodb_tpu.lint.numerics import order_insensitive, precision  # noqa: F401
from filodb_tpu.query.model import RawSeries

# functions servable from aligned tiles (everything endpoint- or
# prefix-sum-expressible; order statistics fall back to the gather path)
ALIGNED_FUNCS = frozenset({
    "rate", "increase", "delta",
    "sum_over_time", "count_over_time", "avg_over_time",
    "stddev_over_time", "stdvar_over_time", "z_score",
    "changes", "resets", "timestamp",
    "last_sample", "last_over_time", "first_over_time",
    "present_over_time", "absent_over_time",
    "rate_over_delta", "increase_over_delta",
})


def _ffill_idx(valid: jnp.ndarray) -> jnp.ndarray:
    """[S,N] bool -> j_last[s,i] = last valid slot <= i (-1 if none)."""
    idx = jnp.arange(valid.shape[1], dtype=jnp.int32)[None, :]
    return jax.lax.cummax(jnp.where(valid, idx, jnp.int32(-1)), axis=1)


@capacity(
    "tilestore-aligned-tiles", bytes_per_sample=17.0,
    reason="the base device residency of an aligned cohort is three "
           "[S, N] tiles — validity bool (1 B) + true-timestamp f64 "
           "(8 B) + value f64 (8 B) = 17 B per slot; the derived "
           "channels (ones/cv/prefix sums/transposes) are lazy "
           "per-function warm caches over the same slot count, not "
           "part of the cold footprint")
class AlignedTiles:
    """One cohort of series sharing cadence dt, as device tiles."""

    def __init__(self, keys: List[Dict[str, str]], base_ms: int, dt_ms: int,
                 valid: np.ndarray, ts_true: np.ndarray, vals: np.ndarray):
        self.keys = keys
        self.base_ms = int(base_ms)          # time of slot 0
        self.dt_ms = int(dt_ms)
        S, N = vals.shape
        self.num_slots = N
        self.valid = jnp.asarray(valid)                      # [S,N] bool
        # true timestamps as f64 ms (exact to 2^53); invalid -> NaN so
        # boundary conditions (ts <= wend) are false on gaps
        self.ts = jnp.where(self.valid, jnp.asarray(ts_true, jnp.float64),
                            jnp.nan)
        self.vals = jnp.where(self.valid, jnp.asarray(vals), 0.0)
        self._channels: Dict[str, jnp.ndarray] = {}
        self._ff: Dict[str, jnp.ndarray] = {}
        self._bf: Dict[str, jnp.ndarray] = {}
        self._ps: Dict[str, jnp.ndarray] = {}
        self._tch: Dict[str, jnp.ndarray] = {}
        self._tff: Dict[str, jnp.ndarray] = {}
        self._tbf: Dict[str, jnp.ndarray] = {}
        self._tps: Dict[str, jnp.ndarray] = {}
        self._tperm: Dict[Tuple[str, int], jnp.ndarray] = {}
        self._jitter = None
        self._jl = None
        self._jf = None
        self._dense = bool(np.asarray(valid).all())

    # -- pack-time derived channels (cached) ---------------------------------

    def channel(self, name: str) -> jnp.ndarray:
        """Per-slot f64 channel (0 at invalid slots)."""
        c = self._channels.get(name)
        if c is not None:
            return c
        v, valid = self.vals, self.valid
        if name == "v":
            c = v
        elif name == "ones":
            c = valid.astype(jnp.float64)
        elif name == "vc2":
            # squared deviation from a per-series shift (the series mean):
            # windowed variance from prefix sums of (x-c)^2 avoids the
            # catastrophic cancellation of the E[x^2]-mean^2 form
            d = jnp.where(valid, v - self.vshift[:, None], 0.0)
            c = d * d
        elif name == "ts":
            c = jnp.where(valid, self.ts, 0.0)
        elif name == "cv":                      # counter-reset corrected
            prev = self.ff("v")[:, :-1]
            prev = jnp.concatenate([jnp.full_like(prev[:, :1], jnp.nan),
                                    prev], axis=1)
            drop = valid & (v < prev) & ~jnp.isnan(prev)
            c = v + jnp.cumsum(jnp.where(drop, prev, 0.0), axis=1)
            c = jnp.where(valid, c, 0.0)
        elif name in ("ev_change", "ev_reset"):
            # event vs previous valid sample, attributed to the later one
            # (AggrOverTimeFunctions ChangesChunkedFunction semantics)
            prev = self.ff("v")[:, :-1]
            prev = jnp.concatenate([jnp.full_like(prev[:, :1], jnp.nan),
                                    prev], axis=1)
            if name == "ev_change":
                ev = valid & (v != prev) & ~jnp.isnan(prev)
            else:
                ev = valid & (v < prev) & ~jnp.isnan(prev)
            c = ev.astype(jnp.float64)
        else:
            raise KeyError(name)
        self._channels[name] = c
        return c

    @property
    def vshift(self) -> jnp.ndarray:
        """Per-series shift for stable variance: mean of valid samples."""
        c = self._channels.get("_vshift")
        if c is None:
            okf = self.valid & jnp.isfinite(self.vals)
            cnt = jnp.maximum(okf.sum(axis=1), 1)
            c = jnp.where(okf, self.vals, 0.0).sum(axis=1) / cnt
            self._channels["_vshift"] = c
        return c

    def ff(self, name: str) -> jnp.ndarray:
        """Forward fill: channel value at last valid slot <= i (NaN none)."""
        if self._dense:
            # fully-valid tiles: the fill is the channel itself (aliased,
            # no extra HBM — the common dense-scrape case)
            return self.ts if name == "ts" else self.channel(name)
        c = self._ff.get(name)
        if c is None:
            if self._jl is None:
                self._jl = _ffill_idx(self.valid)
            src = self.channel(name) if name != "ts" else self.ts
            gathered = jnp.take_along_axis(
                jnp.concatenate([jnp.full_like(src[:, :1], jnp.nan), src],
                                axis=1),
                (self._jl + 1).astype(jnp.int32), axis=1)
            c = gathered
            self._ff[name] = c
        return c

    def bf(self, name: str) -> jnp.ndarray:
        """Backward fill: channel value at first valid slot >= i."""
        if self._dense:
            return self.ts if name == "ts" else self.channel(name)
        c = self._bf.get(name)
        if c is None:
            if self._jf is None:
                rev = jnp.flip(self.valid, axis=1)
                self._jf = (self.valid.shape[1] - 1
                            - jnp.flip(_ffill_idx(rev), axis=1)).astype(
                                jnp.int32)
            src = self.channel(name) if name != "ts" else self.ts
            N = src.shape[1]
            gathered = jnp.take_along_axis(
                jnp.concatenate([src, jnp.full_like(src[:, :1], jnp.nan)],
                                axis=1),
                jnp.clip(self._jf, 0, N), axis=1)
            c = gathered
            self._bf[name] = c
        return c

    def prefix(self, name: str) -> jnp.ndarray:
        """Inclusive prefix sum of a channel, with a leading 0 column:
        ps[:, k+1] = sum of slots 0..k. Shape [S, N+1]."""
        c = self._ps.get(name)
        if c is None:
            cs = jnp.cumsum(self.channel(name), axis=1)
            c = jnp.concatenate([jnp.zeros_like(cs[:, :1]), cs], axis=1)
            self._ps[name] = c
        return c

    def warm(self, names_ff: Sequence[str] = (), names_bf: Sequence[str] = (),
             names_ps: Sequence[str] = ()) -> None:
        for n in names_ff:
            self.ff(n)
        for n in names_bf:
            self.bf(n)
        for n in names_ps:
            self.prefix(n)

    # -- transposed (slot-major) channels --------------------------------
    # [N, S] layout: one query step's shared slot column is a CONTIGUOUS
    # row, so the per-step gathers of the windowed evaluator read
    # sequential HBM instead of stride-N*8 columns (~4x faster on TPU).
    # Built lazily and cached like the row-major channels.

    def _t(self, cache_name: str, name: str, builder) -> jnp.ndarray:
        cache = getattr(self, cache_name)
        c = cache.get(name)
        if c is None:
            c = jnp.asarray(builder(name).T)
            cache[name] = c
        return c

    def t_ts(self) -> jnp.ndarray:
        return self._t("_tch", "ts_nan", lambda _: self.ts)

    def t_channel(self, name: str) -> jnp.ndarray:
        return self._t("_tch", name, self.channel)

    def t_ff(self, name: str) -> jnp.ndarray:
        if self._dense:     # alias: no second transposed copy
            return self.t_ts() if name == "ts" else self.t_channel(name)
        return self._t("_tff", name, self.ff)

    def t_bf(self, name: str) -> jnp.ndarray:
        if self._dense:
            return self.t_ts() if name == "ts" else self.t_channel(name)
        return self._t("_tbf", name, self.bf)

    def t_prefix(self, name: str) -> jnp.ndarray:
        return self._t("_tps", name, self.prefix)

    # -- int32 relative-time channels for the f32-hybrid fast path -------
    # Timestamps as int32 ms relative to base_ms: exact (guarded to spans
    # < 2^31 ms ≈ 24.8 days by the dispatcher), and boundary compares/
    # subtractions become native int32 ops instead of software-emulated
    # f64 — TPU v5e has no f64 ALU, so the all-f64 evaluator is compute-
    # bound on float-float emulation, not HBM.

    def t_tsr_i32(self) -> jnp.ndarray:
        """[N, S] int32: ts - base_ms (0 at invalid slots)."""
        c = self._tch.get("tsr_i32")
        if c is None:
            rel = jnp.where(self.valid, self.ts - self.base_ms, 0.0)
            c = jnp.asarray(rel.T).astype(jnp.int32)
            self._tch["tsr_i32"] = c
        return c

    def t_ff_tsr_i32(self) -> jnp.ndarray:
        """Forward-filled relative ts; INT32_MIN where no valid slot <= i."""
        if self._dense:
            return self.t_tsr_i32()
        c = self._tch.get("ff_tsr_i32")
        if c is None:
            f = self.ff("ts")
            rel = jnp.where(jnp.isnan(f), float(_SENT_LO),
                            f - self.base_ms)
            c = jnp.asarray(rel.T).astype(jnp.int32)
            self._tch["ff_tsr_i32"] = c
        return c

    def t_bf_tsr_i32(self) -> jnp.ndarray:
        """Backward-filled relative ts; INT32_MAX where no valid slot >= i."""
        if self._dense:
            return self.t_tsr_i32()
        c = self._tch.get("bf_tsr_i32")
        if c is None:
            f = self.bf("ts")
            rel = jnp.where(jnp.isnan(f), float(_SENT_HI),
                            f - self.base_ms)
            c = jnp.asarray(rel.T).astype(jnp.int32)
            self._tch["bf_tsr_i32"] = c
        return c

    def t_ones_i8(self) -> jnp.ndarray:
        c = self._tch.get("ones_i8")
        if c is None:
            c = jnp.asarray(self.valid.T).astype(jnp.int8)
            self._tch["ones_i8"] = c
        return c

    def t_ps_ones_i32(self) -> jnp.ndarray:
        """[N+1, S] int32 inclusive prefix count with leading 0 row."""
        c = self._tch.get("ps_ones_i32")
        if c is None:
            cs = jnp.cumsum(self.valid.astype(jnp.int32), axis=1)
            ps = jnp.concatenate([jnp.zeros_like(cs[:, :1]), cs], axis=1)
            c = jnp.asarray(ps.T)
            self._tch["ps_ones_i32"] = c
        return c

    # -- stride-permuted channels for the slide evaluator ----------------
    # Row gathers (jnp.take of T rows) lower to a TPU gather that runs at
    # ~140 GB/s; contiguous/strided slices stream at ~850 GB/s (measured
    # on v5e). For a REGULAR query grid (step % dt == 0, stride st =
    # step//dt) the T boundary rows of each take are k0, k0+st, ... — so
    # storing the [N, S] channel permuted by residue class as [st, G, S]
    # (row k at [k % st, k // st]) turns every take into ONE contiguous
    # dynamic_slice of shape (1, T, S). Cached per (channel, stride);
    # dashboards reuse one stride, so the copy amortizes like the other
    # derived channels.

    def t_perm(self, name: str, st: int, src: jnp.ndarray) -> jnp.ndarray:
        key = (name, st)
        c = self._tperm.get(key)
        if c is None:
            N = src.shape[0]
            G = -(-N // st)
            pad = G * st - N
            if pad:
                fill = jnp.zeros((pad,) + src.shape[1:], src.dtype)
                src = jnp.concatenate([src, fill], axis=0)
            c = jnp.asarray(jnp.swapaxes(
                src.reshape(G, st, *src.shape[1:]), 0, 1))
            self._tperm[key] = c
        return c

    def t_perm_tiled(self, name: str, st: int, src: jnp.ndarray
                     ) -> jnp.ndarray:
        """Stride-permuted AND s-tile-major channel for the Pallas
        group-sum kernel: [n_s, st, G, SS] with SS = kernel lane tile.
        Within one (s-tile, residue) plane, consecutive G rows are
        CONTIGUOUS in HBM, so each kernel DMA is one large linear read
        (the plain [st, G, S] layout would make per-s-tile blocks
        strided 4KB chunks). S is padded to a multiple of SS; G is
        padded past the kernel's tail tile like t_perm."""
        key = (name + "#tiled", st)
        c = self._tperm.get(key)
        if c is None:
            from filodb_tpu.query.pallas_kernels import (_GS_AL,
                                                         _GS_DSPAN_MAX,
                                                         _GS_SS,
                                                         _GS_TT_WIDE)
            N = src.shape[0]
            S = src.shape[1]
            # pad the permuted G axis past every tail tile: the kernel's
            # merged kc/kl stream reads up to dspan (<= _GS_DSPAN_MAX)
            # + alignment rows past the last window-end row — sized for
            # the WIDEST step tile the pipeline chooser can pick
            G = -(-N // st) + _GS_TT_WIDE + 2 * _GS_AL + _GS_DSPAN_MAX
            padn = G * st - N
            if padn:
                src = jnp.concatenate(
                    [src, jnp.zeros((padn, S), src.dtype)], axis=0)
            S_pad = -(-S // _GS_SS) * _GS_SS
            if S_pad != S:
                src = jnp.concatenate(
                    [src, jnp.zeros((G * st, S_pad - S), src.dtype)],
                    axis=1)
            c = jnp.asarray(
                src.reshape(G, st, S_pad // _GS_SS, _GS_SS)
                .transpose(2, 1, 0, 3))
            self._tperm[key] = c
        return c

    @precision(
        "fixed-point-split", bits=61, rel_ulps=4,
        reason="exact int32 hi/lo split: |v - mid| * 2**s <= 2**60, so "
               "boundary subtractions in the group-sum kernel are "
               "exact integer ops; only the final f32 recombine "
               "rounds, relative to the delta, with a fixed-point "
               "quantization floor of span * 2**-59 — certified "
               "against the direct f64 delta")
    def _fixed_channels(self, vch: str):
        """Per-series 61-bit fixed-point encoding of a value channel for
        the group-sum kernel: each series is rebased to its in-tile
        midpoint and scaled by a per-series power of two 2^s chosen so
        |v - mid| * 2^s <= 2^60, then split as hi*2^31 + lo with lo in
        [0, 2^31). Integer boundary subtractions in the kernel are then
        EXACT; only the final f32 recombine rounds, relative to the
        delta — the same noise floor as the reference's f64 arithmetic
        (rangefn/RateFunctions.scala:23).

        Returns (hi [N,S] i32, lo [N,S] i32, mid_f32 [S], s [S] i32) or
        None when the channel has non-finite values."""
        key = (vch, "#fixed")
        c = self._tperm.get(key)
        if c is None:
            v = self.t_channel(vch)                      # [N, S] f64
            vmax = jnp.max(v, axis=0)
            vmin = jnp.min(v, axis=0)
            if not bool(jnp.isfinite(vmax).all()
                        & jnp.isfinite(vmin).all()):
                self._tperm[key] = (None,)
                return None
            mid = (vmax + vmin) * 0.5
            # host-side scale selection ([S]-sized; f64 frexp has no TPU
            # lowering): span2 <= 2^e with frexp's m in [0.5, 1)
            span2 = np.maximum(np.asarray(vmax - vmin) * 0.5, 2.0 ** -130)
            _, e = np.frexp(span2)
            if np.any(60 - e < -96):
                # a span this wide (> 2^156) cannot be represented in
                # the 61-bit fixed-point channel at any in-range scale:
                # clipping the exponent would silently WRAP int64 and
                # corrupt results — take the exact f64 fallback instead
                self._tperm[key] = (None,)
                return None
            s_np = np.clip(60 - e, -96, 126).astype(np.int32)
            s = jnp.asarray(s_np)
            scale = jnp.asarray(np.ldexp(1.0, s_np))
            fixed = jnp.rint(
                (v - mid[None, :]) * scale[None, :]
            ).astype(jnp.int64)
            hi64 = fixed >> 31
            lo = (fixed - (hi64 << 31)).astype(jnp.int32)
            c = (hi64.astype(jnp.int32), lo,
                 mid.astype(jnp.float32), s)
            self._tperm[key] = c
        return None if c == (None,) else c

    def t_perm_fixed_tiled(self, vch: str, st: int) -> jnp.ndarray:
        """The Pallas group-sum kernel's packed channel: s-tile-major
        stride-permuted [n_s, st, G, 3*SS] i32 where plane 0 is the
        int32 relative timestamp and planes 1-2 are the per-series
        fixed-point hi/lo split of the value channel (_fixed_channels).
        One kernel DMA per boundary stream fetches timestamps + values
        as a single contiguous read (see t_perm_tiled)."""
        key = (vch + "#fixed_tiled", st)
        c = self._tperm.get(key)
        if c is None:
            fx = self._fixed_channels(vch)
            assert fx is not None, "dispatcher must gate on finiteness"
            hi, lo = fx[0], fx[1]
            parts = [self.t_perm_tiled(f"{vch}#fx{i}", st, ch)
                     for i, ch in enumerate(
                         (self.t_tsr_i32(), hi, lo))]
            c = jnp.asarray(jnp.concatenate(parts, axis=3))
            for i in range(3):
                self._tperm.pop((f"{vch}#fx{i}" + "#tiled", st), None)
            self._tperm[key] = c
        return c

    def t_fixed_base(self, vch: str) -> jnp.ndarray:
        """[n_s, 8, SS] f32 companion of t_perm_fixed_tiled: row 0 =
        per-series rebase midpoint (f32, used only by the counter-zero
        extrapolation limiter), row 1 = 2^(31-s), row 2 = 2^-s."""
        key = (vch + "#fixed_base", 0)
        c = self._tperm.get(key)
        if c is None:
            from filodb_tpu.query.pallas_kernels import _GS_SS
            fx = self._fixed_channels(vch)
            assert fx is not None
            mid, s = fx[2], fx[3]
            c1 = jnp.ldexp(jnp.float32(1.0), 31 - s)
            c2 = jnp.ldexp(jnp.float32(1.0), -s)
            S = mid.shape[0]
            S_pad = -(-S // _GS_SS) * _GS_SS
            rows = jnp.zeros((3, S_pad), jnp.float32)
            rows = rows.at[0, :S].set(mid).at[1, :S].set(c1)
            rows = rows.at[2, :S].set(c2)
            rows = jnp.pad(rows, ((0, 5), (0, 0)))
            c = jnp.asarray(
                rows.reshape(8, S_pad // _GS_SS, _GS_SS)
                .transpose(1, 0, 2))
            self._tperm[key] = c
        return c

    def jitter_ms(self) -> float:
        """Max |ts - nominal slot tick| over valid slots: the bound the
        group-sum dispatcher uses to elide jitter-fallback families
        when the query grid phase statically clears it."""
        if self._jitter is None:
            ticks = (self.base_ms
                     + jnp.arange(self.num_slots, dtype=jnp.float64)
                     * self.dt_ms)
            d = jnp.where(self.valid,
                          jnp.abs(self.ts - ticks[None, :]), 0.0)
            self._jitter = float(jnp.max(d))
        return self._jitter


_SENT_LO = -(2 ** 31)           # "no sample at or before this slot"
_SENT_HI = 2 ** 31 - 1          # "no sample at or after this slot"


def _estimate_dt_candidates(series: Sequence[RawSeries]) -> List[int]:
    """Scrape-cadence estimate robust to gaps and jitter: iteratively
    refine the pooled diff median by dividing each diff by its rounded
    multiple (a k-sample gap contributes diff/k), then offer round-number
    snaps (real scrape intervals are round) ordered most-likely first."""
    diffs = []
    for s in series:
        if s.ts.size >= 2:
            d = np.diff(s.ts).astype(np.float64)
            diffs.append(d[d > 0])
    if not diffs:
        return []
    d = np.concatenate(diffs)
    if d.size == 0:
        return []
    dt = float(np.median(d))
    for _ in range(3):
        k = np.maximum(np.round(d / dt), 1.0)
        dt = float(np.median(d / k))
    if dt <= 0:
        return []
    cands: List[int] = []
    for q in (60_000, 10_000, 5_000, 1_000, 500, 100, 1):
        c = int(round(dt / q) * q)
        if c > 0 and abs(c - dt) <= dt * 0.25 and c not in cands:
            cands.append(c)
    return cands


def _align_rows(series: Sequence[RawSeries], dt: int):
    rows, aligned_idx = [], []
    lo = hi = None
    for i, s in enumerate(series):
        m = ~np.isnan(s.values)
        ts, vals = s.ts[m], s.values[m]
        if ts.size == 0:
            continue
        slots = np.round(ts / dt).astype(np.int64)
        if np.unique(slots).size != slots.size:
            continue                      # slot collision -> irregular
        if np.abs(ts - slots * dt).max() >= dt / 2:
            continue
        rows.append((i, slots, ts, vals))
        aligned_idx.append(i)
        lo = slots[0] if lo is None else min(lo, slots[0])
        hi = slots[-1] if hi is None else max(hi, slots[-1])
    return rows, aligned_idx, lo, hi


def build_aligned_tiles(series: Sequence[RawSeries],
                        ) -> Tuple[Optional[AlignedTiles], List[int]]:
    """Try to align series onto a shared cadence grid.

    Returns (tiles, aligned_indices). Series that don't fit (slot
    collisions after NaN-drop, or no shared dt) are excluded; the caller
    routes them through the general path. Returns (None, []) if fewer than
    half the series align or cadence can't be established."""
    if not series:
        return None, []
    dt_cands = _estimate_dt_candidates(series)
    if not dt_cands:
        return None, []
    best = None
    for dt in dt_cands:
        attempt = _align_rows(series, dt)
        if best is None or len(attempt[0]) > len(best[0][0]):
            best = (attempt, dt)
        if len(attempt[0]) == len(series):
            break
    (rows, aligned_idx, lo, hi), dt = best
    if not rows or len(rows) * 2 < len(series):
        return None, []
    base = int(lo * dt)
    N = int(hi - lo + 1)
    S = len(rows)
    valid = np.zeros((S, N), dtype=bool)
    ts_true = np.zeros((S, N), dtype=np.float64)
    vals_g = np.zeros((S, N), dtype=np.float64)
    keys = []
    for r, (i, slots, ts, vals) in enumerate(rows):
        pos = slots - lo
        valid[r, pos] = True
        ts_true[r, pos] = ts
        vals_g[r, pos] = vals
        keys.append(dict(series[i].labels))
    return AlignedTiles(keys, base, dt, valid, ts_true, vals_g), aligned_idx


# ---------------------------------------------------------------------------
# Query-time evaluation (shared-column takes only)
# ---------------------------------------------------------------------------

# The whole per-query computation compiles to ONE XLA program (the tunnel
# adds per-dispatch latency, and XLA fuses the take/select/epilogue chain).
# Tile arrays enter as a dict pytree argument; (func, grid shape, tile
# identity) key the jit cache.

def _take(arr: jnp.ndarray, cols: jnp.ndarray) -> jnp.ndarray:
    """[S, N] x [T] shared columns -> [S, T]."""
    return jnp.take(arr, cols, axis=1)


def _select_last(arrs, names, num_slots, k_hi, wend):
    """Channel values at the LAST sample with ts <= wend_t, per series:
    2-candidate select between slot K_hi's forward fill and K_hi-1's."""
    N = num_slots
    kc = jnp.clip(k_hi, 0, N - 1).astype(jnp.int32)
    kp = jnp.clip(k_hi - 1, 0, N - 1).astype(jnp.int32)
    none = (k_hi < 0)[None, :]
    ts1 = _take(arrs["ff_ts"], kc)
    use1 = ts1 <= wend.astype(jnp.float64)[None, :]      # NaN -> False
    out = []
    for n in names:
        a = arrs["ff_" + n]
        v = jnp.where(use1, _take(a, kc), _take(a, kp))
        out.append(jnp.where(none, jnp.nan, v))
    return out


def _select_first(arrs, names, num_slots, k_lo, wstart):
    """Channel values at the FIRST sample with ts >= wstart_t."""
    N = num_slots
    kc = jnp.clip(k_lo, 0, N - 1).astype(jnp.int32)
    kn = jnp.clip(k_lo + 1, 0, N - 1).astype(jnp.int32)
    none = (k_lo > N - 1)[None, :]
    ts1 = _take(arrs["bf_ts"], kc)
    use1 = ts1 >= wstart.astype(jnp.float64)[None, :]
    out = []
    for n in names:
        a = arrs["bf_" + n]
        v = jnp.where(use1, _take(a, kc), _take(a, kn))
        out.append(jnp.where(none, jnp.nan, v))
    return out


def _window_sum(arrs, name, num_slots, k_lo, k_hi, wstart, wend):
    """Exact sum of a channel over samples with ts in [wstart_t, wend_t]:
    prefix difference over slots [K_lo, K_hi] minus edge-slot samples that
    jitter outside the window."""
    N = num_slots
    ps = arrs["ps_" + name]
    ch = arrs["ch_" + name]
    hi_i = (jnp.clip(k_hi, -1, N - 1) + 1).astype(jnp.int32)
    lo_i = jnp.clip(k_lo, 0, N).astype(jnp.int32)
    s = _take(ps, hi_i) - _take(ps, lo_i)
    wend_d = wend.astype(jnp.float64)[None, :]
    wstart_d = wstart.astype(jnp.float64)[None, :]
    khx = jnp.clip(k_hi, 0, N - 1).astype(jnp.int32)
    k_hi_ok = ((k_hi >= 0) & (k_hi <= N - 1))[None, :]
    over = k_hi_ok & (_take(arrs["ts"], khx) > wend_d)
    s = s - jnp.where(over, _take(ch, khx), 0.0)
    klx = jnp.clip(k_lo, 0, N - 1).astype(jnp.int32)
    k_lo_ok = ((k_lo >= 0) & (k_lo <= N - 1))[None, :]
    under = k_lo_ok & (_take(arrs["ts"], klx) < wstart_d)
    s = s - jnp.where(under, _take(ch, klx), 0.0)
    return s


# channels each function needs: (ff/bf endpoint channels, prefix channels)
_ENDPOINT_CH = {
    "rate": ["ts", "cv"], "increase": ["ts", "cv"], "delta": ["ts", "v"],
    "last_sample": ["v"], "last_over_time": ["v"],
    "first_over_time": ["v"], "timestamp": ["ts"],
    "changes": ["ev_change"], "resets": ["ev_reset"], "z_score": ["v"],
}
_PREFIX_CH = {
    "sum_over_time": ["v"], "avg_over_time": ["v"],
    "rate_over_delta": ["v"], "increase_over_delta": ["v"],
    "stddev_over_time": ["v", "vc2"], "stdvar_over_time": ["v", "vc2"],
    "z_score": ["v", "vc2"], "changes": ["ev_change"],
    "resets": ["ev_reset"],
}


def _tiles_arrays(tiles: AlignedTiles, func: str) -> Dict[str, jnp.ndarray]:
    """Collect (and lazily pack) the device arrays `func` needs."""
    arrs: Dict[str, jnp.ndarray] = {
        "ts": tiles.ts,
        "ps_ones": tiles.prefix("ones"),
        "ch_ones": tiles.channel("ones"),
    }
    ep = _ENDPOINT_CH.get(func, ())
    if ep:
        arrs["ff_ts"] = tiles.ff("ts")
        arrs["bf_ts"] = tiles.bf("ts")
    for n in ep:
        if func in ("rate", "increase", "delta"):
            arrs["ff_" + n] = tiles.ff(n)
            arrs["bf_" + n] = tiles.bf(n)
        elif func in ("changes", "resets"):
            arrs["bf_" + n] = tiles.bf(n)
        elif func == "first_over_time":
            arrs["bf_" + n] = tiles.bf(n)
        else:
            arrs["ff_" + n] = tiles.ff(n)
    for n in _PREFIX_CH.get(func, ()):
        arrs["ps_" + n] = tiles.prefix(n)
        arrs["ch_" + n] = tiles.channel(n)
    if "vc2" in _PREFIX_CH.get(func, ()):
        arrs["vshift"] = tiles.vshift
    return arrs


def _eval_core(func: str, nsteps: int, arrs: Dict[str, jnp.ndarray],
               num_slots, base, dt, w0s, w0e, step) -> jnp.ndarray:
    """Traceable evaluation body (jitted via _EVAL_JIT). Everything except
    (func, nsteps) is traced, so one compiled program serves every store
    snapshot of the same shape."""
    from filodb_tpu.query.tpu import _extrapolated_rate

    t = jnp.arange(nsteps, dtype=jnp.int64)
    wend = w0e + t * step
    wstart = w0s + t * step
    # highest slot that could hold a sample <= wend / lowest that could
    # hold one >= wstart (scrape jitter < dt/2 each side)
    k_hi = jnp.floor((wend - base + dt / 2.0) / dt).astype(jnp.int64)
    k_lo = jnp.ceil((wstart - base - dt / 2.0) / dt).astype(jnp.int64)
    counts = _window_sum(arrs, "ones", num_slots, k_lo, k_hi, wstart, wend)
    has = counts >= 0.5
    nan = jnp.nan
    N = num_slots

    if func in ("rate", "increase", "delta"):
        is_counter = func != "delta"
        vch = "cv" if is_counter else "v"
        t2, v2 = _select_last(arrs, ["ts", vch], N, k_hi, wend)
        t1, v1 = _select_first(arrs, ["ts", vch], N, k_lo, wstart)
        out = _extrapolated_rate(wstart[None, :], wend[None, :], counts,
                                 t1, v1, t2, v2,
                                 is_counter, func == "rate")
        return jnp.where(has, out, nan)

    if func in ("last_sample", "last_over_time"):
        (v2,) = _select_last(arrs, ["v"], N, k_hi, wend)
        return jnp.where(has, v2, nan)
    if func == "first_over_time":
        (v1,) = _select_first(arrs, ["v"], N, k_lo, wstart)
        return jnp.where(has, v1, nan)
    if func == "timestamp":
        (t2,) = _select_last(arrs, ["ts"], N, k_hi, wend)
        return jnp.where(has, t2 / 1000.0, nan)
    if func == "present_over_time":
        return jnp.where(has, 1.0, nan)
    if func == "absent_over_time":
        return jnp.where(has, nan, 1.0)

    if func in ("changes", "resets"):
        ch = "ev_change" if func == "changes" else "ev_reset"
        total = _window_sum(arrs, ch, N, k_lo, k_hi, wstart, wend)
        (ev_first,) = _select_first(arrs, [ch], N, k_lo, wstart)
        out = total - jnp.where(jnp.isnan(ev_first), 0.0, ev_first)
        return jnp.where(has, out, nan)

    if func == "count_over_time":
        return jnp.where(has, counts, nan)
    s = _window_sum(arrs, "v", N, k_lo, k_hi, wstart, wend)
    if func in ("sum_over_time", "increase_over_delta"):
        out = s
    elif func == "rate_over_delta":
        out = s / (wend - wstart)[None, :].astype(jnp.float64) * 1000.0
    elif func == "avg_over_time":
        out = s / counts
    else:
        s2 = _window_sum(arrs, "vc2", N, k_lo, k_hi, wstart, wend)
        mean = s / counts
        dmean = mean - arrs["vshift"][:, None]
        var = jnp.maximum(s2 / counts - dmean * dmean, 0.0)
        if func == "stdvar_over_time":
            out = var
        elif func == "stddev_over_time":
            out = jnp.sqrt(var)
        elif func == "z_score":
            (v2,) = _select_last(arrs, ["v"], N, k_hi, wend)
            out = (v2 - mean) / jnp.sqrt(var)
        else:
            raise ValueError(f"aligned path cannot evaluate {func}")
    return jnp.where(has, out, nan)


# ---------------------------------------------------------------------------
# Transposed (slot-major) evaluator for the counter family — the north-star
# hot path. Identical numerics to _eval_core; arrays are [N, S] so each
# step's slot reads are contiguous rows (≈4x the gather bandwidth of
# column reads on TPU). Output is [T, S].
# ---------------------------------------------------------------------------

def _tiles_arrays_t(tiles: AlignedTiles, func: str) -> Dict[str, jnp.ndarray]:
    vch = "cv" if func in ("rate", "increase") else "v"
    if tiles._dense:
        # fully-valid tiles: fills alias the channels and sample counts
        # are slot arithmetic — only (ts, value) tiles live in HBM
        return {"ts": tiles.t_ts(), "ff_v": tiles.t_channel(vch)}
    return {
        "ts": tiles.t_ts(),
        "ps_ones": tiles.t_prefix("ones"),
        "ch_ones": tiles.t_channel("ones"),
        "ff_ts": tiles.t_ff("ts"),
        "bf_ts": tiles.t_bf("ts"),
        "ff_v": tiles.t_ff(vch),
        "bf_v": tiles.t_bf(vch),
    }


@precision(
    "counter-exact-slot-index", bits=31, rel_ulps=4,
    reason="the i64->i32 casts narrow SLOT indices, each clipped to "
           "[0, num_slots] first (num_slots < 2**31 by construction); "
           "the value math stays f64 end to end — certified against "
           "the pure-Python per-window reference evaluator "
           "(promql/refeval) to a few f64 ulps")
def _eval_counter_t(func: str, nsteps: int, arrs: Dict[str, jnp.ndarray],
                    num_slots, base, dt, w0s, w0e, step) -> jnp.ndarray:
    """rate/increase/delta over transposed tiles → [T, S] f64.

    With dense tiles (no "ps_ones"/"ff_ts" in ``arrs``) the fills alias
    the base channels and counts come from slot arithmetic — the hot
    query reads only (ts, value) rows."""
    N = num_slots
    dense = "ps_ones" not in arrs
    t = jnp.arange(nsteps, dtype=jnp.int64)
    wend = w0e + t * step
    wstart = w0s + t * step
    k_hi = jnp.floor((wend - base + dt / 2.0) / dt).astype(jnp.int64)
    k_lo = jnp.ceil((wstart - base - dt / 2.0) / dt).astype(jnp.int64)
    TK = lambda a, k: jnp.take(a, k, axis=0)            # [T, S] rows
    wend_d = wend.astype(jnp.float64)[:, None]
    wstart_d = wstart.astype(jnp.float64)[:, None]
    # counts: prefix diff + edge-slot jitter corrections
    hi_i = (jnp.clip(k_hi, -1, N - 1) + 1).astype(jnp.int32)
    lo_i = jnp.clip(k_lo, 0, N).astype(jnp.int32)
    if dense:
        counts = (hi_i - lo_i).astype(jnp.float64)[:, None]
        one = 1.0
    else:
        counts = TK(arrs["ps_ones"], hi_i) - TK(arrs["ps_ones"], lo_i)
    khx = jnp.clip(k_hi, 0, N - 1).astype(jnp.int32)
    k_hi_ok = ((k_hi >= 0) & (k_hi <= N - 1))[:, None]
    over = k_hi_ok & (TK(arrs["ts"], khx) > wend_d)
    counts = counts - jnp.where(
        over, one if dense else TK(arrs["ch_ones"], khx), 0.0)
    klx = jnp.clip(k_lo, 0, N - 1).astype(jnp.int32)
    k_lo_ok = ((k_lo >= 0) & (k_lo <= N - 1))[:, None]
    under = k_lo_ok & (TK(arrs["ts"], klx) < wstart_d)
    counts = counts - jnp.where(
        under, one if dense else TK(arrs["ch_ones"], klx), 0.0)
    has = counts >= 0.5
    ff_ts = arrs["ts"] if dense else arrs["ff_ts"]
    bf_ts = arrs["ts"] if dense else arrs["bf_ts"]
    bf_v = arrs["ff_v"] if dense else arrs["bf_v"]
    # last sample <= wend (2-candidate select, as _select_last)
    kc = jnp.clip(k_hi, 0, N - 1).astype(jnp.int32)
    kp = jnp.clip(k_hi - 1, 0, N - 1).astype(jnp.int32)
    none_hi = (k_hi < 0)[:, None]
    ts1 = TK(ff_ts, kc)
    use1 = ts1 <= wend_d
    t2 = jnp.where(none_hi, jnp.nan,
                   jnp.where(use1, ts1, TK(ff_ts, kp)))
    v2 = jnp.where(none_hi, jnp.nan,
                   jnp.where(use1, TK(arrs["ff_v"], kc),
                             TK(arrs["ff_v"], kp)))
    # first sample >= wstart
    kcl = jnp.clip(k_lo, 0, N - 1).astype(jnp.int32)
    kn = jnp.clip(k_lo + 1, 0, N - 1).astype(jnp.int32)
    none_lo = (k_lo > N - 1)[:, None]
    tsb = TK(bf_ts, kcl)
    useb = tsb >= wstart_d
    t1 = jnp.where(none_lo, jnp.nan,
                   jnp.where(useb, tsb, TK(bf_ts, kn)))
    v1 = jnp.where(none_lo, jnp.nan,
                   jnp.where(useb, TK(bf_v, kcl),
                             TK(bf_v, kn)))
    from filodb_tpu.query.tpu import _extrapolated_rate
    is_counter = func != "delta"
    out = _extrapolated_rate(wstart_d, wend_d, counts,
                             t1, v1, t2, v2, is_counter, func == "rate")
    return jnp.where(has, out, jnp.nan)


def _tiles_arrays_fast(tiles: AlignedTiles, func: str
                       ) -> Dict[str, jnp.ndarray]:
    """Channels for the f32-hybrid counter evaluator: int32 relative
    timestamps + the exact f64 value tile. Dense tiles need only the two
    (tsr, value) tiles — 12 bytes/sample in HBM."""
    vch = "cv" if func in ("rate", "increase") else "v"
    if tiles._dense:
        return {"tsr": tiles.t_tsr_i32(), "ff_v": tiles.t_channel(vch)}
    return {
        "tsr": tiles.t_tsr_i32(),
        "ones": tiles.t_ones_i8(),
        "ps_ones": tiles.t_ps_ones_i32(),
        "ff_tsr": tiles.t_ff_tsr_i32(),
        "bf_tsr": tiles.t_bf_tsr_i32(),
        "ff_v": tiles.t_ff(vch),
        "bf_v": tiles.t_bf(vch),
    }


@precision(
    "counter-fast-hybrid", bits=31, rel_ulps=16,
    reason="the int31 span-guard idiom: the dispatcher "
           "(_slide_eligible / ShardedTiles.query_fits) proves the "
           "whole query grid fits int32 ms relative to the tile base "
           "before the i64->i32 timestamp narrowing; boundary deltas "
           "stay exact f64 and only the extrapolation epilogue runs "
           "f32 — certified against the exact-f64 evaluator "
           "(_eval_counter_t) within 16 f32 ulps")
def _eval_counter_fast(func: str, nsteps: int, arrs: Dict[str, jnp.ndarray],
                       num_slots, base, dt, w0s, w0e, step) -> jnp.ndarray:
    """rate/increase/delta over transposed tiles → [T, S] **f32**.

    The f32-hybrid path (rangefn/RateFunctions.scala:37 semantics):
      * timestamps are int32 ms relative to the tile base — exact, and
        every boundary compare/subtract is a native int32 op;
      * the boundary value delta (v2 - v1) is computed in f64 from the
        f64 value tile, so large counters (1e15 + small increments) keep
        exact deltas — the catastrophic-cancellation failure a pure-f32
        value channel would hit;
      * the extrapolation epilogue (durations, averages, divisions) runs
        in f32 — native TPU rate vs software-emulated f64.

    Results match the exact-f64 evaluator to ~1e-6 relative (a few f32
    ulps from the extrapolation factor). The dispatcher guards that the
    query grid fits int32 ms relative to base; wider grids take the
    exact path."""
    N = num_slots
    dense = "ps_ones" not in arrs
    t = jnp.arange(nsteps, dtype=jnp.int64)
    wend = w0e + t * step
    wstart = w0s + t * step
    k_hi = jnp.floor((wend - base + dt / 2.0) / dt).astype(jnp.int64)
    k_lo = jnp.ceil((wstart - base - dt / 2.0) / dt).astype(jnp.int64)
    wend_r = (wend - base).astype(jnp.int32)[:, None]       # guarded i32
    wstart_r = (wstart - base).astype(jnp.int32)[:, None]
    TK = lambda a, k: jnp.take(a, k, axis=0)                # [T, S] rows

    kc = jnp.clip(k_hi, 0, N - 1).astype(jnp.int32)         # == khx
    kp = jnp.clip(k_hi - 1, 0, N - 1).astype(jnp.int32)
    kcl = jnp.clip(k_lo, 0, N - 1).astype(jnp.int32)        # == klx
    kn = jnp.clip(k_lo + 1, 0, N - 1).astype(jnp.int32)

    # the 8 unique row-takes (4 of int32 ts, 4 of f64 values); every
    # boundary select and jitter correction below reuses these
    ts_kc, ts_kp = TK(arrs["tsr"] if dense else arrs["ff_tsr"], kc), None
    if dense:
        ts_kp = TK(arrs["tsr"], kp)
        tsb_kcl = TK(arrs["tsr"], kcl)
        tsb_kn = TK(arrs["tsr"], kn)
        raw_kc, raw_kcl = ts_kc, tsb_kcl
    else:
        ts_kp = TK(arrs["ff_tsr"], kp)
        tsb_kcl = TK(arrs["bf_tsr"], kcl)
        tsb_kn = TK(arrs["bf_tsr"], kn)
        raw_kc = TK(arrs["tsr"], kc)
        raw_kcl = TK(arrs["tsr"], kcl)
    v_kc = TK(arrs["ff_v"], kc)
    v_kp = TK(arrs["ff_v"], kp)
    bf_v = arrs["ff_v"] if dense else arrs["bf_v"]
    v_kcl = TK(bf_v, kcl)
    v_kn = TK(bf_v, kn)

    # counts: slot arithmetic (dense) / prefix diff, minus edge-slot
    # samples that jitter outside the window
    hi_i = (jnp.clip(k_hi, -1, N - 1) + 1).astype(jnp.int32)
    lo_i = jnp.clip(k_lo, 0, N).astype(jnp.int32)
    k_hi_ok = ((k_hi >= 0) & (k_hi <= N - 1))[:, None]
    k_lo_ok = ((k_lo >= 0) & (k_lo <= N - 1))[:, None]
    if dense:
        counts = (hi_i - lo_i)[:, None]
        over = k_hi_ok & (raw_kc > wend_r)
        under = k_lo_ok & (raw_kcl < wstart_r)
    else:
        counts = TK(arrs["ps_ones"], hi_i) - TK(arrs["ps_ones"], lo_i)
        ones_kc = TK(arrs["ones"], kc) > 0
        ones_kcl = TK(arrs["ones"], kcl) > 0
        over = k_hi_ok & ones_kc & (raw_kc > wend_r)
        under = k_lo_ok & ones_kcl & (raw_kcl < wstart_r)
    counts = counts - over.astype(jnp.int32) - under.astype(jnp.int32)

    # last sample <= wend (2-candidate select; sentinel/NaN-filled
    # boundaries propagate through the f64 value channel)
    none_hi = (k_hi < 0)[:, None]
    use1 = ts_kc <= wend_r
    t2 = jnp.where(use1, ts_kc, ts_kp)
    v2 = jnp.where(none_hi, jnp.nan, jnp.where(use1, v_kc, v_kp))
    # first sample >= wstart
    none_lo = (k_lo > N - 1)[:, None]
    useb = tsb_kcl >= wstart_r
    t1 = jnp.where(useb, tsb_kcl, tsb_kn)
    v1 = jnp.where(none_lo, jnp.nan, jnp.where(useb, v_kcl, v_kn))

    return _f32_epilogue(func, counts, t1, v1, t2, v2, wstart_r, wend_r,
                         (w0e - w0s).astype(jnp.float32) / 1000.0)


def _tiles_arrays_slide(tiles: AlignedTiles, func: str, st: int
                        ) -> Dict[str, jnp.ndarray]:
    """Stride-permuted channels for the slide evaluator (dense tiles
    only): int32 relative timestamps + the exact f64 value channel,
    each as [st, G, S]."""
    vch = "cv" if func in ("rate", "increase") else "v"
    return {
        "tsr_p": tiles.t_perm("tsr_i32", st, tiles.t_tsr_i32()),
        "ff_v_p": tiles.t_perm(vch, st, tiles.t_channel(vch)),
    }


@precision(
    "counter-slide-hybrid", bits=31, rel_ulps=16,
    reason="same hybrid numerics as counter-fast-hybrid (int32 "
           "relative timestamps under the _slide_eligible span guard, "
           "exact f64 boundary deltas, f32 epilogue); the stride-"
           "permuted dynamic_slice changes only the memory access "
           "pattern — certified against the exact-f64 evaluator "
           "within the same 16 f32 ulps")
def _eval_counter_slide(func: str, nsteps: int, st: int,
                        arrs: Dict[str, jnp.ndarray],
                        num_slots, base, dt, w0s, w0e, step) -> jnp.ndarray:
    """rate/increase/delta on a REGULAR grid over dense tiles → [T, S] f32.

    Same numerics as ``_eval_counter_fast`` (int32 relative timestamps,
    exact f64 boundary deltas, f32 extrapolation epilogue —
    rangefn/RateFunctions.scala:23-79 semantics), but every boundary
    row-take is ONE contiguous dynamic_slice of the stride-permuted
    [st, G, S] channel: rows k0, k0+st, ... live at [k0 % st,
    k0//st : k0//st + T]. ~6x the HBM efficiency of the gather path on
    v5e. The dispatcher guarantees every index is in bounds, so the
    clip/sentinel masks of the gather path vanish."""
    T = nsteps
    G, S = arrs["tsr_p"].shape[1], arrs["tsr_p"].shape[2]
    sti = jnp.int32(st)
    k_c0 = jnp.floor((w0e - base + dt / 2.0) / dt).astype(jnp.int32)
    k_l0 = jnp.ceil((w0s - base - dt / 2.0) / dt).astype(jnp.int32)

    def rows(perm, k0):
        r = jnp.mod(k0, sti)
        g = jnp.floor_divide(k0, sti)
        sl = jax.lax.dynamic_slice(perm, (r, g, jnp.int32(0)), (1, T, S))
        return sl.reshape(T, S)

    ts_kc = rows(arrs["tsr_p"], k_c0)
    ts_kp = rows(arrs["tsr_p"], k_c0 - 1)
    tsb_kcl = rows(arrs["tsr_p"], k_l0)
    tsb_kn = rows(arrs["tsr_p"], k_l0 + 1)
    v_kc = rows(arrs["ff_v_p"], k_c0)
    v_kp = rows(arrs["ff_v_p"], k_c0 - 1)
    v_kcl = rows(arrs["ff_v_p"], k_l0)
    v_kn = rows(arrs["ff_v_p"], k_l0 + 1)

    t = jnp.arange(T, dtype=jnp.int64)
    wend_r = (w0e - base + t * step).astype(jnp.int32)[:, None]
    wstart_r = (w0s - base + t * step).astype(jnp.int32)[:, None]
    counts = (k_c0 + 1 - k_l0).astype(jnp.int32)        # same for every t
    over = ts_kc > wend_r
    under = tsb_kcl < wstart_r
    counts = counts - over.astype(jnp.int32) - under.astype(jnp.int32)
    use1 = ts_kc <= wend_r
    t2 = jnp.where(use1, ts_kc, ts_kp)
    v2 = jnp.where(use1, v_kc, v_kp)
    useb = tsb_kcl >= wstart_r
    t1 = jnp.where(useb, tsb_kcl, tsb_kn)
    v1 = jnp.where(useb, v_kcl, v_kn)
    return _f32_epilogue(func, counts, t1, v1, t2, v2, wstart_r, wend_r,
                         (w0e - w0s).astype(jnp.float32) / 1000.0)


@precision(
    "counter-epilogue-f32", bits=24, rel_ulps=4,
    reason="the extrapolation epilogue narrows the exact f64 boundary "
           "delta and exact i32 time differences to f32 for the "
           "division chain (native TPU rate vs software-emulated "
           "f64); certified within 4 f32 ulps of the f64-reference "
           "formula — XLA lowers the chain per-program, so two "
           "programs (mesh-on vs mesh-off instant queries) may differ "
           "by at most twice that budget (rel_bound(cross_program))")
def _f32_epilogue(func, counts, t1, v1, t2, v2, wstart_r, wend_r, wdur_s):
    """Shared f32 extrapolation epilogue: exact f64 delta, f32 factor."""
    f32 = jnp.float32
    delta = (v2 - v1).astype(f32)                   # exact f64 difference
    sampled = (t2 - t1).astype(f32) / 1000.0        # exact i32 difference
    dstart = (t1 - wstart_r).astype(f32) / 1000.0
    dend = (wend_r - t2).astype(f32) / 1000.0
    counts_f = counts.astype(f32)
    avg_dur = sampled / (counts_f - 1.0)
    if func != "delta":                             # counter zero-clamp
        v1f = v1.astype(f32)
        dzero = jnp.where((delta > 0) & (v1f >= 0),
                          sampled * (v1f / jnp.where(delta == 0, jnp.nan,
                                                     delta)),
                          jnp.inf)
        dstart = jnp.minimum(dstart, dzero)
    thresh = avg_dur * 1.1
    extrap = sampled \
        + jnp.where(dstart < thresh, dstart, avg_dur * 0.5) \
        + jnp.where(dend < thresh, dend, avg_dur * 0.5)
    factor = extrap / sampled
    if func == "rate":
        factor = factor / wdur_s
    out = delta * factor
    return jnp.where(counts >= 2, out, jnp.nan)


_EVAL_T_JIT: Dict[Tuple, object] = {}

# cache inventory (graftlint): the four module-level dispatch tables
# (_EVAL_T_JIT/_EVAL_JIT and their vmapped twins) memoize compiled
# executables keyed purely on (kernel family, func, pow2 shape bucket)
# — a pure function of the request shape, immune to every world event
# by construction, which is exactly what the declaration records.
__cache_registry__ = {
    "tilestore-executables": {"keyed": ("kernel", "func",
                                        "shape-bucket")},
}

# executable-reuse observability: every dispatch-table lookup counts a
# hit (compiled program reused) or a miss (new trace+compile). Shared
# by the scalar and vmapped (micro-batched) dispatch families and
# surfaced in /metrics as the executable-cache counters.
import threading as _threading

_JIT_STATS = {"hits": 0, "misses": 0}
_JIT_STATS_LOCK = _threading.Lock()
__guarded_by__ = {"_JIT_STATS": "_JIT_STATS_LOCK"}


@capacity(
    "tilestore-executable-constants", bytes_per_sample=8.0,
    reason="dispatch-table entries retain the device constants their "
           "closures capture (weight/shape tables lowered into the "
           "compiled program), priced at one f64 (8 B) per packed "
           "slot of the largest captured constant; the executables "
           "themselves are host code, not HBM")
def _jit_lookup(cache: Dict[Tuple, object], key: Tuple, build,
                site: str = "tilestore", cost_args=None) -> object:
    """Dispatch-table lookup with hit/miss accounting; ``build()`` makes
    the jitted callable on a miss. Miss-side builds observe
    ``filodb_kernel_build_seconds`` — a retrace storm (shape-bucket
    churn, cache invalidation) shows up as histogram mass instead of
    unexplained tail latency.

    Compile/cost profiling (obs/devprof.py): with ``cost_args`` (the
    first call's argument tuple) the miss path lowers + compiles the
    executable AOT — the one compile this miss was paying anyway —
    captures XLA ``cost_analysis()`` FLOPs/bytes per executable, and
    caches a :class:`~filodb_tpu.obs.devprof.ProfiledExecutable` whose
    per-call accounting feeds the recompile counters and the
    ``&explain=analyze`` executable attribution."""
    fn = cache.get(key)
    with _JIT_STATS_LOCK:
        _JIT_STATS["hits" if fn is not None else "misses"] += 1
    if fn is None:
        from filodb_tpu.obs import devprof
        from filodb_tpu.obs import metrics as obs_metrics
        from filodb_tpu.obs import trace as obs_trace
        with obs_metrics.timed(
                "filodb_kernel_build_seconds",
                "Wall seconds per evaluator build on a dispatch-table "
                "miss (trace + XLA compile)"), \
                obs_trace.span("kernel-build", site=site):
            fn = devprof.build_profiled(site, key, build,
                                        cost_args=cost_args)
        cache[key] = fn
    return fn


def executable_cache_stats() -> Dict[str, int]:
    """Snapshot of compiled-executable reuse across the tilestore
    dispatch tables (scalar + vmapped families)."""
    with _JIT_STATS_LOCK:
        out = dict(_JIT_STATS)
    out["entries"] = (len(_EVAL_JIT) + len(_EVAL_T_JIT)
                      + len(_EVAL_T_VMAP) + len(_EVAL_VMAP))
    return out


def _slide_eligible(tiles: AlignedTiles, nsteps: int, w0s: int, w0e: int,
                    last_ms: int, step: int):
    """Shared dispatch guard for the slide evaluator AND the Pallas
    group-sum kernel: a REGULAR grid (step % dt == 0) over dense tiles,
    entirely interior (no index clipping: kp = kc-1 >= 0 ... kn =
    kcl+1 <= N-1), with every relative time in int32 ms. Returns
    (st, k_c0, k_l0) or None. Both consumers MUST dispatch off this one
    predicate so they agree on the in-bounds proof."""
    N, dt = tiles.num_slots, tiles.dt_ms
    if nsteps < 2 or not tiles._dense or step % dt != 0:
        return None
    lo_rel = w0s - tiles.base_ms
    hi_rel = last_ms - tiles.base_ms
    if not (_SENT_LO < lo_rel and hi_rel < _SENT_HI
            and N * dt + dt < _SENT_HI):
        return None
    st = step // dt
    k_c0 = int(np.floor((w0e - tiles.base_ms + dt / 2.0) / dt))
    k_l0 = int(np.ceil((w0s - tiles.base_ms - dt / 2.0) / dt))
    span = (nsteps - 1) * st
    if not (st >= 1 and k_c0 >= 1 and k_l0 >= 0
            and k_c0 + span <= N - 1 and k_l0 + 1 + span <= N - 1):
        return None
    return st, k_c0, k_l0


@kernel_contract(
    "counters_t_dispatch", kind="dispatch",
    rel_time_bits=31, span_guard="_slide_eligible",
    notes="transposed counter fast path: slide evaluator when "
          "_slide_eligible proves the regular interior grid, f32-hybrid "
          "when the span fits int31 ms, exact all-f64 otherwise")
def evaluate_counters_t(tiles: AlignedTiles, func: str, steps: np.ndarray,
                        window_ms: int, offset_ms: int = 0) -> jnp.ndarray:
    """rate/increase/delta on the transposed fast path → [T, S].

    Dispatch: the f32-hybrid evaluator (f32 output) when the query grid
    and tile span fit int32 ms relative to the tile base (~24.8 days);
    the exact all-f64 evaluator (f64 output) otherwise."""
    assert func in ("rate", "increase", "delta")
    nsteps = steps.size
    w0e = np.int64(steps[0] - offset_ms)
    w0s = np.int64(w0e - window_ms)
    step = np.int64(steps[1] - steps[0]) if nsteps > 1 else np.int64(1)
    lo_rel = int(w0s) - tiles.base_ms
    hi_rel = int(steps[-1] - offset_ms) - tiles.base_ms
    fits_i32 = (_SENT_LO < lo_rel and hi_rel < _SENT_HI
                and tiles.num_slots * tiles.dt_ms + tiles.dt_ms < _SENT_HI)
    el = _slide_eligible(tiles, nsteps, int(w0s), int(w0e),
                         int(steps[-1] - offset_ms), int(step))
    if el is not None:
        st, _, _ = el
        arrs = _tiles_arrays_slide(tiles, func, st)
        key = ("slide", func, nsteps, st)
        args = (arrs, np.int64(tiles.num_slots),
                np.int64(tiles.base_ms), np.int64(tiles.dt_ms),
                np.int64(w0s), np.int64(w0e), np.int64(step))
        fn = _jit_lookup(_EVAL_T_JIT, key, lambda: jax.jit(
            _functools.partial(_eval_counter_slide, func, nsteps, st)),
            cost_args=args)
        return fn(*args)
    if fits_i32:
        arrs = _tiles_arrays_fast(tiles, func)
        key = ("fast", func, nsteps)
        build = lambda: jax.jit(_functools.partial(
            _eval_counter_fast, func, nsteps))
    else:
        arrs = _tiles_arrays_t(tiles, func)
        key = ("t", func, nsteps)
        build = lambda: jax.jit(_functools.partial(
            _eval_counter_t, func, nsteps))
    args = (arrs, np.int64(tiles.num_slots),
            np.int64(tiles.base_ms), np.int64(tiles.dt_ms),
            np.int64(w0s), np.int64(w0e), np.int64(step))
    fn = _jit_lookup(_EVAL_T_JIT, key, build, cost_args=args)
    return fn(*args)


@kernel_contract(
    "groupsum_dispatch", kind="dispatch",
    vmem_budget=14 << 20,
    rel_time_bits=31, span_guard="_slide_eligible",
    notes="host-side gate for the fused Pallas group-sum kernel: "
          "regular interior grid via _slide_eligible, merged-stream "
          "window/step divisibility, dspan cap, full VMEM re-budget "
          "(accumulators + DMA scratch + onehot + base), Mosaic "
          "compile backstop falls back to the general path")
def groupsum_counters(tiles: AlignedTiles, func: str, steps: np.ndarray,
                      window_ms: int, onehot, offset_ms: int = 0,
                      interpret: bool = False):
    """`sum by (g) (rate/increase/delta(sel[w]))` fused on device via the
    Pallas group-sum kernel -> (sums f32 [T, G], counts f32 [T, G]), or
    None when the preconditions don't hold (caller falls back to
    evaluate_counters_t + host/XLA grouping).

    Preconditions: dense tiles; regular grid with step % dt == 0 fully
    interior to the tile; span fits int32 ms relative to the tile base.
    The kernel pads S to its lane-tile internally via all-zero one-hot
    rows, so any S works."""
    assert func in ("rate", "increase", "delta")
    nsteps = steps.size
    if nsteps < 2:
        return None
    w0e = int(steps[0] - offset_ms)
    w0s = w0e - window_ms
    step = int(steps[1] - steps[0])
    el = _slide_eligible(tiles, nsteps, w0s, w0e,
                         int(steps[-1] - offset_ms), step)
    if el is None:
        return None
    st, k_c0, k_l0 = el
    from filodb_tpu.query import pallas_kernels as pk
    # merged-stream contract: the window must span a whole number of
    # steps so the kc/kl families share a stride-residue plane
    d = k_c0 - k_l0
    if d % st != 0 or not (0 <= d // st <= pk._GS_DSPAN_MAX):
        return None
    dspan = d // st
    if st == 1 and k_l0 < 1:
        return None              # the merged block reads one lead row
    S = len(tiles.keys)
    G = int(np.asarray(onehot).shape[1])
    vch = "cv" if func in ("rate", "increase") else "v"
    if tiles._fixed_channels(vch) is None:
        return None              # non-finite values: exact f64 fallback
    # static jitter-phase elision: when the grid phase clears the
    # tile's max |ts - tick|, the boundary-sample choice is the same
    # for every series and step, and the fallback family is never read
    dt = tiles.dt_ms
    J = tiles.jitter_ms()
    phase_e = (w0e - tiles.base_ms) - k_c0 * dt
    phase_s = k_l0 * dt - (w0s - tiles.base_ms)
    hi_mode = (pk.GS_CUR if phase_e >= J else
               pk.GS_ALT if phase_e < -J else pk.GS_BOTH)
    lo_mode = (pk.GS_CUR if phase_s >= J else
               pk.GS_ALT if phase_s < -J else pk.GS_BOTH)
    # full VMEM budget, not just the accumulators: the pipeline chooser
    # (pk._gs_pipeline) walks the (step-tile width, DMA pipeline depth)
    # frontier — accumulators + nbuf x nstreams x mlen scratch + the
    # onehot/base input blocks — and an oversized query must fall back
    # to the general path HERE, not explode at Mosaic compile time
    nstreams = pk._gs_nstreams(st, hi_mode, lo_mode)
    if pk._gs_pipeline(st, dspan, hi_mode, lo_mode, nsteps, G) is None:
        return None              # no admissible (tt, nbuf) within VMEM
    S_pad = -(-S // pk._GS_SS) * pk._GS_SS
    v_p = tiles.t_perm_fixed_tiled(vch, st)
    base = tiles.t_fixed_base(vch)
    onehot = jnp.asarray(onehot, jnp.float32)
    if S_pad != S:
        onehot = jnp.pad(onehot, ((0, S_pad - S), (0, 0)))
    try:
        return pk.counter_groupsum(
            func, st, dspan, hi_mode, lo_mode, v_p, base, onehot,
            k_l0, w0e - tiles.base_ms, window_ms, step, nsteps,
            interpret=interpret)
    except Exception:
        # backstop for shapes the budget model misses: a Mosaic
        # compile/lowering failure downgrades to the general path
        # instead of killing the query
        import logging
        logging.getLogger(__name__).warning(
            "fused group-sum kernel failed to compile "
            "(T=%d G=%d streams=%d); falling back to the general path",
            nsteps, G, nstreams, exc_info=True)
        return None


import functools as _functools

_EVAL_JIT: Dict[Tuple, object] = {}


def evaluate_aligned(tiles: AlignedTiles, func: str, steps: np.ndarray,
                     window_ms: int, offset_ms: int = 0,
                     func_args: Sequence[float] = ()) -> jnp.ndarray:
    """Evaluate one windowed range function over aligned tiles: [S, T] f64,
    as a single compiled XLA program. Numerics match the oracle (rangefn)
    modulo prefix-sum rounding — the same summation scheme the general
    device path uses."""
    nsteps = steps.size
    w0e = np.int64(steps[0] - offset_ms)
    w0s = np.int64(w0e - window_ms)
    step = np.int64(steps[1] - steps[0]) if nsteps > 1 else np.int64(1)
    arrs = _tiles_arrays(tiles, func)
    args = (arrs, np.int64(tiles.num_slots),
            np.int64(tiles.base_ms), np.int64(tiles.dt_ms),
            np.int64(w0s), np.int64(w0e), np.int64(step))
    fn = _jit_lookup(_EVAL_JIT, (func, nsteps), lambda: jax.jit(
        _functools.partial(_eval_core, func, nsteps)), cost_args=args)
    return fn(*args)


# ---------------------------------------------------------------------------
# Micro-batched (multi-grid) dispatch: vmapped evaluator families
# ---------------------------------------------------------------------------
#
# The query micro-batcher (query/batcher.py) stacks concurrent queries
# that share (tiles, func, nsteps, step, window) but differ in grid
# position (w0s/w0e) — the dashboard-refresh / concurrent-client shape.
# Each family below is the SAME traceable body as its scalar dispatch,
# vmapped over the (w0s, w0e) scalars only, so member i of a batch is
# bit-for-bit the scalar path's output (pinned by test_batcher's parity
# tests): the batch axis adds a leading dim, every op stays row-local.

_EVAL_T_VMAP: Dict[Tuple, object] = {}
_EVAL_VMAP: Dict[Tuple, object] = {}

_GRID_AXES = (None, None, None, None, 0, 0, None)


def _pad_pow2(vals: Sequence[int]) -> np.ndarray:
    """Pad a member-scalar list to a coarse batch-width bucket (2, 8,
    32, 128, ...) by repeating the last member. Coarse x4 buckets keep
    the number of compiled batch widths tiny — an XLA compile costs
    ~100ms while computing a few redundant pad grids costs microseconds,
    so trading pad work for compile-cache hits is the right side of the
    bargain on the serving path."""
    b = 2
    while b < len(vals):
        b <<= 2
    out = list(vals) + [vals[-1]] * (b - len(vals))
    return np.asarray(out, np.int64)


def counters_batch_family(tiles: AlignedTiles, func: str,
                          steps: np.ndarray, window_ms: int,
                          offset_ms: int = 0) -> Optional[Tuple]:
    """Hashable dispatch-family key for one counter query — two queries
    may share a batched dispatch only when their families match (the
    family fixes which compiled evaluator the scalar path would pick,
    so batching never changes the kernel choice)."""
    nsteps = steps.size
    w0e = int(steps[0] - offset_ms)
    w0s = w0e - window_ms
    step = int(steps[1] - steps[0]) if nsteps > 1 else 1
    el = _slide_eligible(tiles, nsteps, w0s, w0e,
                         int(steps[-1] - offset_ms), step)
    if el is not None:
        return ("slide", el[0])
    lo_rel = w0s - tiles.base_ms
    hi_rel = int(steps[-1] - offset_ms) - tiles.base_ms
    fits_i32 = (_SENT_LO < lo_rel and hi_rel < _SENT_HI
                and tiles.num_slots * tiles.dt_ms + tiles.dt_ms < _SENT_HI)
    return ("fast",) if fits_i32 else ("t",)


def evaluate_counters_t_batch(tiles: AlignedTiles, func: str,
                              family: Tuple, nsteps: int, step: int,
                              w0s_list: Sequence[int],
                              w0e_list: Sequence[int]) -> jnp.ndarray:
    """One vmapped dispatch computing B counter grids over shared tiles
    -> device [B_pad, T, S] (callers slice [:len(w0s_list)]). All
    members must share ``family`` (see counters_batch_family)."""
    assert func in ("rate", "increase", "delta")
    w0s_v = jnp.asarray(_pad_pow2(list(w0s_list)))
    w0e_v = jnp.asarray(_pad_pow2(list(w0e_list)))
    b_pad = int(w0s_v.shape[0])
    kind = family[0]
    if kind == "slide":
        st = family[1]
        arrs = _tiles_arrays_slide(tiles, func, st)
        key = ("slide", func, nsteps, st, b_pad)
        build = lambda: jax.jit(jax.vmap(
            _functools.partial(_eval_counter_slide, func, nsteps, st),
            in_axes=_GRID_AXES))
    elif kind == "fast":
        arrs = _tiles_arrays_fast(tiles, func)
        key = ("fast", func, nsteps, b_pad)
        build = lambda: jax.jit(jax.vmap(
            _functools.partial(_eval_counter_fast, func, nsteps),
            in_axes=_GRID_AXES))
    else:
        arrs = _tiles_arrays_t(tiles, func)
        key = ("t", func, nsteps, b_pad)
        build = lambda: jax.jit(jax.vmap(
            _functools.partial(_eval_counter_t, func, nsteps),
            in_axes=_GRID_AXES))
    args = (arrs, np.int64(tiles.num_slots),
            np.int64(tiles.base_ms), np.int64(tiles.dt_ms),
            w0s_v, w0e_v, np.int64(step))
    fn = _jit_lookup(_EVAL_T_VMAP, key, build,
                     site="tilestore-batch", cost_args=args)
    return fn(*args)


def evaluate_aligned_batch(tiles: AlignedTiles, func: str, nsteps: int,
                           step: int, w0s_list: Sequence[int],
                           w0e_list: Sequence[int]) -> jnp.ndarray:
    """One vmapped dispatch computing B aligned grids (non-counter
    families) over shared tiles -> device [B_pad, S, T]."""
    w0s_v = jnp.asarray(_pad_pow2(list(w0s_list)))
    w0e_v = jnp.asarray(_pad_pow2(list(w0e_list)))
    b_pad = int(w0s_v.shape[0])
    arrs = _tiles_arrays(tiles, func)
    args = (arrs, np.int64(tiles.num_slots),
            np.int64(tiles.base_ms), np.int64(tiles.dt_ms),
            w0s_v, w0e_v, np.int64(step))
    fn = _jit_lookup(_EVAL_VMAP, (func, nsteps, b_pad),
                     lambda: jax.jit(jax.vmap(
                         _functools.partial(_eval_core, func, nsteps),
                         in_axes=_GRID_AXES)),
                     site="tilestore-batch", cost_args=args)
    return fn(*args)
