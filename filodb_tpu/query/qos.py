"""Tenant QoS and brownout control: cost-based admission, per-tenant
token-bucket budgets, and priority classes for the micro-batcher.

The reference keeps one noisy tenant from taking down shared serving
with per-tenant guardrails (the ``ratelimit`` cardinality quota tree,
per-query sample limits). This module is the end-to-end overload story
those pieces were missing:

* **Cost estimation before execution** — :func:`estimate_plan_cost`
  prices a parsed plan from its SHAPE (node count, window/step ratio),
  the evaluation grid's step count, and the shard-key cardinality the
  per-shard :class:`~filodb_tpu.core.cardinality.CardinalityTracker`
  prefix tree / tag-index postings record for the plan's leaf filters.
  The estimate need not be right in absolute terms; it must be
  MONOTONE — a strictly heavier query must never price below a lighter
  one (pinned by the golden ordering tests against measured device
  time in tests/test_qos.py).

* **Per-tenant token buckets** — :class:`TenantBudgets` charges each
  admitted query's estimated cost against its tenant's
  :class:`TokenBucket` (tenant = ``X-Filo-Tenant`` header / ``&tenant=``
  param, ``default`` otherwise; by convention the workspace ``_ws_``).
  An over-budget tenant is throttled SELECTIVELY — other tenants'
  queries sail through untouched — and fan-out legs (gRPC Exec, raw
  leaf dispatch, ``dispatch=local`` pushdown) inherit the charge via
  :meth:`TenantBudgets.charge_forced`, so a query's cluster-wide cost
  lands on its tenant no matter where the work runs.

* **Admission control with a bounded wait** — :class:`AdmissionController`
  replaces the HTTP edge's blind ``BoundedSemaphore``: slot waits are
  BOUNDED (``wait_s``), and saturation maps to HTTP 429 +
  ``Retry-After`` (:class:`AdmissionRejected`) instead of a silent hang
  until the client's own timeout — distinct from the 503 deadline path.

* **Priority classes** — interactive (0) > rules/background (1) >
  over-budget best-effort (2). The active class rides a thread-local
  :class:`QosContext` (captured across the device-executor hop like the
  trace context) so the micro-batcher can order its dispatch queue by
  class: a brownout's monster scans never head-of-line block cheap
  interactive queries.

Budgets default OFF (``default_rate == 0`` and no overrides): every
path then short-circuits to the pre-QoS behavior, so a deployment that
never sets a budget knob is byte-identical to the old edge.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from filodb_tpu.lint.locks import guarded_by

DEFAULT_TENANT = "default"
TENANT_HEADER = "X-Filo-Tenant"
PRIORITY_HEADER = "X-Filo-Priority"

# reserved internal tenants: self-telemetry (obs/selfmon.py) and the
# recording-rules engine (filodb_tpu/rules) run at the BACKGROUND
# priority class and charge FORCED like fan-out legs — standing
# background evaluation must never bounce off a drained admission
# bucket, and must never crowd out interactive user queries. Not a
# bypass a user should borrow: forced charges still land on the
# tenant's bucket (driving it into debt), they just never shed.
SELFMON_TENANT = "__selfmon__"
RULES_TENANT = "__rules__"
INTERNAL_TENANTS = frozenset({SELFMON_TENANT, RULES_TENANT})

# priority classes, lower = sooner. Interactive is the default for
# client traffic; rules/background is for standing evaluation and
# maintenance work; best-effort is what an over-budget tenant's
# degraded queries run at.
PRIORITY_INTERACTIVE = 0
PRIORITY_BACKGROUND = 1
PRIORITY_BEST_EFFORT = 2
PRIORITY_NAMES = {PRIORITY_INTERACTIVE: "interactive",
                  PRIORITY_BACKGROUND: "background",
                  PRIORITY_BEST_EFFORT: "best_effort"}
_PRIORITY_BY_NAME = {
    "interactive": PRIORITY_INTERACTIVE,
    "background": PRIORITY_BACKGROUND,
    "rules": PRIORITY_BACKGROUND,
    "best_effort": PRIORITY_BEST_EFFORT,
    "best-effort": PRIORITY_BEST_EFFORT,
}


def parse_priority(raw: Optional[str]) -> int:
    """Priority class from a header/param value; unknown/absent values
    are interactive (never reject a query over a bad priority hint)."""
    if not raw:
        return PRIORITY_INTERACTIVE
    return _PRIORITY_BY_NAME.get(str(raw).strip().lower(),
                                 PRIORITY_INTERACTIVE)


@dataclass
class QosContext:
    """Per-query QoS state riding a thread-local (and hopping threads
    with the batcher closure, like the trace context)."""
    tenant: str = DEFAULT_TENANT
    priority: int = PRIORITY_INTERACTIVE
    # True once the query entered the degrade ladder (over budget /
    # host saturated): executions run best-effort and responses carry
    # the shed warning
    degraded: bool = False
    # True on fan-out legs (gRPC Exec / raw leaf / dispatch=local):
    # the entry node already made the admission decision — legs charge
    # forced and never shed
    forced: bool = False


_state = threading.local()


def current() -> Optional[QosContext]:
    """The thread's active QoS context (None outside a query)."""
    return getattr(_state, "ctx", None)


def current_priority() -> int:
    ctx = current()
    return ctx.priority if ctx is not None else PRIORITY_INTERACTIVE


def capture() -> Optional[QosContext]:
    """Snapshot for cross-thread hops (the batcher's executor closure
    re-installs it with :func:`use`)."""
    return current()


@contextmanager
def activate(ctx: Optional[QosContext]):
    """Install ``ctx`` as the thread's QoS context for the duration."""
    prev = getattr(_state, "ctx", None)
    _state.ctx = ctx
    try:
        yield ctx
    finally:
        _state.ctx = prev


# `use` mirrors obs_trace.use: same name, same re-install semantics
use = activate


# ---------------------------------------------------------------------------
# cost estimation
# ---------------------------------------------------------------------------

@dataclass
class QueryCost:
    """One plan's pre-execution price breakdown. ``total`` is the unit
    charged against the tenant bucket; the parts ride trace tags and
    the slow-query log so an operator can see WHY a query priced high."""
    series: int = 1
    steps: int = 1
    window_factor: float = 1.0
    shape_weight: float = 1.0
    total: float = 1.0


# fallback guess when no cardinality source can price a leaf (cold
# tracker, pure remote dispatch with no metering view): assume a
# mid-size selector rather than 0 — underpricing unknown work is how a
# noisy tenant sneaks past the bucket
_UNKNOWN_SERIES_GUESS = 64


def _leaf_series_estimate(filters: Sequence[object],
                          shards: Sequence[object],
                          metering: Optional[object] = None) -> int:
    """Series-count estimate for one RawSeries leaf: the cardinality
    tracker's count at the longest concrete shard-key prefix the
    filters pin, refined (min) by the tag-index posting upper bound,
    summed over local shards. Remote shard groups carry no tracker —
    the tenant-metering snapshot (cross-shard per-(ws, ns) counts)
    prices them when it knows the prefix."""
    from filodb_tpu.core.cardinality import SHARD_KEY_LABELS
    eq = {f.label: str(f.value) for f in filters
          if getattr(f, "op", "") == "eq"}
    prefix: List[str] = []
    for lbl in SHARD_KEY_LABELS:
        if lbl in eq:
            prefix.append(eq[lbl])
        else:
            break
    # extra equality filters beyond the shard key (instance=..., ...)
    # narrow the match set; damp the estimate per filter. The damping
    # is uniform, so it cannot reorder two shapes that differ only in
    # breadth (the monotonicity contract).
    extra_eq = sum(1 for lbl in eq if lbl not in SHARD_KEY_LABELS)
    total = 0
    found = False
    remote = 0
    for s in shards:
        tracker = getattr(s, "card_tracker", None)
        if tracker is None:
            if hasattr(s, "fetch_raw"):
                remote += 1
            continue
        n = tracker.series_count(prefix)
        if n is None:
            continue
        idx = getattr(s, "index", None)
        if idx is not None and hasattr(idx, "posting_upper_bound"):
            ub = idx.posting_upper_bound(filters)
            if ub is not None:
                n = min(n, ub)
        total += n
        found = True
    if remote:
        # fan-out legs: the gossip-fed metering snapshot prices the
        # whole tenant prefix across the cluster when it can
        counted = None
        if metering is not None and prefix:
            counted = metering.count_for(tuple(prefix))
        if counted is not None:
            total += int(counted)
            found = True
        else:
            total += _UNKNOWN_SERIES_GUESS * remote
            found = True
    if not found:
        return _UNKNOWN_SERIES_GUESS
    return max(1, total >> (2 * extra_eq))


def estimate_plan_cost(plan, shards: Sequence[object],
                       metering: Optional[object] = None) -> QueryCost:
    """Pre-execution price of a parsed LogicalPlan over ``shards``.

    cost = series x steps x (1 + window/step) x shape_weight

    * series — cardinality-tracker / tag-index estimate per leaf
      selector (see :func:`_leaf_series_estimate`), summed over leaves;
    * steps — the evaluation grid's step count;
    * window/step — how many overlapping windows touch each sample
      (rate(x[5m]) at 10s steps re-reads each sample ~30x);
    * shape_weight — 1 + 0.15 per plan node (joins, aggregations,
      function applications each add passes over the grid).
    """
    from filodb_tpu.query.planner import (plan_range, walk_leaf_filters,
                                          walk_plan_tree)
    rng = plan_range(plan)
    if rng is not None:
        start, step, end, window, _lookback = rng
        if step > 0:
            steps = (end - start) // step + 1
            window_factor = 1.0 + (float(window) / float(step)
                                   if window and window < (1 << 61)
                                   else 0.0)
        else:
            steps = 1
            window_factor = 1.0
    else:
        steps, window_factor = 1, 1.0
    nodes = [0]
    walk_plan_tree(plan, lambda p: nodes.__setitem__(0, nodes[0] + 1))
    shape_weight = 1.0 + 0.15 * max(0, nodes[0] - 1)
    leaves = walk_leaf_filters(plan)
    series = sum(_leaf_series_estimate(f, shards, metering)
                 for f in leaves) if leaves else 1
    total = max(1.0, float(series)) * max(1, int(steps)) \
        * window_factor * shape_weight
    return QueryCost(series=int(series), steps=int(steps),
                     window_factor=round(window_factor, 3),
                     shape_weight=round(shape_weight, 3),
                     total=float(total))


def estimate_leaf_cost(filters: Sequence[object],
                       shards: Sequence[object],
                       start_ms: int, end_ms: int) -> float:
    """Price of a raw leaf-dispatch read (no plan tree to walk):
    series estimate x span, with one cost unit per series-minute —
    the same order of magnitude a one-step-per-minute plan would
    charge, so leaf legs and whole-query hops price comparably."""
    series = _leaf_series_estimate(filters, shards)
    span_min = max(1.0, (int(end_ms) - int(start_ms)) / 60_000.0)
    return float(series) * span_min


# ---------------------------------------------------------------------------
# token buckets
# ---------------------------------------------------------------------------

@guarded_by("_lock", "_tokens", "_last_s", "charged_total", "admitted",
            "throttled", "forced_charges")
class TokenBucket:
    """Cost-unit token bucket: refills at ``rate``/s up to ``burst``.

    ``try_charge`` is the admission check (atomic check-and-debit: no
    lost or double charges under concurrent callers — pinned by the
    concurrent-accounting test). ``charge_forced`` debits
    unconditionally — fan-out legs inherit the entry node's admission
    decision — and may drive the balance negative, throttling the
    tenant's NEXT queries; debt is floored at ``-3 x burst`` so one
    mispriced monster cannot lock a tenant out for unbounded time.

    A query priced above ``burst`` can never charge cleanly: it is
    permanently a degrade-ladder query for this tenant. That is the
    documented meaning of burst — the largest clean-admission query."""

    def __init__(self, rate: float, burst: float,
                 clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst) if burst else max(1.0, 10.0 * rate)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._last_s = clock()
        self.charged_total = 0.0
        self.admitted = 0
        self.throttled = 0
        self.forced_charges = 0

    def _refill(self) -> None:
        """Advance the bucket to now. MUST be called with ``_lock``
        held (every public method does; the accesses below are inside
        the callers' critical sections)."""
        now = self._clock()
        dt = now - self._last_s  # graftlint: disable=lock-guarded-access (called under _lock by every public method)
        if dt > 0:
            self._tokens = min(self.burst,  # graftlint: disable=lock-guarded-access (called under _lock by every public method)
                               self._tokens + dt * self.rate)  # graftlint: disable=lock-guarded-access (called under _lock by every public method)
            self._last_s = now  # graftlint: disable=lock-guarded-access (called under _lock by every public method)

    def try_charge(self, cost: float) -> bool:
        with self._lock:
            self._refill()
            if cost <= self._tokens:
                self._tokens -= cost
                self.charged_total += cost
                self.admitted += 1
                return True
            self.throttled += 1
            return False

    def note_throttled(self) -> None:
        """Count a throttle decided WITHOUT pricing (the drained-bucket
        fast path skips the plan walk entirely)."""
        with self._lock:
            self.throttled += 1

    def charge_forced(self, cost: float) -> None:
        with self._lock:
            self._refill()
            self._tokens = max(-3.0 * self.burst, self._tokens - cost)
            self.charged_total += cost
            self.forced_charges += 1

    def refund(self, cost: float) -> None:
        with self._lock:
            self._refill()
            self._tokens = min(self.burst, self._tokens + cost)

    def retry_after_s(self, cost: float) -> float:
        """Seconds until ``cost`` (capped at burst) could charge."""
        with self._lock:
            self._refill()
            needed = min(float(cost), self.burst) - self._tokens
        if needed <= 0:
            return 0.0
        if self.rate <= 0:
            return 60.0
        return needed / self.rate

    def remaining(self) -> float:
        with self._lock:
            self._refill()
            return self._tokens

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            self._refill()
            return {"remaining": round(self._tokens, 3),
                    "rate": self.rate, "burst": self.burst,
                    "charged_total": round(self.charged_total, 3),
                    "admitted": self.admitted,
                    "throttled": self.throttled,
                    "forced_charges": self.forced_charges}


@guarded_by("_lock", "_buckets", "degraded", "rejected")
class TenantBudgets:
    """Tenant -> :class:`TokenBucket`, created lazily from the default
    rate/burst or a per-tenant override.

    ``enabled`` is False when no budget is configured anywhere — every
    charge path then short-circuits (the pre-QoS behavior). Lock
    order: ``TenantBudgets._lock`` (map) strictly outside
    ``TokenBucket._lock`` (per-bucket counters)."""

    def __init__(self, default_rate: float = 0.0,
                 default_burst: float = 0.0,
                 overrides: Optional[Dict[str, object]] = None,
                 clock=time.monotonic):
        self.default_rate = float(default_rate or 0.0)
        self.default_burst = float(default_burst or 0.0)
        # tenant -> rate | [rate, burst]
        self.overrides = dict(overrides or {})
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        # degrade-ladder outcomes by rung name (stale/downsample/
        # partial) + hard rejections, across all tenants per tenant
        self.degraded: Dict[Tuple[str, str], int] = {}
        self.rejected: Dict[str, int] = {}

    @property
    def enabled(self) -> bool:
        return self.default_rate > 0 or bool(self.overrides)

    def _rate_burst(self, tenant: str) -> Tuple[float, float]:
        ov = self.overrides.get(tenant)
        if ov is None:
            return self.default_rate, self.default_burst
        if isinstance(ov, (list, tuple)):
            rate = float(ov[0])
            burst = float(ov[1]) if len(ov) > 1 else 0.0
            return rate, burst
        return float(ov), 0.0

    def bucket(self, tenant: str) -> Optional[TokenBucket]:
        """The tenant's bucket, or None when it is unbudgeted (rate 0
        and no override — unlimited)."""
        if not self.enabled:
            return None
        tenant = tenant or DEFAULT_TENANT
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                rate, burst = self._rate_burst(tenant)
                if rate <= 0:
                    return None         # explicitly unlimited tenant
                b = TokenBucket(rate, burst, clock=self._clock)
                self._buckets[tenant] = b
        return b

    def try_charge(self, tenant: str, cost: float) -> bool:
        b = self.bucket(tenant)
        if b is None:
            return True
        return b.try_charge(cost)

    def charge_forced(self, tenant: str, cost: float) -> None:
        b = self.bucket(tenant)
        if b is not None:
            b.charge_forced(cost)

    def retry_after_s(self, tenant: str, cost: float) -> float:
        b = self.bucket(tenant)
        if b is None:
            return 0.0
        return b.retry_after_s(cost)

    def refund(self, tenant: str, cost: float) -> None:
        """Return a charge whose work never happened (a degrade-ladder
        rung that failed mid-execution): the tenant must not pay for an
        answer it never received."""
        b = self.bucket(tenant)
        if b is not None:
            b.refund(cost)

    def record_degraded(self, tenant: str, rung: str) -> None:
        with self._lock:
            k = (tenant, rung)
            self.degraded[k] = self.degraded.get(k, 0) + 1

    def record_rejected(self, tenant: str) -> None:
        with self._lock:
            self.rejected[tenant] = self.rejected.get(tenant, 0) + 1

    def snapshot(self) -> Dict[str, Dict]:
        """Per-tenant budget state for /metrics (bucket counters +
        degrade/reject outcomes)."""
        with self._lock:
            buckets = dict(self._buckets)
            degraded = dict(self.degraded)
            rejected = dict(self.rejected)
        out: Dict[str, Dict] = {}
        for tenant, b in buckets.items():
            out[tenant] = b.snapshot()
        for (tenant, rung), n in degraded.items():
            out.setdefault(tenant, {}).setdefault(
                "degraded", {})[rung] = n
        for tenant, n in rejected.items():
            out.setdefault(tenant, {})["rejected"] = n
        return out


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

class AdmissionRejected(Exception):
    """Admission said no and no degraded answer exists: HTTP 429 with
    ``Retry-After`` (never the 503 deadline shape — a rejected query
    was never executed).

    ``retry_after_s=None`` means NO amount of waiting can help (a
    never-admittable query: its cost exceeds the tenant's burst at
    every degraded resolution) — the edge then omits the Retry-After
    header instead of emitting a misleading ``Retry-After: 1``, and
    the detail string says what would actually admit."""

    def __init__(self, detail: str,
                 retry_after_s: Optional[float] = 1.0,
                 tenant: str = DEFAULT_TENANT, reason: str = ""):
        super().__init__(detail)
        self.retry_after_s = None if retry_after_s is None \
            else max(0.0, float(retry_after_s))
        self.tenant = tenant
        self.reason = reason or "throttled"


@guarded_by("_lock", "inflight", "wait_timeouts", "slot_rejections")
class AdmissionController:
    """The HTTP edge's query gate, tenant-aware.

    Host concurrency stays a global bound (``max_inflight`` slots; a
    supervisor deployment splits the host total across workers exactly
    like before), but the wait is BOUNDED: a query that cannot get a
    slot within ``wait_s`` raises :class:`AdmissionRejected` (429 +
    Retry-After) instead of hanging on the semaphore until the client's
    own timeout. Per-tenant budget decisions live in ``budgets``; the
    HTTP layer runs the degrade ladder between the two."""

    def __init__(self, max_inflight: int = 0, wait_s: float = 5.0,
                 budgets: Optional[TenantBudgets] = None):
        self.max_inflight = max(0, int(max_inflight or 0))
        self.wait_s = float(wait_s)
        self.budgets = budgets if budgets is not None else TenantBudgets()
        self._sem = threading.BoundedSemaphore(self.max_inflight) \
            if self.max_inflight else None
        self._lock = threading.Lock()
        self.inflight = 0
        self.wait_timeouts = 0
        self.slot_rejections = 0

    @property
    def gated(self) -> bool:
        return self._sem is not None

    def try_acquire(self, wait_s: Optional[float] = None) -> bool:
        """Bounded slot acquire; True when admitted (or ungated)."""
        if self._sem is None:
            return True
        ok = self._sem.acquire(timeout=self.wait_s
                               if wait_s is None else float(wait_s))
        if ok:
            with self._lock:
                self.inflight += 1
        else:
            with self._lock:
                self.wait_timeouts += 1
        return ok

    def release(self) -> None:
        if self._sem is None:
            return
        with self._lock:
            self.inflight -= 1
        self._sem.release()

    @contextmanager
    def slot(self, tenant: str = DEFAULT_TENANT):
        """Bounded-wait admission slot; raises AdmissionRejected on
        saturation (the caller may still serve the stale-cache rung —
        that path reads memory, not a slot)."""
        if not self.try_acquire():
            with self._lock:
                self.slot_rejections += 1
            raise AdmissionRejected(
                f"host saturated: no admission slot freed within "
                f"{self.wait_s:.1f}s", retry_after_s=self.wait_s,
                tenant=tenant, reason="saturated")
        try:
            yield self
        finally:
            self.release()

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {"max_inflight": self.max_inflight,
                    "inflight": self.inflight,
                    "wait_s": self.wait_s,
                    "wait_timeouts": self.wait_timeouts,
                    "slot_rejections": self.slot_rejections}


# what a stale-cache serve charges per served matrix cell, relative to
# the ~1 cost unit a computed step cell prices at: no selection, no
# decode, no device eval — just encode. Without this the stale rung
# would be free and an over-budget tenant could hammer it into a
# GIL-load vector; with it, the budget bounds TOTAL work done for the
# tenant, degraded serving included.
STALE_COST_FACTOR = 0.1


def stale_serve_cost(num_series: int, num_steps: int) -> float:
    return STALE_COST_FACTOR * max(1, num_series) * max(1, num_steps)


def coarsen_step_s(start_s: int, step_s: int, end_s: int,
                   max_steps: int) -> int:
    """Brownout rung: the smallest power-of-two multiple of ``step_s``
    that brings the grid to at most ``max_steps`` evaluation steps.
    Power-of-two multiples keep the bucketed executable-shape set tiny
    (the same reasoning as the results cache's pow2 span widening).
    Returns ``step_s`` unchanged when the grid is already small."""
    if step_s <= 0 or max_steps <= 0:
        return step_s
    n = (end_s - start_s) // step_s + 1
    mult = 1
    while n > max_steps:
        mult <<= 1
        n = (end_s - start_s) // (step_s * mult) + 1
    return step_s * mult
