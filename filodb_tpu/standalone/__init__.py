"""Standalone server (reference: standalone/FiloServer.scala:112,
NewFiloServerMain.scala:21) and the process-sharded serving supervisor.

Imports are lazy (PEP 562): the supervisor process deliberately never
imports the query/engine stack (numpy/jax) — it only forks, monitors,
and aggregates workers — so pulling :class:`Supervisor` must not drag
:class:`FiloServer`'s dependency tree in.
"""


def __getattr__(name):
    if name == "FiloServer":
        from filodb_tpu.standalone.server import FiloServer
        return FiloServer
    if name == "Supervisor":
        from filodb_tpu.standalone.supervisor import Supervisor
        return Supervisor
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["FiloServer", "Supervisor"]
