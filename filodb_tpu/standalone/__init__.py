"""Standalone server (reference: standalone/FiloServer.scala:112,
NewFiloServerMain.scala:21)."""

from filodb_tpu.standalone.server import FiloServer

__all__ = ["FiloServer"]
