"""Local control plane for the process-sharded serving tier.

One supervisor process owns N query-worker processes on a host
(``standalone/supervisor.py``). Worker caches are per-process (plan,
executable, results), so cache-coherence events that used to be a
single in-process subscriber hop — ShardMapper topology transitions,
schema invalidations, ingest-watermark/backfill gossip — need a local
plane to reach every sibling interpreter. That plane is this bus: a
loopback JSON-lines hub. Each worker holds one connection to the
supervisor; an event published by any worker (or by the supervisor
itself) is fanned out to every OTHER worker, which applies it to its
local mapper/caches.

Why not rely on the existing health-body gossip alone? The failure
detector polls at ``failure-detect-interval-s`` (default 0.5s) — a
topology flip would leave sibling caches serving extents keyed on the
old world for up to a full poll. The bus delivers the invalidation in
the same millisecond the transition commits, host-locally, with the
detector gossip remaining the (cross-host) backstop.

Event shapes (one JSON object per line):

  {"type": "hello", "worker": 0, "node": "node0"}      worker handshake
  {"type": "topology", "origin": "node0", "shard": 3,
   "status": "active", "node": "node1", "epoch": 7}    mapper transition
  {"type": "schema", "origin": "node0", "reason": "…"} plan/results drop
  {"type": "watermarks", "origin": "node0",
   "watermarks": {...}, "backfill_epochs": {...},
   "topo_epoch": 7}                                    freshness gossip
  {"type": "worker-exit", "node": "node1"}             supervisor hint
  {"type": "worker-up", "node": "node1"}               supervisor hint

The protocol is deliberately at-most-once / fire-and-forget: every
event is an *idempotent hint* (invalidate, update a sink) and the
detector's periodic gossip re-converges anything a dropped connection
missed.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.lint.threads import thread_root


def _send_line(sock: socket.socket, lock: threading.Lock,
               event: Dict) -> bool:
    """One JSON line onto a connection; False on any transport error
    (the caller drops/reconnects — events are idempotent hints)."""
    data = (json.dumps(event, separators=(",", ":")) + "\n").encode()
    try:
        with lock:
            sock.sendall(data)
        return True
    except OSError:
        return False


@guarded_by("_lock", "_conns", "events_seen", "topo_epochs")
class SupervisorBus:
    """The hub: accepts one connection per worker, fans every received
    event out to all OTHER workers, and lets the supervisor broadcast
    its own events (worker lifecycle hints, operator-initiated schema
    invalidations)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.create_server((host, port), backlog=64)
        self.port = self._srv.getsockname()[1]
        self._lock = threading.Lock()
        # conn id -> (sock, send lock, worker id or None)
        self._conns: Dict[int, tuple] = {}
        self._next_id = 0
        self._closed = threading.Event()
        self.events_seen = 0
        # supervisor-side view fed by worker events (observability):
        # last topology epoch each worker reported
        self.topo_epochs: Dict[str, int] = {}
        self._accept_thread = threading.Thread(
            target=self._accept_run, daemon=True, name="bus-accept")

    def start(self) -> "SupervisorBus":
        self._accept_thread.start()
        return self

    @thread_root("bus-accept")
    def _accept_run(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return              # closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                cid = self._next_id
                self._next_id += 1
                self._conns[cid] = (conn, threading.Lock(), None)
            threading.Thread(target=self._reader_run, args=(cid, conn),
                             daemon=True,
                             name=f"bus-reader-{cid}").start()

    @thread_root("bus-reader")
    def _reader_run(self, cid: int, conn: socket.socket) -> None:
        try:
            f = conn.makefile("rb")
            for raw in f:
                try:
                    ev = json.loads(raw)
                except ValueError:
                    continue
                with self._lock:
                    self.events_seen += 1
                if ev.get("type") == "hello":
                    with self._lock:
                        sock, lk, _ = self._conns[cid]
                        self._conns[cid] = (sock, lk, ev.get("worker"))
                    continue
                if ev.get("type") == "watermarks" \
                        and ev.get("origin"):
                    with self._lock:
                        self.topo_epochs[str(ev["origin"])] = \
                            int(ev.get("topo_epoch") or 0)
                self._fanout(ev, exclude=cid)
        except OSError:
            pass
        finally:
            with self._lock:
                self._conns.pop(cid, None)
            try:
                conn.close()
            except OSError:
                pass

    def _fanout(self, event: Dict, exclude: Optional[int] = None
                ) -> None:
        with self._lock:
            targets = [(cid, sock, lk) for cid, (sock, lk, _w)
                       in self._conns.items() if cid != exclude]
        for cid, sock, lk in targets:
            if not _send_line(sock, lk, event):
                with self._lock:
                    self._conns.pop(cid, None)

    def broadcast(self, event: Dict) -> None:
        """Supervisor-originated event to every connected worker."""
        self._fanout(event, exclude=None)

    def connected_workers(self) -> List:
        with self._lock:
            return sorted(w for _s, _l, w in self._conns.values()
                          if w is not None)

    def stop(self) -> None:
        self._closed.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for sock, _lk, _w in conns:
            try:
                sock.close()
            except OSError:
                pass


@guarded_by("_lock", "_sock", "published", "applied", "reconnects")
class BusClient:
    """A worker's end of the control plane: one loopback connection to
    the supervisor's hub, a reader loop applying inbound events through
    registered handlers, and ``publish()`` for local events. Reconnects
    with backoff — the bus is a latency optimization over detector
    gossip, so a dead supervisor degrades coherence latency, never
    correctness."""

    def __init__(self, port: int, worker_id: int, node_id: str,
                 host: str = "127.0.0.1"):
        self.host = host
        self.port = int(port)
        self.worker_id = int(worker_id)
        self.node_id = node_id
        self._handlers: Dict[str, Callable[[Dict], None]] = {}
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self.published = 0
        self.applied = 0
        self.reconnects = 0
        # reentrancy guard: events APPLIED from the bus may trigger the
        # same local subscribers that normally PUBLISH to the bus (a
        # mapper transition applied from a sibling fires this worker's
        # mapper subscriber); per-thread, so concurrent local
        # transitions on other threads still publish
        self._applying = threading.local()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"bus-client-{worker_id}")

    # -- wiring -----------------------------------------------------------
    def on(self, event_type: str,
           handler: Callable[[Dict], None]) -> "BusClient":
        self._handlers[event_type] = handler
        return self

    def start(self) -> "BusClient":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            sock = self._sock
            self._sock = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._thread.join(timeout=5)

    @property
    def applying(self) -> bool:
        """True on the reader thread while a bus event is being applied
        (publishers consult this to break the apply→republish loop)."""
        return bool(getattr(self._applying, "flag", False))

    # -- outbound ---------------------------------------------------------
    def publish(self, event: Dict) -> None:
        """Fire-and-forget: a transport failure just drops the event
        (detector gossip re-converges) and lets the reader loop
        reconnect."""
        if self.applying:
            return          # this event originated from the bus itself
        event.setdefault("origin", self.node_id)
        with self._lock:
            sock = self._sock
        if sock is None:
            return
        if _send_line(sock, self._send_lock, event):
            with self._lock:
                self.published += 1
        else:
            self._drop_sock(sock)

    def _drop_sock(self, sock) -> None:
        with self._lock:
            if self._sock is sock:
                self._sock = None
        try:
            sock.close()
        except OSError:
            pass

    # -- reader loop ------------------------------------------------------
    @thread_root("bus-client")
    def _run(self) -> None:
        backoff = 0.05
        while not self._stop.is_set():
            try:
                sock = socket.create_connection(
                    (self.host, self.port), timeout=5)
            except OSError:
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 2.0)
                continue
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._sock = sock
                self.reconnects += 1
            _send_line(sock, self._send_lock,
                       {"type": "hello", "worker": self.worker_id,
                        "node": self.node_id})
            backoff = 0.05
            try:
                f = sock.makefile("rb")
                for raw in f:
                    if self._stop.is_set():
                        break
                    try:
                        ev = json.loads(raw)
                    except ValueError:
                        continue
                    self._apply(ev)
            except OSError:
                pass
            self._drop_sock(sock)

    def _apply(self, ev: Dict) -> None:
        handler = self._handlers.get(str(ev.get("type")))
        if handler is None:
            return
        self._applying.flag = True
        try:
            handler(ev)
            with self._lock:
                self.applied += 1
        except Exception:   # noqa: BLE001 — a bad event must not kill
            pass            # the reader loop; events are hints
        finally:
            self._applying.flag = False

    @property
    def connected(self) -> bool:
        with self._lock:
            return self._sock is not None

    def metrics_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"published": self.published,
                    "applied": self.applied,
                    # first successful connect counts in reconnects;
                    # report re-dials only
                    "reconnects": max(0, self.reconnects - 1),
                    "connected": 1 if self._sock is not None else 0}


def wait_connected(client: BusClient, timeout_s: float = 5.0) -> bool:
    """Test/startup helper: block until the client's first connect (or
    timeout). The bus stays best-effort afterwards."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if client.connected:
            return True
        time.sleep(0.01)
    return client.connected
