"""Process-sharded serving supervisor: N query-worker processes, one
public port.

PR 3's serving fast path plateaus at ~2x baseline qps because one
Python interpreter owns parse/plan/encode for every connection (GIL).
This is the FiloDB coordinator/standalone split (PAPER.md layer 6)
done process-native: instead of actor-per-shard coordinators inside
one JVM, the supervisor forks N OS processes, each a full standalone
node owning ``shards_for_ordinal(i, N)`` — the ordinal-ownership model
``parallel/cluster.py`` already describes — with PRIVATE plan /
executable / results caches, its own micro-batcher and device
executor, and its own ``ThreadingHTTPServer`` loop.

The pieces:

* **Accept edge** — every worker binds the public port with
  SO_REUSEPORT (the kernel balances connections across worker
  processes); where the platform lacks SO_REUSEPORT the supervisor
  binds ONCE and passes the listening fd to each worker
  (``accept-fd`` + ``pass_fds``), and all workers accept on the shared
  socket. Each worker additionally serves a private port — the peer /
  control plane, where sibling leaf-dispatch, health polling, and the
  supervisor's own probes land deterministically.

* **Control plane** — a loopback JSON-lines bus
  (``standalone/bus.py``). Topology transitions, schema
  invalidations, and watermark/backfill gossip fan out to every
  sibling at sub-millisecond latency, keeping per-process caches
  coherent with membership; the failure-detector health gossip remains
  the backstop. The supervisor broadcasts worker lifecycle hints
  (``worker-exit`` on waitpid — ground truth, no probe needed).

* **Supervision** — the monitor thread reaps crashed workers
  (kill -9 included) and respawns them with the identical config:
  same ordinal, same ports, so sibling routing rides its retry budget
  through the restart window instead of rewiring. Hung workers
  (alive but failing private-port health checks) are killed and
  respawned the same way.

* **Aggregation** — ``/metrics`` merges every worker's exposition with
  a ``worker`` label injected (per-worker batcher occupancy, cache hit
  ratios, qps side by side); ``/debug/traces``, ``/debug/queries``,
  ``/debug/slow_queries``, and ``/debug/threads`` concatenate worker
  payloads tagged by worker. Workers stay individually scrapeable on
  their private ports.

* **Shutdown / rolling restart** — graceful stop drains each worker
  through the PR 6 membership protocol (``POST /admin/drain`` walks
  its shards through make-before-break handoff to the surviving
  siblings) before SIGTERM; ``POST /admin/restart?worker=k&graceful=
  true`` does the same for one worker, whose rejoin defers shards and
  receives them back through the same protocol.

Admission control stays GLOBAL: the configured
``max-inflight-queries`` is split across workers (worker ``i`` gets
``total//N`` plus one of the remainder slots), so a supervisor
deployment admits the same aggregate in-flight work as the
single-process edge it replaces — not N× it. ``results-cache-mb`` is
split the same way, keeping the host's cache byte budget constant.

This module must stay light: it imports neither numpy nor jax — a
supervisor is a process manager plus a text-format aggregator.
"""

from __future__ import annotations

import argparse
import json
import os
import select
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

from filodb_tpu.lint.locks import guarded_by
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.obs.metrics import (ExpositionBuilder, merge_expositions,
                                    parse_exposition)
from filodb_tpu.standalone.bus import SupervisorBus

SUPERVISOR_DEFAULTS = {
    # worker fleet size; 0 = one worker per core
    "serving-workers": 0,
    # the aggregate admin/metrics edge (0 = ephemeral)
    "supervisor-port": 0,
    # monitor cadence + hung-worker threshold: a worker failing this
    # many consecutive private-port health probes is killed + respawned
    "monitor-interval-s": 0.15,
    "health-check-interval-s": 1.0,
    "health-fail-threshold": 5,
    # min seconds between respawns of one worker (crash-loop brake)
    "restart-backoff-s": 1.0,
    "worker-startup-timeout-s": 180.0,
}

# keys the supervisor consumes itself and must not leak into workers
_SUPERVISOR_ONLY = tuple(SUPERVISOR_DEFAULTS) + ("run-dir",)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def split_quota(total: int, n: int) -> List[int]:
    """Split a global admission budget across ``n`` workers: worker i
    gets ``total//n`` plus one remainder slot. ``sum == total`` always
    holds when ``total >= n``; a budget smaller than the fleet is
    raised to one slot per worker (a zero-quota worker could never
    answer a query), which is the documented lower bound."""
    total = int(total)
    n = max(1, int(n))
    if total <= 0:
        return [0] * n          # 0 = admission control off
    if total < n:
        return [1] * n
    base, rem = divmod(total, n)
    return [base + (1 if i < rem else 0) for i in range(n)]


def worker_config(base: Dict, ordinal: int, num_workers: int,
                  private_ports: List[int], public_port: int,
                  bus_port: int, accept_fd: Optional[int] = None
                  ) -> Dict:
    """Derive worker ``ordinal``'s standalone-server config from the
    supervisor's base config. Each worker is a full multi-node
    cluster member: ordinal shard ownership, the sibling private
    ports as its peer map, a share of the global admission and
    results-cache budgets, and the shared public accept edge."""
    cfg = {k: v for k, v in base.items() if k not in _SUPERVISOR_ONLY}
    cfg["num-nodes"] = num_workers
    cfg["node-ordinal"] = ordinal
    cfg["port"] = private_ports[ordinal]
    cfg["peers"] = {f"node{i}": f"http://127.0.0.1:{p}"
                    for i, p in enumerate(private_ports)}
    cfg["worker-id"] = ordinal
    cfg["bus-port"] = bus_port
    if accept_fd is not None:
        cfg["accept-fd"] = accept_fd
    else:
        cfg["accept-port"] = public_port
    # ONE producer edge per host: the gateway publishes to EVERY
    # shard's stream (two gateways on one log would interleave), so
    # only worker 0 gets it; it follows worker 0 through restarts
    if ordinal != 0:
        cfg["gateway-port"] = None
    quotas = split_quota(int(base.get("max-inflight-queries", 4) or 0),
                         num_workers)
    cfg["max-inflight-queries"] = quotas[ordinal]
    cache_mb = float(base.get("results-cache-mb", 64) or 0)
    cfg["results-cache-mb"] = cache_mb / num_workers
    # tenant QoS budgets are HOST bounds like admission: each worker
    # gets 1/N of every refill rate and bucket depth, so an N-worker
    # fleet charges the same aggregate budget per tenant as the
    # single-process edge it replaces — not N x it. (Rates are floats;
    # an even split loses nothing, unlike the slot split above.)
    if base.get("qos-tenant-rate"):
        cfg["qos-tenant-rate"] = \
            float(base["qos-tenant-rate"]) / num_workers
    if base.get("qos-tenant-burst"):
        cfg["qos-tenant-burst"] = \
            float(base["qos-tenant-burst"]) / num_workers
    overrides = dict(base.get("qos-tenant-overrides") or {})
    if overrides:
        split_ov = {}
        for tenant, ov in overrides.items():
            if isinstance(ov, (list, tuple)):
                split_ov[tenant] = [float(v) / num_workers for v in ov]
            else:
                split_ov[tenant] = float(ov) / num_workers
        cfg["qos-tenant-overrides"] = split_ov
    return cfg


# tenant families summed host-wide on the supervisor's /metrics: the
# per-worker samples already flow through merge_expositions with a
# worker label, but a tenant's shards (and its budget split) spread
# ACROSS workers — the host-level sum is what an operator alerts on.
# Gauges sum correctly here because each is an amount (series counts,
# remaining budget units), not a ratio.
_TENANT_SUM_FAMILIES = (
    "filodb_tenant_time_series_total",
    "filodb_tenant_time_series_active",
    "filodb_tenant_budget_remaining",
    "filodb_tenant_budget_rate",
    "filodb_tenant_cost_charged_total",
    "filodb_tenant_admitted_total",
    "filodb_tenant_throttled_total",
    "filodb_tenant_forced_charges_total",
    "filodb_tenant_degraded_total",
    "filodb_tenant_rejected_total",
)


def aggregate_tenant_families(by_worker: Dict[str, str]) -> str:
    """Host-level per-tenant rollup of the workers' tenant cardinality
    and budget families: same label sets, values summed across the
    fleet, re-emitted as ``filodb_host_tenant_*`` (the per-worker view
    keeps its ``worker`` label via merge_expositions; this is the
    one-series-per-tenant view dashboards and alerts want)."""
    sums: Dict[tuple, float] = {}
    mtypes: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for body in by_worker.values():
        for fam, mtype, name, labels, value in parse_exposition(
                body, help_sink=helps):
            if fam not in _TENANT_SUM_FAMILIES or name != fam:
                continue
            try:
                v = float(value)
            except ValueError:
                continue
            if mtype:       # keep the workers' declared type (the
                mtypes[fam] = mtype  # cardinality gauges end in _total)
            key = (fam, tuple(sorted(labels.items())))
            sums[key] = sums.get(key, 0.0) + v
    if not sums:
        return ""
    b = ExpositionBuilder()
    for (fam, labels) in sorted(sums, key=str):
        host_fam = fam.replace("filodb_tenant_", "filodb_host_tenant_")
        v = sums[(fam, labels)]
        b.sample(host_fam, dict(labels),
                 int(v) if float(v).is_integer() else round(v, 3),
                 mtype=mtypes.get(
                     fam, "counter" if fam.endswith("_total")
                     else "gauge"),
                 help="Host-wide sum of %s across workers" % fam)
    return b.render()


class _Worker:
    """One supervised worker process (bookkeeping only — mutation is
    guarded by the supervisor's lock)."""

    def __init__(self, ordinal: int, cfg_path: str, port: int):
        self.ordinal = ordinal
        self.node_id = f"node{ordinal}"
        self.cfg_path = cfg_path
        self.port = port            # private (peer/control) port
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self.health_misses = 0
        self.ready = False
        self.last_spawn = 0.0


@guarded_by("_lock", "_workers", "_stopping")
class Supervisor:
    """Fork, monitor, and aggregate N standalone query workers."""

    def __init__(self, config: Optional[Dict] = None):
        self.config = {**SUPERVISOR_DEFAULTS, **(config or {})}
        n = int(self.config.get("serving-workers", 0) or 0)
        if n <= 0:
            n = os.cpu_count() or 1
        self.num_workers = n
        self._lock = threading.Lock()
        self._workers: Dict[int, _Worker] = {}
        self._stopping = False
        self._stop_evt = threading.Event()
        self.public_port = int(self.config.get("port", 0) or 0) \
            or _free_port()
        self.bus: Optional[SupervisorBus] = None
        self._accept_sock: Optional[socket.socket] = None
        self._monitor: Optional[threading.Thread] = None
        self._admin = None
        self.supervisor_port: Optional[int] = None
        self.run_dir = self.config.get("run-dir")

    def _worker_snapshot(self) -> List[_Worker]:
        with self._lock:
            return sorted(self._workers.values(),
                          key=lambda w: w.ordinal)

    def worker_ports(self) -> List[Dict]:
        return [{"ordinal": w.ordinal, "port": w.port}
                for w in self._worker_snapshot()]

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Supervisor":
        if self.run_dir is None:
            self.run_dir = tempfile.mkdtemp(prefix="filodb-sup-")
        os.makedirs(self.run_dir, exist_ok=True)
        self.bus = SupervisorBus().start()
        # accept edge: prefer per-worker SO_REUSEPORT binds; without
        # platform support, bind once here and pass the fd down
        accept_fd = None
        if not hasattr(socket, "SO_REUSEPORT"):
            self._accept_sock = socket.create_server(
                ("127.0.0.1", self.public_port), backlog=128)
            self._accept_sock.set_inheritable(True)
            accept_fd = self._accept_sock.fileno()
        ports = [_free_port() for _ in range(self.num_workers)]
        for i in range(self.num_workers):
            cfg = worker_config(self.config, i, self.num_workers,
                                ports, self.public_port,
                                self.bus.port, accept_fd=accept_fd)
            cfg_path = os.path.join(self.run_dir, f"worker{i}.json")
            with open(cfg_path, "w") as f:
                json.dump(cfg, f, indent=2)
            w = _Worker(i, cfg_path, ports[i])
            with self._lock:
                self._workers[i] = w
        for w in self._worker_snapshot():
            self._spawn(w)
        self._start_admin()
        self._monitor = threading.Thread(target=self._monitor_run,
                                         daemon=True,
                                         name="worker-supervisor")
        self._monitor.start()
        return self

    def _spawn(self, w: _Worker) -> None:
        """Start (or restart) one worker process; a side thread waits
        for its machine-readable startup line and broadcasts
        ``worker-up`` when the node is serving."""
        pass_fds = ()
        if self._accept_sock is not None:
            pass_fds = (self._accept_sock.fileno(),)
        proc = subprocess.Popen(
            [sys.executable, "-m", "filodb_tpu.standalone.server",
             "--config", w.cfg_path],
            stdout=subprocess.PIPE, pass_fds=pass_fds)
        with self._lock:
            w.proc = proc
            w.ready = False
            w.health_misses = 0
            w.last_spawn = time.monotonic()
        threading.Thread(target=self._await_startup, args=(w, proc),
                         daemon=True,
                         name=f"worker-startup-{w.ordinal}").start()

    @thread_root("worker-startup")
    def _await_startup(self, w: _Worker, proc: subprocess.Popen) -> None:
        deadline = time.monotonic() + float(
            self.config.get("worker-startup-timeout-s", 180.0))
        buf = b""
        while time.monotonic() < deadline and b"\n" not in buf:
            if proc.poll() is not None:
                return              # died during startup; monitor reaps
            r, _, _ = select.select([proc.stdout], [], [], 0.5)
            if r:
                ch = proc.stdout.read1(4096)
                if not ch:
                    return
                buf += ch
        if b"\n" not in buf:
            return
        # keep draining stdout so a chatty worker can never block on a
        # full pipe
        threading.Thread(target=self._drain, args=(proc,), daemon=True,
                         name=f"worker-drain-{w.ordinal}").start()
        with self._lock:
            if w.proc is proc:
                w.ready = True
        if self.bus is not None:
            self.bus.broadcast({"type": "worker-up", "node": w.node_id})

    @thread_root("worker-drain")
    def _drain(self, proc: subprocess.Popen) -> None:
        try:
            while proc.stdout.read1(65536):
                pass
        except (OSError, ValueError):
            pass

    # -- supervision ------------------------------------------------------
    @thread_root("worker-supervisor")
    def _monitor_run(self) -> None:
        interval = float(self.config.get("monitor-interval-s", 0.15))
        health_every = float(self.config.get(
            "health-check-interval-s", 1.0))
        threshold = int(self.config.get("health-fail-threshold", 5))
        backoff = float(self.config.get("restart-backoff-s", 1.0))
        last_health = 0.0
        while not self._stop_evt.wait(interval):
            now = time.monotonic()
            do_health = now - last_health >= health_every
            if do_health:
                last_health = now
            for w in self._worker_snapshot():
                with self._lock:
                    proc, ready = w.proc, w.ready
                    stopping = self._stopping
                if stopping or proc is None:
                    continue
                rc = proc.poll()
                if rc is not None:
                    # ground truth: the process is GONE (crash,
                    # kill -9, OOM). Tell the siblings immediately —
                    # they drop its gossiped watermarks / data-plane
                    # channel — then respawn with the same config.
                    if self.bus is not None:
                        self.bus.broadcast({"type": "worker-exit",
                                            "node": w.node_id})
                    wait = backoff - (now - w.last_spawn)
                    if wait > 0 and self._stop_evt.wait(wait):
                        return
                    with self._lock:
                        w.restarts += 1
                    self._spawn(w)
                    continue
                if do_health and ready:
                    if self._healthy(w):
                        with self._lock:
                            w.health_misses = 0
                    else:
                        with self._lock:
                            w.health_misses += 1
                            wedged = w.health_misses >= threshold
                        if wedged:
                            # alive but unresponsive: treat like a
                            # crash (the next loop pass reaps + respawns)
                            try:
                                proc.kill()
                            except OSError:
                                pass

    def _healthy(self, w: _Worker) -> bool:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{w.port}/__health",
                    timeout=2.0) as r:
                return r.status == 200
        except OSError:
            return False

    # -- aggregate admin/metrics edge -------------------------------------
    def _start_admin(self) -> None:
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        sup = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _reply(self, code: int, payload, ctype=None) -> None:
                if isinstance(payload, str):
                    body = payload.encode()
                    ctype = ctype or "text/plain; version=0.0.4"
                else:
                    body = json.dumps(payload).encode()
                    ctype = ctype or "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            # the supervisor's admin edge runs on stdlib handler
            # threads, like the worker HTTP edge
            @thread_root("supervisor-admin")
            def do_GET(self):
                try:
                    code, payload = sup._admin_route(
                        self.path, method="GET")
                except Exception as e:  # noqa: BLE001 — edge survives
                    code, payload = 500, {"status": "error",
                                          "error": str(e)}
                self._reply(code, payload)

            def do_POST(self):
                try:
                    code, payload = sup._admin_route(
                        self.path, method="POST")
                except Exception as e:  # noqa: BLE001 — edge survives
                    code, payload = 500, {"status": "error",
                                          "error": str(e)}
                self._reply(code, payload)

        self._admin = ThreadingHTTPServer(
            ("127.0.0.1", int(self.config.get("supervisor-port", 0)
                              or 0)), Handler)
        self.supervisor_port = self._admin.server_port
        threading.Thread(target=self._admin.serve_forever, daemon=True,
                         name="supervisor-admin").start()

    def _admin_route(self, path: str, method: str = "GET"):
        parsed = urllib.parse.urlparse(path)
        qs = urllib.parse.parse_qs(parsed.query)
        route = parsed.path
        if route in ("/__health", "/__liveness"):
            return 200, self.status()
        if route == "/metrics":
            return 200, self.metrics_text()
        if route in ("/debug/traces", "/debug/queries",
                     "/debug/slow_queries", "/debug/threads",
                     "/debug/events"):
            return 200, self._debug_merge(route, parsed.query)
        if route == "/admin/invalidate" and method == "POST":
            reason = (qs.get("reason") or ["schema"])[0]
            self.bus.broadcast({"type": "schema", "reason": reason,
                                "origin": "supervisor"})
            return 200, {"status": "success",
                         "data": {"reason": reason,
                                  "workers": self.bus.connected_workers()}}
        if route == "/admin/restart" and method == "POST":
            try:
                ordinal = int((qs.get("worker") or [""])[0])
            except ValueError:
                return 400, {"status": "error",
                             "error": "worker must be an ordinal"}
            graceful = (qs.get("graceful") or ["true"])[0].lower() \
                not in ("false", "0", "no")
            out = self.restart_worker(ordinal, graceful=graceful)
            return (200 if out.get("ok") else 500,
                    {"status": "success" if out.get("ok") else "error",
                     "data": out})
        return 404, {"status": "error",
                     "error": f"no route for {route}"}

    def _worker_get(self, w: _Worker, path: str,
                    timeout: float = 5.0) -> Optional[object]:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{w.port}{path}",
                    timeout=timeout) as r:
                body = r.read()
        except OSError:
            return None
        try:
            return json.loads(body)
        except ValueError:
            return body.decode("utf-8", "replace")

    def status(self) -> Dict:
        workers = {}
        with self._lock:
            snap = [(w.ordinal, w.port, w.proc, w.ready, w.restarts)
                    for w in self._workers.values()]
        for ordinal, port, proc, ready, restarts in snap:
            workers[str(ordinal)] = {
                "port": port,
                "alive": proc is not None and proc.poll() is None,
                "pid": proc.pid if proc is not None else None,
                "ready": ready,
                "restarts": restarts,
            }
        return {"status": "healthy", "role": "supervisor",
                "public_port": self.public_port,
                "bus_port": self.bus.port if self.bus else None,
                "bus_connected": (self.bus.connected_workers()
                                  if self.bus else []),
                "workers": workers}

    def metrics_text(self) -> str:
        """The one-target scrape: every worker's exposition with a
        ``worker`` label injected, plus the supervisor's own fleet
        gauges."""
        by_worker: Dict[str, str] = {}
        with self._lock:
            targets = list(self._workers.values())
        for w in targets:
            body = self._worker_get(w, "/metrics")
            if isinstance(body, str):
                by_worker[str(w.ordinal)] = body
        out = merge_expositions(by_worker)
        # host-wide per-tenant rollup (filodb_host_tenant_*): the
        # per-worker tenant families above carry worker labels; this is
        # the summed view a noisy-neighbor alert reads
        out += aggregate_tenant_families(by_worker)
        b = ExpositionBuilder()
        with self._lock:
            snap = [(w.ordinal, w.proc, w.restarts)
                    for w in self._workers.values()]
        b.sample("filodb_supervisor_workers", {}, len(snap),
                 help="Configured worker-process fleet size")
        for ordinal, proc, restarts in snap:
            lbl = {"worker": str(ordinal)}
            b.sample("filodb_supervisor_worker_alive", lbl,
                     1 if proc is not None and proc.poll() is None
                     else 0,
                     help="1 while the worker process is running")
            b.sample("filodb_supervisor_worker_restarts_total", lbl,
                     restarts, mtype="counter",
                     help="Times the supervisor respawned this worker")
        b.sample("filodb_supervisor_bus_connected_workers", {},
                 len(self.bus.connected_workers()) if self.bus else 0,
                 help="Workers currently connected to the control "
                      "plane bus")
        return out + b.render()

    def _debug_merge(self, route: str, query: str) -> Dict:
        """Fan a /debug/* request out to every worker and merge the
        ``data`` lists, each entry tagged with its worker ordinal."""
        merged: List = []
        summaries: Dict[str, object] = {}
        with self._lock:
            targets = list(self._workers.values())
        for w in targets:
            path = route + (f"?{query}" if query else "")
            body = self._worker_get(w, path)
            if not isinstance(body, dict) \
                    or body.get("status") != "success":
                continue
            if "summary" in body:
                summaries[str(w.ordinal)] = body["summary"]
            for entry in body.get("data") or []:
                if isinstance(entry, dict):
                    entry = {**entry, "worker": w.ordinal}
                merged.append(entry)
        out: Dict[str, object] = {"status": "success", "data": merged}
        if summaries:
            out["summary"] = summaries
        return out

    # -- drain / restart / stop -------------------------------------------
    def _drain_worker(self, w: _Worker, timeout_s: float = 60.0) -> bool:
        """PR 6 membership drain: the worker's shards hand off
        make-before-break to the surviving siblings."""
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{w.port}/admin/drain", data=b"{}",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                body = json.loads(r.read())
            return body.get("status") == "success" \
                and not (body.get("data") or {}).get("failed")
        except (OSError, ValueError):
            return False

    def restart_worker(self, ordinal: int, graceful: bool = True
                       ) -> Dict:
        """Rolling-restart one worker: drain (planned handoff to the
        siblings), terminate, and let the monitor respawn it; its
        rejoin defers shards and receives them back through the same
        membership protocol."""
        with self._lock:
            w = self._workers.get(int(ordinal))
            proc = w.proc if w is not None else None
        if w is None or proc is None:
            return {"ok": False, "error": f"no worker {ordinal}"}
        drained = self._drain_worker(w) if graceful else None
        try:
            proc.terminate()
        except OSError:
            pass
        return {"ok": True, "worker": int(ordinal), "drained": drained}

    def stop(self, graceful: bool = True,
             drain_timeout_s: float = 60.0) -> None:
        with self._lock:
            self._stopping = True
        self._stop_evt.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        with self._lock:
            workers = sorted(self._workers.values(),
                             key=lambda w: w.ordinal)
        if graceful and len(workers) > 1:
            # drain all but the last live worker through the membership
            # protocol, so every shard's final flush/checkpoint happens
            # under a serving owner (the last worker just stops — its
            # durable state is the restart source)
            for w in workers[:-1]:
                if w.proc is not None and w.proc.poll() is None:
                    self._drain_worker(w, timeout_s=drain_timeout_s)
                    try:
                        w.proc.terminate()
                    except OSError:
                        pass
        for w in workers:
            if w.proc is not None and w.proc.poll() is None:
                try:
                    w.proc.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + 20
        for w in workers:
            if w.proc is None:
                continue
            try:
                w.proc.wait(timeout=max(0.1,
                                        deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                w.proc.kill()
                try:
                    w.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        if self._admin is not None:
            self._admin.shutdown()
            self._admin.server_close()
        if self.bus is not None:
            self.bus.stop()
        if self._accept_sock is not None:
            try:
                self._accept_sock.close()
            except OSError:
                pass


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="filodb-tpu-supervisor")
    p.add_argument("--config", help="JSON config file (standalone "
                                    "server schema + supervisor keys)")
    p.add_argument("--workers", type=int,
                   help="worker fleet size (default: one per core)")
    p.add_argument("--port", type=int, help="shared public port")
    p.add_argument("--supervisor-port", type=int,
                   help="aggregate admin/metrics port")
    args = p.parse_args(argv)
    config: Dict = {}
    if args.config:
        with open(args.config) as f:
            config.update(json.load(f))
    if args.workers is not None:
        config["serving-workers"] = args.workers
    if args.port is not None:
        config["port"] = args.port
    if args.supervisor_port is not None:
        config["supervisor-port"] = args.supervisor_port
    sup = Supervisor(config).start()
    # machine-readable startup line (harness/dev scripts read this)
    print(json.dumps({
        "port": sup.public_port,
        "supervisor_port": sup.supervisor_port,
        "bus_port": sup.bus.port,
        "workers": sup.worker_ports(),
    }), flush=True)
    print(f"filodb-tpu supervisor: {sup.num_workers} workers behind "
          f":{sup.public_port} (admin :{sup.supervisor_port})",
          file=sys.stderr)
    stop_evt = threading.Event()

    def _sig(_signum, _frame):
        stop_evt.set()
    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        while not stop_evt.wait(0.5):
            pass
    finally:
        sup.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
