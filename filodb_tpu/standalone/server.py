"""FiloServer: the standalone node binary.

Wires config -> memstore shards -> shard mapper -> TPU query backend ->
HTTP API, mirroring the v2 startup path (standalone/NewFiloServerMain.scala:21:
start memstore, discovery, ingestion, http) without Akka: shard state is a
local ShardMapper FSM; the distributed query path is the mesh executor.

Config keys follow conf/timeseries-dev-source.conf naming where sensible:
  dataset, num-shards, groups-per-shard, max-chunks-size, port.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Dict, Optional

from filodb_tpu.core.memstore import TimeSeriesMemStore
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetRef
from filodb_tpu.http.server import FiloHttpServer
from filodb_tpu.lint.threads import thread_root
from filodb_tpu.parallel.shardmapper import (ShardMapper,
                                             assign_shards_evenly,
                                             shards_for_ordinal)
from filodb_tpu.query.model import QueryLimits

DEFAULTS = {
    "dataset": "timeseries",
    "num-shards": 4,
    "groups-per-shard": 8,
    "max-chunks-size": 400,
    "port": 8080,
    "node-id": "node0",
    # spread used for shard-key routing (filodb-defaults.conf:319
    # default-spread); must match the ingest-side spread
    "default-spread": 1,
    # lower agg(rangefunc(...)) onto the device mesh when >1 jax device
    "mesh-enabled": False,
    # with mesh-enabled: serve eligible aligned-tile cohorts from
    # device-RESIDENT sharded tiles (shard_map slot-major evaluators,
    # donated zero-copy refreshes) instead of single-device dispatch
    "mesh-tile-serving": True,
    # chunk/partkey/checkpoint persistence root; None = memory-only
    # (conf/timeseries-filodb-server.conf store path equivalent)
    "data-dir": None,
    # streaming ingestion: per-shard durable stream logs (the Kafka
    # partition analogue, conf/timeseries-dev-source.conf sourceconfig);
    # None = no streaming ingestion (direct/test ingest only)
    "stream-dir": None,
    # influx line-protocol ingest edge (GatewayServer.scala); None = off,
    # 0 = ephemeral port
    "gateway-port": None,
    # flush cadence: one flush group every interval, rotating round-robin
    # (flush-interval in the reference source config)
    "flush-interval-s": 2.0,
    "flush-every-records": None,
    # raw retention in seconds; queries reaching further back split to the
    # downsample tier (LongTimeRangePlanner). Requires data-dir (the ds
    # tier reads downsampler-job output from the ColumnStore). None = off.
    "raw-retention-s": None,
    # downsample resolutions in ms (conf multi-resolution config)
    "downsample-resolutions": [300_000, 3_600_000],
    # emit downsample records during flush (ShardDownsampler.scala:40);
    # requires data-dir. The batch job remains for backfill + histograms.
    "flush-downsample": False,
    # per-shard resident-sample budget; exceeded -> evict least-recently
    # written partitions to ODP shells (headroom task). 0 = no cap.
    "max-resident-samples": 0,
    # per-query guardrails (filodb-defaults.conf sample-limit equivalent;
    # 0 = unlimited). Over-limit queries return HTTP 422.
    "query-sample-limit": 1_000_000,
    "query-series-limit": 100_000,
    # degraded-mode execution (parallel/resilience.py): default per-query
    # deadline budget (overridable per request via &timeout=), bounded
    # retries on peer transport failures, and per-peer circuit breakers
    # (open after N consecutive failures; half-open probe after the
    # reset window). Partial responses stay opt-in per request
    # (&allow_partial=true).
    "query-timeout-s": 30.0,
    # serving fast path (query/batcher.py + query/plancache.py):
    # micro-batch gather window for concurrent same-shape queries (the
    # continuous-batching admission layer in front of the TPU backend),
    # max queries per device dispatch, and the parsed-plan LRU size
    # (0 disables the respective piece)
    "batch-gather-window-ms": 1.0,
    "batch-max": 8,
    "batch-enabled": True,
    "plan-cache-size": 256,
    # incremental range-query results cache (query/resultcache.py):
    # byte budget for cached per-step matrix extents (0 disables) and
    # the freshness hot window — steps within this many ms of now (or
    # above a shard's ingest watermark) are never served from cache.
    # Per-request escape hatch: &cache=false.
    "results-cache-mb": 64,
    "results-cache-hot-window-ms": 10_000,
    # WAL read batch per ingest poll (was hardcoded at 64); also the
    # recovery replay batch size
    "ingest-batch-records": 64,
    # host decode/merge cache byte budget per shard (0 = unbounded);
    # trimmed on the flush path — fully-persisted partitions' decoded
    # duplicates are released first (filodb_decode_cache_bytes gauge)
    "decode-cache-mb": 0,
    # observability (filodb_tpu.obs): distributed tracing is OFF by
    # default (zero overhead, byte-identical responses); when enabled,
    # fresh queries sample at trace-sample-rate and finished traces land
    # in the /debug/traces ring (&explain=trace forces + inlines one).
    # Queries slower than slow-query-ms leave a structured record at
    # /debug/slow_queries (0 = off); /debug/queries lists in-flight.
    "trace-enabled": False,
    "trace-sample-rate": 1.0,
    "trace-max-traces": 256,
    "slow-query-ms": 1000.0,
    # tail-sampling retention: with tracing enabled, EVERY request
    # records into a pending trace and the sample-rate coin only
    # decides uninteresting outcomes — errors, QoS-shed rungs, and
    # queries slower than trace-slow-ms are ALWAYS retained. None
    # defaults the slow threshold to slow-query-ms, so slowlog entries
    # always link a resolvable trace id.
    "trace-slow-ms": None,
    # trace export: POST retained traces as OTLP/JSON batches to this
    # sink URL (None = off) through the breaker+backoff stack; the
    # queue is bounded drop-oldest (export lag never blocks serving)
    "trace-export-url": None,
    "trace-export-batch": 64,
    "trace-export-interval-s": 2.0,
    "trace-export-queue": 1024,
    # wall-clock sampling profiler (obs/profiler.py): OFF by default
    # (no sampler thread, no metric families, byte-identical /metrics);
    # when on, /debug/profile serves folded stacks + top self-time and
    # filodb_profile_self_seconds_total{root,func} rides the registry
    "profiler-enabled": False,
    "profiler-hz": 29.0,
    "profiler-max-stacks": 4096,
    "profiler-top-n": 20,
    # self-monitoring (obs/selfmon.py): a per-process loop snapshots
    # the metrics registry in-process every interval and ingests the
    # samples into the reserved __selfmon__ dataset through the normal
    # ingest path (WAL + driver replay when stream-dir is set; direct
    # ingest + flush otherwise), tagged to the reserved __selfmon__
    # tenant (background priority, forced charges). PromQL over our own
    # telemetry: /promql/__selfmon__/api/v1/query_range?query=...
    "self-monitor": False,
    "self-monitor-interval-s": 5.0,
    # direct-ingest mode flush cadence (ticks between flushes; the
    # internal shard's ingest watermark — the results cache's
    # freshness input — advances on flush)
    "self-monitor-flush-ticks": 4,
    # -- recording rules & alerting (filodb_tpu/rules) ----------------
    # rules-file: a Prometheus-style YAML/JSON rule-group file;
    # "rules" accepts the same structure inline ({"groups": [...]}) —
    # handy for tests and generated configs. Groups evaluate in-process
    # as standing queries (background priority, forced-charge
    # __rules__ tenant, step-aligned tail recomputes through the
    # results cache); recorded series + synthetic ALERTS land in the
    # reserved __rules__ dataset via the selfmon write-back rail
    # (durable WAL + driver replay under stream-dir). Under the
    # supervisor every worker loads the config but only the lowest
    # ALIVE ordinal evaluates (re-elected on bus worker-exit).
    "rules-file": None,
    "rules": None,
    # steps per evaluation window: each tick queries the last N
    # interval-aligned steps so the results cache serves the warm
    # prefix and only the newest step recomputes
    "rules-eval-span-steps": 8,
    # alert webhook receiver (Alertmanager-webhook-shaped POSTs,
    # retried with backoff through a per-receiver circuit breaker);
    # None = no notifications
    "rules-webhook-url": None,
    # group-commit fsync for the durable ingest streams (ROADMAP
    # follow-up: per-append fsync stalls on shared container disks).
    # Appends fsync at most every this-many ms (or 1MB unsynced);
    # 0 = strict fsync-per-append. The durability window is bounded by
    # this knob; stream close / checkpoint sync() force the tail out.
    "stream-group-commit-ms": 5.0,
    # storage-integrity knob: quarantined-record loss a shard tolerates
    # before degrading to read-only (queries keep serving, flagged in
    # /__health "integrity"). 0 = ANY quarantined record trips it — the
    # zero-silent-loss default; raise it only when replay-through-
    # damage is preferred over read-only (fsck can repair offline).
    "integrity-max-quarantined-records": 0,
    # admission control: query endpoints admit at most this many
    # in-flight evaluations (excess parks on a semaphore); 0 = off.
    # The wait is BOUNDED: a slot that does not free within
    # admission-wait-s answers 429 + Retry-After (never a silent hang)
    "max-inflight-queries": 4,
    "admission-wait-s": 5.0,
    # -- tenant QoS / brownout control (query/qos.py) -----------------
    # Per-tenant query budgets in estimated cost units/second (tenant =
    # X-Filo-Tenant header / &tenant= param, by convention the
    # workspace; "default" otherwise). 0 = budgets off (the pre-QoS
    # edge). Burst is the bucket depth (0 = 10x rate); per-tenant
    # overrides: {tenant: rate} or {tenant: [rate, burst]} (rate 0 =
    # that tenant is unlimited). Over-budget queries degrade down the
    # ladder (stale-cache -> downsample -> partial -> 429) unless
    # qos-shed-degraded is false; the coarsen rung targets at most
    # qos-degrade-max-steps evaluation steps.
    "qos-tenant-rate": 0,
    "qos-tenant-burst": 0,
    "qos-tenant-overrides": {},
    "qos-shed-degraded": True,
    "qos-degrade-max-steps": 64,
    "peer-retry-attempts": 3,
    "peer-retry-base-delay-s": 0.05,
    "breaker-failure-threshold": 3,
    "breaker-reset-s": 5.0,
    # multi-process cluster (coordinator/v2 FiloDbClusterDiscovery.scala:50
    # ordinal->shards; explicit peer list like the akka-bootstrapper's
    # explicit-list mode): this node owns shards_for_ordinal(node-ordinal);
    # peers maps node ids ("node0"...) -> base URLs for leaf dispatch
    "num-nodes": 1,
    "node-ordinal": 0,
    "peers": {},
    # seed discovery (akka-bootstrapper AkkaBootstrapper.scala:31): when
    # "peers" is empty, resolve them at startup —
    #   {"mode": "dns-srv", "srv-name": "_filodb._tcp.ns.svc"} or
    #   {"mode": "consul", "url": "http://consul:8500", "service": "filodb"}
    # "advertise-url" identifies THIS node among the discovered seeds
    # (ordinals follow the sorted seed list on every node).
    "discovery": None,
    "advertise-url": None,
    # HA buddy replica cluster (HighAvailabilityPlanner.scala:31): maps a
    # node id to the SAME-ordinal node of a replica cluster ingesting the
    # same streams; queries route a DOWN node's shards to its buddy
    "buddy-peers": {},
    # cross-cluster federation: _ws_ value -> base URL of the cluster
    # owning that workspace (MultiPartitionPlanner.scala:53); workspaces
    # in local-partitions are served here and never forwarded
    "partitions": {},
    "local-partitions": [],
    # per-shard-key spread overrides {"ws,ns": spread}
    # (core/SpreadProvider.scala; doc/sharding.md "Spread")
    "spread-overrides": {},
    # cardinality quotas (ratelimit QuotaSource, filodb-defaults.conf:277):
    # default quota per prefix depth [root, ws, ns, metric]; 0 = unlimited.
    # Per-prefix overrides: {"ws,ns": quota}. Breaches drop new series.
    "card-default-quotas": [0, 0, 0, 0],
    "card-quotas": {},
    "failure-detect-interval-s": 0.5,
    "failure-detect-threshold": 3,
    # per-tenant cardinality gauges published on a timer
    # (TenantIngestionMetering.scala; 0 = off)
    "tenant-metering-interval-s": 60,
    # gRPC query service port (PromQLGrpcServer.scala; 0 = ephemeral,
    # None = off). ON by default: this is the data plane — leaf dispatch
    # and pushdown ride protobuf + NibblePack over persistent channels;
    # base64-JSON HTTP remains the control plane and the fallback. Fixed
    # peer addrs can be given via "grpc-peers" {node_id: "host:port"};
    # otherwise each node advertises its ephemeral port in its health
    # body and peers learn it through the failure detector's gossip.
    "grpc-port": 0,
    "grpc-peers": {},
    "grpc-partitions": {},
    # elastic recovery (ShardManager.scala:28 assignShardsToNodes): when a
    # peer stays DOWN this many seconds past detection, survivors adopt
    # its shards — bootstrap from the ColumnStore, replay the stream from
    # the checkpoint watermark, then serve them. None = survive-only
    # (buddy failover still applies). Requires the shared data-dir /
    # stream-dir deployment (the Cassandra/Kafka analogue).
    "shard-reassign-grace-s": None,
    # elastic membership (parallel/membership.py): POST /admin/drain
    # walks this node's shards through planned make-before-break
    # handoff, rejoining nodes defer shards a peer still serves and
    # receive them back through the same protocol, and topology epochs
    # + stale-routing retries keep routing/caches coherent. False falls
    # back to the legacy on_node_up hard cutover.
    "elastic-membership": True,
    # per-shard handoff budget: flush + successor bootstrap/replay +
    # ACTIVE advertisement must fit, or the shard rolls back to the
    # draining owner
    "handoff-timeout-s": 30.0,
    # metadata/cardinality peer fan-out concurrency (was hard-coded 8);
    # 0 = auto-size from the host core count. Surfaced in /metrics as
    # filodb_peer_fanout_workers.
    "peer-fanout-workers": 0,
    # -- process-sharded serving tier (standalone/supervisor.py) ------
    # These keys are normally derived by the supervisor, which forks N
    # worker processes per host — each an ordinal-owned shard-group
    # node with PRIVATE plan/executable/results caches, batcher, and
    # device executor — behind ONE public port.
    #   worker-id:    this process's worker ordinal (None = standalone)
    #   accept-port:  shared public port; bound here with SO_REUSEPORT
    #                 so the kernel balances accepted connections
    #                 across workers
    #   accept-fd:    inherited listening-socket fd (the fd-passing
    #                 fallback where SO_REUSEPORT is unavailable; the
    #                 supervisor binds once and every worker accepts
    #                 on the shared socket)
    #   bus-port:     the supervisor's local control plane
    #                 (standalone/bus.py): topology / schema /
    #                 watermark events fan out to every sibling so
    #                 per-process caches stay coherent with membership
    "worker-id": None,
    "accept-port": None,
    "accept-host": "127.0.0.1",
    "accept-fd": None,
    "bus-port": None,
    # cadence of this worker's watermark/backfill gossip on the bus
    # (the detector's health-body gossip remains the backstop)
    "bus-watermark-interval-s": 0.25,
}


def bind_reuseport(host: str, port: int):
    """A listening socket on (host, port) with SO_REUSEPORT, or None
    when the platform doesn't support it (the supervisor then falls
    back to binding once and passing the fd to every worker)."""
    import socket as _socket
    if not hasattr(_socket, "SO_REUSEPORT"):
        return None
    s = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    try:
        s.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEPORT, 1)
        s.bind((host, int(port)))
        s.listen(128)
    except OSError:
        s.close()
        raise
    return s


class FiloServer:
    def __init__(self, config: Optional[Dict] = None,
                 backend: Optional[object] = None):
        self.config = {**DEFAULTS, **(config or {})}
        self.ref = DatasetRef(self.config["dataset"])
        column_store = None
        if self.config.get("data-dir"):
            from filodb_tpu.store import FlatFileColumnStore
            column_store = FlatFileColumnStore(self.config["data-dir"])
        self.store = TimeSeriesMemStore(DEFAULT_SCHEMAS,
                                        column_store=column_store)
        self.mapper = ShardMapper(self.config["num-shards"])
        self.backend = backend
        self.http: Optional[FiloHttpServer] = None
        self.streams: Dict[int, object] = {}
        # ONE driver map for primary, adopted, and handed-back shards:
        # the per-shard single-writer invariant is "at most one entry
        # here, cluster-wide, per shard" (membership + chaos pin it)
        self.drivers: Dict[int, object] = {}
        self.gateway = None
        self.detector = None
        self.membership = None
        self.node_id: str = self.config["node-id"]
        self.owned_shards: list = []
        # rejoin deferral: ordinal shards a peer still served at startup
        # (it adopted them while this node was down); created only when
        # the peer hands them back through /admin/adopt
        self.deferred_shards: set = set()
        # elastic-recovery bookkeeping: origin node -> shards THIS node
        # adopted (crash or planned); node -> original assignment
        self._adopted: Dict[str, list] = {}
        self._reassign_lock = threading.Lock()
        self._original_shards: Dict[str, list] = {}
        self._gw_streams: Dict[int, object] = {}
        # process-sharded serving: the worker's control-plane client
        # (standalone/bus.py) + the watermark-gossip tick that rides it
        self.bus_client = None
        self._bus_tick_stop = threading.Event()
        self._bus_tick_thread: Optional[threading.Thread] = None
        # self-monitoring (obs/selfmon.py): loop + its internal
        # dataset's dedicated stream/driver (None when off)
        self.selfmon = None
        self._selfmon_stream = None
        self._selfmon_driver = None
        # recording rules & alerting (filodb_tpu/rules): engine +
        # the reserved __rules__ dataset's stream/driver (None when no
        # rules are configured)
        self.rules = None
        self._rules_stream = None
        self._rules_driver = None

    def _make_qos_budgets(self):
        """Per-tenant token-bucket budgets from the qos-* knobs (None
        semantics live in TenantBudgets.enabled: rate 0 and no
        overrides = budgets off, the pre-QoS edge)."""
        from filodb_tpu.query.qos import TenantBudgets
        return TenantBudgets(
            default_rate=float(self.config.get("qos-tenant-rate", 0)
                               or 0),
            default_burst=float(self.config.get("qos-tenant-burst", 0)
                                or 0),
            overrides=dict(self.config.get("qos-tenant-overrides")
                           or {}))

    def _make_tracer(self):
        from filodb_tpu.obs.trace import Tracer, TraceExporter
        slow_ms = self.config.get("trace-slow-ms")
        if slow_ms is None:
            # tail retention inherits the slowlog threshold, so every
            # slow-query record links a retained (resolvable) trace
            slow_ms = self.config.get("slow-query-ms", 1000.0)
        exporter = None
        url = self.config.get("trace-export-url")
        if url:
            exporter = TraceExporter(
                str(url),
                batch_max=int(self.config.get("trace-export-batch", 64)),
                interval_s=float(self.config.get(
                    "trace-export-interval-s", 2.0)),
                queue_max=int(self.config.get(
                    "trace-export-queue", 1024))).start()
        return Tracer(
            enabled=bool(self.config.get("trace-enabled", False)),
            sample_rate=float(self.config.get("trace-sample-rate", 1.0)),
            max_traces=int(self.config.get("trace-max-traces", 256)),
            node=self.node_id,
            slow_ms=float(slow_ms or 0.0),
            exporter=exporter)

    def _make_profiler(self):
        from filodb_tpu.obs.profiler import SamplingProfiler
        if not self.config.get("profiler-enabled", False):
            return None
        return SamplingProfiler(
            hz=float(self.config.get("profiler-hz", 29.0)),
            max_stacks=int(self.config.get("profiler-max-stacks", 4096)),
            top_n=int(self.config.get("profiler-top-n", 20))).start()

    def _make_shard(self, shard: int):
        """One shard's full construction — tracker with quota overrides,
        flush-downsampler, store setup + bootstrap. Shared by startup and
        elastic adoption so adopted shards cannot silently diverge."""
        from filodb_tpu.core.cardinality import CardinalityTracker
        tracker = CardinalityTracker(
            tuple(self.config.get("card-default-quotas", ())))
        for pfx, quota in dict(
                self.config.get("card-quotas") or {}).items():
            tracker.set_quota([p for p in pfx.split(",") if p],
                              int(quota))
        # shard-registry maps (card_trackers/streams/drivers + the HTTP
        # shard-list publish) are mutated from adopt/release/handback
        # worker threads concurrently — every mutation rides
        # _reassign_lock (graftlint thread-unguarded-shared-state);
        # reads stay lock-free GIL-atomic snapshots
        with self._reassign_lock:
            self.card_trackers[shard] = tracker
        fds = None
        if self.config.get("flush-downsample") \
                and self.store.column_store is not None:
            from filodb_tpu.downsample.flush import FlushDownsampler
            fds = FlushDownsampler(
                self.store.column_store, self.config["dataset"], shard,
                DEFAULT_SCHEMAS,
                resolutions=tuple(self.config["downsample-resolutions"]))
        return self.store.setup(
            self.ref, shard,
            num_groups=self.config["groups-per-shard"],
            max_chunk_rows=self.config["max-chunks-size"],
            bootstrap=self.store.column_store is not None,
            card_tracker=tracker,
            flush_downsampler=fds)

    def start(self) -> "FiloServer":
        # GIL convoy mitigation on the serving path: handler threads do
        # short bursts of socket I/O between compute; with CPython's
        # default 5ms switch interval every GIL reacquisition after a
        # send/recv can stall a full interval behind a compute-bound
        # thread. A ~1ms interval keeps request threads interleaving.
        swi = self.config.get("gil-switch-interval-ms")
        if swi:
            import sys as _sys
            _sys.setswitchinterval(float(swi) / 1000.0)
        n = self.config["num-shards"]
        num_nodes = int(self.config.get("num-nodes", 1))
        ordinal = int(self.config.get("node-ordinal", 0))
        # seed discovery (akka-bootstrapper analogue): resolve the peer
        # map + this node's ordinal from DNS-SRV/Consul when no explicit
        # peer list is configured
        disc = self.config.get("discovery")
        if disc and not self.config.get("peers"):
            from filodb_tpu.parallel.discovery import discover_peers
            all_nodes = discover_peers(disc)
            adv = self.config.get("advertise-url")
            if adv is None:
                raise ValueError(
                    "discovery needs advertise-url to identify this "
                    "node among the discovered seeds")
            me = [nid for nid, url in all_nodes.items()
                  if url.rstrip("/") == adv.rstrip("/")]
            if len(me) != 1:
                raise ValueError(
                    f"advertise-url {adv!r} matched {len(me)} "
                    f"discovered seeds {sorted(all_nodes.values())}")
            ordinal = int(me[0].removeprefix("node"))
            num_nodes = len(all_nodes)
            self.config["num-nodes"] = num_nodes
            self.config["node-ordinal"] = ordinal
            self.config["peers"] = {nid: url for nid, url
                                    in all_nodes.items()
                                    if nid != me[0]}
        if num_nodes > 1:
            self.node_id = f"node{ordinal}"
            self.owned_shards = shards_for_ordinal(ordinal, num_nodes, n)
        else:
            self.node_id = self.config["node-id"]
            self.owned_shards = list(range(n))
        from filodb_tpu.core.cardinality import CardinalityTracker
        from filodb_tpu.core.spread import SpreadProvider
        self.spread_provider = SpreadProvider(
            int(self.config.get("default-spread", 1)),
            dict(self.config.get("spread-overrides") or {}))
        self.card_trackers = {}
        # rejoin deferral (parallel/membership.py): before creating a
        # shard this node owns by ordinal, ask the peers whether one of
        # them still SERVES it (it adopted the shard while this node
        # was down). Deferred shards are neither created nor ingested
        # here — the temporary owner hands them back make-before-break
        # through /admin/adopt, closing the dual-writer window the
        # legacy hard cutover opened.
        peer_claims: Dict[int, tuple] = {}
        elastic = bool(self.config.get("elastic-membership", True))
        if num_nodes > 1 and elastic:
            probe_peers = {k: v for k, v in
                           dict(self.config.get("peers") or {}).items()
                           if k != self.node_id}
            if probe_peers:
                from filodb_tpu.parallel.membership import \
                    probe_peer_claims
                peer_claims = probe_peer_claims(probe_peers,
                                                self.owned_shards)
        self.deferred_shards = set(peer_claims)
        for shard in self.owned_shards:
            if shard in self.deferred_shards:
                continue
            self._make_shard(shard)
        if num_nodes > 1:
            for i in range(num_nodes):
                owned_i = shards_for_ordinal(i, num_nodes, n)
                self._original_shards[f"node{i}"] = list(owned_i)
                for shard in owned_i:
                    self.mapper.assign(shard, f"node{i}")
        else:
            assign_shards_evenly(self.mapper, [self.node_id])
        streaming = bool(self.config.get("stream-dir"))
        # peer shards start ACTIVE optimistically; the failure detector
        # flips them DOWN when health checks fail. Own shards activate
        # immediately only without streaming (the ingestion drivers take
        # them through RECOVERY -> ACTIVE otherwise).
        owned = set(self.owned_shards) - self.deferred_shards
        for shard in range(n) if num_nodes > 1 else self.owned_shards:
            if shard in self.deferred_shards:
                continue
            if shard in owned and streaming:
                continue
            self.mapper.activate(shard)
        # deferred shards are owned by their claimer until handed back
        from filodb_tpu.parallel.shardmapper import ShardStatus
        for shard, (claimer, st) in sorted(peer_claims.items()):
            self.mapper.assign(shard, claimer)
            try:
                self.mapper.update(shard, ShardStatus(st), claimer)
            except ValueError:
                self.mapper.update(shard, ShardStatus.ACTIVE, claimer)
        if self.backend is None:
            try:
                from filodb_tpu.query.batcher import MicroBatcher
                from filodb_tpu.query.tpu import TpuBackend
                self.backend = TpuBackend(batcher=MicroBatcher(
                    gather_window_s=float(self.config.get(
                        "batch-gather-window-ms", 1.0)) / 1000.0,
                    max_batch=int(self.config.get("batch-max", 8)),
                    enabled=bool(self.config.get("batch-enabled", True))))
            except Exception:            # device unavailable -> oracle
                self.backend = None
        mesh_ex = None
        if self.config.get("mesh-enabled"):
            try:
                import jax

                from filodb_tpu.parallel.mesh import MeshExecutor, make_mesh
                if len(jax.devices()) > 1:
                    mesh_ex = MeshExecutor(make_mesh())
            except Exception:
                mesh_ex = None
        if mesh_ex is not None and self.backend is not None \
                and self.config.get("mesh-tile-serving", True):
            # multi-chip serving path: eligible aligned-tile cohorts
            # live sharded across the mesh and the slot-major
            # evaluators dispatch from the resident tiles (zero-copy
            # donated refreshes across flushes) — parallel/shardstore
            try:
                from filodb_tpu.parallel.shardstore import \
                    ShardedTileEvaluator
                self.backend.mesh_eval = ShardedTileEvaluator(
                    mesh_ex.mesh)
            except Exception:
                self.backend.mesh_eval = None
        ds_stores: Dict[str, object] = {}
        retention_ms = 0
        if (self.config.get("raw-retention-s")
                and self.store.column_store is not None):
            from filodb_tpu.downsample import DownsampledTimeSeriesStore
            retention_ms = int(self.config["raw-retention-s"]) * 1000
            ds_stores[self.ref.dataset] = DownsampledTimeSeriesStore(
                self.store.column_store, self.ref.dataset, n,
                resolutions=tuple(self.config["downsample-resolutions"]))
        peers = {k: v for k, v in
                 dict(self.config.get("peers") or {}).items()
                 if k != self.node_id}
        from filodb_tpu.parallel.resilience import (BreakerRegistry,
                                                    PeerResilience,
                                                    RetryPolicy)
        resilience = PeerResilience(
            retry=RetryPolicy(
                max_attempts=int(self.config.get(
                    "peer-retry-attempts", 3)),
                base_delay_s=float(self.config.get(
                    "peer-retry-base-delay-s", 0.05))),
            breakers=BreakerRegistry(
                failure_threshold=int(self.config.get(
                    "breaker-failure-threshold", 3)),
                reset_timeout_s=float(self.config.get(
                    "breaker-reset-s", 5.0))))
        self.http = FiloHttpServer(
            {self.ref.dataset: self.store.shards(self.ref)},
            backend=self.backend, shard_mapper=self.mapper,
            mesh_executor=mesh_ex,
            spread=int(self.config.get("default-spread", 1)),
            port=self.config["port"],
            ds_store_by_dataset=ds_stores,
            raw_retention_ms=retention_ms,
            query_limits=QueryLimits(
                series_limit=int(self.config.get("query-series-limit", 0)),
                sample_limit=int(self.config.get("query-sample-limit", 0))),
            spread_provider=self.spread_provider,
            node_id=self.node_id, peers=peers,
            buddies=dict(self.config.get("buddy-peers") or {}),
            partitions=dict(self.config.get("partitions") or {}),
            local_partitions=list(
                self.config.get("local-partitions") or ()),
            grpc_peers={k: v for k, v in dict(
                self.config.get("grpc-peers") or {}).items()
                if k != self.node_id},
            grpc_partitions=dict(
                self.config.get("grpc-partitions") or {}),
            query_timeout_s=float(self.config.get("query-timeout-s",
                                                  30.0)),
            resilience=resilience,
            plan_cache_size=int(self.config.get("plan-cache-size", 256)),
            results_cache_mb=float(
                self.config.get("results-cache-mb", 64)),
            results_cache_hot_window_ms=float(
                self.config.get("results-cache-hot-window-ms", 10_000)),
            max_inflight_queries=int(self.config.get(
                "max-inflight-queries", 4)),
            admission_wait_s=float(self.config.get(
                "admission-wait-s", 5.0)),
            qos_budgets=self._make_qos_budgets(),
            qos_degrade_max_steps=int(self.config.get(
                "qos-degrade-max-steps", 64)),
            qos_shed_degraded=bool(self.config.get(
                "qos-shed-degraded", True)),
            tracer=self._make_tracer(),
            profiler=self._make_profiler(),
            slow_query_ms=float(self.config.get("slow-query-ms",
                                                1000.0)),
            peer_fanout_workers=int(self.config.get(
                "peer-fanout-workers", 0) or 0),
            worker_id=self.config.get("worker-id"))
        # process-sharded serving: the shared public accept edge —
        # either this worker binds the public port itself with
        # SO_REUSEPORT (kernel balances connections across workers) or
        # it accepts on a listening socket inherited from the
        # supervisor (fd-passing fallback). Both feed the same handler
        # machinery as the private per-worker port.
        self.accept_port = None
        accept_sock = None
        if self.config.get("accept-fd") is not None:
            import socket as _socket
            accept_sock = _socket.socket(
                fileno=int(self.config["accept-fd"]))
            self.accept_port = accept_sock.getsockname()[1]
        elif self.config.get("accept-port"):
            accept_sock = bind_reuseport(
                str(self.config.get("accept-host", "127.0.0.1")),
                int(self.config["accept-port"]))
            if accept_sock is None:
                raise RuntimeError(
                    "accept-port configured but SO_REUSEPORT is "
                    "unavailable on this platform — run the supervisor "
                    "with fd passing (it detects this automatically)")
            self.accept_port = accept_sock.getsockname()[1]
        if accept_sock is not None:
            self.http.add_listener(accept_sock)
        # elastic membership: wire the planned-handoff coordinator
        # BEFORE the HTTP edge starts serving, so an adopt/hand-back
        # request arriving the instant the health endpoint answers
        # (the peer's failure detector reacts fast) finds it ready
        from filodb_tpu.parallel.membership import MembershipManager
        self.membership = MembershipManager(
            self, handoff_timeout_s=float(
                self.config.get("handoff-timeout-s", 30.0)))
        self.http.membership = self.membership
        self.http.start()
        self.grpc_server = None
        if self.config.get("grpc-port") is not None:
            from filodb_tpu.grpcsvc import GrpcQueryServer
            self.grpc_server = GrpcQueryServer(
                self.http, port=int(self.config["grpc-port"])).start()
            self.http.grpc_server = self.grpc_server   # /metrics gauge
        if peers:
            from filodb_tpu.parallel.cluster import FailureDetector
            shards_by_node = {node: self.mapper.shards_for_node(node)
                              for node in peers}
            grace = self.config.get("shard-reassign-grace-s")
            self.detector = FailureDetector(
                self.mapper, peers, shards_by_node,
                interval_s=float(self.config.get(
                    "failure-detect-interval-s", 0.5)),
                threshold=int(self.config.get(
                    "failure-detect-threshold", 3)),
                reassign_grace_s=(float(grace) if grace is not None
                                  else None),
                on_node_down=self._on_node_down,
                on_node_up=self._on_node_up,
                grpc_peer_sink=self.http.grpc_peers,
                peer_state_sink=self.http.peer_watermarks).start()
            # the health body advertises this node's down-view (quorum
            # input) and served-shard statuses (gossip) to its peers
            self.http.detector = self.detector
        # process-sharded serving: connect the control plane. Local
        # mapper transitions are published to siblings the instant they
        # commit (per-process plan/results caches must not serve
        # extents keyed on a stale topology for a detector-poll
        # interval), sibling transitions are applied to the local
        # mapper (whose subscribers invalidate the caches), schema
        # invalidations broadcast host-wide, and watermark/backfill
        # gossip ticks faster than the health-body path.
        if self.config.get("bus-port"):
            from filodb_tpu.standalone.bus import BusClient
            bc = BusClient(int(self.config["bus-port"]),
                           int(self.config.get("worker-id") or 0),
                           self.node_id)
            bc.on("topology", self._bus_apply_topology)
            bc.on("schema", self._bus_apply_schema)
            bc.on("watermarks", self._bus_apply_watermarks)
            bc.on("worker-exit", self._bus_apply_worker_exit)
            bc.on("worker-up", self._bus_apply_worker_up)
            self.bus_client = bc.start()
            self.http.bus_client = bc
            self.mapper.subscribe(self._bus_publish_topology)
            tick_s = float(self.config.get(
                "bus-watermark-interval-s", 0.25))
            if tick_s > 0:
                self._bus_tick_thread = threading.Thread(
                    target=self._bus_watermark_run, args=(tick_s,),
                    daemon=True, name="bus-watermark-tick")
                self._bus_tick_thread.start()
        self.tenant_metering = None
        meter_s = float(self.config.get("tenant-metering-interval-s", 0))
        if meter_s > 0 and self.card_trackers:
            from filodb_tpu.core.metering import TenantMetering
            self.tenant_metering = TenantMetering(
                self.card_trackers, interval_s=meter_s).start()
            self.http.tenant_metering = self.tenant_metering
        # host-level series from day one: RSS/fds/threads/GC/uptime +
        # filodb_build_info ride every exposition build (and therefore
        # the self-monitoring ingest below)
        from filodb_tpu.obs.process import register_process_collector
        register_process_collector()
        if streaming:
            self._start_ingestion()
        if self.config.get("self-monitor"):
            self._start_selfmon()
        if self.config.get("rules") or self.config.get("rules-file"):
            self._start_rules()
        # serving-path GC hygiene: move the (large, permanent) startup
        # object graph out of the collector's reach and make full
        # collections 10x rarer — a gen-2 sweep over jax/XLA module
        # state stalls every in-flight query for ~100ms+ on small hosts
        if self.config.get("gc-freeze", True):
            import gc
            gc.collect()
            gc.freeze()
            t0, t1, t2 = gc.get_threshold()
            gc.set_threshold(t0, t1, max(t2, 100))
        return self

    # -- control plane (standalone/bus.py) --------------------------------
    def _bus_publish_topology(self, ev) -> None:
        """ShardMapper subscriber: ship every locally-witnessed FSM
        transition to the siblings. BusClient.publish() is a no-op on
        the bus reader thread (the apply→republish loop breaker) and on
        transport failure (detector gossip re-converges)."""
        bc = self.bus_client
        if bc is None:
            return
        bc.publish({"type": "topology", "shard": int(ev.shard),
                    "status": ev.status.value, "node": ev.node,
                    "epoch": self.mapper.topology_epoch})

    def _bus_apply_topology(self, ev: Dict) -> None:
        """A sibling witnessed a shard FSM transition: converge the
        local mapper (idempotent — the mapper bumps its epoch only when
        the ownership edge actually rewires), which fires this worker's
        own subscribers and therefore the plan/results-cache
        invalidation. Already-converged events are dropped without
        touching the caches."""
        from filodb_tpu.parallel.shardmapper import ShardStatus
        try:
            shard = int(ev.get("shard", -1))
            st = ShardStatus(str(ev.get("status")))
        except (TypeError, ValueError):
            return
        if not (0 <= shard < self.mapper.num_shards):
            return
        node = ev.get("node")
        if self.mapper.status(shard) is st \
                and self.mapper.node_of(shard) == node:
            return
        self.mapper.update(shard, st, node)

    def _bus_apply_schema(self, ev: Dict) -> None:
        if self.http is not None:
            self.http.invalidate_plan_cache(
                str(ev.get("reason") or "schema-bus"))

    def _bus_apply_watermarks(self, ev: Dict) -> None:
        """Sibling watermark/backfill gossip → the same per-peer sink
        the failure detector fills from health bodies, so the results
        cache's freshness horizon tracks sibling ingest at bus latency
        instead of poll latency."""
        origin = str(ev.get("origin") or "")
        if not origin or origin == self.node_id or self.http is None:
            return
        def _ints(raw):
            try:
                return {int(k): int(v) for k, v in (raw or {}).items()}
            except (TypeError, ValueError):
                return {}
        self.http.peer_watermarks[origin] = {
            "watermarks": _ints(ev.get("watermarks")),
            "epochs": _ints(ev.get("backfill_epochs")),
            "topo_epoch": int(ev.get("topo_epoch") or 0),
        }

    @staticmethod
    def _worker_ordinal(node: str) -> Optional[int]:
        try:
            return int(node.removeprefix("node"))
        except ValueError:
            return None

    def _bus_apply_worker_exit(self, ev: Dict) -> None:
        node = str(ev.get("node") or "")
        if self.detector is not None and node:
            self.detector.note_peer_exit(node)
        # single-owner rule scheduling: a dead sibling triggers
        # re-election (the next-lowest ALIVE ordinal takes over at the
        # next interval boundary — no duplicated tick by construction)
        ordinal = self._worker_ordinal(node)
        if self.rules is not None and ordinal is not None:
            self.rules.note_worker_exit(ordinal)

    def _bus_apply_worker_up(self, ev: Dict) -> None:
        node = str(ev.get("node") or "")
        if self.detector is not None and node:
            self.detector.note_peer_up(node)
        ordinal = self._worker_ordinal(node)
        if self.rules is not None and ordinal is not None:
            self.rules.note_worker_up(ordinal)

    def _bus_gossip_once(self) -> None:
        """One watermark/backfill gossip beat onto the bus (the same
        per-shard fields the health body advertises)."""
        watermarks: Dict[str, int] = {}
        epochs: Dict[str, int] = {}
        for lst in self.http.shards_by_dataset.values():
            for i, s in enumerate(lst):
                n = getattr(s, "shard_num", i)
                wm = getattr(s, "ingest_watermark_ms", None)
                if wm is not None:
                    watermarks[str(n)] = int(wm)
                epochs[str(n)] = int(getattr(
                    s, "ingest_backfill_epoch", 0) or 0)
        self.bus_client.publish({
            "type": "watermarks", "watermarks": watermarks,
            "backfill_epochs": epochs,
            "topo_epoch": self.mapper.topology_epoch})

    @thread_root("bus-watermark-tick")
    def _bus_watermark_run(self, interval_s: float) -> None:
        while not self._bus_tick_stop.wait(interval_s):
            try:
                self._bus_gossip_once()
            except Exception:   # noqa: BLE001 — gossip must not die
                pass

    def _start_ingestion(self) -> None:
        """Streaming path: per-shard durable stream logs + ingestion
        drivers (recovery -> active), plus the optional influx gateway
        (NewFiloServerMain.start: memstore, ingestion, http)."""
        import os

        from filodb_tpu.ingest import LogIngestionStream
        stream_dir = self.config["stream-dir"]
        n = self.config["num-shards"]
        gc_s = float(self.config.get("stream-group-commit-ms", 0)) / 1000
        for shard in self.owned_shards:
            if shard in self.deferred_shards:
                continue        # a peer still serves it (single-writer)
            path = os.path.join(stream_dir, f"shard={shard}", "stream.log")
            stream = LogIngestionStream(
                path, DEFAULT_SCHEMAS, group_commit_s=gc_s)
            with self._reassign_lock:
                self.streams[shard] = stream
        for shard in sorted(self.streams):
            drv = self._make_driver(shard, self.streams[shard])
            with self._reassign_lock:
                self.drivers[shard] = drv
            drv.start()
        if self.config.get("gateway-port") is not None:
            from filodb_tpu.gateway.server import GatewayServer
            # the gateway is the producer edge: in multi-node mode it
            # publishes to EVERY shard's stream (kafka/KafkaContainerSink
            # writes all partitions), not just this node's consumer set.
            # One gateway process per stream set — frames are appended
            # whole, but two gateways on one log would interleave.
            gw_streams = dict(self.streams)
            if int(self.config.get("num-nodes", 1)) > 1:
                for shard in range(n):
                    if shard not in gw_streams:
                        path = os.path.join(stream_dir, f"shard={shard}",
                                            "stream.log")
                        gw_streams[shard] = LogIngestionStream(
                            path, DEFAULT_SCHEMAS, group_commit_s=gc_s)
            self._gw_streams = gw_streams
            self.gateway = GatewayServer(
                gw_streams, DEFAULT_SCHEMAS, num_shards=n,
                spread=int(self.config.get("default-spread", 1)),
                spread_provider=self.spread_provider,
                port=int(self.config["gateway-port"])).start()
            if self.http is not None:
                # remote-ingest edge with backpressure: the HTTP
                # /api/v1/ingest/influx route publishes through the
                # same builders/streams as the TCP gateway
                self.http.gateway = self.gateway

    # -- reserved internal datasets (selfmon + rules write-back) ----------
    def _setup_internal_dataset(self, dataset: str, subdir: str):
        """One internal shard per process for a reserved dataset,
        numbered by worker ordinal so a supervisor fleet sharing
        data/stream dirs never collides. The shard gets its OWN
        CardinalityTracker — internal series are invisible to user
        cardinality accounting and quotas. With a stream-dir the
        producer appends to a dedicated WAL and a normal
        IngestionDriver replays it (recovery included: derived series
        survive restarts); memory-only deployments ingest directly and
        flush explicitly so the freshness watermark still advances.
        Returns ``(shard, stream, driver)`` (stream/driver None without
        a stream-dir)."""
        import os

        from filodb_tpu.core.cardinality import CardinalityTracker
        wid = self.config.get("worker-id")
        shard_num = int(wid or 0)
        ref = DatasetRef(dataset)
        shard = self.store.setup(
            ref, shard_num,
            num_groups=2,
            max_chunk_rows=self.config["max-chunks-size"],
            bootstrap=self.store.column_store is not None,
            card_tracker=CardinalityTracker())
        self.http.shards_by_dataset[dataset] = self.store.shards(ref)
        stream = driver = None
        if self.config.get("stream-dir"):
            from filodb_tpu.ingest import (IngestionDriver,
                                           LogIngestionStream)
            path = os.path.join(self.config["stream-dir"], subdir,
                                f"shard={shard_num}", "stream.log")
            stream = LogIngestionStream(
                path, DEFAULT_SCHEMAS,
                group_commit_s=float(self.config.get(
                    "stream-group-commit-ms", 0)) / 1000)
            driver = IngestionDriver(
                shard, stream, mapper=None,
                flush_interval_s=float(self.config.get(
                    "flush-interval-s", 2.0)),
                ingest_batch_records=int(self.config.get(
                    "ingest-batch-records", 64)))
            driver.start()
        return shard, stream, driver

    # -- self-monitoring (obs/selfmon.py) ---------------------------------
    def _start_selfmon(self) -> None:
        """Wire the reserved ``__selfmon__`` dataset and start the
        loop (see _setup_internal_dataset for the shard/WAL model;
        internal series are stamped with a ``worker`` label and each
        worker serves its own via a strictly-local planner)."""
        from filodb_tpu.obs.selfmon import SELFMON_DATASET, SelfMonitor
        wid = self.config.get("worker-id")
        shard, stream, driver = self._setup_internal_dataset(
            SELFMON_DATASET, "selfmon")
        self._selfmon_stream = stream
        self._selfmon_driver = driver
        self.selfmon = SelfMonitor(
            self.http.build_exposition, shard,
            schemas=DEFAULT_SCHEMAS, stream=stream,
            interval_s=float(self.config.get(
                "self-monitor-interval-s", 5.0)),
            node=self.node_id,
            worker_id=int(wid) if wid is not None else None,
            flush_every_ticks=int(self.config.get(
                "self-monitor-flush-ticks", 4)))
        self.http.selfmon = self.selfmon
        self.selfmon.start()

    # -- recording rules & alerting (filodb_tpu/rules) --------------------
    def _start_rules(self) -> None:
        """Load the rule groups and start the scheduler.

        Evaluations run through ``FiloHttpServer.rule_eval_range`` —
        the normal plan-cache/results-cache/QoS path under the reserved
        ``__rules__`` tenant; recorded series and ALERTS state series
        write back through the shared IngestWriteBack rail into the
        reserved ``__rules__`` dataset (same shard/WAL model as
        selfmon). Under the supervisor every worker builds the engine
        from the propagated config, but only the lowest ALIVE worker
        ordinal evaluates; the bus ``worker-exit``/``worker-up``
        lifecycle events re-elect (wired in the bus handlers above)."""
        from filodb_tpu.obs.writeback import IngestWriteBack
        from filodb_tpu.rules import (RULES_DATASET, RulesEngine,
                                      WebhookNotifier, load_groups,
                                      load_rules_file)
        if self.config.get("rules"):
            groups = load_groups(self.config["rules"])
        else:
            groups = load_rules_file(self.config["rules-file"])
        if not groups:
            return
        shard, stream, driver = self._setup_internal_dataset(
            RULES_DATASET, "rules")
        self._rules_stream = stream
        self._rules_driver = driver
        notifier = None
        url = self.config.get("rules-webhook-url")
        if url:
            notifier = WebhookNotifier(url).start()
        wid = self.config.get("worker-id")
        self.rules = RulesEngine(
            groups,
            evaluator=self.http.rule_eval_range,
            writeback=IngestWriteBack(shard, schemas=DEFAULT_SCHEMAS,
                                      stream=stream),
            default_dataset=self.config["dataset"],
            node=self.node_id,
            worker_id=int(wid) if wid is not None else None,
            num_workers=int(self.config.get("num-nodes", 1) or 1),
            span_steps=int(self.config.get("rules-eval-span-steps", 8)),
            notifier=notifier,
            # supervised workers stand by until their own worker-up
            # broadcast (single-owner handover in one bus beat);
            # bus-less processes are announced from birth
            announced=not self.config.get("bus-port"))
        self.http.rules = self.rules
        # topology/schema invalidations reach the engine's rule-plan
        # cache through the plan cache's listener chain (the same chain
        # the results cache rides) — see the @cache_registry inventory
        self.http.plan_cache.add_invalidation_listener(
            self.rules.invalidate_plans)
        self.rules.start()

    # -- elastic recovery (shard reassignment on node loss) ---------------
    # ShardManager.scala:28 assignShardsToNodes / IngestionActor.scala:297
    # recovery protocol: every survivor independently computes the same
    # round-robin table; the shard's new owner bootstraps index + chunks
    # from the ColumnStore, replays the shared stream log from the
    # checkpoint watermark (RECOVERY with progress), then serves it.

    def _make_driver(self, shard: int, stream):
        """One ingestion driver, unstarted — shared by startup, crash
        adoption, planned adoption, and handoff rollback so a shard's
        writer is always built the same way."""
        from filodb_tpu.ingest import IngestionDriver
        return IngestionDriver(
            self.store.get_shard(self.ref, shard), stream,
            mapper=self.mapper,
            flush_every_records=self.config.get("flush-every-records"),
            flush_interval_s=float(self.config.get("flush-interval-s",
                                                   2.0)),
            max_resident_samples=int(
                self.config.get("max-resident-samples", 0)),
            ingest_batch_records=int(
                self.config.get("ingest-batch-records", 64)),
            max_decode_cache_bytes=int(float(
                self.config.get("decode-cache-mb", 0)) * (1 << 20)),
            max_quarantined_records=int(self.config.get(
                "integrity-max-quarantined-records", 0)))

    def _restart_driver(self, shard: int) -> None:
        """Handoff rollback: the successor never went ACTIVE — resume
        ingesting locally from the checkpoint watermark (the recovery
        replay covers the stopped window; the shard never left this
        node's serving set)."""
        if not self.config.get("stream-dir"):
            return
        import os

        from filodb_tpu.ingest import LogIngestionStream
        stream = self.streams.get(shard)
        if stream is None:
            path = os.path.join(self.config["stream-dir"],
                                f"shard={shard}", "stream.log")
            stream = LogIngestionStream(
                path, DEFAULT_SCHEMAS,
                group_commit_s=float(self.config.get(
                    "stream-group-commit-ms", 0)) / 1000)
            with self._reassign_lock:
                self.streams[shard] = stream
        drv = self._make_driver(shard, stream)
        with self._reassign_lock:
            self.drivers[shard] = drv
        drv.start()

    def _on_node_down(self, node: str) -> None:
        import threading

        from filodb_tpu.parallel.cluster import reassign_dead_shards
        from filodb_tpu.parallel.shardmapper import ShardStatus
        dead = sorted(self.mapper.shards_for_node(node))
        if not dead:
            return
        survivors = [self.node_id] + (self.detector.alive_peers()
                                      if self.detector else [])
        table = reassign_dead_shards(dead, survivors)
        with self._reassign_lock:
            self._adopted[node] = []
        mine = []
        for sh, owner in table.items():
            self.mapper.assign(sh, owner)
            if owner == self.node_id:
                self.mapper.update(sh, ShardStatus.RECOVERY, owner)
                mine.append(sh)
            else:
                # another survivor adopts it; hold RECOVERY until the
                # adopter's health body advertises it (the detector's
                # status gossip promotes it ACTIVE) — queries routed
                # meanwhile carry a partial-result warning instead of
                # silently missing the bootstrapping shard
                self.mapper.update(sh, ShardStatus.RECOVERY, owner)

        @thread_root("crash-adopt")
        def adopt_all():
            # off the detector's poll thread: ColumnStore bootstrap can
            # take long, and health checks must keep running meanwhile
            if self.membership is not None:
                self.membership.note_crash_adoption()
            for sh in mine:
                with self._reassign_lock:
                    if node not in self._adopted:
                        return           # owner came back mid-adoption
                try:
                    self._adopt_shard(sh)
                    with self._reassign_lock:
                        if node in self._adopted:
                            self._adopted[node].append(sh)
                            continue
                    # owner recovered while we bootstrapped: hand back
                    self._release_shard(sh)
                except Exception:
                    self._release_shard(sh)      # drop partial state
                    self.mapper.update(sh, ShardStatus.ERROR,
                                       self.node_id)
        threading.Thread(target=adopt_all, daemon=True,
                         name=f"adopt-{node}").start()

    def _on_node_up(self, node: str) -> None:
        import threading

        from filodb_tpu.parallel.shardmapper import ShardStatus
        if self.membership is not None \
                and self.config.get("elastic-membership", True):
            # planned hand-back: each adopted shard replays and flips
            # ACTIVE on its home node BEFORE this node releases it —
            # the same make-before-break handoff the drain path runs,
            # replacing the legacy hard cutover below
            self.membership.handback(node)
            return
        with self._reassign_lock:
            mine = self._adopted.pop(node, [])
        # legacy hard cutover: hand every reassigned shard back to its
        # original owner at once (each node recomputes identically; the
        # returned node re-bootstraps from the shared store + streams
        # on its own startup). Held in RECOVERY until the owner's
        # health body advertises the shard — the detector's status
        # gossip promotes it, so queries carry a partial-result warning
        # instead of silently missing data while the owner bootstraps
        for sh in self._original_shards.get(node, []):
            self.mapper.assign(sh, node)
            self.mapper.update(sh, ShardStatus.RECOVERY, node)

        @thread_root("crash-release")
        def release_all():
            # off the poll thread: driver stops join + flush (the same
            # reason adoption runs in the background)
            for sh in mine:
                self._release_shard(sh)
        if mine:
            threading.Thread(target=release_all, daemon=True,
                             name=f"release-{node}").start()

    def _adopt_shard(self, shard: int, on_event=None,
                     register=None) -> None:
        import os

        from filodb_tpu.parallel.shardmapper import ShardStatus
        with self._reassign_lock:
            self.deferred_shards.discard(shard)   # hand-back on rejoin
        self._make_shard(shard)
        # publish the widened local shard list to the HTTP layer (atomic
        # rebind; request handlers read the dict per request) BEFORE
        # claiming ownership in the mapper: a query planned in between
        # would see "owned by me" with no local shard and silently drop
        # it — published-but-unclaimed just routes to the previous
        # owner (planned handoff) or stays DOWN (crash path) instead
        with self._reassign_lock:
            self.http.shards_by_dataset[self.ref.dataset] = \
                self.store.shards(self.ref)
        self.mapper.update(shard, ShardStatus.RECOVERY, self.node_id)
        if self.config.get("stream-dir"):
            from filodb_tpu.ingest import LogIngestionStream
            path = os.path.join(self.config["stream-dir"],
                                f"shard={shard}", "stream.log")
            stream = LogIngestionStream(
                path, DEFAULT_SCHEMAS,
                group_commit_s=float(self.config.get(
                    "stream-group-commit-ms", 0)) / 1000)
            with self._reassign_lock:
                self.streams[shard] = stream  # gateway routes to it too
            drv = self._make_driver(shard, stream)
            if on_event is not None:
                # planned adoption: membership clears the read redirect
                # when the replay completes (driver flips ACTIVE)
                drv.on_event = on_event
            if register is None:
                with self._reassign_lock:
                    self.drivers[shard] = drv
                drv.start()
            elif register(drv):
                # planned adoption: registration is the single-writer
                # gate — it is refused (atomically with the abort path)
                # when the handoff was cancelled mid-bootstrap, so a
                # writer never starts after the draining owner resumed
                drv.start()
        else:
            self.mapper.update(shard, ShardStatus.ACTIVE, self.node_id)

    def _release_shard(self, shard: int) -> None:
        # registry pops ride _reassign_lock; the blocking teardown
        # (driver stop() joins its thread, stream close() syncs the
        # log tail) runs strictly outside it
        with self._reassign_lock:
            drv = self.drivers.pop(shard, None)
            stream = self.streams.pop(shard, None)
            self.card_trackers.pop(shard, None)
        if drv is not None:
            drv.stop()
        if stream is not None and self._gw_streams.get(shard) \
                is not stream:
            # close by OBJECT identity: if the local gateway publishes
            # through this very stream (a draining node keeps its
            # producer edge alive), only drop the consumer reference
            try:
                stream.close()
            except OSError:
                pass
        self.store.remove_shard(self.ref, shard)
        with self._reassign_lock:
            self.http.shards_by_dataset[self.ref.dataset] = \
                self.store.shards(self.ref)
        if self.membership is not None:
            self.membership.note_release()

    def seed_dev_data(self, n_samples: int = 360, n_instances: int = 4,
                      start_ms: Optional[int] = None) -> int:
        """Dev loop seed (dev-gateway.sh + TestTimeseriesProducer)."""
        from filodb_tpu.gateway.producer import (TestTimeseriesProducer,
                                                 ingest_builders)
        producer = TestTimeseriesProducer(
            DEFAULT_SCHEMAS, num_shards=self.config["num-shards"])
        if start_ms is None:
            start_ms = (int(time.time()) - n_samples * 10) * 1000
        owned = set(self.owned_shards) - set(self.deferred_shards)

        def _mine(builders):
            return {sh: b for sh, b in builders.items() if sh in owned}
        rows = 0
        rows += ingest_builders(self.store, self.ref,
                                _mine(producer.gauges(start_ms, n_samples,
                                                      n_instances)))
        rows += ingest_builders(self.store, self.ref,
                                _mine(producer.counters(start_ms, n_samples,
                                                        n_instances)))
        rows += ingest_builders(self.store, self.ref,
                                _mine(producer.histograms(start_ms,
                                                          n_samples)))
        self.store.flush_all(self.ref)
        return rows

    def stop(self) -> None:
        if self.rules is not None:
            self.rules.stop()
        if self._rules_driver is not None:
            self._rules_driver.stop()
        if self._rules_stream is not None:
            try:
                self._rules_stream.close()
            except OSError:
                pass
        if self.selfmon is not None:
            self.selfmon.stop()
        if self._selfmon_driver is not None:
            self._selfmon_driver.stop()
        if self._selfmon_stream is not None:
            try:
                self._selfmon_stream.close()
            except OSError:
                pass
        self._bus_tick_stop.set()
        if self._bus_tick_thread is not None:
            self._bus_tick_thread.join(timeout=5)
        if self.bus_client is not None:
            self.bus_client.stop()
        if getattr(self, "grpc_server", None) is not None:
            self.grpc_server.stop()
        if getattr(self, "tenant_metering", None) is not None:
            self.tenant_metering.stop()
        if self.detector is not None:
            self.detector.stop()
        if self.gateway is not None:
            self.gateway.stop()
        for drv in list(self.drivers.values()):
            drv.stop()
        for stream in self.streams.values():
            stream.close()
        for shard, stream in self._gw_streams.items():
            # close by OBJECT identity: an adopted shard put a different
            # stream object in self.streams for the same path
            if stream is not self.streams.get(shard):
                stream.close()
        if self.http:
            if self.http.profiler is not None:
                self.http.profiler.stop()
            if self.http.tracer is not None \
                    and self.http.tracer.exporter is not None:
                self.http.tracer.exporter.stop()
            self.http.stop()

    @property
    def port(self) -> int:
        return self.http.port if self.http else -1


@thread_root("main-idle")
def _main_idle() -> None:
    # the main thread parks here for the life of the process; a
    # registered root so the sampling profiler attributes it instead
    # of counting a permanently-asleep thread as unattributed
    while True:
        time.sleep(3600)


def main(argv=None) -> int:
    # honor JAX_PLATFORMS even where a sitecustomize pre-imports jax
    # pointed at an accelerator (env alone is too late then; the config
    # update still works before first backend init)
    import os
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass
    p = argparse.ArgumentParser(prog="filodb-tpu-server")
    p.add_argument("--config", help="JSON config file")
    p.add_argument("--port", type=int)
    p.add_argument("--num-shards", type=int)
    p.add_argument("--dataset")
    p.add_argument("--data-dir")
    p.add_argument("--stream-dir")
    p.add_argument("--gateway-port", type=int)
    p.add_argument("--self-monitor", action="store_true", default=None,
                   help="ingest this node's own metrics into the "
                        "reserved __selfmon__ dataset (PromQL over "
                        "our own telemetry)")
    p.add_argument("--rules-file",
                   help="Prometheus-style recording/alerting rule "
                        "file evaluated in-process (validate with "
                        "python -m filodb_tpu.rules --check)")
    p.add_argument("--seed-dev-data", action="store_true",
                   help="generate dev series on startup")
    args = p.parse_args(argv)
    config: Dict = {}
    if args.config:
        with open(args.config) as f:
            config.update(json.load(f))
    for k in ("port", "num_shards", "dataset", "data_dir", "stream_dir",
              "gateway_port", "self_monitor", "rules_file"):
        v = getattr(args, k)
        if v is not None:
            config[k.replace("_", "-")] = v
    server = FiloServer(config).start()
    if args.seed_dev_data or config.get("seed-dev-data"):
        rows = server.seed_dev_data(
            n_samples=int(config.get("seed-samples", 360)),
            n_instances=int(config.get("seed-instances", 4)),
            start_ms=config.get("seed-start-ms"))
        print(f"seeded {rows} dev samples", file=sys.stderr)
    # machine-readable startup line (test harness / dev scripts read this)
    gw = server.gateway.port if server.gateway is not None else None
    gp = server.grpc_server.port if getattr(server, "grpc_server", None) \
        is not None else None
    line = {"port": server.port, "gateway_port": gw, "grpc_port": gp}
    if getattr(server, "accept_port", None) is not None:
        line["accept_port"] = server.accept_port
        line["worker_id"] = server.config.get("worker-id")
    print(json.dumps(line), flush=True)
    print(f"filodb-tpu server listening on :{server.port}", file=sys.stderr)
    try:
        _main_idle()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
