"""Offline durable-file checker/repairer for the storage-integrity rail.

    python -m filodb_tpu.fsck <data-dir> [--json] [--repair] [--quiet]

Walks every durable file under ``<data-dir>`` — WAL segments
(``stream.log``), chunk logs (``chunks.log``), partkey logs
(``partkeys.log``) and checkpoint documents (``checkpoints.json``) —
verifies every frame with the same scanner the online readers use
(store/integrity.py), and prints a per-file report: record counts split
by format (framed vs legacy unframed), corrupt regions with offsets and
reasons, and the tail state.

``--repair`` makes the findings go away the same way the online path
would, but eagerly and including the cases the online path must leave
pending:

  * torn tails are truncated (the bytes are first copied to the
    ``quarantine/`` sidecar — repair never destroys the only copy);
  * corrupt tails (bad bytes with no resync point) are quarantined and
    truncated;
  * corrupt regions MID-log are quarantined and the log is compacted —
    surviving records are rewritten byte-identical (format preserved),
    so replay and ODP indexing walk a clean file;
  * an unverifiable checkpoint is quarantined and removed (replay
    restarts from offset 0, which is safe: chunk/partkey appends
    upsert and re-ingest is idempotent).

Exit status: 0 when every file is clean (or was fully repaired),
1 when findings remain (no ``--repair``), 2 on usage errors.

The import chain is deliberately jax-free so the tool starts fast on
any host with the package installed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

from filodb_tpu.ingest.stream import legacy_wal_probe
from filodb_tpu.store import integrity
from filodb_tpu.store.columnstore import (legacy_chunk_probe,
                                          legacy_pk_probe)

# durable file basenames -> (file_kind, legacy probe); checkpoints are
# JSON documents handled separately
_LOG_KINDS = {
    "stream.log": ("wal", legacy_wal_probe),
    "chunks.log": ("chunklog", legacy_chunk_probe),
    "partkeys.log": ("partkeys", legacy_pk_probe),
}
_CKPT_NAME = "checkpoints.json"


def _find_durable_files(root: str) -> List[str]:
    out: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        # never descend into sidecars: quarantined bytes are corrupt
        # by definition and not part of the durable set
        dirnames[:] = [d for d in dirnames if d != "quarantine"]
        for name in sorted(filenames):
            if name in _LOG_KINDS or name == _CKPT_NAME:
                out.append(os.path.join(dirpath, name))
    return sorted(out)


def _check_log(path: str, kind: str, probe) -> Dict:
    with open(path, "rb") as f:
        buf = f.read()
    res = integrity.scan_buffer(buf, probe=probe)
    framed = sum(1 for r in res.records if r.framed)
    report = {
        "path": path, "kind": kind, "size": len(buf),
        "records": {"framed": framed,
                    "legacy": len(res.records) - framed},
        "corrupt_regions": [
            {"offset": c.offset, "length": c.length, "reason": c.reason}
            for c in res.corrupt],
        "tail": {"state": res.tail_state, "offset": res.tail_off,
                 "reason": res.tail_reason},
        "clean": not res.corrupt and res.tail_state == "clean",
    }
    report["_scan"] = res          # for repair; stripped before output
    report["_buf"] = buf
    return report


def _check_checkpoint(path: str) -> Dict:
    with open(path, "rb") as f:
        raw = f.read()
    report = {"path": path, "kind": "checkpoint", "size": len(raw),
              "records": {"framed": 0, "legacy": 0},
              "corrupt_regions": [], "tail": {"state": "clean",
                                              "offset": len(raw),
                                              "reason": ""},
              "clean": True}
    try:
        _, framed = integrity.decode_checkpoint(raw)
        report["records"]["framed" if framed else "legacy"] = 1
    except integrity.FrameError as e:
        report["clean"] = False
        report["corrupt_regions"].append(
            {"offset": 0, "length": len(raw), "reason": e.reason})
    report["_buf"] = raw
    return report


def _repair_log(report: Dict) -> List[str]:
    """Quarantine bad ranges and leave the file containing exactly the
    verified records. Returns human-readable action lines."""
    path, kind = report["path"], report["kind"]
    res = report["_scan"]
    buf = report["_buf"]
    actions: List[str] = []
    for c in res.corrupt:
        integrity.quarantine(path, kind, c.offset,
                             buf[c.offset:c.offset + c.length], c.reason,
                             action="fsck-quarantined")
        actions.append(f"quarantined {c.length} bytes @ {c.offset}: "
                       f"{c.reason}")
    if res.tail_state != "clean":
        tail = buf[res.tail_off:]
        if tail:
            integrity.quarantine(path, kind, res.tail_off, tail,
                                 res.tail_reason or res.tail_state,
                                 action="fsck-truncated")
        actions.append(f"truncated {res.tail_state} tail "
                       f"({len(tail)} bytes @ {res.tail_off})")
    if res.corrupt:
        # compact: rewrite surviving records byte-identical (format
        # preserved) so readers walk a contiguous clean file
        tmp = path + ".fsck-tmp"
        with open(tmp, "wb") as f:
            for r in res.records:
                f.write(buf[r.offset:r.offset + r.length])
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        actions.append(f"compacted: kept {len(res.records)} records")
    elif res.tail_state != "clean":
        os.truncate(path, res.consumed)
    return actions


def _repair_checkpoint(report: Dict) -> List[str]:
    path = report["path"]
    raw = report["_buf"]
    reason = report["corrupt_regions"][0]["reason"]
    integrity.quarantine(path, "checkpoint", 0, raw, reason,
                         action="fsck-removed")
    os.unlink(path)
    return ["quarantined + removed unverifiable checkpoint "
            "(replay restarts from offset 0)"]


def check_dir(root: str, repair: bool = False) -> Dict:
    """Programmatic entry point: scan (and optionally repair) every
    durable file under ``root``; returns the full report dict."""
    files: List[Dict] = []
    for path in _find_durable_files(root):
        base = os.path.basename(path)
        if base == _CKPT_NAME:
            rep = _check_checkpoint(path)
            if not rep["clean"] and repair:
                rep["repair_actions"] = _repair_checkpoint(rep)
                rep["repaired"] = True
        else:
            kind, probe = _LOG_KINDS[base]
            rep = _check_log(path, kind, probe)
            if not rep["clean"] and repair:
                rep["repair_actions"] = _repair_log(rep)
                rep["repaired"] = True
        rep.pop("_scan", None)
        rep.pop("_buf", None)
        files.append(rep)
    dirty = [f for f in files if not f["clean"]]
    return {
        "root": os.path.abspath(root),
        "files": files,
        "summary": {
            "files_checked": len(files),
            "files_clean": len(files) - len(dirty),
            "files_with_findings": len(dirty),
            "corrupt_regions": sum(len(f["corrupt_regions"])
                                   for f in files),
            "torn_tails": sum(1 for f in files
                              if f["tail"]["state"] == "torn"),
            "repaired": repair,
        },
    }


def _human(report: Dict, out) -> None:
    s = report["summary"]
    for f in report["files"]:
        recs = f["records"]
        status = "clean" if f["clean"] else (
            "REPAIRED" if f.get("repaired") else "CORRUPT")
        fmt = []
        if recs["framed"]:
            fmt.append(f"{recs['framed']} framed")
        if recs["legacy"]:
            fmt.append(f"{recs['legacy']} legacy")
        print(f"{status:8s} {f['kind']:10s} {f['path']} "
              f"({f['size']} bytes, {', '.join(fmt) or 'no records'})",
              file=out)
        for c in f["corrupt_regions"]:
            print(f"         corrupt @ {c['offset']} "
                  f"({c['length']} bytes): {c['reason']}", file=out)
        if f["tail"]["state"] != "clean":
            print(f"         {f['tail']['state']} tail @ "
                  f"{f['tail']['offset']}: {f['tail']['reason']}",
                  file=out)
        for a in f.get("repair_actions", ()):
            print(f"         repair: {a}", file=out)
    print(f"{s['files_checked']} files checked: {s['files_clean']} "
          f"clean, {s['files_with_findings']} with findings "
          f"({s['corrupt_regions']} corrupt regions, "
          f"{s['torn_tails']} torn tails)", file=out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m filodb_tpu.fsck",
        description="Verify (and optionally repair) FiloDB durable "
                    "files: WAL, chunk log, partkey log, checkpoints.")
    ap.add_argument("data_dir", help="root directory to walk "
                    "(a --data-dir, --stream-dir, or any parent)")
    ap.add_argument("--repair", action="store_true",
                    help="quarantine bad frames, truncate torn tails, "
                         "compact damaged logs, remove unverifiable "
                         "checkpoints")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the full report as one JSON object")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the human report (exit status only)")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.data_dir):
        print(f"fsck: not a directory: {args.data_dir}",
              file=sys.stderr)
        return 2
    report = check_dir(args.data_dir, repair=args.repair)
    if args.as_json:
        print(json.dumps(report, indent=2))
    elif not args.quiet:
        _human(report, sys.stdout)
    if report["summary"]["files_with_findings"] and not args.repair:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
